"""Deterministic fault injection — ONE mechanism for tests and bench.

The chaos story (ISSUE 4) needs the same failure to be reproducible in a
unit test, in an in-proc bench leg, and in a worker SUBPROCESS: a seeded
``FaultPlan`` is therefore a pure function of (spec, seed) and is
installable three ways that all meet at ``fire()``/``check()``:

  - programmatically: ``with faults.use(plan): ...`` (tests, bench legs);
  - per component: pass a plan to the component that should see it;
  - by environment: ``RTPU_FAULTS="publish:fail@10-40" python -m
    reporter_tpu.streaming ...`` — a spawned worker inherits the env and
    injects the same faults its parent planned (the bench's outage and
    chaos legs drive subprocesses exactly this way).

Injection SITES (each consults the active plan at one seam):

  publish     datastore transport (service/datastore.py) — an injected
              fault raises ``InjectedFault`` (an OSError: transport-shaped,
              so the publisher's real retry/backoff/dead-letter machinery
              handles it exactly like a network outage)
  checkpoint  streaming/state.save_checkpoint — fires AFTER the tmp file
              is written, BEFORE the atomic rename: the simulated
              mid-checkpoint death the atomic-write contract must survive
  broker      durable broker batch append (streaming/durable_columnar.py)
              — ``torn`` writes half the frame then dies, exercising the
              torn-tail recovery path with an acked prefix intact
  dispatch    device dispatch (matcher/api.py, jax path only) — ``hang``
              sleeps like the axon tunnel does (it hangs, it does not
              error: CLAUDE.md), which is what the dispatch watchdog
              exists to bound
  fleet_promote  fleet residency page-in (fleet/residency.py) — fires
              inside the guarded device_put body, so an injected hang
              stalls a promotion exactly where a dead tunnel would
              (bounded by ``FleetConfig.promote_timeout_s``)
  quality     drift-sentinel evaluation (quality/monitor.py) — a rule
              covering the evaluation's call index forces the window
              comparison to read DRIFTED (consultation via ``check()``,
              the broker-torn pattern: the monitor acts, the plan only
              schedules), driving the ``quality_drift`` post-mortem
              deterministically in chaos tests and the bench leg
  backfill    open-loop chunk harvest (backfill/engine.py) — fires once
              per aggregated chunk, so ``backfill:crash@N`` kills a
              spool replay mid-stream exactly between a harvest and its
              checkpoint: the at-least-once resume contract the chaos
              test replays (coverage-exact aggregates, counted tax)

Rules are windows over a per-site CALL COUNTER (0-based), so a plan is
deterministic run to run regardless of wall clock; the optional ``p``
probability is drawn from a per-site ``random.Random(seed)`` stream, so
even probabilistic plans replay exactly. Spec grammar (';'-separated):

    site:kind[(seconds)]@lo[-hi][~p]

    publish:fail@10-40          calls 10..39 raise InjectedFault
    checkpoint:crash@1          the 2nd checkpoint dies before rename
    dispatch:hang(2.5)@0-2      first two dispatches stall 2.5 s
    broker:torn@3               4th batch append tears mid-frame
    publish:fail@0-~0.25        every call fails w.p. 0.25 (seeded)

``hi`` omitted ⇒ ``lo+1``; ``hi`` empty (``@5-``) ⇒ open-ended.

Parsing is STRICT (round 23): a malformed spec raises ``ValueError``
naming the bad clause at plan construction — ``install()``/parse time,
never silently at fire time. A typo'd site name, an empty window, a
zero probability, a duration on a non-hang kind, a hang without one,
or ``torn`` outside the broker site are all plans that can never fire
the way their author meant (the r14 env-parse bug class, one layer
up), so they are rejected where the author can see them. Validation
lives in ``FaultPlan.__post_init__`` so hand-built plans get the same
gate as parsed specs.
"""

from __future__ import annotations

import os
import random
import re
import time
from dataclasses import dataclass, field

from reporter_tpu.utils import locks

SITES = ("publish", "checkpoint", "broker", "dispatch", "fleet_promote",
         "quality", "backfill")
KINDS = ("fail", "crash", "hang", "torn")


class InjectedFault(OSError):
    """Transport-shaped injected failure (publish site): callers' real
    error paths — retry, backoff, dead-letter — handle it unchanged."""


class InjectedCrash(RuntimeError):
    """Simulated process death mid-operation (checkpoint/broker sites).
    Tests catch it where a real crash would have killed the process."""


@dataclass(frozen=True)
class FaultRule:
    kind: str                 # fail | crash | hang | torn
    lo: int = 0               # fire on call indices lo <= i < hi
    hi: float = 1             # float so inf can mean open-ended
    seconds: float = 0.0      # hang duration
    p: float = 1.0            # fire probability within the window

    def covers(self, i: int) -> bool:
        return self.lo <= i < self.hi

    def clause(self, site: str) -> str:
        """Canonical spec-grammar text for this rule (error messages
        name the bad clause in the author's own notation)."""
        secs = f"({self.seconds:g})" if self.seconds else ""
        if self.hi == float("inf"):
            span = f"{self.lo}-"
        elif self.hi == self.lo + 1:
            span = f"{self.lo}"
        else:
            span = f"{self.lo}-{int(self.hi)}"
        # p == 1.0 exactly is the grammar default and elides; an
        # out-of-range p must still render so validation errors can
        # name the offending clause verbatim
        prob = "" if self.p == 1.0 else f"~{self.p:g}"
        return f"{site}:{self.kind}{secs}@{span}{prob}"


_RULE_RE = re.compile(
    r"^(?P<site>\w+):(?P<kind>\w+)"
    r"(?:\((?P<seconds>[0-9.]+)\))?"
    r"@(?P<lo>\d+)(?P<span>-(?P<hi>\d*))?"
    r"(?:~(?P<p>[0-9.]+))?$")


@dataclass
class FaultPlan:
    """Seeded, counted fault schedule over the injection sites."""

    rules: "dict[str, list[FaultRule]]" = field(default_factory=dict)
    seed: int = 0

    def __post_init__(self):
        # Strict validation (round 23): every rule that can never fire
        # as written is an error HERE, with the clause spelled out —
        # not a plan that silently does nothing (satellite of ISSUE 19;
        # the r14 REPORTER_TPU_NO_NATIVE=0 bug class).
        for site, site_rules in self.rules.items():
            if site not in SITES:
                raise ValueError(f"unknown fault site {site!r}; "
                                 f"one of {SITES}")
            for r in site_rules:
                clause = r.clause(site)
                if r.kind not in KINDS:
                    raise ValueError(f"unknown fault kind {r.kind!r} in "
                                     f"{clause!r}; one of {KINDS}")
                if r.lo < 0:
                    raise ValueError(
                        f"negative call window start in {clause!r}")
                if not r.hi > r.lo:
                    raise ValueError(
                        f"empty call window in {clause!r}: hi ({r.hi:g}) "
                        f"must exceed lo ({r.lo})")
                if not 0.0 < r.p <= 1.0:
                    raise ValueError(
                        f"fire probability {r.p:g} in {clause!r} outside "
                        "(0, 1] — the rule would never/over fire")
                if r.kind == "hang" and r.seconds <= 0:
                    raise ValueError(
                        f"hang rule {clause!r} needs a positive duration: "
                        "write hang(seconds)")
                if r.kind != "hang" and r.seconds:
                    raise ValueError(
                        f"duration only applies to hang rules, got "
                        f"{clause!r}")
                if r.kind == "torn" and site != "broker":
                    raise ValueError(
                        f"torn is a broker-site kind (the caller must "
                        f"cooperate to tear a frame), got {clause!r}")
        self._lock = locks.named_lock("faults.plan")
        self.calls = {s: 0 for s in SITES}
        self.fired = {s: 0 for s in SITES}
        # zlib.crc32, not hash(): string hashing is per-process
        # randomized, and the whole point is that a SUBPROCESS replays
        # its parent's schedule exactly
        import zlib
        self._rng = {s: random.Random((self.seed << 8)
                                      ^ (zlib.crc32(s.encode()) & 0xFFFF))
                     for s in SITES}

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules: "dict[str, list[FaultRule]]" = {}
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            m = _RULE_RE.match(part)
            if not m:
                raise ValueError(f"bad fault rule {part!r}; grammar: "
                                 "site:kind[(seconds)]@lo[-hi][~p]")
            site, kind = m["site"], m["kind"]
            if site not in SITES:
                raise ValueError(f"unknown fault site {site!r}; "
                                 f"one of {SITES}")
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}; "
                                 f"one of {KINDS}")
            lo = int(m["lo"])
            if m["span"] is None:
                hi: float = lo + 1
            else:
                hi = float("inf") if not m["hi"] else int(m["hi"])
            rules.setdefault(site, []).append(FaultRule(
                kind=kind, lo=lo, hi=hi,
                seconds=float(m["seconds"] or 0.0),
                p=float(m["p"] or 1.0)))
        return cls(rules=rules, seed=seed)

    # ---- the two consultation surfaces ----------------------------------

    def check(self, site: str) -> "FaultRule | None":
        """Count one call at ``site``; return the rule that fires for it
        (or None). Sites with caller-specific behavior (broker torn
        writes) use this and act themselves."""
        with self._lock:
            i = self.calls[site]
            self.calls[site] = i + 1
            for r in self.rules.get(site, ()):
                if r.covers(i) and (r.p >= 1.0
                                    or self._rng[site].random() < r.p):
                    self.fired[site] += 1
                    return r
        return None

    def fire(self, site: str) -> None:
        """check() + the standard action: fail ⇒ InjectedFault, crash ⇒
        InjectedCrash, hang ⇒ sleep (the axon tunnel stalls, it does not
        error), torn ⇒ returned to the caller via check() only."""
        r = self.check(site)
        if r is None:
            return
        if r.kind == "hang":
            time.sleep(r.seconds)
        elif r.kind == "crash":
            raise InjectedCrash(f"injected crash at {site} "
                                f"(call {self.calls[site] - 1})")
        elif r.kind == "fail":
            raise InjectedFault(f"injected {site} failure "
                                f"(call {self.calls[site] - 1})")
        # "torn" needs caller cooperation; fire() alone does nothing

    def stats(self) -> dict:
        with self._lock:
            return {"calls": dict(self.calls), "fired": dict(self.fired)}


# ---------------------------------------------------------------------------
# Active-plan registry (programmatic installs layered over the env plan)

_ENV_VAR = "RTPU_FAULTS"
_ENV_SEED = "RTPU_FAULT_SEED"
_lock = locks.named_lock("faults.registry")
_installed: "FaultPlan | None" = None
_env_plan: "FaultPlan | None | str" = "unset"   # lazy one-shot parse


def active() -> "FaultPlan | None":
    """The plan injection sites consult: an installed plan wins; else the
    env plan (parsed once — subprocesses inherit RTPU_FAULTS and replay
    the same schedule); else None (the common case: one dict lookup)."""
    global _env_plan
    if _installed is not None:
        return _installed
    if _env_plan == "unset":
        with _lock:
            if _env_plan == "unset":
                spec = os.environ.get(_ENV_VAR, "")
                _env_plan = (FaultPlan.parse(
                    spec, seed=int(os.environ.get(_ENV_SEED, "0")))
                    if spec else None)
    return _env_plan


def install(plan: "FaultPlan | None") -> None:
    global _installed
    _installed = plan


class use:
    """``with faults.use(plan):`` — install for a scope, restore after
    (tests/bench legs must never leak a plan into the next test)."""

    def __init__(self, plan: "FaultPlan | None"):
        self._plan = plan
        self._prev: "FaultPlan | None" = None

    def __enter__(self) -> "FaultPlan | None":
        global _installed
        self._prev = _installed
        _installed = self._plan
        return self._plan

    def __exit__(self, *exc) -> None:
        global _installed
        _installed = self._prev


def fire(site: str) -> None:
    """Module-level convenience: consult the active plan (no-op without
    one). The one line every injection site carries."""
    p = active()
    if p is not None:
        p.fire(site)


def check(site: str) -> "FaultRule | None":
    p = active()
    return None if p is None else p.check(site)


# ---------------------------------------------------------------------------
# Deterministic retry backoff (shared by the publisher + its tests)


def backoff_schedule(attempts: int, base_s: float, cap_s: float,
                     jitter: float = 0.1, seed: int = 0) -> "list[float]":
    """The publisher's bounded-exponential-with-jitter schedule as a PURE
    function: sleep[i] = min(cap, base·2^i)·(1 + jitter·u_i) with u_i from
    ``random.Random(seed)`` — same (attempts, base, cap, jitter, seed) ⇒
    same schedule, byte for byte, so tests pin determinism and a capture
    can name the exact delays a retried wave paid."""
    rng = random.Random(seed)
    out = []
    for i in range(max(0, int(attempts))):
        d = min(cap_s, base_s * (2.0 ** i))
        out.append(d * (1.0 + jitter * rng.random()))
    return out
