"""Build + load the native library.

No pybind11 in this image, so the binding is plain ctypes over an
``extern "C"`` surface. The .so is compiled next to the sources on first
import (and rebuilt whenever reach.cc is newer), so a source checkout works
without a packaging step — the moral equivalent of the reference's
Docker-image build of Valhalla (SURVEY.md §2.1 "Packaging").
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile

log = logging.getLogger("reporter_tpu.native")

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("reach.cc", "walker.cc", "prepare.cc")
_LIB_NAME = "_libreporter.so"


# Sanitizer build flavors (SURVEY.md §5 "Race detection / sanitizers":
# the reference's C++ deps ran ASan/TSan in upstream CI). Each flavor
# compiles to its own .so; tests/test_native_sanitizers.py drives the
# multithreaded walker and the reach builder under both. The DEFAULT
# flavor is warning-clean and enforced (-Wall -Wextra -Werror, round 14)
# — a new warning fails the build and falls back to Python, which the
# native-parity tests then surface loudly; the sanitizer flavors keep
# their round-9 flags unchanged (their drivers already wedge-probe on
# this box, and -Werror there would conflate toolchain noise with races).
_SANITIZE_FLAGS = {
    None: ["-O3", "-Wall", "-Wextra", "-Werror"],
    "asan": ["-fsanitize=address,undefined", "-fno-omit-frame-pointer",
             "-g", "-O1"],
    "tsan": ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g", "-O1"],
}


def _lib_name(sanitize: "str | None") -> str:
    return _LIB_NAME if sanitize is None else f"_libreporter_{sanitize}.so"


def _source_digest(sanitize: "str | None") -> str:
    """Content hash of every source file + the flags that compile them.

    The old mtime comparison (source newer than the committed .so) served
    a STALE library after any operation that rewinds source mtimes — a
    branch switch, a ``git checkout`` of older sources, a revert — because
    the .so's mtime stayed newest. Content addressing can't be fooled by
    clock order: the digest is stored next to the .so and a mismatch (or
    a missing sidecar) forces a rebuild."""
    h = hashlib.sha256()
    for s in _SOURCES:
        h.update(s.encode())
        with open(os.path.join(_SRC_DIR, s), "rb") as f:
            h.update(f.read())
    h.update(repr(_SANITIZE_FLAGS[sanitize]).encode())
    return h.hexdigest()


def _hash_path(lib_path: str) -> str:
    return lib_path + ".hash"


def _needs_build(lib_path: str, digest: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    try:
        with open(_hash_path(lib_path)) as f:
            return f.read().strip() != digest
    except OSError:
        return True     # no sidecar (pre-hash build, or deleted) ⇒ rebuild


def build_native_lib(force: bool = False,
                     sanitize: "str | None" = None) -> str | None:
    """Compile the shared library; returns its path or None on failure.

    The temp .so lives in its own ``tempfile`` DIRECTORY under the
    source dir (same filesystem, so the publish rename stays atomic
    w.r.t. concurrent importers) and the whole directory is removed on
    every exit path — the bare ``mkstemp(dir=_SRC_DIR)`` temps used
    before this leaked ``tmp*.so`` strays into the package tree whenever
    a sanitizer build's driver subprocess was killed mid-compile."""
    import shutil

    lib_path = os.path.join(_SRC_DIR, _lib_name(sanitize))
    digest = _source_digest(sanitize)
    if not force and not _needs_build(lib_path, digest):
        return lib_path
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    tmpdir = tempfile.mkdtemp(prefix="tmpbuild_", dir=_SRC_DIR)
    tmp = os.path.join(tmpdir, _lib_name(sanitize))
    cmd = ["g++", *_SANITIZE_FLAGS[sanitize], "-std=c++17",
           "-shared", "-fPIC", "-o", tmp, *srcs, "-lpthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            log.warning("native build failed (falling back to Python):\n%s",
                        proc.stderr[-2000:])
            return None
        # .so first, sidecar after: a crash between the two leaves a
        # missing/stale sidecar, which _needs_build reads as "rebuild" —
        # never the reverse (fresh sidecar blessing a stale .so)
        os.replace(tmp, lib_path)
        tmp_hash = os.path.join(tmpdir, "digest")
        with open(tmp_hash, "w") as f:
            f.write(digest)
        os.replace(tmp_hash, _hash_path(lib_path))
        return lib_path
    except (OSError, subprocess.SubprocessError) as exc:
        log.warning("native build unavailable: %s", exc)
        return None
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def load_native_lib(sanitize: "str | None" = None) -> "ctypes.CDLL | None":
    """Build if needed, load, and declare signatures. None ⇒ use Python.

    ``sanitize`` ("asan"/"tsan") loads the instrumented flavor — the
    process must have the matching sanitizer runtime preloaded
    (LD_PRELOAD=libasan.so/libtsan.so), so sanitized runs live in
    subprocesses (tests/test_native_sanitizers.py)."""
    # env_flag, not bare truthiness: REPORTER_TPU_NO_NATIVE=0 used to
    # DISABLE native (any non-empty string read as "set") — exactly the
    # drift class the round-14 env-flag lint exists to catch
    from reporter_tpu.utils.tracing import env_flag

    if env_flag(os.environ.get("REPORTER_TPU_NO_NATIVE")):
        return None
    lib_path = build_native_lib(sanitize=sanitize)
    if lib_path is None:
        return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as exc:
        log.warning("failed to load %s: %s", lib_path, exc)
        return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.reporter_build_reach.restype = ctypes.c_int64
    lib.reporter_build_reach.argtypes = [
        i32p, ctypes.c_int64, ctypes.c_int64,        # node_out, N, deg
        i32p, f32p,                                  # edge_dst, edge_len
        ctypes.c_double, ctypes.c_int32,             # radius, max_targets
        ctypes.c_int32,                              # n_threads
        i32p, f32p, i32p,                            # outputs
    ]
    lib.reporter_build_grid.restype = ctypes.c_int64
    lib.reporter_build_grid.argtypes = [
        f32p, f32p, f32p, f32p, ctypes.c_int64,      # ax, ay, bx, by, S
        ctypes.c_double, ctypes.c_double,            # lox, loy
        ctypes.c_double, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        i32p, i32p,                                  # grid, counts
    ]
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.reporter_walk_segments.restype = ctypes.c_int64
    lib.reporter_walk_segments.argtypes = [
        i32p, f32p, u8p, f64p,                       # edges, offs, starts, times
        ctypes.c_int64, ctypes.c_int64,              # B, T
        f32p, i64p, i32p, f32p,                      # edge_{len,way,osmlr,osmlr_off}
        i64p, f32p,                                  # osmlr_{id,len}
        i32p,                                        # reach_row (edge → row)
        i32p, f32p, i32p, ctypes.c_int32,            # reach_{to,dist,next}, M
        ctypes.c_double, ctypes.c_int32,             # backward_slack, n_threads
        i32p, i64p, f64p, f64p, f64p, f64p, u8p,     # record columns
        ctypes.c_int64,                              # rec_cap
        i32p, i64p, ctypes.c_int64,                  # way_off, way_ids, way_cap
        i64p,                                        # n_ways_out
    ]
    i16p = ctypes.POINTER(ctypes.c_int16)
    i8p = ctypes.POINTER(ctypes.c_int8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.reporter_prepare_slice.restype = ctypes.c_int32
    lib.reporter_prepare_slice.argtypes = [
        f32p, i64p,                                  # xy flat, offs
        ctypes.c_int64, ctypes.c_int64,              # B, b
        ctypes.c_int32,                              # n_threads
        f32p, i32p, f32p,                            # pts, lens, origins
        i16p, i8p,                                   # dq16, d8
    ]
    lib.reporter_morton_keys.restype = None
    lib.reporter_morton_keys.argtypes = [f64p, ctypes.c_int64, u64p]
    lib.reporter_build_reports.restype = ctypes.c_int64
    lib.reporter_build_reports.argtypes = [
        i32p, i64p, f64p, f64p, f64p, f64p, u8p,     # record columns
        ctypes.c_int64, ctypes.c_double,             # n, min_length
        ctypes.c_int64,                              # n_traces (-1 = skip)
        i64p, i64p, f64p, f64p, f64p, f64p,          # outputs
        i64p,                                        # per_trace
    ]
    lib.reporter_tail_cuts.restype = None
    lib.reporter_tail_cuts.argtypes = [
        f64p, i64p, ctypes.c_int64,                  # time_flat, bounds, V
        f64p, ctypes.c_int64, i64p,                  # from_time, max_pts, lo
    ]
    return lib
