"""Native (C++) tile-compiler kernels, built on demand with g++.

See reach.cc for what lives here and why. Import surface:

    from reporter_tpu.native import lib        # ctypes CDLL or None
"""

from reporter_tpu.native.build import load_native_lib

lib = load_native_lib()

__all__ = ["lib", "load_native_lib"]
