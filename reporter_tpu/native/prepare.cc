// Native host-side prepare: probe columns → device wire buffers.
//
// The measured e2e critical path is host Python before the first dispatch
// (CLAUDE.md round-4 nuance; ROADMAP item 3): at 16k-trace batches the
// wire bytes fully overlap compute, so the submit leg — pad → i16
// quantize (0.25 m) → i8 delta pack with the exact i16-absolute overflow
// fallback — is what caps sustained ingest, the same shape as the
// reference's native-code prepare/walk around its matcher core
// (SURVEY.md §2.2). These entries do that leg in one C pass over flat
// columnar buffers, filling preallocated wire arrays the caller hands
// straight to jax.device_put.
//
// BYTE-IDENTITY contract with the numpy path (matcher/api.py
// _submit_many / native_prepare._prepare_slice_python, fuzz-asserted by
// tests/test_native_prepare.py and bench detail.prepare_bench):
//   - pad at the trace's first point (keeps the quantized form in i16
//     range); empty traces stay all-zero with len 0
//   - quantization is f32: round((x − origin_x) * 4.0f) with
//     round-half-to-even (np.round == rint); 4.0f == 1/OFFSET_QUANTUM
//   - the i16 gate is the FLOAT comparison |q| < 32767 — NaN/inf fail it
//     exactly like numpy's NaN-propagating max, falling back to f32
//   - deltas are int32 diffs of the int32 quanta, zeroed at t >= len;
//     the i8 gate is |d| < 128 in integers
//   - Morton keys floor(first/64)+0x8000, 16-bit masked, bit-spread —
//     the same curve as ops/dense_candidates._morton; non-finite firsts
//     cast like numpy's cvttsd2si (INT64_MIN)
//
// reporter_build_reports / reporter_tail_cuts are the report-build half:
// the group-id chaining of streaming/columnar.build_report_columns and
// the tail-retention cut of ColumnarTraceCache.retain, one pass each.
//
// Build: via reporter_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// One slice row: pad, quantize, delta-pack. Returns (q_ok, d_ok) for the
// caller's global mode reduction. Writes are row-disjoint (thread-safe).
void prepare_row(const float* xy, int64_t n, int64_t b, float* pts,
                 int32_t* len_out, float* origins, int16_t* dq16,
                 int8_t* d8, bool* q_ok_out, bool* d_ok_out) {
  // pad fill: [:n] = xy, [n:] = xy[0] (origin pad); empty row stays 0
  if (n > 0) {
    std::memcpy(pts, xy, size_t(n) * 2 * sizeof(float));
    for (int64_t t = n; t < b; ++t) {
      pts[t * 2] = xy[0];
      pts[t * 2 + 1] = xy[1];
    }
    *len_out = static_cast<int32_t>(n);
  } else {
    std::memset(pts, 0, size_t(b) * 2 * sizeof(float));
    *len_out = 0;
  }
  const float ox = pts[0], oy = pts[1];
  origins[0] = ox;
  origins[1] = oy;
  bool q_ok = true, d_ok = true;
  int32_t px = 0, py = 0;
  for (int64_t t = 0; t < b; ++t) {
    // f32 arithmetic + rint (ties-to-even) == np.round of the f32 array
    float qx = std::nearbyintf((pts[t * 2] - ox) * 4.0f);
    float qy = std::nearbyintf((pts[t * 2 + 1] - oy) * 4.0f);
    // negated comparison so NaN/inf fail the gate exactly like numpy's
    // NaN-propagating max() < 32767
    if (!(std::fabs(qx) < 32767.0f && std::fabs(qy) < 32767.0f)) {
      q_ok = false;
      break;
    }
    int32_t qxi = static_cast<int32_t>(qx), qyi = static_cast<int32_t>(qy);
    dq16[t * 2] = static_cast<int16_t>(qxi);
    dq16[t * 2 + 1] = static_cast<int16_t>(qyi);
    int32_t dx = qxi - px, dy = qyi - py;
    if (t >= n) dx = dy = 0;  // pad-region deltas are zeroed (api parity)
    if (!(std::abs(dx) < 128 && std::abs(dy) < 128)) d_ok = false;
    d8[t * 2] = static_cast<int8_t>(dx);
    d8[t * 2 + 1] = static_cast<int8_t>(dy);
    px = qxi;
    py = qyi;
  }
  *q_ok_out = q_ok;
  *d_ok_out = q_ok && d_ok;
}

// ops/dense_candidates._morton: interleave 16-bit coords, 64-bit lanes.
uint64_t spread16(uint64_t v) {
  v = (v | (v << 16)) & 0x0000FFFF0000FFFFULL;
  v = (v | (v << 8)) & 0x00FF00FF00FF00FFULL;
  v = (v | (v << 4)) & 0x0F0F0F0F0F0F0F0FULL;
  v = (v | (v << 2)) & 0x3333333333333333ULL;
  v = (v | (v << 1)) & 0x5555555555555555ULL;
  return v;
}

// numpy f64→i64 cast semantics (cvttsd2si): out-of-range / NaN / ±inf
// all collapse to INT64_MIN — keep the native keys bit-equal to the
// numpy path even on poison coordinates.
int64_t cast_i64(double v) {
  if (!(v >= -9.223372036854775e18 && v <= 9.223372036854775e18))
    return INT64_MIN;
  return static_cast<int64_t>(v);
}

}  // namespace

extern "C" {

// Pack one submit slice from a flat [n_pts, 2] f32 buffer. offs[B+1]
// bounds each row's points (offs[r+1]-offs[r] <= b; caller enforces the
// bucket). Fills pts [B,b,2] f32, lens [B] i32, origins [B,2] f32,
// dq16 [B,b,2] i16, d8 [B,b,2] i8.
//
// Returns the wire mode: 2 = i8 deltas (the preferred infeed), 1 = i16
// absolutes (some step overflowed ±127 quanta), 0 = f32 points (some
// trace spans past the i16 range, or poison NaN/inf coordinates). Rows
// are processed in parallel; dq16/d8 contents are only meaningful for
// the returned mode (matching what the numpy path materializes).
int32_t reporter_prepare_slice(const float* xy, const int64_t* offs,
                               int64_t B, int64_t b, int32_t n_threads,
                               float* pts, int32_t* lens, float* origins,
                               int16_t* dq16, int8_t* d8) {
  if (B <= 0) return 2;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > B) n_threads = static_cast<int32_t>(B);
  std::vector<uint8_t> q_ok(B), d_ok(B);
  int64_t per = (B + n_threads - 1) / n_threads;

  auto run = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      bool qo = false, dok = false;
      prepare_row(xy + offs[r] * 2, offs[r + 1] - offs[r], b,
                  pts + r * b * 2, lens + r, origins + r * 2,
                  dq16 + r * b * 2, d8 + r * b * 2, &qo, &dok);
      q_ok[r] = qo ? 1 : 0;
      d_ok[r] = dok ? 1 : 0;
    }
  };
  if (n_threads == 1) {
    run(0, B);
  } else {
    std::vector<std::thread> workers;
    for (int32_t w = 0; w < n_threads; ++w) {
      int64_t lo = w * per, hi = std::min(B, lo + per);
      if (lo < hi) workers.emplace_back(run, lo, hi);
    }
    for (auto& th : workers) th.join();
  }
  int32_t mode = 2;
  for (int64_t r = 0; r < B; ++r) {
    if (!q_ok[r]) return 0;
    if (!d_ok[r]) mode = 1;
  }
  return mode;
}

// Morton keys of per-work-item first points (f64 [W,2], biased +0x8000
// at 64 m resolution) — matcher/api._morton_keys without the numpy
// passes. Keys land in the low 32 bits of u64 lanes.
void reporter_morton_keys(const double* first, int64_t W, uint64_t* keys) {
  for (int64_t w = 0; w < W; ++w) {
    uint64_t qx = static_cast<uint64_t>(
                      cast_i64(std::floor(first[w * 2] / 64.0)) + 0x8000) &
                  0xFFFF;
    uint64_t qy = static_cast<uint64_t>(
                      cast_i64(std::floor(first[w * 2 + 1] / 64.0)) + 0x8000) &
                  0xFFFF;
    keys[w] = spread16(qx) | (spread16(qy) << 1);
  }
}

// streaming/columnar.build_report_columns as ONE pass: a chain boundary
// between consecutive records survives iff same trace, time-adjacent
// (|t0[r] − t1[r-1]| < 1e-3), and both records carry (reportable, or a
// complete internal connector). Reportable records within one group
// chain through next_segment_id. Returns the reportable count R;
// out arrays must hold n rows. per_trace (len n_traces) is bincounted
// when n_traces >= 0 (pass -1 to skip — the flush hot path does).
int64_t reporter_build_reports(const int32_t* trace, const int64_t* seg,
                               const double* t0, const double* t1,
                               const double* len, const double* queue,
                               const uint8_t* internal, int64_t n,
                               double min_length, int64_t n_traces,
                               int64_t* out_seg, int64_t* out_nxt,
                               double* out_t0, double* out_t1,
                               double* out_len, double* out_queue,
                               int64_t* per_trace) {
  if (n_traces >= 0)
    std::memset(per_trace, 0, size_t(n_traces) * sizeof(int64_t));
  int64_t R = 0;
  int64_t group = 0, last_rep = -1, last_rep_group = -1;
  bool prev_carry = false;
  for (int64_t r = 0; r < n; ++r) {
    // NaN t0/t1 fail the >= 0 gates exactly like the numpy comparisons
    bool complete = (t0[r] >= 0.0) && (t1[r] >= 0.0);
    bool reportable = complete && !internal[r] && (len[r] >= min_length);
    bool carry = reportable || (internal[r] && complete);
    if (r > 0) {
      bool link = (trace[r] == trace[r - 1]) &&
                  (std::fabs(t0[r] - t1[r - 1]) < 1e-3) && carry &&
                  prev_carry;
      if (!link) ++group;
    }
    if (reportable) {
      if (last_rep >= 0 && last_rep_group == group)
        out_nxt[last_rep] = seg[r];
      out_seg[R] = seg[r];
      out_nxt[R] = -1;
      out_t0[R] = t0[r];
      out_t1[R] = t1[r];
      out_len[R] = len[r];
      out_queue[R] = queue[r];
      if (n_traces >= 0) ++per_trace[trace[r]];
      last_rep = R;
      last_rep_group = group;
      ++R;
    }
    prev_carry = carry;
  }
  return R;
}

// ColumnarTraceCache.retain's cut, batched over a wave's merged traces:
// per vehicle v with times time_flat[bounds[v]:bounds[v+1]] (sorted
// ascending), emit lo = max(max(0, first_at_or_after(from_time) − 1),
// n − max_points); lo >= n means "retain nothing" (caller drops the
// entry). One call replaces a per-vehicle numpy nonzero+max chain.
void reporter_tail_cuts(const double* time_flat, const int64_t* bounds,
                        int64_t V, const double* from_time,
                        int64_t max_points, int64_t* lo_out) {
  for (int64_t v = 0; v < V; ++v) {
    const double* ts = time_flat + bounds[v];
    int64_t n = bounds[v + 1] - bounds[v];
    const double* at = std::lower_bound(ts, ts + n, from_time[v]);
    int64_t cut;
    if (at == ts + n)
      cut = std::max<int64_t>(0, n - 1);
    else
      cut = std::max<int64_t>(0, (at - ts) - 1);
    lo_out[v] = std::max(cut, n - max_points);
  }
}

}  // extern "C"
