// Native tile-compiler kernels: bounded Dijkstra reach tables + spatial grid.
//
// Plays the role of Valhalla's C++ offline pipeline (SURVEY.md §2.2 "Tile
// build pipeline", §3.4): the per-node bounded Dijkstra that builds the
// reach tables is the dominant cost of tile compilation for real metros, so
// it runs here as multithreaded C++ instead of Python. Bit-for-bit parity
// with the Python reference (reporter_tpu/tiles/reach.py) is part of the
// contract and is what tests/test_native.py asserts:
//   - distances accumulate in double, stored as float (same as numpy path)
//   - the heap pops (dist, node) in tuple order, matching Python's heapq
//   - targets sort by (dist, edge id) for nearest-M truncation, matching
//     np.lexsort((tos, dists)); the KEPT entries then re-sort ascending by
//     target id (schema-4 layout, binary-searched by walker.cc)
//
// Build: g++ -O3 -shared -fPIC -o _libreporter.so reach.cc -lpthread
// (driven by reporter_tpu/native/build.py; no external deps).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <limits>
#include <queue>
#include <thread>
#include <vector>

namespace {

struct Target {
  double dist;
  int32_t to;
  int32_t next;
};

// Single-source bounded Dijkstra from node u; appends one Target per
// out-edge of every reached node (u itself included at dist 0).
void node_targets(int32_t u,
                  const int32_t* node_out, int64_t /*num_nodes*/, int64_t deg,
                  const int32_t* edge_dst, const float* edge_len,
                  double radius,
                  // scratch, epoch-stamped so no per-call clearing:
                  std::vector<double>& dist, std::vector<int32_t>& first,
                  std::vector<int32_t>& stamp, int32_t epoch,
                  std::vector<Target>& out) {
  using QItem = std::pair<double, int32_t>;  // (dist, node) — heapq order
  std::priority_queue<QItem, std::vector<QItem>, std::greater<QItem>> pq;

  auto get_dist = [&](int32_t v) {
    return stamp[v] == epoch ? dist[v]
                             : std::numeric_limits<double>::infinity();
  };

  dist[u] = 0.0;
  first[u] = -1;
  stamp[u] = epoch;
  pq.push({0.0, u});
  std::vector<int32_t> reached;
  std::vector<char> done(0);
  reached.push_back(u);

  while (!pq.empty()) {
    auto [d, v] = pq.top();
    pq.pop();
    if (d > get_dist(v)) continue;  // stale entry
    const int32_t* row = node_out + int64_t(v) * deg;
    for (int64_t i = 0; i < deg; ++i) {
      int32_t e = row[i];
      if (e < 0) break;
      int32_t w = edge_dst[e];
      double nd = d + double(edge_len[e]);
      if (nd <= radius && nd < get_dist(w)) {
        if (stamp[w] != epoch) {
          stamp[w] = epoch;
          reached.push_back(w);
        }
        dist[w] = nd;
        first[w] = (v == u) ? e : first[v];
        pq.push({nd, w});
      }
    }
  }

  for (int32_t v : reached) {
    const int32_t* row = node_out + int64_t(v) * deg;
    for (int64_t i = 0; i < deg; ++i) {
      int32_t e2 = row[i];
      if (e2 < 0) break;
      out.push_back({dist[v], e2, (v == u) ? e2 : first[v]});
    }
  }
}

}  // namespace

extern "C" {

// Outputs: reach_to/reach_next i32 [N, max_targets] (-1 pad),
// reach_dist f32 [N, max_targets] (+inf pad) — NODE-keyed (the row for
// edge e is row edge_dst[e]; see tiles/reach.py). Outputs must arrive
// pre-filled with the pad values. Returns the number of nodes whose
// target list was truncated (parity with the Python builder).
int64_t reporter_build_reach(const int32_t* node_out, int64_t num_nodes,
                             int64_t deg, const int32_t* edge_dst,
                             const float* edge_len,
                             double radius, int32_t max_targets,
                             int32_t n_threads, int32_t* reach_to,
                             float* reach_dist, int32_t* reach_next) {
  std::atomic<int64_t> truncated{0};
  std::atomic<int64_t> next_node{0};
  if (n_threads <= 0) {
    n_threads = int32_t(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }

  auto worker = [&]() {
    std::vector<double> dist(num_nodes);
    std::vector<int32_t> first(num_nodes);
    std::vector<int32_t> stamp(num_nodes, -1);
    std::vector<Target> targets;
    int32_t epoch = 0;
    for (;;) {
      int64_t u = next_node.fetch_add(1);
      if (u >= num_nodes) break;
      targets.clear();
      node_targets(int32_t(u), node_out, num_nodes, deg, edge_dst, edge_len,
                   radius, dist, first, stamp, epoch++, targets);
      std::sort(targets.begin(), targets.end(),
                [](const Target& a, const Target& b) {
                  if (a.dist != b.dist) return a.dist < b.dist;
                  return a.to < b.to;
                });
      if (int64_t(targets.size()) > max_targets) {
        truncated.fetch_add(1);
        targets.resize(max_targets);
      }
      // Schema-4 row layout: kept entries ascend by target edge id so the
      // walker can binary-search (matches _pack_rows in tiles/reach.py).
      std::sort(targets.begin(), targets.end(),
                [](const Target& a, const Target& b) { return a.to < b.to; });
      int32_t* rt = reach_to + u * max_targets;
      float* rd = reach_dist + u * max_targets;
      int32_t* rn = reach_next + u * max_targets;
      for (size_t k = 0; k < targets.size(); ++k) {
        rt[k] = targets[k].to;
        rd[k] = float(targets[k].dist);
        rn[k] = targets[k].next;
      }
    }
  };

  std::vector<std::thread> pool;
  for (int32_t t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return truncated.load();
}

// Spatial grid fill (parity with tiles/compiler._build_grid): register each
// line segment in every cell its bbox overlaps. grid is i32 [gw*gh, cap]
// pre-filled with -1. Returns the number of dropped registrations.
int64_t reporter_build_grid(const float* ax, const float* ay, const float* bx,
                            const float* by, int64_t num_segs, double lox,
                            double loy, double cell, int32_t gw, int32_t gh,
                            int32_t cap, int32_t* grid, int32_t* counts) {
  int64_t overflow = 0;
  for (int64_t s = 0; s < num_segs; ++s) {
    double sx0 = std::min(ax[s], bx[s]), sx1 = std::max(ax[s], bx[s]);
    double sy0 = std::min(ay[s], by[s]), sy1 = std::max(ay[s], by[s]);
    int64_t cx0 = std::clamp(int64_t(std::floor((sx0 - lox) / cell)),
                             int64_t(0), int64_t(gw - 1));
    int64_t cx1 = std::clamp(int64_t(std::floor((sx1 - lox) / cell)),
                             int64_t(0), int64_t(gw - 1));
    int64_t cy0 = std::clamp(int64_t(std::floor((sy0 - loy) / cell)),
                             int64_t(0), int64_t(gh - 1));
    int64_t cy1 = std::clamp(int64_t(std::floor((sy1 - loy) / cell)),
                             int64_t(0), int64_t(gh - 1));
    for (int64_t cx = cx0; cx <= cx1; ++cx) {
      for (int64_t cy = cy0; cy <= cy1; ++cy) {
        int64_t c = cx * gh + cy;
        if (counts[c] < cap) {
          grid[c * cap + counts[c]] = int32_t(s);
          counts[c] += 1;
        } else {
          ++overflow;
        }
      }
    }
  }
  return overflow;
}

}  // extern "C"
