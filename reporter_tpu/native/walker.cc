// Native segment walker: decoded match output → OSMLR segment records.
//
// Plays the role of the C++ edge walk + OSMLR association inside the
// reference's segment_matcher (SURVEY.md §3.1 "edge walk + OSMLR
// association lookup", §2.2 row 1): the per-trace Python walk in
// matcher/segments.py costs ~1.6 ms/trace, which caps the e2e pipeline two
// orders of magnitude below the device matcher. This is the same walk over
// the same flat arrays, multithreaded across traces.
//
// Exact-parity contract with matcher/segments.py (tests/test_native.py):
//   - accumulation in double; edge lengths are float32 widened per element
//   - route expansion via reach_to/reach_dist/reach_next with the same
//     first-hit / monotone-gap / next<0 bail-outs
//   - _time_at: searchsorted-left with index clamped to [1, len-1]
//   - record emission thresholds (kMinSpan, 1.0 m origin/tail tolerance)
//
// Build: via reporter_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

namespace {

// matcher/segments.MIN_RECORD_SPAN: spans below one wire offset quantum
// are float noise; both walkers must agree on the emission threshold.
constexpr double kMinSpan = 0.25;

// matcher/segments.QUEUE_SPEED / QUEUE_WINDOW: movement slower than
// kQueueSpeed averaged over a kQueueWindow trailing span counts as queued
// traffic (dwell-at-the-stop-line model; the window absorbs the decoder's
// plateau-then-pulse shape for creeping points).
constexpr double kQueueSpeed = 2.0;
constexpr double kQueueWindow = 10.0;

struct Record {
  int64_t seg_id;
  double t0, t1, length, queue;
  bool internal;
  std::vector<int64_t> way_ids;
};

// matcher/segments._queue_length: queue backed up from the segment tail.
// Walk points backward from the tail anchor; a point extends the queue when
// the average speed over the kQueueWindow span after it (capped at the
// anchor) is below kQueueSpeed (dd < kQueueSpeed*dt — divisionless, so
// dt<=0 spans are never slow). Clamped to [0, seg_len].
double queue_length(const std::vector<double>& pd,
                    const std::vector<double>& pt, double d_tail,
                    double seg_len) {
  // Anchor at the LAST point at/before the tail (segments.py parity);
  // point distances are monotone, so binary-search the anchor.
  size_t i = std::upper_bound(pd.begin(), pd.end(), d_tail + 1e-6) -
             pd.begin();
  i = (i == 0) ? 0 : i - 1;
  double q_start = d_tail;
  size_t j = i, k = i;  // j: min index with time >= cand time + window
  while (k >= 1) {
    size_t cand = k - 1;
    while (j > cand + 1 && pt[j - 1] - pt[cand] >= kQueueWindow) --j;
    double dd = pd[j] - pd[cand];
    double dt = pt[j] - pt[cand];
    if (!(dd < kQueueSpeed * dt)) break;
    q_start = pd[cand];
    k = cand;
  }
  return std::min(std::max(d_tail - q_start, 0.0), seg_len);
}

struct Tile {
  const float* edge_len;
  const int64_t* edge_way;
  const int32_t* edge_osmlr;
  const float* edge_osmlr_off;
  const int64_t* osmlr_id;
  const float* osmlr_len;
  const int32_t* reach_row;   // edge → governing reach row (node row, or a
                              // private ban-aware row for restricted edges)
  const int32_t* reach_to;
  const float* reach_dist;
  const int32_t* reach_next;
  int32_t reach_m;
};

// reach_route_fn: intermediate edges strictly between e1 and e2, or nullopt
// (signalled by returning false) when unreachable within the reach tables.
bool route_between(const Tile& t, int32_t e1, int32_t e2,
                   std::vector<int32_t>& mid) {
  mid.clear();
  if (e1 == e2) return true;
  int32_t e = e1;
  double gap = std::numeric_limits<double>::infinity();
  while (true) {
    int64_t u = t.reach_row[e];
    const int32_t* row = t.reach_to + u * t.reach_m;
    // Rows are laid out ascending by target id with -1 padding at the end
    // (schema 4, tiles/reach._pack_rows) — binary search with -1 mapped
    // past every real id, instead of an O(M) scan per hop.
    auto key = [](int32_t v) {
      return v < 0 ? std::numeric_limits<int64_t>::max() : int64_t(v);
    };
    const int32_t* lo = std::lower_bound(
        row, row + t.reach_m, e2,
        [&](int32_t a, int32_t b) { return key(a) < key(b); });
    if (lo == row + t.reach_m || *lo != e2) return false;
    int32_t hit = int32_t(lo - row);
    double new_gap = t.reach_dist[u * t.reach_m + hit];
    if (new_gap >= gap) return false;  // no progress ⇒ inconsistent tables
    gap = new_gap;
    int32_t nxt = t.reach_next[u * t.reach_m + hit];
    if (nxt == e2) return true;
    if (nxt < 0) return false;
    mid.push_back(nxt);
    e = nxt;
  }
}

// matcher/segments._time_at: linear interpolation at path distance d.
double time_at(const std::vector<double>& ds, const std::vector<double>& ts,
               double d) {
  if (ds.empty() || d < ds.front() - 1e-6 || d > ds.back() + 1e-6) return -1.0;
  // np.searchsorted side='left'
  size_t i = std::lower_bound(ds.begin(), ds.end(), d) - ds.begin();
  if (i < 1) i = 1;
  if (i > ds.size() - 1) i = ds.size() - 1;
  double d0 = ds[i - 1], t0 = ts[i - 1];
  double d1 = ds[i], t1 = ts[i];
  if (d1 <= d0 + 1e-9) return t0;
  double w = (d - d0) / (d1 - d0);
  return t0 + w * (t1 - t0);
}

// matcher/segments._path_to_records for one (path, pts) pair.
void path_to_records(const Tile& t, const std::vector<int32_t>& path,
                     const std::vector<double>& pd,   // per-point path dist
                     const std::vector<double>& pt,   // per-point time
                     std::vector<Record>& out) {
  size_t n = path.size();
  std::vector<double> cum(n + 1, 0.0);
  for (size_t i = 0; i < n; ++i)
    cum[i + 1] = cum[i] + static_cast<double>(t.edge_len[path[i]]);
  double observed_lo = pd.front(), observed_hi = pd.back();

  size_t i = 0;
  while (i < n) {
    int32_t row = t.edge_osmlr[path[i]];
    size_t j = i;
    while (j + 1 < n && t.edge_osmlr[path[j + 1]] == row &&
           (row < 0 ||
            std::fabs(static_cast<double>(t.edge_osmlr_off[path[j + 1]]) -
                      (static_cast<double>(t.edge_osmlr_off[path[j]]) +
                       static_cast<double>(t.edge_len[path[j]]))) < 1.0)) {
      ++j;
    }
    double d_lo = cum[i], d_hi = cum[j + 1];
    double c_lo = std::max(d_lo, observed_lo);
    double c_hi = std::min(d_hi, observed_hi);
    if (c_hi > c_lo + kMinSpan) {
      Record r;
      for (size_t e = i; e <= j; ++e) {
        int64_t w = t.edge_way[path[e]];
        if (r.way_ids.empty() || r.way_ids.back() != w) r.way_ids.push_back(w);
      }
      if (row < 0) {
        r.seg_id = -1;
        r.t0 = time_at(pd, pt, c_lo);
        r.t1 = time_at(pd, pt, c_hi);
        r.length = c_hi - c_lo;
        r.queue = 0.0;
        r.internal = true;
      } else {
        double o_start = static_cast<double>(t.edge_osmlr_off[path[i]]);
        double seg_len = static_cast<double>(t.osmlr_len[row]);
        double covered_lo = o_start + (c_lo - d_lo);
        double covered_hi = o_start + (c_hi - d_lo);
        bool starts_at_origin = covered_lo <= 1.0;
        bool ends_at_tail = covered_hi >= seg_len - 1.0;
        r.seg_id = t.osmlr_id[row];
        r.t0 = starts_at_origin ? time_at(pd, pt, c_lo) : -1.0;
        r.t1 = ends_at_tail ? time_at(pd, pt, c_hi) : -1.0;
        r.length = covered_hi - covered_lo;
        // Queue needs the stop line observed (matcher/segments.py parity).
        r.queue = ends_at_tail
                      ? queue_length(pd, pt, d_lo + (seg_len - o_start),
                                     seg_len)
                      : 0.0;
        r.internal = false;
      }
      out.push_back(std::move(r));
    }
    i = j + 1;
  }
}

// matcher/segments._chain_to_path + build_segments for one trace.
void walk_trace(const Tile& tile, const int32_t* edges, const float* offs,
                const uint8_t* starts, const double* times, int64_t T,
                double backward_slack, std::vector<Record>& out) {
  // _to_chains: group matched points into breakage-free chains
  std::vector<int32_t> ce;       // chain edges
  std::vector<double> co, ct;    // chain offsets / times
  std::vector<int32_t> path, mid;
  std::vector<double> cum, pd, pt;

  auto flush_path = [&]() {
    if (!path.empty() && !pd.empty()) path_to_records(tile, path, pd, pt, out);
    path.clear();
    cum.clear();
    pd.clear();
    pt.clear();
  };

  auto run_chain = [&]() {
    if (ce.empty()) return;
    // _chain_to_path
    path.assign(1, ce[0]);
    cum.assign(1, 0.0);
    pd.assign(1, co[0]);
    pt.assign(1, ct[0]);
    for (size_t i = 1; i < ce.size(); ++i) {
      int32_t e_prev = ce[i - 1], e_cur = ce[i];
      double off = co[i], tm = ct[i];
      if (e_cur == e_prev && off >= co[i - 1] - backward_slack) {
        double d = cum.back() + std::max(off, pd.back() - cum.back());
        pd.push_back(d);
        pt.push_back(tm);
        continue;
      }
      if (!route_between(tile, e_prev, e_cur, mid)) {
        flush_path();
        path.assign(1, e_cur);
        cum.assign(1, 0.0);
        pd.assign(1, off);
        pt.assign(1, tm);
        continue;
      }
      mid.push_back(e_cur);
      for (int32_t m : mid) {
        cum.push_back(cum.back() +
                      static_cast<double>(tile.edge_len[path.back()]));
        path.push_back(m);
      }
      pd.push_back(cum.back() + off);
      pt.push_back(tm);
    }
    flush_path();
    ce.clear();
    co.clear();
    ct.clear();
  };

  for (int64_t t = 0; t < T; ++t) {
    if (edges[t] < 0) continue;
    if (starts[t]) run_chain();  // closes the previous chain (no-op if empty)
    ce.push_back(edges[t]);
    co.push_back(static_cast<double>(offs[t]));
    ct.push_back(times[t]);
  }
  run_chain();
}

}  // namespace

extern "C" {

// Returns the total record count (which may exceed rec_cap — caller retries
// with larger buffers; outputs are only written up to the capacities).
// way_off must hold rec_cap + 1 entries; *n_ways_out reports the total
// way-id count (valid only when everything fit).
int64_t reporter_walk_segments(
    const int32_t* edges, const float* offs, const uint8_t* starts,
    const double* times, int64_t B, int64_t T,
    const float* edge_len, const int64_t* edge_way, const int32_t* edge_osmlr,
    const float* edge_osmlr_off,
    const int64_t* osmlr_id, const float* osmlr_len,
    const int32_t* reach_row,
    const int32_t* reach_to, const float* reach_dist,
    const int32_t* reach_next, int32_t reach_m,
    double backward_slack, int32_t n_threads,
    int32_t* rec_trace, int64_t* rec_seg, double* rec_t0, double* rec_t1,
    double* rec_len, double* rec_queue, uint8_t* rec_internal,
    int64_t rec_cap,
    int32_t* way_off, int64_t* way_ids, int64_t way_cap,
    int64_t* n_ways_out) {
  Tile tile{edge_len,  edge_way,  edge_osmlr, edge_osmlr_off, osmlr_id,
            osmlr_len, reach_row, reach_to,   reach_dist,     reach_next,
            reach_m};

  if (n_threads < 1) n_threads = 1;
  if (n_threads > B) n_threads = static_cast<int32_t>(B > 0 ? B : 1);
  std::vector<std::vector<std::vector<Record>>> shards(n_threads);
  std::vector<std::thread> workers;
  int64_t per = (B + n_threads - 1) / n_threads;
  for (int32_t w = 0; w < n_threads; ++w) {
    workers.emplace_back([&, w]() {
      int64_t lo = w * per, hi = std::min(B, lo + per);
      if (lo >= hi) return;
      shards[w].resize(hi - lo);
      for (int64_t b = lo; b < hi; ++b) {
        walk_trace(tile, edges + b * T, offs + b * T, starts + b * T,
                   times + b * T, T, backward_slack, shards[w][b - lo]);
      }
    });
  }
  for (auto& th : workers) th.join();

  int64_t nrec = 0, nway = 0;
  for (int32_t w = 0; w < n_threads; ++w) {
    int64_t lo = w * per;
    for (size_t i = 0; i < shards[w].size(); ++i) {
      for (Record& r : shards[w][i]) {
        if (nrec < rec_cap &&
            nway + static_cast<int64_t>(r.way_ids.size()) <= way_cap) {
          rec_trace[nrec] = static_cast<int32_t>(lo + i);
          rec_seg[nrec] = r.seg_id;
          rec_t0[nrec] = r.t0;
          rec_t1[nrec] = r.t1;
          rec_len[nrec] = r.length;
          rec_queue[nrec] = r.queue;
          rec_internal[nrec] = r.internal ? 1 : 0;
          way_off[nrec] = static_cast<int32_t>(nway);
          std::memcpy(way_ids + nway, r.way_ids.data(),
                      r.way_ids.size() * sizeof(int64_t));
        }
        nway += static_cast<int64_t>(r.way_ids.size());
        ++nrec;
      }
    }
  }
  if (nrec <= rec_cap) way_off[nrec] = static_cast<int32_t>(nway);
  *n_ways_out = nway;
  return nrec;
}

}  // extern "C"
