"""Persistent XLA compilation cache (service restarts / repeated benches).

The matcher's jit programs take tens of seconds to compile for the big
batch shapes; the cache turns warm restarts into sub-second loads. Opt-in
per entry point (bench.py, service.server, __graft_entry__) rather than at
import — a library shouldn't mutate global jax config on import.
"""

from __future__ import annotations

import os


def enable_compilation_cache(path: "str | None" = None) -> str:
    """Point jax at a persistent compilation cache directory.

    Priority: explicit ``path`` → $REPORTER_TPU_XLA_CACHE →
    ~/.cache/reporter_tpu/xla. Set $REPORTER_TPU_XLA_CACHE=off to disable.
    Safe to call before or after the backend initializes.
    """
    import jax

    target = (path or os.environ.get("REPORTER_TPU_XLA_CACHE")
              or os.path.join(os.path.expanduser("~"), ".cache",
                              "reporter_tpu", "xla"))
    if target.lower() == "off":
        return ""
    os.makedirs(target, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", target)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return target
