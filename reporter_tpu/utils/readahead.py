"""Single-thread read-ahead executor for pipelined wave prepare (r22).

The closed-loop serving paths (streaming columnar worker, batch
scheduler) overlap the PURE host prepare for wave N+1 with wave N's
device occupancy by handing the prepare callable to this worker. One
daemon thread per worker instance — prepare is CPU-bound Python on a
one-core host, so more threads would only contend; the win is
overlapping host compute with the device/link wait, not host-host
parallelism.

Discipline (the r14 lockdep rules):

  - the ONE lock is ``locks.named_condition("readahead.tasks")`` — a
    single stable class name; per-instance names would blow up the
    golden lock graph (the per-metro build-lock precedent).
  - submitted callables run strictly OUTSIDE the condition: the lock
    only guards the task deque. A task's own lock acquisitions
    (cache.entries, metrics.registry, ...) therefore start from an
    empty held-set and add no contract edges.
  - tickets resolve via a per-ticket ``threading.Event`` (not a
    condvar wait): ``Event.wait`` is not a patched blocking call, and
    waiters never hold ``readahead.tasks`` while waiting.

``close()`` fails every never-started ticket with ``RuntimeError`` so
a consumer waiting on a ticket after shutdown gets a loud error, never
a hang. Tasks already running complete normally (their ticket resolves
with the real result).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable

from reporter_tpu.utils import locks


class ReadAheadClosed(RuntimeError):
    """Ticket failed because the worker was closed before it ran."""


class Ticket:
    """Handle for one submitted prepare task. ``result()`` blocks until
    the task ran (or the worker closed) and re-raises the task's error
    in the caller's thread — the prepare exception surfaces on the wave
    that would have consumed the prepare, which is exactly where the
    serial loop would have raised it."""

    __slots__ = ("_done", "_result", "_error")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result: Any = None
        self._error: "BaseException | None" = None

    def _resolve(self, result: Any = None,
                 error: "BaseException | None" = None) -> None:
        self._result = result
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: "float | None" = None) -> Any:
        if not self._done.wait(timeout=timeout):
            raise TimeoutError("read-ahead ticket not resolved in time")
        if self._error is not None:
            raise self._error
        return self._result


class ReadAheadWorker:
    """One daemon thread draining a FIFO of prepare callables."""

    def __init__(self, name: str = "readahead") -> None:
        self._cv = locks.named_condition("readahead.tasks")
        self._tasks: "deque[tuple[Ticket, Callable[[], Any]]]" = deque()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, fn: Callable[[], Any]) -> Ticket:
        t = Ticket()
        with self._cv:
            if self._closed:
                raise ReadAheadClosed("read-ahead worker is closed")
            self._tasks.append((t, fn))
            self._cv.notify()
        return t

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._tasks:
                    if self._closed:
                        return
                    self._cv.wait()
                ticket, fn = self._tasks.popleft()
            # run OUTSIDE the condition: the lock guards the deque only
            try:
                ticket._resolve(result=fn())
            except BaseException as exc:  # resolve, never kill the thread
                ticket._resolve(error=exc)

    def close(self, timeout: "float | None" = 5.0) -> None:
        """Stop accepting work, fail queued-but-unstarted tickets, join
        the thread (bounded — a task wedged on a dead link must not
        wedge shutdown; the thread is a daemon). Idempotent."""
        with self._cv:
            if self._closed:
                pending: "list[Ticket]" = []
            else:
                self._closed = True
                pending = [t for t, _ in self._tasks]
                self._tasks.clear()
            self._cv.notify_all()
        for t in pending:
            t._resolve(error=ReadAheadClosed(
                "read-ahead worker closed before task ran"))
        self._thread.join(timeout=timeout)
