"""ONE JSONL event-log spelling (round 24).

Before this round three subsystems each hand-rolled the same append-only
JSONL event log — ``topology_events.jsonl`` (supervisor),
``lease_events.jsonl`` (lease table) and now the round-24 SLO alert
ledger — with three slightly different torn-tail policies.  ``EventLog``
is the single spelling: every append is ONE ``write()`` of complete
lines followed by ``flush()`` (the r9 append-log discipline: a reader
never observes a half-written *prefix* of the log, only possibly a torn
final line after a crash), and reopening an existing log truncates a
torn tail so a restarted appender never extends a half-written line into
a permanently corrupt one.

No fsync: durability-to-the-platter is the snapshot spool's job
(``distributed/aggregate.py``), and an event log that fsynced under its
lock would trip the r14 blocking-under-lock gate.  Crash exposure is one
tail line, which truncation-at-reopen plus the tolerant reader both
handle.

The lock is ONE named class (``eventlog.append``) shared by every
instance — per-path dynamic names would blow up the r14 golden lock
graph (the per-metro build-lock precedent); distinct instances
serializing against each other is harmless at event-log rates.
"""

from __future__ import annotations

import json
import os

from reporter_tpu.utils.locks import named_lock


def _truncate_torn_tail(path: str) -> None:
    """Cut a trailing partial line (crash mid-append) back to the last
    complete one.  Event logs are small — whole-file read keeps this
    obviously correct."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0:
        return
    with open(path, "rb+") as fh:
        fh.seek(-1, os.SEEK_END)
        if fh.read(1) == b"\n":
            return
        fh.seek(0)
        data = fh.read()
        cut = data.rfind(b"\n")
        fh.truncate(cut + 1 if cut >= 0 else 0)


def read_events(path):
    """Tolerant JSONL reader: parse complete lines, stop at the first
    unparsable one (with atomic appends the only malformed line is a
    torn tail written by a process that crashed since the last
    reopen)."""
    out = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    break
    except FileNotFoundError:
        pass
    return out


class EventLog:
    """Append-only JSONL log with torn-tail truncation at reopen."""

    def __init__(self, path: str):
        self.path = path
        self._lock = named_lock("eventlog.append")
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        _truncate_torn_tail(path)

    def append(self, doc: dict) -> None:
        self.extend((doc,))

    def extend(self, docs) -> None:
        lines = "".join(json.dumps(d) + "\n" for d in docs)
        if not lines:
            return
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(lines)
                fh.flush()

    def read(self):
        return read_events(self.path)
