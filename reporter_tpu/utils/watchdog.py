"""Bounded execution of work that can HANG forever.

The axon tunnel's failure mode is an infinite stall inside a host
transfer — no try/except can catch it (CLAUDE.md). The r9 answer, shared
by the matcher's device dispatch and the fleet's promotion ``device_put``
(one copy: a race-window or un-count fix must not land in one path and
silently miss the other): run the body on a fresh daemon thread and
bound the wait. On timeout the stuck thread is ABANDONED (daemon — it
can never block exit); abandoned-and-still-stuck threads are counted so
callers can open a circuit breaker at ``cap`` and degrade immediately
instead of pinning one more thread + payload per retry — a permanently
dead link must cost bounded memory. A body that lands AFTER abandonment
un-counts itself and its result is discarded (the ``gave_up`` check): a
zombie completion must not race the caller's retry.
"""

from __future__ import annotations

import threading
from typing import Callable

from reporter_tpu.utils import locks
from reporter_tpu import faults

TIMED_OUT = object()    # sentinel: the body was abandoned (a body may
#                         legally return None)


class AbandonedThreadWatchdog:
    """Abandoned-thread ledger + the guarded-call primitive.

    ``lock`` guards only the counter and the per-call abandoned/finished
    handshake — callers must NEVER hold their own data locks around
    ``run()`` (the whole point is that the body may stall for minutes).
    """

    def __init__(self, cap: int = 4, thread_name: str = "watchdog"):
        self.lock = locks.named_lock("watchdog.ledger")
        self.abandoned = 0
        self.cap = cap
        self.thread_name = thread_name

    @property
    def tripped(self) -> bool:
        """True while the breaker is open: ``cap`` abandoned bodies are
        already stuck — degrade without spawning another."""
        with self.lock:
            return self.abandoned >= self.cap

    def run(self, fn: Callable, timeout: float, fault_site: str = ""):
        """Run ``fn`` on a daemon thread, waiting at most ``timeout``
        seconds. Returns ``fn``'s result (re-raising its exception) when
        it lands in time; returns the module sentinel ``TIMED_OUT`` when
        the body was abandoned. ``fault_site`` fires inside the guarded
        body, so an injected hang stalls exactly where a dead tunnel
        would."""
        box: dict = {}
        done = threading.Event()
        state = {"abandoned": False, "finished": False}

        def _run():
            try:
                if fault_site:
                    faults.fire(fault_site)     # injected stall lands HERE
                with self.lock:
                    gave_up = state["abandoned"]
                if gave_up:
                    return    # the watchdog gave up while we stalled: a
                    #           zombie body must not race the retry
                box["out"] = fn()
            except BaseException as exc:    # noqa: BLE001 — relayed below
                box["exc"] = exc
            finally:
                with self.lock:
                    state["finished"] = True
                    if state["abandoned"]:      # wedge cleared: un-count
                        self.abandoned -= 1
                done.set()

        threading.Thread(target=_run, daemon=True,
                         name=self.thread_name).start()
        finished = done.wait(timeout)
        if not finished:
            with self.lock:
                if not state["finished"]:       # really stuck: abandon it
                    state["abandoned"] = True
                    self.abandoned += 1
                else:
                    finished = True   # landed in the timeout race window
        if not finished:
            return TIMED_OUT
        if "exc" in box:
            raise box["exc"]
        return box["out"]
