"""Link-health telemetry — the measurement conditions behind every number.

The chip is remote-attached ("axon"): RTT ~130 ms, ~25 MB/s, throughput
swinging ~2x run to run, and the tunnel can die outright (a host
transfer then stalls FOREVER — CLAUDE.md). That mood swing is the single
largest unexplained variance in every capture (VERDICT r5 weak #2), yet
until round 15 nothing recorded what the link was doing while a number
was taken. This module is the recorder:

  LinkHealthSampler   a daemon thread probing RTT + host->device
                      bandwidth at low duty cycle (default one ~0.25 s
                      probe per 60 s, <0.5% — the recorded
                      ``probe_duty_pct`` keeps the claim measured), each
                      probe bounded by the SHARED watchdog primitive
                      (utils/watchdog.AbandonedThreadWatchdog — the
                      matcher-dispatch/fleet-promotion guard, not a
                      fork), classifying the link's mood:

                        healthy    rtt and bandwidth inside thresholds
                        degraded   slow but alive (rtt above
                                   ``degraded_rtt_s`` or bandwidth below
                                   ``degraded_mbps``)
                        dead       a probe timed out / raised, or the
                                   dispatch watchdog reported a timeout
                        cpu        no device link in play (CPU backend)

  window(since)       the contemporaneous summary every journaled bench
                      leg is stamped with: median rtt/bandwidth over the
                      window + the WORST mood seen in it (a leg that
                      straddled a dead spell must say so even if the
                      link recovered before the leg ended).

Mood surfaces everywhere the existing observability lives instead of
growing a parallel system: gauges (``link_rtt_ms`` / ``link_mbps`` /
``link_mood`` -> ``rtpu_link_*`` at /metrics) publish into every
attached MetricsRegistry; a dead-link DETECTION (probe timeout or
transition into "dead") emits a tracer instant + a flight-recorder
post-mortem through utils/tracing — the same ring the dispatch-watchdog
and breaker sites dump into; and the matcher's dispatch watchdog feeds
detections BACK via ``note_dispatch_timeout()`` (its own site already
post-mortems, so the note only records the sample — one event, one
dump).

Thread-safety: ``linkhealth.state`` (a named lock — the lockdep gate
sees it) guards the ring + attached registries; probes run OUTSIDE the
lock always (a stalled transfer must never wedge readers), results are
recorded under it, and the gauge publication inside the section is a
leaf write (contract edge ``linkhealth.state`` -> ``metrics.registry``,
dated in analysis/concurrency_contract.py).

One process-global sampler (``sampler()`` / ``ensure_serving()``), the
tracer()/faults.active() discipline: bench and every ReporterApp in the
process share one probe thread and one mood, not one thread per app.
"""

from __future__ import annotations

import collections
import os
import threading
import time
import weakref
from typing import Callable

from reporter_tpu.utils import locks, tracing
from reporter_tpu.utils.watchdog import TIMED_OUT, AbandonedThreadWatchdog

__all__ = [
    "LinkSample", "LinkHealthSampler", "sampler", "ensure_serving",
    "note_dispatch_timeout", "configure", "MOOD_LEVELS",
]

# mood -> numeric gauge level (rtpu_link_mood); order IS severity — the
# window summary reports the max level seen, so "dead for one probe in a
# ten-minute leg" reads dead, never averaged away
MOOD_LEVELS = {"healthy": 0, "degraded": 1, "dead": 2, "cpu": 3}
_SEVERITY = {"healthy": 0, "cpu": 0, "degraded": 1, "dead": 2}

_ENV_PROBE = "RTPU_LINK_PROBE"
_ENV_PERIOD = "RTPU_LINK_PROBE_PERIOD_S"
_ENV_BYTES = "RTPU_LINK_PROBE_BYTES"
_ENV_DEGRADED_RTT = "RTPU_LINK_DEGRADED_RTT_MS"
_ENV_DEGRADED_MBPS = "RTPU_LINK_DEGRADED_MBPS"
_ENV_DEAD = "RTPU_LINK_DEAD_S"


class LinkSample:
    """One probe (or externally reported) observation."""

    __slots__ = ("t", "rtt_s", "mbps", "mood", "source")

    def __init__(self, t: float, rtt_s: "float | None",
                 mbps: "float | None", mood: str, source: str = "probe"):
        self.t = t
        self.rtt_s = rtt_s
        self.mbps = mbps
        self.mood = mood
        self.source = source

    def to_json(self) -> dict:
        return {"t": round(self.t, 3),
                "rtt_ms": (None if self.rtt_s is None
                           else round(self.rtt_s * 1e3, 2)),
                "mbps": (None if self.mbps is None
                         else round(self.mbps, 2)),
                "mood": self.mood, "source": self.source}


_probe_warmed = False


def _device_probe(nbytes: int) -> "tuple[float | None, float | None]":
    """(rtt_s, mbps) through one tiny dispatch+readback and one
    host->device->host transfer of ``nbytes``. Returns (None, None) on a
    CPU backend — no link in the loop, the caller records mood "cpu".
    May stall forever on a dead tunnel; the sampler bounds it with the
    shared watchdog, never calls it under a lock. The tiny executable is
    warmed ONCE per process — re-warming every probe doubled the paid
    RTTs and pushed steady-state duty past the 0.5% budget."""
    global _probe_warmed
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.devices()[0].platform == "cpu":
        return None, None
    tiny = jnp.zeros(8, jnp.float32)
    if not _probe_warmed:
        np.asarray(tiny + 1)                 # compile, once per process
        _probe_warmed = True
    t0 = time.perf_counter()
    np.asarray(tiny + 1)
    rtt = time.perf_counter() - t0
    buf = np.zeros(max(int(nbytes), 1024), np.uint8)
    t0 = time.perf_counter()
    dev = jax.device_put(buf)
    np.asarray(dev)                          # the only real sync
    dt = max(time.perf_counter() - t0 - rtt, 1e-6)   # one RTT rides along
    return rtt, 2 * buf.nbytes / dt / 1e6    # bytes moved both ways


class LinkHealthSampler:
    """Bounded ring of link observations + the probe thread."""

    def __init__(self,
                 probe: "Callable[[int], tuple] | None" = None,
                 period_s: "float | None" = None,
                 probe_bytes: "int | None" = None,
                 ring: int = 512,
                 degraded_rtt_s: "float | None" = None,
                 degraded_mbps: "float | None" = None,
                 dead_timeout_s: "float | None" = None,
                 clock: Callable[[], float] = time.monotonic):
        e = os.environ
        self.probe = probe if probe is not None else _device_probe
        # 90 s default: ~2 RTTs + one small transfer per probe is
        # ~0.3 s on the documented ~130 ms link — ~0.33% steady-state
        # duty, inside the <0.5% budget with margin (the measured duty
        # is recorded either way; bench tightens to 30 s for finer
        # per-leg windows and pays the duty knowingly)
        self.period_s = float(period_s if period_s is not None
                              else e.get(_ENV_PERIOD, "90"))
        self.probe_bytes = int(probe_bytes if probe_bytes is not None
                               else e.get(_ENV_BYTES, str(256 * 1024)))
        self.degraded_rtt_s = float(
            degraded_rtt_s if degraded_rtt_s is not None
            else float(e.get(_ENV_DEGRADED_RTT, "400")) / 1e3)
        self.degraded_mbps = float(degraded_mbps if degraded_mbps is not None
                                   else e.get(_ENV_DEGRADED_MBPS, "5"))
        self.dead_timeout_s = float(dead_timeout_s
                                    if dead_timeout_s is not None
                                    else e.get(_ENV_DEAD, "10"))
        self.clock = clock
        self._lock = locks.named_lock("linkhealth.state")
        self._ring: "collections.deque[LinkSample]" = collections.deque(
            maxlen=int(ring))
        self._registries: "list[weakref.ref]" = []
        self._watchdog = AbandonedThreadWatchdog(
            cap=4, thread_name="linkhealth-probe")
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.probe_seconds_total = 0.0
        self.probes_total = 0
        self.dead_probes_total = 0
        self._started_at: "float | None" = None

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "LinkHealthSampler":
        """Idempotent; the thread probes once immediately, then every
        ``period_s`` (jittered by nothing — the probes themselves are the
        low-duty load)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            self._started_at = self.clock()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="linkhealth-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:
                # a probe bug must never kill the sampler thread; the
                # classifier already maps probe exceptions to "dead", so
                # anything landing here is a recorder bug — skip the tick
                pass
            self._stop.wait(self.period_s)

    # ---- probing ---------------------------------------------------------

    def sample_once(self) -> LinkSample:
        """One bounded probe -> classify -> record. NEVER called under
        the state lock: the whole point is that the probe may stall."""
        if self._watchdog.tripped:
            # breaker open: cap probes are already wedged on the dead
            # link — record the dead observation WITHOUT pinning one
            # more thread + buffer per period (the matcher-dispatch
            # discipline, api.py's breaker; a weekend-long dead tunnel
            # must cost bounded memory). A wedged probe that finally
            # lands un-counts itself and probing resumes.
            sample = LinkSample(self.clock(), None, None, "dead",
                                source="probe_breaker_open")
            self._record(sample)
            return sample
        t0 = time.perf_counter()
        try:
            out = self._watchdog.run(lambda: self.probe(self.probe_bytes),
                                     timeout=self.dead_timeout_s)
        except Exception as exc:
            # a probe that RAISES (tunnel torn down mid-transfer) is a
            # dead observation, not a sampler crash
            out = TIMED_OUT
            src = f"probe_error:{type(exc).__name__}"
        else:
            src = "probe_timeout"
        dt = time.perf_counter() - t0
        if out is TIMED_OUT:
            sample = LinkSample(self.clock(), None, None, "dead",
                                source=src)
        else:
            try:
                rtt_s, mbps = out
            except Exception:
                rtt_s = mbps = None
            sample = LinkSample(self.clock(), rtt_s, mbps,
                                self._classify(rtt_s, mbps))
        self._record(sample, probe_seconds=dt)
        return sample

    def _classify(self, rtt_s: "float | None",
                  mbps: "float | None") -> str:
        if rtt_s is None and mbps is None:
            return "cpu"
        if rtt_s is not None and rtt_s > self.degraded_rtt_s:
            return "degraded"
        if mbps is not None and mbps < self.degraded_mbps:
            return "degraded"
        return "healthy"

    def note_dispatch_timeout(self, reason: str = "dispatch_timeout",
                              **args) -> None:
        """External dead-link signal — the matcher's dispatch watchdog
        (and the fleet's promotion watchdog) observed a stalled transfer
        the probe thread may be minutes from noticing. The reporting
        site already post-mortems (dispatch_timeout / breaker_open /
        fleet_promote), so this only records the sample + gauges: one
        event, one flight-recorder dump."""
        self._record(LinkSample(self.clock(), None, None, "dead",
                                source=reason), post_mortem=False)

    def _record(self, sample: LinkSample, probe_seconds: float = 0.0,
                post_mortem: bool = True) -> None:
        with self._lock:
            prev = self._ring[-1].mood if self._ring else None
            self._ring.append(sample)
            self.probes_total += 1
            self.probe_seconds_total += probe_seconds
            if sample.mood == "dead":
                self.dead_probes_total += 1
            self._publish_locked(sample)
        if sample.mood == "dead" and post_mortem:
            # detection (not every dead sample while the link stays
            # dead): a flapping tunnel must not spam the bounded dump
            # budget the fault sites share
            tr = tracing.tracer()
            tr.instant("link_dead", source=sample.source)
            if prev != "dead":
                tr.post_mortem("link_dead", failing="link_probe",
                               source=sample.source)

    # ---- gauges ----------------------------------------------------------

    def attach(self, registry) -> None:
        """Publish ``link_*`` gauges into ``registry`` on every sample
        from now on (weakly held — a closed app's registry just ages
        out). The latest sample, if any, is published immediately so
        /metrics carries the series as soon as serving starts."""
        with self._lock:
            if not any(r() is registry for r in self._registries):
                self._registries.append(weakref.ref(registry))
            last = self._ring[-1] if self._ring else None
            if last is not None:
                self._publish_locked(last)

    def _publish_locked(self, sample: LinkSample) -> None:
        # caller holds self._lock; registry writes are leaf O(1) dict
        # ops (contract edge linkhealth.state -> metrics.registry)
        alive = []
        for ref in self._registries:
            reg = ref()
            if reg is None:
                continue
            alive.append(ref)
            if sample.rtt_s is not None:
                reg.gauge("link_rtt_ms", sample.rtt_s * 1e3)
            if sample.mbps is not None:
                reg.gauge("link_mbps", sample.mbps)
            reg.gauge("link_mood", MOOD_LEVELS[sample.mood])
            reg.gauge("link_dead_probes", self.dead_probes_total)
            reg.gauge("link_probes", self.probes_total)
        self._registries[:] = alive

    # ---- read side -------------------------------------------------------

    def latest(self) -> "LinkSample | None":
        with self._lock:
            return self._ring[-1] if self._ring else None

    def samples(self) -> "list[LinkSample]":
        with self._lock:
            return list(self._ring)

    def probe_duty_pct(self) -> "float | None":
        """Measured probe duty over the sampler's lifetime — the
        recorded form of the <0.5% steady-state claim."""
        with self._lock:
            if self._started_at is None:
                return None
            up = max(self.clock() - self._started_at, 1e-6)
            return round(100.0 * self.probe_seconds_total / up, 4)

    def window(self, since: "float | None" = None) -> dict:
        """The contemporaneous link window [since, now] every journaled
        bench leg is stamped with: median rtt/bandwidth + WORST mood in
        the window (dead > degraded > healthy/cpu; a leg that straddled
        a dead spell says so). Falls back to the latest sample when the
        window itself is empty (long leg gaps between low-duty probes),
        and to mood None when nothing was ever sampled."""
        with self._lock:
            xs = [s for s in self._ring
                  if since is None or s.t >= since]
            if not xs and self._ring:
                xs = [self._ring[-1]]
        if not xs:
            return {"rtt_ms": None, "mbps": None, "mood": None,
                    "samples": 0}
        rtts = sorted(s.rtt_s for s in xs if s.rtt_s is not None)
        bws = sorted(s.mbps for s in xs if s.mbps is not None)
        mood = max(xs, key=lambda s: _SEVERITY[s.mood]).mood
        return {
            "rtt_ms": (None if not rtts
                       else round(rtts[len(rtts) // 2] * 1e3, 2)),
            "mbps": (None if not bws
                     else round(bws[len(bws) // 2], 2)),
            "mood": mood,
            "samples": len(xs),
        }


# ---------------------------------------------------------------------------
# Process-global sampler (the tracer()/faults.active() discipline): bench
# and every ReporterApp share ONE probe thread + one recorded mood.

_global: "LinkHealthSampler | None" = None
_global_lock = locks.named_lock("linkhealth.registry")


def sampler() -> LinkHealthSampler:
    """THE process sampler (constructed lazily, env-configured, NOT
    started — ``ensure_serving``/bench start it)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = LinkHealthSampler()
        return _global


def configure(s: "LinkHealthSampler | None") -> None:
    """Swap the process sampler (tests install a fake-probe instance;
    pass None to reset to lazy construction)."""
    global _global
    with _global_lock:
        _global = s


def enabled() -> bool:
    """``RTPU_LINK_PROBE`` gate, default ON (strict parse: a typo'd
    lever must raise, not silently probe — the config.py discipline)."""
    raw = os.environ.get(_ENV_PROBE)
    if raw is None or not raw.strip():
        return True
    return tracing.env_flag(raw, strict=True)


def ensure_serving(registry) -> "LinkHealthSampler | None":
    """Serving-face hook (ReporterApp construction): attach the app's
    registry to the process sampler and start the probe thread if the
    env gate allows. Returns the sampler (None when disabled) —
    /metrics then carries ``rtpu_link_*`` for the app's lifetime."""
    if not enabled():
        return None
    s = sampler()
    s.attach(registry)
    s.start()
    return s


def note_dispatch_timeout(reason: str = "dispatch_timeout",
                          **args) -> None:
    """Module-level dead-link signal for sites that don't hold a sampler
    (matcher dispatch watchdog). No-op when no sampler was ever
    constructed — arming telemetry must never be a prerequisite for
    dispatching."""
    with _global_lock:
        s = _global
    if s is not None:
        s.note_dispatch_timeout(reason, **args)
