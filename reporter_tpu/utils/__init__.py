"""Cross-cutting utilities (metrics/observability)."""

from reporter_tpu.utils.metrics import MetricsRegistry, StageTimer

__all__ = ["MetricsRegistry", "StageTimer"]
