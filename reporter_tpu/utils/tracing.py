"""Span tracing + flight recorder — end-to-end latency attribution.

The reference has stdout logging only (SURVEY.md §5) and this repo's
gap-fill so far (utils/metrics.py counters/reservoirs, the XPlane hook
in utils/profiling.py) can say *how slow* but not *where the time went*:
the round-5 verdict's open question — p50 probe→report spanning 2.5-20 s
depending on wave size, offer, and link mood — was answered by prose.
This module makes the decomposition a recorded artifact:

  Span            one named host-side interval (wave/batch-tagged)
  FlightRecorder  a bounded ring of recent spans, thread-safe, cheap,
                  OFF by default (a disabled recorder costs one
                  attribute read per call site), that can dump a
                  Chrome-trace-event JSON (perfetto /
                  ``chrome://tracing``-loadable) on demand — and does so
                  AUTOMATICALLY at the round-9 fault sites (dispatch
                  watchdog timeout, circuit-breaker open, dead-letter
                  spool, admission shed) — joined by the r15 link_dead
                  detection and the r18 quality_drift sentinel
                  (quality/monitor.py), which dump through the same
                  bounded post_mortem path — so every one of those
                  events leaves a post-mortem naming the failing span
                  instead of firing blind.

One PROCESS-GLOBAL recorder (``tracer()``), mirroring faults.py: the
fault sites live in the matcher/publisher/scheduler and must reach the
same ring the pipeline writes its wave spans into. ``configure()``
mutates the singleton in place, so references cached at import stay
valid. Enablement layers exactly like the fault plan's:

  - env: ``RTPU_TRACE=1`` (+ ``RTPU_TRACE_DIR=/dir`` for post-mortem
    dumps, ``RTPU_TRACE_RING=N`` for ring capacity) — a worker
    SUBPROCESS inherits its parent's tracing, like RTPU_FAULTS;
  - config: ``ServiceConfig(trace=True, trace_dir=..., trace_ring=...)``
    applied at ReporterApp / ColumnarStreamPipeline construction;
  - programmatic: ``tracing.configure(enabled=True, dump_dir=...)``
    (bench legs, tests).

Span timestamps are ``time.monotonic`` seconds (the streaming
pipeline's default clock, so wave spans recorded from pipeline
timestamps and publisher spans recorded here share one time base);
dumps convert to the Chrome trace format's microseconds.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any

from reporter_tpu.utils import locks

__all__ = ["FlightRecorder", "Span", "tracer", "configure", "span",
           "post_mortem", "NOOP", "TRACE_KEY", "stamp_record",
           "trace_id_of"]

# ---------------------------------------------------------------------------
# Broker-propagated trace context (round 19). A PRODUCER may stamp a
# probe record with ``record[TRACE_KEY] = {"id": ..., "ts": wall}``
# before appending it to a broker; the record-format brokers store dicts
# verbatim, so the metadata rides the log untouched. Consumers that
# recognize the key tag their spans with the inherited id
# (streaming/pipeline.py); consumers that don't simply ignore an extra
# dict key — which is exactly why format-pinned broker dirs stay
# compatible in BOTH directions: old logs have no key (reads as
# untraced), old readers skip the key (records stay valid). The
# canonical-record validators never look at it.

TRACE_KEY = "_trace"


def stamp_record(record: dict, trace_id, ts: "float | None" = None) -> dict:
    """Attach producer-side trace context to one broker record (in
    place; returned for chaining). ``ts`` is WALL time (``time.time()``)
    — the cross-process axis stitch.py aligns dumps on."""
    record[TRACE_KEY] = {"id": str(trace_id),
                         "ts": time.time() if ts is None else float(ts)}
    return record


def trace_id_of(record) -> "str | None":
    """The inherited trace id of a broker record, or None when the
    record is untraced (absent/malformed metadata is untraced, never an
    error — a poisoned producer must not wedge consumption)."""
    meta = record.get(TRACE_KEY) if isinstance(record, dict) else None
    if isinstance(meta, dict) and meta.get("id") is not None:
        return str(meta["id"])
    return None


class Span:
    """One completed host-side interval. ``wave`` carries the
    wave/batch id propagated through the pipeline (None for spans
    outside a wave); ``args`` is the small free-form payload that lands
    in the Chrome event's ``args``."""

    __slots__ = ("name", "t0", "t1", "tid", "wave", "args")

    def __init__(self, name: str, t0: float, t1: float, tid: int,
                 wave: "int | None" = None,
                 args: "dict | None" = None):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.wave = wave
        self.args = args

    def to_event(self) -> dict:
        """Chrome trace-event ("X" = complete event; µs timestamps)."""
        ev: dict[str, Any] = {
            "name": self.name, "ph": "X", "pid": os.getpid(),
            "tid": self.tid, "ts": round(self.t0 * 1e6, 1),
            "dur": round(max(0.0, self.t1 - self.t0) * 1e6, 1),
        }
        args = dict(self.args) if self.args else {}
        if self.wave is not None:
            args["wave"] = self.wave
        if args:
            ev["args"] = args
        return ev


class _Instant(Span):
    """Point-in-time marker (fault fired, dispatch started)."""

    __slots__ = ()

    def to_event(self) -> dict:
        ev = super().to_event()
        ev["ph"] = "i"
        ev["s"] = "p"                 # process-scoped instant
        ev.pop("dur", None)
        return ev


class _NoopSpan:
    """Shared do-nothing context manager: what ``span()`` hands out when
    tracing is off, so the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP = _NoopSpan()


class _SpanCtx:
    """Context manager that records one Span into the ring on exit."""

    __slots__ = ("_rec", "_name", "_wave", "_args", "_t0")

    def __init__(self, rec: "FlightRecorder", name: str,
                 wave: "int | None", args: "dict | None"):
        self._rec = rec
        self._name = name
        self._wave = wave
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._rec.add(self._name, self._t0, time.monotonic(),
                      wave=self._wave, **(self._args or {}))


class FlightRecorder:
    """Bounded ring of recent spans + the post-mortem dump machinery.

    Thread-safety: the ring is a ``deque(maxlen=...)`` and every span is
    appended as ONE completed object — appends from concurrent threads
    interleave at whole-span granularity (GIL-atomic), never inside a
    span, so no lock sits on the record path. Dumps snapshot the ring
    under a lock that only other dumps contend on.
    """

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self.dump_dir = ""
        self.max_dumps = 16
        self._ring: "collections.deque[Span]" = collections.deque(
            maxlen=int(capacity))
        self._dump_lock = locks.named_lock("tracer.dump")
        self._dump_seq = 0
        self.dumps_written = 0
        self.dumps_suppressed = 0     # past max_dumps (counted, not silent)
        self._tids: dict[int, int] = {}   # thread ident → small stable id
        self._tid_lock = locks.named_lock("tracer.tid")  # its own lock: dump() calls
        #                                     _tid while holding _dump_lock

    # ---- configuration ---------------------------------------------------

    def configure(self, enabled: "bool | None" = None,
                  dump_dir: "str | None" = None,
                  capacity: "int | None" = None,
                  max_dumps: "int | None" = None) -> "FlightRecorder":
        """Mutate IN PLACE (call sites cache the singleton). Only the
        arguments given change; ``capacity`` rebuilds the ring keeping
        the newest spans."""
        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = collections.deque(self._ring,
                                           maxlen=int(capacity))
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if max_dumps is not None:
            self.max_dumps = int(max_dumps)
        if enabled is not None:
            self.enabled = bool(enabled)
        return self

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    # ---- record side -----------------------------------------------------

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)          # hot path: one dict read
        if tid is None:
            with self._tid_lock:             # len+insert must be atomic:
                tid = self._tids.get(ident)  # two first-span threads
                if tid is None:              # racing would share a tid
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
        return tid

    def span(self, name: str, wave: "int | None" = None,
             **args):
        """Context manager recording one span — or the shared no-op when
        disabled (zero allocation on the off path beyond the call)."""
        if not self.enabled:
            return NOOP
        return _SpanCtx(self, name, wave, args or None)

    def add(self, name: str, t0: float, t1: float,
            wave: "int | None" = None, **args) -> None:
        """Record a completed span from explicit ``time.monotonic``
        timestamps (the pipeline's wave legs carry their own)."""
        if not self.enabled:
            return
        self._ring.append(Span(name, t0, t1, self._tid(), wave,
                               args or None))

    def instant(self, name: str, wave: "int | None" = None,
                **args) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        self._ring.append(_Instant(name, now, now, self._tid(), wave,
                                   args or None))

    # ---- read side -------------------------------------------------------

    def snapshot(self) -> "list[Span]":
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def to_chrome(self, reason: "str | None" = None,
                  failing: "str | None" = None) -> dict:
        """The ring as a Chrome-trace-event document. Extra top-level
        keys (``reason`` / ``failing_span``) are legal — viewers read
        ``traceEvents`` and ignore the rest — and make the post-mortem
        self-describing without opening a viewer."""
        events = [s.to_event() for s in self.snapshot()]
        if reason is not None:
            now = time.monotonic()
            mark = _Instant(f"FAULT:{reason}", now, now, self._tid(),
                            None, {"failing_span": failing or ""})
            events.append(mark.to_event())
        doc: dict[str, Any] = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
            # clock anchor (round 19): span timestamps are per-process
            # ``time.monotonic`` — meaningless across pids. One
            # (monotonic, wall) pair taken at dump time lets
            # distributed/stitch.py shift every event onto the shared
            # wall-clock axis and merge dumps from many processes into
            # one causally ordered trace.
            "clock_sync": {"monotonic_us": round(time.monotonic() * 1e6,
                                                 1),
                           "unix_us": round(time.time() * 1e6, 1),
                           "pid": os.getpid()},
        }
        if reason is not None:
            doc["reason"] = reason
        if failing is not None:
            doc["failing_span"] = failing
        return doc

    def dump(self, path: "str | None" = None, reason: str = "manual",
             failing: "str | None" = None) -> "str | None":
        """Write the ring as Chrome trace JSON. ``path=None`` names the
        file ``flight_{seq:03d}_{reason}.json`` under ``dump_dir``
        (None returned when no dir is configured)."""
        with self._dump_lock:
            return self._dump_locked(path, reason, failing)

    def _dump_locked(self, path: "str | None", reason: str,
                     failing: "str | None") -> "str | None":
        # caller holds _dump_lock
        if path is None:
            if not self.dump_dir:
                return None
            self._dump_seq += 1
            path = os.path.join(
                self.dump_dir,
                f"flight_{self._dump_seq:03d}_{reason}.json")
        doc = self.to_chrome(reason=reason, failing=failing)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)         # a reader never sees a torn dump
        self.dumps_written += 1
        return path

    def post_mortem(self, reason: str, failing: "str | None" = None,
                    **args) -> "str | None":
        """The fault-site hook: record the fault as an instant event and
        dump the ring, bounded by ``max_dumps`` per process (a flapping
        link must not fill the disk with identical post-mortems — the
        suppressed count keeps the overflow visible). No-op unless
        tracing is enabled AND a dump dir is configured."""
        if not self.enabled or not self.dump_dir:
            return None
        self.instant(f"FAULT:{reason}", **dict(args,
                                               failing_span=failing or ""))
        # check-and-write under ONE _dump_lock acquisition: a separate
        # check section let two racing fault sites both pass at
        # max_dumps-1 and write past the bound
        with self._dump_lock:
            if self.dumps_written >= self.max_dumps:
                self.dumps_suppressed += 1
                return None
            try:
                return self._dump_locked(None, reason, failing)
            except OSError:           # ENOSPC etc: a post-mortem must
                return None           # never take the worker down with it


# ---------------------------------------------------------------------------
# Process-global recorder (env-configured once, like faults.active())

_ENV_ON = "RTPU_TRACE"
_ENV_DIR = "RTPU_TRACE_DIR"
_ENV_RING = "RTPU_TRACE_RING"


def env_flag(value: "str | None", strict: bool = False) -> bool:
    """THE env-var truthiness parse for RTPU_*/REPORTER_* boolean knobs
    — shared with ServiceConfig.with_env_overrides so the config view
    and the process-global recorder can never disagree on the same
    string. Unset, blank/whitespace, and 0/false/off/no are False.

    ``strict=True`` raises ValueError on a token outside the recognized
    true/false sets instead of reading it as True — the matcher-lever
    discipline (config.py round 8): a typo'd kernel knob must fail
    loudly, or an on-chip A/B measures an arm against itself. The
    analysis/ env-flag lint requires every boolean RTPU_*/REPORTER_*
    parse to go through this function (round 14)."""
    if not value:
        return False
    tok = value.strip().lower()
    if strict and tok not in ("", "0", "false", "off", "no",
                              "1", "true", "on", "yes"):
        raise ValueError(f"unrecognized boolean env value {value!r}; "
                         "use 0/1 (or true/false, on/off, yes/no)")
    return tok not in ("", "0", "false", "off", "no")

_tracer = FlightRecorder()
_env_lock = locks.named_lock("tracer.env")
_env_applied = False


def tracer() -> FlightRecorder:
    """THE recorder every call site shares. Env enablement is applied
    once, lazily — a spawned worker inherits RTPU_TRACE* and records
    the same way its parent did (the RTPU_FAULTS discipline)."""
    global _env_applied
    if not _env_applied:
        with _env_lock:
            if not _env_applied:
                if env_flag(os.environ.get(_ENV_ON)):
                    _tracer.configure(enabled=True)
                d = os.environ.get(_ENV_DIR, "")
                if d:
                    _tracer.configure(dump_dir=d)
                ring = os.environ.get(_ENV_RING, "")
                if ring:
                    _tracer.configure(capacity=int(ring))
                _env_applied = True
    return _tracer


def configure(**kw) -> FlightRecorder:
    return tracer().configure(**kw)


def configure_from_service(svc) -> None:
    """ServiceConfig → recorder, applied at app/pipeline construction.
    Only ever turns tracing ON, and only applies ring/dir knobs set
    AWAY from their defaults — a second component constructed with the
    defaults must never degrade an env-configured recorder (e.g.
    RTPU_TRACE_RING=65536 trimmed back to 4096, discarding 15/16ths of
    the flight history, by an app whose config left trace_ring alone)."""
    if getattr(svc, "trace", False):
        import dataclasses

        defaults = ({f.name: f.default for f in dataclasses.fields(svc)}
                    if dataclasses.is_dataclass(svc) else {})
        tr = tracer()
        tr.configure(enabled=True)
        ring = int(getattr(svc, "trace_ring", 4096))
        if ring != defaults.get("trace_ring", 4096):
            tr.configure(capacity=ring)
        d = getattr(svc, "trace_dir", "")
        if d:
            tr.configure(dump_dir=d)


def span(name: str, wave: "int | None" = None, **args):
    return tracer().span(name, wave=wave, **args)


def post_mortem(reason: str, failing: "str | None" = None,
                **args) -> "str | None":
    return tracer().post_mortem(reason, failing=failing, **args)
