"""Per-stage wall-clock metrics and counters.

The reference has no in-process observability (SURVEY.md §5 "Metrics /
logging": stdout logging only; the external Datastore is the product's
metric sink). This module is the TPU build's deliberate gap-fill: the
north-star metrics — probes/sec, p50 per-trace match latency, match-failure
rate (BASELINE.md) — need a home that both the HTTP service and the
streaming worker can feed, cheaply, from any thread.

Design: a registry of named counters + stage timers with bounded reservoir
percentiles. Everything is O(1) per event, lock-guarded (service handlers
are threaded), and snapshot() renders a plain-dict view for /stats or logs.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class _Reservoir:
    """Bounded sample ring for percentile estimates (newest-N policy —
    streaming metrics should reflect recent behavior, not all of history)."""

    __slots__ = ("_buf", "_cap", "_n")

    def __init__(self, cap: int = 1024):
        self._buf: list[float] = []
        self._cap = cap
        self._n = 0

    def add(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(v)
        else:
            self._buf[self._n % self._cap] = v
        self._n += 1

    def quantile(self, q: float) -> float:
        if not self._buf:
            return float("nan")
        s = sorted(self._buf)
        i = min(len(s) - 1, max(0, int(q * (len(s) - 1) + 0.5)))
        return s[i]


class StageTimer:
    """Context manager that records one stage's wall time:

        with metrics.stage("decode"):
            ...
    """

    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self._registry = registry
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "StageTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._registry.observe(self._name + "_seconds",
                               time.perf_counter() - self._t0)


class MetricsRegistry:
    """Named counters + observation series; thread-safe; snapshot-able."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._series: dict[str, _Reservoir] = {}
        self._born = time.time()

    # ---- write side ------------------------------------------------------

    def count(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time level (lag, in-flight waves, queue depth) —
        last write wins, snapshot reports it verbatim. Counters accumulate
        events; gauges answer "how deep is the backlog RIGHT NOW", which
        is what streaming overload monitoring alerts on."""
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            r = self._series.get(name)
            if r is None:
                r = self._series[name] = _Reservoir()
            r.add(value)
            self._counters[name + "_total"] = (
                self._counters.get(name + "_total", 0.0) + value)
            self._counters[name + "_count"] = (
                self._counters.get(name + "_count", 0.0) + 1)

    def stage(self, name: str) -> StageTimer:
        return StageTimer(self, name)

    # ---- read side -------------------------------------------------------

    def value(self, name: str) -> float:
        """Counter value, falling back to the gauge of the same name
        (scheduler tests/operators read point-in-time levels like
        sched_inflight_batches through the same accessor)."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def snapshot(self) -> dict[str, Any]:
        """Plain-dict view: counters + gauges verbatim + p50/p95 per series
        + derived rates for the north-star metrics when their inputs
        exist."""
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            out.update(self._gauges)
            for name, r in self._series.items():
                out[name + "_p50"] = r.quantile(0.50)
                out[name + "_p95"] = r.quantile(0.95)
            probes = out.get("probes", 0.0)
            busy = out.get("match_seconds_total", 0.0)
            if probes and busy:
                out["probes_per_sec_busy"] = probes / busy
            out["uptime_seconds"] = time.time() - self._born
            return out
