"""Per-stage wall-clock metrics and counters.

The reference has no in-process observability (SURVEY.md §5 "Metrics /
logging": stdout logging only; the external Datastore is the product's
metric sink). This module is the TPU build's deliberate gap-fill: the
north-star metrics — probes/sec, p50 per-trace match latency, match-failure
rate (BASELINE.md) — need a home that both the HTTP service and the
streaming worker can feed, cheaply, from any thread.

Design: a registry of named counters + stage timers with bounded reservoir
percentiles. Everything is O(1) per event, lock-guarded (service handlers
are threaded), and snapshot() renders a plain-dict view for /stats or logs.
"""

from __future__ import annotations

import bisect
import re
import time
from typing import Any

from reporter_tpu.utils import locks

# Fixed histogram bucket upper bounds (seconds-scale, matching the
# stage-timer series this registry mostly holds). FIXED, not adaptive:
# Prometheus histogram_quantile aggregates across workers only when every
# exposition shares the same ``le`` grid, and a capture's buckets must
# mean the same thing run over run. Dimensionless series (occupancies,
# ratios) land in the low buckets — still monotone, still aggregable.
HISTOGRAM_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_VALUE = re.compile(r"[\"\\\n]")

# version tag on MetricsRegistry.export() documents (the snapshot files
# the topology supervisor tails) — bump when the merge semantics change
EXPORT_SCHEMA = 1


def labeled(name: str, **labels) -> str:
    """Canonical label-suffixed series key: ``labeled("fleet_hits",
    metro="sf")`` → ``fleet_hits{metro="sf"}``. THE spelling for
    per-metro (and any future per-partition/per-worker) series — the
    registry stores the full string as the key, snapshot() reports it
    verbatim, and render_prometheus() splits it back into a metric name
    plus a real Prometheus label block (grouped under one # TYPE line
    per base name). Label order is sorted so the same logical series
    can never fork into two keys."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_LABEL_VALUE.sub("_", str(v))}"'
        for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _split_labels(key: str) -> tuple[str, str]:
    """``name{a="b"}`` → (``name``, ``{a="b"}``); plain names pass
    through with an empty label block."""
    if key.endswith("}") and "{" in key:
        base, _, rest = key.partition("{")
        return base, "{" + rest
    return key, ""


def _with_suffix(key: str, suffix: str) -> str:
    """Append a derived-series suffix BEFORE any label block, so
    ``fleet_promote_seconds{metro="a"}`` derives
    ``fleet_promote_seconds_total{metro="a"}`` (a valid labeled series)
    rather than a name with trailing braces in the middle."""
    base, lab = _split_labels(key)
    return base + suffix + lab


_LABEL_PAIR = re.compile(r'(\w+)="([^"]*)"')


def with_labels(key: str, **extra) -> str:
    """Add labels to a series key that may ALREADY carry a label block:
    ``with_labels('stream_lag{metro="sf"}', worker="w0")`` →
    ``stream_lag{metro="sf",worker="w0"}``. Existing labels win on a
    name clash (a member's own label is its identity; an aggregator
    must never overwrite it). Routed through ``labeled()`` so the
    sorted-label canonical spelling holds here too."""
    base, lab = _split_labels(key)
    labels = dict(_LABEL_PAIR.findall(lab))
    for k, v in extra.items():
        labels.setdefault(k, v)
    return labeled(base, **labels)


class _Reservoir:
    """Bounded sample ring for percentile estimates (newest-N policy —
    streaming metrics should reflect recent behavior, not all of history)."""

    __slots__ = ("_buf", "_cap", "_n")

    def __init__(self, cap: int = 1024):
        self._buf: list[float] = []
        self._cap = cap
        self._n = 0

    def add(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(v)
        else:
            self._buf[self._n % self._cap] = v
        self._n += 1

    def quantile(self, q: float) -> float:
        if not self._buf:
            return float("nan")
        s = sorted(self._buf)
        return _pick(s, q)


def _pick(sorted_buf: "list[float]", q: float) -> float:
    i = min(len(sorted_buf) - 1,
            max(0, int(q * (len(sorted_buf) - 1) + 0.5)))
    return sorted_buf[i]


class StageTimer:
    """Context manager that records one stage's wall time:

        with metrics.stage("decode"):
            ...
    """

    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self._registry = registry
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "StageTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        # _with_suffix, not concatenation: a labeled stage name
        # (stage(labeled("x", metro="sf"))) must derive
        # x_seconds{metro="sf"}, not a key with braces mid-name
        self._registry.observe(_with_suffix(self._name, "_seconds"),
                               time.perf_counter() - self._t0)


class MetricsRegistry:
    """Named counters + observation series; thread-safe; snapshot-able."""

    def __init__(self):
        self._lock = locks.named_lock("metrics.registry")
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._series: dict[str, _Reservoir] = {}
        # per-series fixed-bucket cumulative counts (len(BUCKETS)+1, the
        # last slot is +Inf) for the Prometheus histogram exposition —
        # reservoirs forget history by design, histograms must not
        self._hist: dict[str, list[int]] = {}
        self._born = time.time()

    # ---- write side ------------------------------------------------------

    def count(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time level (lag, in-flight waves, queue depth) —
        last write wins, snapshot reports it verbatim. Counters accumulate
        events; gauges answer "how deep is the backlog RIGHT NOW", which
        is what streaming overload monitoring alerts on."""
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            r = self._series.get(name)
            if r is None:
                r = self._series[name] = _Reservoir()
                # setdefault, not assignment: a merged registry
                # (merge_exports) carries histograms with no reservoir
                # behind them — a later observe() into the same name
                # must extend those bucket counts, never zero them
                self._hist.setdefault(
                    name, [0] * (len(HISTOGRAM_BUCKETS) + 1))
            r.add(value)
            self._hist[name][bisect.bisect_left(HISTOGRAM_BUCKETS,
                                                value)] += 1
            total = _with_suffix(name, "_total")
            count = _with_suffix(name, "_count")
            self._counters[total] = self._counters.get(total, 0.0) + value
            self._counters[count] = self._counters.get(count, 0.0) + 1

    def stage(self, name: str) -> StageTimer:
        return StageTimer(self, name)

    # ---- read side -------------------------------------------------------

    def value(self, name: str) -> float:
        """Counter value, falling back to the gauge of the same name
        (scheduler tests/operators read point-in-time levels like
        sched_inflight_batches through the same accessor)."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def snapshot(self) -> dict[str, Any]:
        """Plain-dict view: counters + gauges verbatim + p50/p95/p99 per
        series + derived rates for the north-star metrics when their
        inputs exist. The sample buffers are COPIED out under the lock
        and sorted outside it — a snapshot with many fat series must not
        stall every concurrent count()/observe() on its O(n log n)."""
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            out.update(self._gauges)
            bufs = {name: list(r._buf) for name, r in self._series.items()}
        for name, buf in bufs.items():
            if buf:
                buf.sort()
                out[_with_suffix(name, "_p50")] = _pick(buf, 0.50)
                out[_with_suffix(name, "_p95")] = _pick(buf, 0.95)
                out[_with_suffix(name, "_p99")] = _pick(buf, 0.99)
            else:
                nan = float("nan")
                out[_with_suffix(name, "_p50")] = nan
                out[_with_suffix(name, "_p95")] = nan
                out[_with_suffix(name, "_p99")] = nan
        probes = out.get("probes", 0.0)
        busy = out.get("match_seconds_total", 0.0)
        if probes and busy:
            out["probes_per_sec_busy"] = probes / busy
        out["uptime_seconds"] = time.time() - self._born
        return out

    def export(self) -> dict:
        """The MERGE-ABLE wire form of the whole registry (round 19's
        cross-worker aggregation — the reason ``HISTOGRAM_BUCKETS`` has
        been fixed since round 10): counters and gauges verbatim plus
        every observation series' fixed-bucket counts. Reservoir SAMPLES
        are deliberately absent — percentiles are a process-local
        affordance (/stats), the aggregable artifact is the histogram,
        so merged expositions DROP ``_p50/_p99`` rather than publish a
        quantile no math can justify (test-pinned)."""
        with self._lock:
            return {
                "schema": EXPORT_SCHEMA,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hist": {k: list(v) for k, v in self._hist.items()},
            }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the whole
        registry: counters and gauges verbatim, each observation series
        as a histogram over the FIXED ``HISTOGRAM_BUCKETS`` grid (the
        reservoir percentiles stay a /stats affordance; scrapers get
        aggregable cumulative buckets). Names are prefixed ``rtpu_`` and
        sanitized to the exposition charset."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: (list(h),
                            self._counters.get(
                                _with_suffix(name, "_total"), 0.0),
                            int(self._counters.get(
                                _with_suffix(name, "_count"), 0.0)))
                     for name, h in self._hist.items()}
        lines: list[str] = []

        def _name(raw: str) -> str:
            return "rtpu_" + _PROM_NAME.sub("_", raw)

        def _grouped(items: dict) -> dict:
            """base name → [(label block, value)], insertion-sorted:
            labeled series (the per-metro fleet counters/gauges) share
            ONE ``# TYPE`` line per base, as the exposition format
            requires — a TYPE line per labeled sample would be a
            duplicate-metadata parse error for strict scrapers."""
            groups: dict[str, list] = {}
            for key, value in sorted(items.items()):
                base, lab = _split_labels(key)
                groups.setdefault(base, []).append((lab, value))
            return groups

        # series aggregates re-emit as the histogram's _sum/_count below
        shadow = {_with_suffix(k, suffix) for k in hists for suffix in
                  ("_total", "_count")}
        for base, samples in _grouped({k: v for k, v in counters.items()
                                       if k not in shadow}).items():
            n = _name(base)
            lines.append(f"# TYPE {n} counter")
            for lab, value in samples:
                lines.append(f"{n}{lab} {float(value)}")
        gauges["uptime_seconds"] = time.time() - self._born
        for base, samples in _grouped(gauges).items():
            n = _name(base)
            lines.append(f"# TYPE {n} gauge")
            for lab, value in samples:
                lines.append(f"{n}{lab} {float(value)}")
        for base, series in _grouped(hists).items():
            n = _name(base)
            lines.append(f"# TYPE {n} histogram")
            for lab, (buckets, total, count) in series:
                # merge ``le`` into any existing label block: a labeled
                # histogram's buckets are {metro="a",le="0.5"}, one series
                inner = lab[1:-1] + "," if lab else ""
                cum = 0
                for le, c in zip(HISTOGRAM_BUCKETS, buckets):
                    cum += c
                    lines.append(f'{n}_bucket{{{inner}le="{le}"}} {cum}')
                cum += buckets[-1]
                lines.append(f'{n}_bucket{{{inner}le="+Inf"}} {cum}')
                lines.append(f"{n}_sum{lab} {float(total)}")
                lines.append(f"{n}_count{lab} {count}")
        return "\n".join(lines) + "\n"


def delta_exports(newer: dict, older: dict) -> dict:
    """Element-wise ``newer − older`` over two ``export()`` documents —
    the windowed-rate primitive the round-24 SLO plane burns on.

    Counters and histogram buckets diff (clamped at 0: a restarted
    worker's reset must read as "no progress", never as negative burn);
    gauges carry ``newer``'s values verbatim (a level has no meaningful
    difference over a window — the SLO engine samples gauges into
    synthetic counters instead). The result carries the ``schema`` tag
    so it IS a valid export: ``merge_exports`` accepts delta documents,
    which is what makes topology-wide burn well-defined — on counters
    and buckets the diff is linear, so delta-of-merged-exports equals
    merge-of-per-worker-deltas exactly (property-tested,
    tests/test_slo.py)."""
    old_counters = older.get("counters") or {}
    counters = {
        k: max(0.0, float(v) - float(old_counters.get(k, 0.0)))
        for k, v in (newer.get("counters") or {}).items()}
    old_hist = older.get("hist") or {}
    hist = {}
    for k, buckets in (newer.get("hist") or {}).items():
        prev = list(old_hist.get(k) or ())
        prev += [0] * (len(buckets) - len(prev))
        hist[k] = [max(0, int(b) - int(p))
                   for b, p in zip(buckets, prev)]
    return {"schema": EXPORT_SCHEMA, "counters": counters,
            "gauges": dict(newer.get("gauges") or {}), "hist": hist}


def delta_since(snapshots, window_s: float, now: "float | None" = None):
    """Windowed diff over a chronological ``[(monotonic_t, export), …]``
    sequence: returns ``(delta_exports(newest, baseline), span_s)``
    where the baseline is the LATEST snapshot at or before
    ``now − window_s`` (fallback: the oldest held — a young ring yields
    a shorter, honestly-reported span rather than a fabricated one).
    With fewer than two snapshots the delta is all-zero and the span 0.0
    — a first tick can never alert."""
    if not snapshots:
        return None, 0.0
    t_new, newest = snapshots[-1]
    if now is None:
        now = t_new
    cutoff = now - window_s
    base_t, base = snapshots[0]
    for t, exp in snapshots:
        if t <= cutoff:
            base_t, base = t, exp
        else:
            break
    return delta_exports(newest, base), max(0.0, t_new - base_t)


class SnapshotRing:
    """Bounded chronological ring of (monotonic_t, export) snapshots —
    the state behind ``delta_since``. Unlocked by design: the one
    writer/reader is the SLO evaluator's tick, which holds its own named
    lock."""

    __slots__ = ("_snaps", "_cap")

    def __init__(self, cap: int = 512):
        self._snaps: list = []
        self._cap = cap

    def push(self, t: float, export: dict) -> None:
        self._snaps.append((float(t), export))
        if len(self._snaps) > self._cap:
            del self._snaps[0]

    def __len__(self) -> int:
        return len(self._snaps)

    def delta_since(self, window_s: float, now: "float | None" = None):
        return delta_since(self._snaps, window_s, now)


def merge_exports(exports: "dict[str, dict]") -> MetricsRegistry:
    """K member ``export()`` documents → ONE fleet-wide registry (the
    round-10 promise, finally performed): keyed by member name so gauges
    stay attributable.

      counters    sum — labeled series union per full ``{metro=…}`` key
                  (identical keys from two members are the same logical
                  series and add; the ``_total``/``_count`` shadows ride
                  along, keeping histogram ``_sum``/``_count`` exact);
      gauges      carry a ``worker`` label — two members' backlog depths
                  are different facts; last-write-wins across processes
                  would fabricate a fleet-wide level nobody measured;
      histograms  sum BUCKET-WISE over the shared fixed ``le`` grid
                  (legal precisely because the grid is pinned);
      reservoirs  dropped — the merged exposition publishes no
                  ``_p50/_p99`` (see ``export()``).

    The result is a plain MetricsRegistry: ``render_prometheus()`` is
    the fleet exposition, ``snapshot()``/``value()`` serve /health math.
    Property-tested (tests/test_distributed.py): merging K exports
    equals one registry observing the union of all K observation
    streams, exactly, on every counter and every bucket."""
    out = MetricsRegistry()
    with out._lock:
        for member in sorted(exports):
            exp = exports[member] or {}
            if exp.get("schema") != EXPORT_SCHEMA:
                # the tag exists to be CHECKED: an export from a
                # version-skewed process is skipped, never mis-merged
                # (empty dicts — a member with no metrics yet — carry
                # no tag and contribute nothing either way)
                continue
            for k, v in (exp.get("counters") or {}).items():
                out._counters[k] = out._counters.get(k, 0.0) + float(v)
            for k, v in (exp.get("gauges") or {}).items():
                out._gauges[with_labels(k, worker=member)] = float(v)
            for k, buckets in (exp.get("hist") or {}).items():
                h = out._hist.setdefault(
                    k, [0] * (len(HISTOGRAM_BUCKETS) + 1))
                for i, c in enumerate(buckets[:len(h)]):
                    h[i] += int(c)
    return out
