"""Per-stage wall-clock metrics and counters.

The reference has no in-process observability (SURVEY.md §5 "Metrics /
logging": stdout logging only; the external Datastore is the product's
metric sink). This module is the TPU build's deliberate gap-fill: the
north-star metrics — probes/sec, p50 per-trace match latency, match-failure
rate (BASELINE.md) — need a home that both the HTTP service and the
streaming worker can feed, cheaply, from any thread.

Design: a registry of named counters + stage timers with bounded reservoir
percentiles. Everything is O(1) per event, lock-guarded (service handlers
are threaded), and snapshot() renders a plain-dict view for /stats or logs.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Any

# Fixed histogram bucket upper bounds (seconds-scale, matching the
# stage-timer series this registry mostly holds). FIXED, not adaptive:
# Prometheus histogram_quantile aggregates across workers only when every
# exposition shares the same ``le`` grid, and a capture's buckets must
# mean the same thing run over run. Dimensionless series (occupancies,
# ratios) land in the low buckets — still monotone, still aggregable.
HISTOGRAM_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


class _Reservoir:
    """Bounded sample ring for percentile estimates (newest-N policy —
    streaming metrics should reflect recent behavior, not all of history)."""

    __slots__ = ("_buf", "_cap", "_n")

    def __init__(self, cap: int = 1024):
        self._buf: list[float] = []
        self._cap = cap
        self._n = 0

    def add(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(v)
        else:
            self._buf[self._n % self._cap] = v
        self._n += 1

    def quantile(self, q: float) -> float:
        if not self._buf:
            return float("nan")
        s = sorted(self._buf)
        return _pick(s, q)


def _pick(sorted_buf: "list[float]", q: float) -> float:
    i = min(len(sorted_buf) - 1,
            max(0, int(q * (len(sorted_buf) - 1) + 0.5)))
    return sorted_buf[i]


class StageTimer:
    """Context manager that records one stage's wall time:

        with metrics.stage("decode"):
            ...
    """

    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self._registry = registry
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "StageTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._registry.observe(self._name + "_seconds",
                               time.perf_counter() - self._t0)


class MetricsRegistry:
    """Named counters + observation series; thread-safe; snapshot-able."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._series: dict[str, _Reservoir] = {}
        # per-series fixed-bucket cumulative counts (len(BUCKETS)+1, the
        # last slot is +Inf) for the Prometheus histogram exposition —
        # reservoirs forget history by design, histograms must not
        self._hist: dict[str, list[int]] = {}
        self._born = time.time()

    # ---- write side ------------------------------------------------------

    def count(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time level (lag, in-flight waves, queue depth) —
        last write wins, snapshot reports it verbatim. Counters accumulate
        events; gauges answer "how deep is the backlog RIGHT NOW", which
        is what streaming overload monitoring alerts on."""
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            r = self._series.get(name)
            if r is None:
                r = self._series[name] = _Reservoir()
                self._hist[name] = [0] * (len(HISTOGRAM_BUCKETS) + 1)
            r.add(value)
            self._hist[name][bisect.bisect_left(HISTOGRAM_BUCKETS,
                                                value)] += 1
            self._counters[name + "_total"] = (
                self._counters.get(name + "_total", 0.0) + value)
            self._counters[name + "_count"] = (
                self._counters.get(name + "_count", 0.0) + 1)

    def stage(self, name: str) -> StageTimer:
        return StageTimer(self, name)

    # ---- read side -------------------------------------------------------

    def value(self, name: str) -> float:
        """Counter value, falling back to the gauge of the same name
        (scheduler tests/operators read point-in-time levels like
        sched_inflight_batches through the same accessor)."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def snapshot(self) -> dict[str, Any]:
        """Plain-dict view: counters + gauges verbatim + p50/p95/p99 per
        series + derived rates for the north-star metrics when their
        inputs exist. The sample buffers are COPIED out under the lock
        and sorted outside it — a snapshot with many fat series must not
        stall every concurrent count()/observe() on its O(n log n)."""
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            out.update(self._gauges)
            bufs = {name: list(r._buf) for name, r in self._series.items()}
        for name, buf in bufs.items():
            if buf:
                buf.sort()
                out[name + "_p50"] = _pick(buf, 0.50)
                out[name + "_p95"] = _pick(buf, 0.95)
                out[name + "_p99"] = _pick(buf, 0.99)
            else:
                nan = float("nan")
                out[name + "_p50"] = nan
                out[name + "_p95"] = nan
                out[name + "_p99"] = nan
        probes = out.get("probes", 0.0)
        busy = out.get("match_seconds_total", 0.0)
        if probes and busy:
            out["probes_per_sec_busy"] = probes / busy
        out["uptime_seconds"] = time.time() - self._born
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the whole
        registry: counters and gauges verbatim, each observation series
        as a histogram over the FIXED ``HISTOGRAM_BUCKETS`` grid (the
        reservoir percentiles stay a /stats affordance; scrapers get
        aggregable cumulative buckets). Names are prefixed ``rtpu_`` and
        sanitized to the exposition charset."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: (list(h),
                            self._counters.get(name + "_total", 0.0),
                            int(self._counters.get(name + "_count", 0.0)))
                     for name, h in self._hist.items()}
        lines: list[str] = []

        def _name(raw: str) -> str:
            return "rtpu_" + _PROM_NAME.sub("_", raw)

        # series aggregates re-emit as the histogram's _sum/_count below
        shadow = {k + suffix for k in hists for suffix in
                  ("_total", "_count")}
        for key, value in sorted(counters.items()):
            if key in shadow:
                continue
            n = _name(key)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {float(value)}")
        for key, value in sorted(gauges.items()):
            n = _name(key)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {float(value)}")
        n = _name("uptime_seconds")
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {time.time() - self._born}")
        for key, (buckets, total, count) in sorted(hists.items()):
            n = _name(key)
            lines.append(f"# TYPE {n} histogram")
            cum = 0
            for le, c in zip(HISTOGRAM_BUCKETS, buckets):
                cum += c
                lines.append(f'{n}_bucket{{le="{le}"}} {cum}')
            cum += buckets[-1]
            lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{n}_sum {float(total)}")
            lines.append(f"{n}_count {count}")
        return "\n".join(lines) + "\n"
