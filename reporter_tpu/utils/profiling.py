"""Device profiling hooks (SURVEY.md §5 "Tracing / profiling").

The reference has no tracing; Valhalla only has timing logs. The TPU
build's device side is opaque without XLA-level traces, so this wraps
jax.profiler with a uniform entry point:

    from reporter_tpu.utils.profiling import device_trace
    with device_trace("/tmp/xplane"):          # no-op when dir is falsy
        matcher.match_many(traces)

The dump is an XPlane/perfetto trace directory loadable in TensorBoard's
profile plugin or ui.perfetto.dev. `REPORTER_TPU_TRACE_DIR` turns every
`device_trace(None)` call site on without code changes — the service and
stream workers wrap their match calls with it.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

from reporter_tpu.utils import locks

# jax.profiler.trace is a PROCESS-GLOBAL singleton (start_trace raises if
# one is already active). The serving scheduler overlaps match_many calls
# from several worker threads, so concurrent device_trace entries are
# normal — only the first concurrent entrant starts a capture; the rest
# run untraced (their device work still lands in the active capture,
# which is what an XPlane trace of overlapped batches should show).
_trace_lock = locks.named_lock("profiling.trace")
_trace_active = False


@contextlib.contextmanager
def device_trace(trace_dir: "str | None" = None) -> Iterator[None]:
    """Context manager: capture a jax.profiler trace into ``trace_dir``.

    Falsy ``trace_dir`` falls back to $REPORTER_TPU_TRACE_DIR; if that is
    unset too, the context is a no-op (zero overhead in production).
    Re-entrant across threads: nested/concurrent entries while a capture
    is active are no-ops instead of profiler errors.
    """
    global _trace_active
    target = trace_dir or os.environ.get("REPORTER_TPU_TRACE_DIR", "")
    if not target:
        yield
        return
    with _trace_lock:
        if _trace_active:
            owner = False
        else:
            _trace_active = owner = True
    if not owner:
        yield
        return
    import jax

    try:
        with jax.profiler.trace(target):
            yield
    finally:
        with _trace_lock:
            _trace_active = False


def annotate(name: str):
    """Named sub-span inside a device_trace (TraceAnnotation wrapper)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
