"""Device profiling hooks (SURVEY.md §5 "Tracing / profiling").

The reference has no tracing; Valhalla only has timing logs. The TPU
build's device side is opaque without XLA-level traces, so this wraps
jax.profiler with a uniform entry point:

    from reporter_tpu.utils.profiling import device_trace
    with device_trace("/tmp/xplane"):          # no-op when dir is falsy
        matcher.match_many(traces)

The dump is an XPlane/perfetto trace directory loadable in TensorBoard's
profile plugin or ui.perfetto.dev. `REPORTER_TPU_TRACE_DIR` turns every
`device_trace(None)` call site on without code changes — the service and
stream workers wrap their match calls with it.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator


@contextlib.contextmanager
def device_trace(trace_dir: "str | None" = None) -> Iterator[None]:
    """Context manager: capture a jax.profiler trace into ``trace_dir``.

    Falsy ``trace_dir`` falls back to $REPORTER_TPU_TRACE_DIR; if that is
    unset too, the context is a no-op (zero overhead in production).
    """
    target = trace_dir or os.environ.get("REPORTER_TPU_TRACE_DIR", "")
    if not target:
        yield
        return
    import jax

    with jax.profiler.trace(target):
        yield


def annotate(name: str):
    """Named sub-span inside a device_trace (TraceAnnotation wrapper)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
