"""Named-lock registry + lockdep runtime (concurrency contract, round 14).

The repo is a long-running multithreaded service: 14 threaded modules
(scheduler, fleet residency, publisher, watchdog, tracing, brokers) and
the last three rounds each shipped multiple hand-found lock bugs — a
promotion ``device_put`` stalling the fleet behind one lock, a
post-mortem ring dump serialized under the scheduler condvar, dead-letter
replay holding the spool lock across POSTs. Every one of those classes is
mechanically detectable; this module is the detector:

  named locks   ``named_lock("scheduler.stats")`` etc. wrap
                ``threading.Lock/RLock/Condition`` with a stable CLASS
                name (Linux-lockdep style: order is tracked per name, so
                every ``PartialTraceCache`` instance shares one node);
  order edges   each acquisition of B while holding A records the edge
                A→B once; an edge that closes a cycle in the global
                order graph is a POTENTIAL DEADLOCK and is recorded as a
                violation at the acquisition that would create it — no
                actual deadlock needs to manifest;
  blocking      while armed, known-blocking entry points (``time.sleep``,
                ``urllib.request.urlopen``, ``socket.create_connection``,
                ``subprocess.run``, ``os.fsync``, ``jax.device_put``,
                ``jax.block_until_ready``) are wrapped; calling one while
                holding any named lock is a violation unless the
                (lock, call) pair is in the committed allowlist
                (``analysis/concurrency_contract.py`` — dated
                justifications only);
  foreign wait  ``NamedCondition.wait`` while holding any OTHER named
                lock is a blocking violation too (the condvar releases
                only its own lock; everything else stays held across an
                unbounded sleep).

Arming: OFF by default — ``named_lock`` then returns the plain
``threading`` primitive, so production/bench paths pay literally nothing
(no wrapper frame, no flag check). ``arm()`` (the tests' conftest does
this before any reporter_tpu module with locks is imported) or env
``RTPU_LOCKDEP=1`` makes subsequently created named locks instrumented.
Arming is creation-time on purpose: retrofit would require wrapper
indirection on every lock forever.

The bookkeeping never blocks: the internal ``_meta`` lock is only ever
taken AFTER a user lock acquisition returns (or around pure reads) and
no user lock is ever acquired under it. Violations and edges accumulate
monotonically; the pytest gate snapshots counts per test and fails the
test that grew them (tests/conftest.py, tests/test_static_analysis.py).

Seeded-violation tests use a private ``Lockdep`` instance via
``NamedLock(name, dep=...)`` + ``use(dep)`` so synthetic inversions
never pollute the process-global graph the CI gate compares against the
committed golden set.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
from typing import Any, Iterable

__all__ = [
    "Lockdep", "NamedLock", "NamedCondition", "named_lock", "named_rlock",
    "named_condition", "arm", "armed", "global_dep", "use",
    "BLOCKING_CALLS",
]


def _site() -> str:
    """``file.py:line`` of the nearest caller frame outside this module
    (cheap: no full stack render — violations carry a short context, not
    a traceback; the pytest gate's assertion message is the report)."""
    f = sys._getframe(1)
    try:
        while f is not None and f.f_globals.get("__name__") == __name__:
            f = f.f_back
        if f is None:
            return "?"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    finally:
        del f


class Lockdep:
    """One order graph + violation ledger. The process-global instance
    backs every ``named_lock``; tests may run a private one."""

    def __init__(self, blocking_allow: "Iterable[tuple[str, str]]" = ()):
        self._meta = threading.Lock()
        self._tls = threading.local()
        self.edges: "dict[tuple[str, str], str]" = {}   # (a,b) → first site
        self.violations: "list[dict]" = []
        self._seen_blocking: "set[tuple]" = set()   # dedupe: one record
        #                                             per (call, held, site)
        self._seen_order: "set[tuple[str, str]]" = set()   # violating
        #                               edges are never inserted into
        #                               the graph (they'd poison
        #                               _reaches), so dedupe them here
        #                               or a hot loop floods the ledger
        self.blocking_allow = set(blocking_allow)

    # ---- per-thread held stack -------------------------------------------

    def _stack(self) -> "list[str]":
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> "tuple[str, ...]":
        return tuple(getattr(self._tls, "stack", ()) or ())

    # ---- bookkeeping (called by NamedLock/NamedCondition) ----------------

    def note_acquire(self, name: str, reentrant: bool) -> None:
        """Record order edges for an acquisition ATTEMPT (before the real
        lock blocks — an inversion must be caught even when the schedule
        happens not to deadlock)."""
        st = self._stack()
        if not st:
            return
        if reentrant and name in st:
            return                       # RLock re-entry: no new ordering
        with self._meta:
            for h in st:
                if (h, name) in self.edges:
                    continue
                if h == name or self._reaches(name, h):
                    if (h, name) in self._seen_order:
                        continue
                    self._seen_order.add((h, name))
                    self.violations.append({
                        "kind": "lock-order",
                        "edge": (h, name),
                        "site": _site(),
                        "held": tuple(st),
                        "detail": (f"acquiring {name!r} while holding "
                                   f"{h!r} inverts the recorded order "
                                   f"{name!r}→…→{h!r}"
                                   if h != name else
                                   f"nested acquisition of lock class "
                                   f"{name!r} (self-deadlock shape)"),
                    })
                    # report WITHOUT inserting (Linux-lockdep semantics):
                    # a recorded cyclic edge would make _reaches flag
                    # innocent later nestings through the bogus path and
                    # tell the developer to commit an edge validate()
                    # must reject
                    continue
                self.edges[(h, name)] = _site()

    def _reaches(self, src: str, dst: str) -> bool:
        """True when dst is reachable from src in the edge graph
        (caller holds _meta)."""
        seen = {src}
        frontier = [src]
        while frontier:
            a = frontier.pop()
            for (x, y) in self.edges:
                if x == a and y not in seen:
                    if y == dst:
                        return True
                    seen.add(y)
                    frontier.append(y)
        return False

    def note_acquired(self, name: str) -> None:
        self._stack().append(name)

    def note_release(self, name: str) -> bool:
        st = self._stack()
        # remove the newest matching entry (RLock counts push per acquire)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return True
        return False

    def note_blocking(self, call: str, exempt: "str | None" = None) -> None:
        st = self._stack()
        if not st:
            return
        offenders = [h for h in st
                     if h != exempt and (h, call) not in self.blocking_allow]
        if not offenders:
            return
        site = _site()
        with self._meta:
            key = (call, tuple(offenders), site)
            if key in self._seen_blocking:
                return
            self._seen_blocking.add(key)
            self.violations.append({
                "kind": "blocking-under-lock",
                "call": call,
                "site": site,
                "held": tuple(offenders),
                "detail": (f"blocking call {call} while holding "
                           f"{offenders!r} — add a dated entry to "
                           "analysis/concurrency_contract.BLOCKING_ALLOW "
                           "only if the hold is load-bearing"),
            })

    # ---- gate surface ----------------------------------------------------

    def counts(self) -> "tuple[int, int]":
        with self._meta:
            return len(self.violations), len(self.edges)

    def snapshot(self) -> dict:
        with self._meta:
            return {"edges": dict(self.edges),
                    "violations": list(self.violations)}


_GLOBAL = Lockdep()
_ACTIVE: "list[Lockdep]" = []       # extra instances (seeded tests)
_armed = False
_patched = False


def global_dep() -> Lockdep:
    return _GLOBAL


def armed() -> bool:
    if _armed:
        return True
    # env arming (worker subprocesses inherit, like RTPU_FAULTS) — lazy
    # import: tracing adopts named locks, so a top-level import would be
    # circular. env_flag is THE truthiness parser (round-10 rule).
    from reporter_tpu.utils.tracing import env_flag

    if not env_flag(os.environ.get("RTPU_LOCKDEP")):
        return False
    # env arming must be EQUIVALENT to programmatic arming: patch the
    # blocking entry points and load the committed allowlist, or a
    # worker would record order edges but silently skip the
    # blocking-call checks (and flag the legitimately allowlisted
    # holds). concurrency_contract is reporter_tpu-import-free, so this
    # lazy import cannot cycle.
    from reporter_tpu.analysis.concurrency_contract import BLOCKING_ALLOW

    arm(blocking_allow=set(BLOCKING_ALLOW))
    return True


def arm(blocking_allow: "Iterable[tuple[str, str]] | None" = None) -> Lockdep:
    """Turn instrumentation on for locks created FROM NOW ON and patch
    the blocking entry points. Idempotent; returns the global instance so
    callers can read its ledger."""
    global _armed
    _armed = True
    if blocking_allow is not None:
        _GLOBAL.blocking_allow = set(blocking_allow)
    _patch_blocking()
    return _GLOBAL


class use:
    """``with locks.use(dep):`` route blocking-call checks to a private
    Lockdep too (seeded-violation tests). Named locks built with
    ``dep=dep`` already report to it; this covers the patched functions,
    which consult every active instance."""

    def __init__(self, dep: Lockdep):
        self._dep = dep

    def __enter__(self) -> Lockdep:
        _ACTIVE.append(self._dep)
        return self._dep

    def __exit__(self, *exc) -> None:
        _ACTIVE.remove(self._dep)


# ---------------------------------------------------------------------------
# Instrumented primitives

class NamedLock:
    """Lock/RLock wrapper reporting to a Lockdep instance. API-compatible
    with the stdlib primitives for every use in this repo (acquire /
    release / context manager / locked)."""

    __slots__ = ("name", "_raw", "_dep", "_reentrant")

    def __init__(self, name: str, dep: "Lockdep | None" = None,
                 reentrant: bool = False):
        self.name = name
        self._dep = dep or _GLOBAL
        self._reentrant = reentrant
        self._raw = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._dep.note_acquire(self.name, self._reentrant)
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            if not blocking:
                # try-acquire can't deadlock, but a success still orders
                self._dep.note_acquire(self.name, self._reentrant)
            self._dep.note_acquired(self.name)
        return ok

    def release(self) -> None:
        self._raw.release()
        self._dep.note_release(self.name)

    def locked(self) -> bool:
        raw = self._raw
        if hasattr(raw, "locked"):          # Lock always; RLock ≥ 3.14
            return raw.locked()
        if raw._is_owned():                 # RLock pre-3.14 fallback
            return True
        if raw.acquire(blocking=False):
            raw.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:        # pragma: no cover - debug aid
        return f"<NamedLock {self.name!r} {self._raw!r}>"


class NamedCondition:
    """Condition over a named lock. ``wait`` releases only its OWN lock,
    so waiting while any other named lock is held is flagged as a
    blocking violation (kind ``wait:{name}``)."""

    __slots__ = ("name", "_nl", "_cond", "_dep")

    def __init__(self, name: str, lock: "NamedLock | None" = None,
                 dep: "Lockdep | None" = None):
        self.name = name
        self._dep = dep or (lock._dep if lock is not None else _GLOBAL)
        self._nl = lock if lock is not None else NamedLock(name,
                                                           dep=self._dep)
        self._cond = threading.Condition(self._nl._raw)

    # lock surface (scheduler code does ``with self._cv:``)
    def acquire(self, *a, **k) -> bool:
        return self._nl.acquire(*a, **k)

    def release(self) -> None:
        self._nl.release()

    def __enter__(self) -> bool:
        return self._nl.__enter__()

    def __exit__(self, *exc) -> None:
        self._nl.__exit__(*exc)

    # condvar surface
    def wait(self, timeout: "float | None" = None) -> bool:
        self._dep.note_blocking(f"wait:{self.name}", exempt=self._nl.name)
        held = self._dep.note_release(self._nl.name)   # cond drops the lock
        try:
            return self._cond.wait(timeout)
        finally:
            # re-acquisition records no NEW edge: any foreign held lock
            # already tripped the wait check above. `held` guards the
            # misuse path (wait without the lock raises in the stdlib
            # Condition — the phantom entry must not survive it).
            if held:
                self._dep.note_acquired(self._nl.name)

    def wait_for(self, predicate, timeout: "float | None" = None):
        self._dep.note_blocking(f"wait:{self.name}", exempt=self._nl.name)
        held = self._dep.note_release(self._nl.name)

        def _instrumented():
            # the stdlib wait_for evaluates the predicate with the lock
            # RE-ACQUIRED — re-push the class around each evaluation or
            # a named-lock acquisition / patched blocking call inside
            # the predicate would run with the lock genuinely held yet
            # invisible to the ledger
            self._dep.note_acquired(self._nl.name)
            try:
                return predicate()
            finally:
                self._dep.note_release(self._nl.name)

        try:
            return self._cond.wait_for(_instrumented if held else predicate,
                                       timeout)
        finally:
            if held:
                self._dep.note_acquired(self._nl.name)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# Registry constructors — THE spelling for every lock in reporter_tpu

def named_lock(name: str) -> "Any":
    """A mutex with a stable lockdep class name. Unarmed: the plain
    ``threading.Lock`` (zero overhead — no wrapper, no flag check on the
    hot path)."""
    if armed():
        return NamedLock(name)
    return threading.Lock()


def named_rlock(name: str) -> "Any":
    if armed():
        return NamedLock(name, reentrant=True)
    return threading.RLock()


def named_condition(name: str, lock: "Any | None" = None) -> "Any":
    """Condition bound to ``lock`` (a named_lock result) or its own
    fresh lock of class ``name``."""
    if isinstance(lock, NamedLock):
        return NamedCondition(name, lock=lock)
    if armed() and lock is None:
        return NamedCondition(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# Blocking-call patches

# label → (module name, attribute). jax entries patch lazily: arming must
# not drag jax in, and in the test process jax is always already loaded.
BLOCKING_CALLS = {
    "time.sleep": ("time", "sleep"),
    "os.fsync": ("os", "fsync"),
    "subprocess.run": ("subprocess", "run"),
    "urllib.request.urlopen": ("urllib.request", "urlopen"),
    "socket.create_connection": ("socket", "create_connection"),
    "jax.device_put": ("jax", "device_put"),
    "jax.block_until_ready": ("jax", "block_until_ready"),
}


def _deps() -> "list[Lockdep]":
    return [_GLOBAL, *_ACTIVE]


def _make_wrapper(orig, label: str):
    @functools.wraps(orig)
    def _blocking_guard(*a, **k):
        for dep in _deps():
            dep.note_blocking(label)
        return orig(*a, **k)

    _blocking_guard.__lockdep_label__ = label
    _blocking_guard.__lockdep_orig__ = orig
    return _blocking_guard


def _patch_blocking() -> None:
    global _patched
    if _patched:
        _patch_jax()                 # jax may have appeared since arming
        return
    import importlib

    for label, (mod_name, attr) in BLOCKING_CALLS.items():
        if mod_name == "jax":
            continue
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr)
        if getattr(fn, "__lockdep_label__", None) == label:
            continue
        setattr(mod, attr, _make_wrapper(fn, label))
    _patched = True
    _patch_jax()


def _patch_jax() -> None:
    jax = sys.modules.get("jax")
    if jax is None:
        return
    for label, (mod_name, attr) in BLOCKING_CALLS.items():
        if mod_name != "jax":
            continue
        fn = getattr(jax, attr, None)
        if fn is None or getattr(fn, "__lockdep_label__", None) == label:
            continue
        setattr(jax, attr, _make_wrapper(fn, label))
