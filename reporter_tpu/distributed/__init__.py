"""Topology observability + elasticity plane: the supervised
multi-process topology (supervisor.py, round 19), cross-worker metrics
aggregation over atomically spooled snapshots (aggregate.py +
utils/metrics.merge_exports), cross-pid trace stitching (stitch.py),
and the epoch-fenced partition lease table that makes membership
elastic (lease.py, round 23). See DISTRIBUTED.md "Topology
observability" and "Partition leasing"."""

from reporter_tpu.distributed.lease import (LeaseError, LeaseRunner,
                                            LeaseTable, StaleLeaseError,
                                            plan_rebalance)
from reporter_tpu.distributed.supervisor import (MemberSpec, ReportSink,
                                                 Supervisor,
                                                 worker_member)

__all__ = ["MemberSpec", "ReportSink", "Supervisor", "worker_member",
           "LeaseTable", "LeaseRunner", "LeaseError", "StaleLeaseError",
           "plan_rebalance"]
