"""Topology observability plane (round 19): the supervised
multi-process topology (supervisor.py), cross-worker metrics
aggregation over atomically spooled snapshots (aggregate.py +
utils/metrics.merge_exports), and cross-pid trace stitching
(stitch.py). See DISTRIBUTED.md "Topology observability" for the
measured artifact."""

from reporter_tpu.distributed.supervisor import (MemberSpec, ReportSink,
                                                 Supervisor,
                                                 worker_member)

__all__ = ["MemberSpec", "ReportSink", "Supervisor", "worker_member"]
