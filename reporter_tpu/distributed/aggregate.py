"""Cross-worker metrics aggregation over spooled snapshot files.

The transport is the filesystem, on purpose: every process in a
topology on this box shares a disk, the r9 tmp+fsync+rename discipline
makes each snapshot an atomic document (a tailer NEVER sees a torn
file this writer produced), and no inter-process HTTP means a wedged
worker can't stall the supervisor's scrape — the supervisor reads
whatever snapshots exist, stamps their age, and serves the merge.

Worker side:  ``write_snapshot(path, registry, member=...)`` — called
              periodically by ``streaming.__main__`` when a snapshot
              dir is configured (``--snapshot-dir`` /
              ``RTPU_TOPO_SNAPSHOT_DIR``).
Supervisor :  ``load_dir(dir)`` tails every member's latest snapshot;
              ``merge_registry`` folds the K exports through
              ``utils.metrics.merge_exports`` (counters sum, labeled
              series union, fixed-bucket histograms sum bucket-wise,
              gauges gain a ``worker`` label); ``fleet_exposition`` is
              the merged ``/metrics`` text; ``member_health`` is the
              per-member liveness/lag block ``/health`` serves.

The merge math itself lives in utils/metrics.py next to the registry it
inverts — this module owns only the file protocol.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from reporter_tpu.utils import metrics

__all__ = ["SNAPSHOT_SCHEMA", "write_snapshot", "read_snapshot",
           "load_dir", "merge_registry", "fleet_exposition",
           "member_health"]

SNAPSHOT_SCHEMA = 1


def snapshot_path(dirpath: str, member: str) -> str:
    """One file per member, overwritten in place (atomically): the
    supervisor wants each member's LATEST state, not a history — the
    histories live in the metrics themselves (counters/histograms are
    cumulative by construction, so no observation is lost to
    overwrites)."""
    return os.path.join(dirpath, f"{member}.json")


def write_snapshot(path: str, registry, member: str, seq: int = 0,
                   stats: "dict | None" = None) -> str:
    """Spool one atomic metrics/health snapshot (tmp+fsync+rename — the
    r9 checkpoint discipline; a crash between any two syscalls leaves
    the previous snapshot intact, never a torn one)."""
    doc: "dict[str, Any]" = {
        "snapshot": "rtpu-member",
        "schema": SNAPSHOT_SCHEMA,
        "member": member,
        "pid": os.getpid(),
        "seq": int(seq),
        "written_at": time.time(),
        "metrics": registry.export(),
    }
    if stats is not None:
        doc["stats"] = stats
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_snapshot(path: str) -> "dict | None":
    """One member snapshot, or None when absent/unreadable/foreign.
    Unreadable is NOT an error path: our own writers are atomic, so a
    bad file is a foreign artifact in the spool dir — skipping it must
    never take the aggregation down."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("snapshot") != "rtpu-member":
        return None
    if doc.get("schema") != SNAPSHOT_SCHEMA:
        # the version tag exists to be CHECKED (the staged_layout
        # discipline): a snapshot from a version-skewed member must be
        # skipped, never mis-merged into the fleet exposition
        return None
    return doc


def load_dir(dirpath: str) -> "dict[str, dict]":
    """member name → latest snapshot doc for every valid snapshot in
    the spool directory."""
    out: "dict[str, dict]" = {}
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        doc = read_snapshot(os.path.join(dirpath, name))
        if doc is not None:
            out[str(doc.get("member") or name[:-5])] = doc
    return out


def merge_registry(snapshots: "dict[str, dict]"):
    """K member snapshots → one fleet-wide MetricsRegistry (see
    utils.metrics.merge_exports for the math and its property-test
    contract)."""
    return metrics.merge_exports(
        {m: (doc.get("metrics") or {}) for m, doc in snapshots.items()})


def fleet_exposition(snapshots: "dict[str, dict]") -> str:
    """The merged Prometheus text — what the supervisor's /metrics
    serves."""
    return merge_registry(snapshots).render_prometheus()


def member_health(snapshots: "dict[str, dict]",
                  now: "float | None" = None) -> "dict[str, dict]":
    """Per-member snapshot provenance for /health: pid, seq, and
    snapshot LAG (age of the latest spool write — a member that stopped
    spooling is stale long before its process object says dead)."""
    now = time.time() if now is None else now
    out: "dict[str, dict]" = {}
    for m, doc in snapshots.items():
        written = float(doc.get("written_at") or 0.0)
        out[m] = {
            "pid": doc.get("pid"),
            "seq": doc.get("seq"),
            "snapshot_age_s": (round(now - written, 3) if written else None),
        }
    return out
