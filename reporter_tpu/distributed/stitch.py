"""Cross-process trace stitching — many flight-recorder dumps, ONE
Chrome trace.

Every process in a topology dumps its own ring (utils/tracing.py):
the producer's ``produce`` spans carry the trace ids it stamped into
broker records (``tracing.stamp_record``), the workers' ``consume`` /
``worker_match`` / ``publish`` spans carry the ids they inherited from
those records. Each dump's timestamps are that process's
``time.monotonic`` — meaningless across pids — so r19 dumps carry a
``clock_sync`` anchor (one (monotonic, wall) pair taken at dump time)
and this module shifts every event onto the shared wall-clock axis
before merging.

The stitched document is a normal Chrome/perfetto trace:

  - every source event, time-shifted, keeping its real pid/tid;
  - one ``process_name`` metadata row per member, so the viewer shows
    "producer", "worker-0", … instead of raw pids;
  - per traced probe seen in more than one process, a FLOW
    (``ph:"s"/"t"/"f"``, one ``id`` per trace id) threading
    producer → worker events into a single causal track, plus a
    synthesized ``broker_dwell`` span on a dedicated "broker" track
    covering produce-end → first-consume-start — the probe's
    producer→broker-dwell→worker-match→publish path reads as one story
    across pids.

Dumps WITHOUT a clock anchor (pre-r19) still merge — unshifted and
counted in ``stitched.unsynced_processes`` — so old post-mortems stay
loadable next to new ones.
"""

from __future__ import annotations

import json
import os
from typing import Any

__all__ = ["stitch", "load_dump"]

# Chrome disallows pid collisions for synthetic tracks; real pids are
# >0, so the synthesized broker-dwell track claims pid 0.
_BROKER_PID = 0


def load_dump(path: str) -> "dict | None":
    """One flight-recorder dump, or None when absent/unreadable (a
    member that died before its exit dump is an expected topology
    outcome, not a stitch error)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None
    return doc


def _ids_of(event: dict) -> "list[str]":
    """Trace ids an event claims (``trace_id`` scalar and/or the
    bounded ``trace_ids`` list the pipelines record per wave)."""
    args = event.get("args") or {}
    ids = []
    if args.get("trace_id") is not None:
        ids.append(str(args["trace_id"]))
    for t in args.get("trace_ids") or ():
        if t is not None:
            ids.append(str(t))
    return ids


def stitch(dumps: "dict[str, Any]",
           out_path: "str | None" = None) -> dict:
    """Merge named dumps (member name → path or already-loaded doc)
    into one Chrome trace document; optionally write it atomically.
    Returns the stitched doc with a ``stitched`` summary block
    (processes, events, traced ids, cross-pid tracks) the bench leg
    shape-checks."""
    events: "list[dict]" = []
    unsynced = 0
    processes = 0
    # trace id → [(shifted t0 us, shifted t1 us, member, pid, name)]
    by_id: "dict[str, list[tuple]]" = {}
    for member in sorted(dumps):
        doc = dumps[member]
        if isinstance(doc, str):
            doc = load_dump(doc)
        if doc is None:
            continue
        processes += 1
        sync = doc.get("clock_sync") or {}
        shift = 0.0
        if sync.get("monotonic_us") is not None \
                and sync.get("unix_us") is not None:
            shift = float(sync["unix_us"]) - float(sync["monotonic_us"])
        else:
            unsynced += 1
        pid = None
        for ev in doc.get("traceEvents", ()):
            ev = dict(ev)
            ev["ts"] = round(float(ev.get("ts", 0.0)) + shift, 1)
            pid = ev.get("pid", pid)
            events.append(ev)
            for tid in _ids_of(ev):
                by_id.setdefault(tid, []).append(
                    (ev["ts"], ev["ts"] + float(ev.get("dur", 0.0)),
                     member, ev.get("pid"), ev.get("tid", 0),
                     ev.get("name")))
        if pid is not None:
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": member}})

    # flows: one causal thread per trace id that crossed a pid boundary
    cross = 0
    for tid_str, occ in sorted(by_id.items()):
        pids = {o[3] for o in occ}
        if len(pids) < 2:
            continue
        cross += 1
        occ.sort()
        for i, (t0, _t1, _m, pid, tid, _name) in enumerate(occ):
            ph = "s" if i == 0 else ("f" if i == len(occ) - 1 else "t")
            ev = {"name": "probe_path", "cat": "topo", "ph": ph,
                  "id": tid_str, "pid": pid, "tid": tid, "ts": t0}
            if ph == "f":
                ev["bp"] = "e"        # bind to enclosing slice
            events.append(ev)
        # broker dwell: produce-end → first event in ANOTHER process
        first_pid = occ[0][3]
        foreign = [o for o in occ if o[3] != first_pid]
        if foreign:
            t0 = occ[0][1]
            t1 = max(t0, foreign[0][0])
            events.append({
                "name": "broker_dwell", "cat": "topo", "ph": "X",
                "pid": _BROKER_PID, "tid": 1, "ts": round(t0, 1),
                "dur": round(t1 - t0, 1),
                "args": {"trace_id": tid_str}})
    if cross:
        events.append({"name": "process_name", "ph": "M",
                       "pid": _BROKER_PID, "tid": 1,
                       "args": {"name": "broker"}})

    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "stitched": {
            "processes": processes,
            "unsynced_processes": unsynced,
            "events": len(events),
            "traced_ids": len(by_id),
            "cross_pid_tracks": cross,
        },
    }
    if out_path is not None:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, out_path)    # a viewer never loads a torn trace
    return doc
