"""Partition leasing — elastic membership for the streaming topology.

Round 23. The r19 topology plane supervises workers over STATIC
partition subsets: a dead member restarts onto its own partitions, but
the fleet cannot scale worker count under live load. This module adds
the consumer-group rebalance analog the reference gets from Kafka
(SURVEY.md §3.3): a file-backed **lease table** beside the broker dir
through which workers acquire time-bounded, epoch-fenced leases over
partitions, heartbeat to renew, and pick up orphaned or reassigned
partitions as membership changes.

Protocol (see DISTRIBUTED.md "Partition leasing" for the failure
model):

  - ONE table directory holds ``leases.json`` (the whole state,
    rewritten atomically tmp+fsync+rename per transaction),
    ``lease_events.jsonl`` (append-only audit log), and ``lock`` (an
    ``fcntl.flock`` file serializing transactions ACROSS processes; a
    ``named_lock("lease.table")`` serializes within one).
  - Every ownership change bumps the partition's **epoch**. Commits
    carry (member, epoch) and are rejected with ``StaleLeaseError``
    unless the committer still holds an unexpired lease at that exact
    epoch — a zombie that lost its lease can never move a floor, no
    matter how delayed its write arrives (fencing).
  - Expiry is STRICT: an expired lease neither renews nor commits.
    The renewing owner observes the loss (``lease_lost`` event, owner
    cleared), discards its buffered rows, and the next owner resumes
    at the table's committed floor — the at-least-once replay the r19
    recovery contract already guarantees, now across elastic
    membership. Committed offsets live IN the table, so handoff is
    conservation-exact at offset granularity by construction: floors
    only move via fenced commits.
  - ``plan_rebalance`` is a PURE function of (state, now): orphaned
    partitions (unowned/expired) are assigned to the least-loaded
    live members; surplus ownership beyond the fair share is revoked
    toward under-loaded members with an ``assigned`` hint, and the
    owner hands off gracefully (flush → commit → release). The
    Supervisor drives it from its monitor loop; the table applies it.

Concurrency: ``lease.table`` holds exactly two contract-dated edges —
the load-bearing state-file fsync (BLOCKING_ALLOW) and the audit-log
append through the shared ``eventlog.append`` lock (LOCK_ORDER_EDGES,
round 24: events must persist in the same transaction window that
produced them, StaleLeaseError included) — and table transactions never
call into supervisor or pipeline locks.
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from typing import Callable

from reporter_tpu.utils import eventlog, locks

_STATE = "leases.json"
_EVENTS = "lease_events.jsonl"
_LOCK = "lock"

DEFAULT_TTL_S = 5.0


class LeaseError(RuntimeError):
    """Lease-table contract violation (caller bug: floor regression,
    partition out of range, table shape mismatch)."""


class StaleLeaseError(LeaseError):
    """A commit carried a (member, epoch) that no longer holds the
    lease — the fencing rejection. ``partitions`` maps each rejected
    partition to a reason string."""

    def __init__(self, partitions: "dict[int, str]"):
        super().__init__(f"stale lease commit rejected: {partitions}")
        self.partitions = dict(partitions)


class _Txn:
    """One flock-serialized read-modify-write over the table state."""

    __slots__ = ("state", "events", "dirty")

    def __init__(self, state: dict):
        self.state = state
        self.events: list[dict] = []
        self.dirty = False

    def event(self, kind: str, **fields) -> None:
        self.events.append({"event": kind, **fields})


class LeaseTable:
    """File-backed, epoch-fenced partition lease table.

    Safe for concurrent use from many processes (flock) and many
    threads (named lock). All mutation goes through one transaction
    shape: take ``lease.table`` → flock EX → read state → mutate →
    atomic rewrite + append events → unlock.
    """

    def __init__(self, path: str, num_partitions: "int | None" = None,
                 ttl_s: float = DEFAULT_TTL_S,
                 clock: Callable[[], float] = time.time,
                 metrics=None):
        self.path = str(path)
        self.ttl_s = float(ttl_s)
        self.clock = clock
        # optional registry: feeds the r24 lease_reacquire SLO (the
        # observation runs OUTSIDE lease.table — the registry lock must
        # never nest under the table)
        self._metrics = metrics
        if self.ttl_s <= 0:
            raise LeaseError(f"lease ttl must be positive, got {ttl_s}")
        self._lock = locks.named_lock("lease.table")
        os.makedirs(self.path, exist_ok=True)
        self._state_path = os.path.join(self.path, _STATE)
        self._events_path = os.path.join(self.path, _EVENTS)
        self._lock_path = os.path.join(self.path, _LOCK)
        self._events = eventlog.EventLog(self._events_path)
        with self._txn() as t:
            st = t.state
            if not st:
                if num_partitions is None:
                    raise LeaseError(
                        f"no lease table at {self.path!r} and "
                        "num_partitions not given to create one")
                t.state.update({
                    "version": 1,
                    "num_partitions": int(num_partitions),
                    "members": {},
                    "partitions": {
                        str(p): {"owner": None, "epoch": 0,
                                 "expires": 0.0, "committed": 0,
                                 "assigned": None, "revoke": False}
                        for p in range(int(num_partitions))},
                })
                t.dirty = True
                t.event("create", num_partitions=int(num_partitions))
            elif (num_partitions is not None
                  and int(st["num_partitions"]) != int(num_partitions)):
                raise LeaseError(
                    f"lease table at {self.path!r} has "
                    f"{st['num_partitions']} partitions, caller expected "
                    f"{num_partitions}")
        self.num_partitions = int(self._read()["num_partitions"])

    # ---- transaction plumbing -------------------------------------------

    def _read(self) -> dict:
        try:
            with open(self._state_path, encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    def _txn(self):
        table = self

        class _Ctx:
            def __enter__(ctx):
                table._lock.acquire()
                ctx._fd = os.open(table._lock_path,
                                  os.O_CREAT | os.O_RDWR, 0o644)
                fcntl.flock(ctx._fd, fcntl.LOCK_EX)
                ctx._t = _Txn(table._read())
                return ctx._t

            def __exit__(ctx, exc_type, exc, tb):
                try:
                    if exc_type is None or isinstance(exc, StaleLeaseError):
                        # fencing rejections still persist their audit
                        # events + any commits applied before the raise
                        table._write(ctx._t)
                finally:
                    fcntl.flock(ctx._fd, fcntl.LOCK_UN)
                    os.close(ctx._fd)
                    table._lock.release()
                return False

        return _Ctx()

    def _write(self, t: _Txn) -> None:
        if t.dirty:
            tmp = self._state_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(t.state, f)
                f.flush()
                # Load-bearing: the lease file is the cross-process
                # ownership truth — a torn or reordered write could
                # hand one partition to two workers
                # (BLOCKING_ALLOW: lease.table, os.fsync).
                os.fsync(f.fileno())
            os.replace(tmp, self._state_path)
        if t.events:
            # the shared EventLog spelling (r24); runs while lease.table
            # is held — the contract-dated (lease.table, eventlog.append)
            # edge: audit events must land in the same transaction
            # window that produced them (incl. through StaleLeaseError)
            now = self.clock()
            self._events.extend({"t": now, **e} for e in t.events)

    def _ent(self, t: _Txn, partition: int) -> dict:
        ent = t.state["partitions"].get(str(int(partition)))
        if ent is None:
            raise LeaseError(f"partition {partition} out of range "
                             f"0..{t.state['num_partitions'] - 1}")
        return ent

    @staticmethod
    def _expired(ent: dict, now: float) -> bool:
        return ent["owner"] is not None and now > float(ent["expires"])

    # ---- the lease protocol ---------------------------------------------

    def acquire(self, member: str, partition: int,
                ttl_s: "float | None" = None) -> "int | None":
        """Try to take ``partition``. Returns the lease epoch on success
        (ownership change bumps it; re-acquiring one's own live lease
        renews and keeps it), None if another member holds it or it is
        assigned elsewhere by the rebalancer."""
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        reacquire_gap: "float | None" = None
        with self._txn() as t:
            ent = self._ent(t, partition)
            now = self.clock()
            if ent["owner"] == member and not self._expired(ent, now):
                ent["expires"] = now + ttl
                t.dirty = True
                return int(ent["epoch"])
            if ent["owner"] is not None and not self._expired(ent, now):
                return None
            hint = ent["assigned"]
            if hint is not None and hint != member:
                return None      # rebalancer reserved it for someone else
            prev = ent["owner"]
            if prev is not None:
                t.event("expired", partition=int(partition), member=prev,
                        epoch=int(ent["epoch"]))
                # expiry→takeover gap: how long the partition sat
                # unserved — the r24 lease_reacquire SLO's observation
                reacquire_gap = max(0.0, now - float(ent["expires"]))
            ent["epoch"] = int(ent["epoch"]) + 1
            ent["owner"] = member
            ent["expires"] = now + ttl
            ent["revoke"] = False
            ent["assigned"] = None
            t.dirty = True
            t.event("acquire", partition=int(partition), member=member,
                    epoch=int(ent["epoch"]),
                    committed=int(ent["committed"]),
                    takeover_from=prev)
            epoch = int(ent["epoch"])
        # observed AFTER the transaction exits: metrics.registry must
        # never nest under lease.table
        if reacquire_gap is not None and self._metrics is not None:
            self._metrics.observe("lease_reacquire_seconds",
                                  reacquire_gap)
        return epoch

    def renew(self, member: str, ttl_s: "float | None" = None) -> dict:
        """Heartbeat + one consistent view for ``member``: renew every
        live lease it holds, observe losses (strict expiry — an expired
        lease is cleared, never resurrected), and report what the
        rebalancer wants: ``revoke`` (hand off gracefully), ``assigned``
        (reserved for this member), ``orphans`` (free for anyone)."""
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        with self._txn() as t:
            now = self.clock()
            t.state["members"][member] = {"heartbeat": now}
            t.dirty = True
            owned: dict[int, int] = {}
            revoke: list[int] = []
            assigned: list[int] = []
            orphans: list[int] = []
            lost: list[int] = []
            for key, ent in sorted(t.state["partitions"].items(),
                                   key=lambda kv: int(kv[0])):
                p = int(key)
                if ent["owner"] == member:
                    if now > float(ent["expires"]):
                        # strict expiry: the lease is gone; clear the
                        # owner so the next acquire is a clean takeover
                        ent["owner"] = None
                        ent["revoke"] = False
                        lost.append(p)
                        t.event("lease_lost", partition=p, member=member,
                                epoch=int(ent["epoch"]))
                        continue
                    ent["expires"] = now + ttl
                    owned[p] = int(ent["epoch"])
                    if ent["revoke"]:
                        revoke.append(p)
                elif ent["owner"] is None or now > float(ent["expires"]):
                    if ent["assigned"] == member:
                        assigned.append(p)
                    elif ent["assigned"] is None:
                        orphans.append(p)
            return {"owned": owned, "revoke": revoke,
                    "assigned": assigned, "orphans": orphans,
                    "lost": lost}

    def commit_many(self, member: str,
                    updates: "dict[int, tuple[int, int]]") -> None:
        """Fenced floor movement: ``updates[p] = (epoch, offset)``.
        Every passing update applies (monotonic: equal floors are
        no-ops, regressions are a caller bug and raise ``LeaseError``);
        if ANY update fails the fence, ``StaleLeaseError`` is raised
        after the passing ones are applied, naming the rejected
        partitions."""
        if not updates:
            return
        rejected: dict[int, str] = {}
        with self._txn() as t:
            now = self.clock()
            for p, (epoch, offset) in sorted(updates.items()):
                ent = self._ent(t, p)
                if (ent["owner"] != member or int(ent["epoch"]) != int(epoch)
                        or now > float(ent["expires"])):
                    why = ("expired" if ent["owner"] == member
                           else f"owner={ent['owner']!r} "
                                f"epoch={ent['epoch']}")
                    rejected[int(p)] = why
                    t.event("commit_rejected", partition=int(p),
                            member=member, epoch=int(epoch), reason=why)
                    continue
                cur = int(ent["committed"])
                off = int(offset)
                if off < cur:
                    raise LeaseError(
                        f"commit regression on partition {p}: "
                        f"{off} < floor {cur} (member {member!r})")
                if off == cur:
                    continue
                ent["committed"] = off
                t.dirty = True
                t.event("commit", partition=int(p), member=member,
                        epoch=int(epoch), floor_from=cur, floor_to=off)
            if rejected:
                raise StaleLeaseError(rejected)

    def commit(self, member: str, partition: int, epoch: int,
               offset: int) -> None:
        self.commit_many(member, {int(partition): (int(epoch),
                                                   int(offset))})

    def release(self, member: str, partition: int, epoch: int,
                floor: "int | None" = None) -> bool:
        """Graceful handoff: optionally push a final fenced floor, then
        free the partition (keeping the epoch — the next owner bumps
        it). Returns False (with an audit event) if the lease was
        already lost."""
        with self._txn() as t:
            ent = self._ent(t, partition)
            now = self.clock()
            if (ent["owner"] != member or int(ent["epoch"]) != int(epoch)
                    or now > float(ent["expires"])):
                t.event("release_noop", partition=int(partition),
                        member=member, epoch=int(epoch))
                return False
            if floor is not None and int(floor) > int(ent["committed"]):
                t.event("commit", partition=int(partition), member=member,
                        epoch=int(epoch),
                        floor_from=int(ent["committed"]),
                        floor_to=int(floor))
                ent["committed"] = int(floor)
            ent["owner"] = None
            ent["revoke"] = False
            t.dirty = True
            t.event("release", partition=int(partition), member=member,
                    epoch=int(epoch))
            return True

    def apply_plan(self, plan: dict) -> None:
        """Apply a ``plan_rebalance`` output: ``assign`` reserves
        orphans ({partition: member}), ``revoke`` flags owned
        partitions for graceful handoff with a destination hint
        ({partition: member}), ``clear`` drops stale hints."""
        if not (plan.get("assign") or plan.get("revoke")
                or plan.get("clear")):
            return
        with self._txn() as t:
            for p, m in sorted(plan.get("assign", {}).items()):
                ent = self._ent(t, p)
                if ent["assigned"] != m:
                    ent["assigned"] = m
                    t.dirty = True
                    t.event("assign", partition=int(p), member=m)
            for p, m in sorted(plan.get("revoke", {}).items()):
                ent = self._ent(t, p)
                if ent["owner"] is not None and not ent["revoke"]:
                    ent["revoke"] = True
                    ent["assigned"] = m
                    t.dirty = True
                    t.event("revoke_requested", partition=int(p),
                            member=ent["owner"], to=m)
            for p in plan.get("clear", ()):
                if p in plan.get("assign", {}):
                    continue             # fresh assignment wins the slot
                ent = self._ent(t, p)
                if ent["assigned"] is not None and ent["owner"] is None:
                    ent["assigned"] = None
                    t.dirty = True

    # ---- read surfaces ---------------------------------------------------

    def state(self) -> dict:
        """A point-in-time copy of the whole table state."""
        with self._txn() as t:
            return json.loads(json.dumps(t.state))

    def committed(self, partition: int) -> int:
        with self._txn() as t:
            return int(self._ent(t, partition)["committed"])

    def floors(self) -> "list[int]":
        """Committed floors, indexed by partition."""
        with self._txn() as t:
            parts = t.state["partitions"]
            return [int(parts[str(p)]["committed"])
                    for p in range(int(t.state["num_partitions"]))]

    def events(self) -> "list[dict]":
        return self._events.read()


def plan_rebalance(state: dict, now: float, member_ttl_s: float,
                   running: "set[str] | None" = None) -> dict:
    """Pure rebalance planner over a ``LeaseTable.state()`` snapshot.

    Live members = heartbeat within ``member_ttl_s``. ``running``
    narrows that with out-of-band knowledge (the supervisor's process
    table): a member the caller KNOWS is dead must not receive
    assignments during its heartbeat grace window — a stale hint to a
    corpse pins the partition against every other acquirer until a
    later pass clears it (measured: +8 s on the orphan-reacquire path).
    Orphans (unowned or lease-expired, no standing hint) go to the
    least-loaded live member; when the spread between the most- and
    least-loaded members is ≥ 2 partitions, one surplus partition is
    revoked toward the least-loaded (repeat until fair). Revoke-pending
    partitions count toward their DESTINATION so a slow handoff is
    never double-revoked. Deterministic: ties break on member name,
    partitions scan in order.
    """
    members = state.get("members", {})
    live = sorted(m for m, md in members.items()
                  if now - float(md.get("heartbeat", 0.0)) <= member_ttl_s
                  and (running is None or m in running))
    plan: dict = {"assign": {}, "revoke": {}, "clear": []}
    if not live:
        return plan
    load = {m: 0 for m in live}
    orphans: list[int] = []
    owner_of: dict[int, str] = {}
    revocable: dict[str, list[int]] = {m: [] for m in live}
    for key, ent in sorted(state["partitions"].items(),
                           key=lambda kv: int(kv[0])):
        p = int(key)
        hint = ent["assigned"] if ent["assigned"] in load else None
        alive = ent["owner"] is not None and now <= float(ent["expires"])
        if alive:
            owner_of[p] = ent["owner"]
            if ent["revoke"] and hint is not None:
                load[hint] += 1          # handoff in flight: count at dest
            elif ent["owner"] in load:
                load[ent["owner"]] += 1
                if not ent["revoke"]:
                    revocable[ent["owner"]].append(p)
            # owner alive lease-wise but heartbeat-stale: leave it —
            # expiry frees it without a second mechanism
        elif hint is not None:
            load[hint] += 1              # standing assignment: honor it
        else:
            if ent["assigned"] is not None:
                plan["clear"].append(p)  # hint to a dead member: drop it
            orphans.append(p)
    for p in orphans:
        m = min(live, key=lambda x: (load[x], x))
        plan["assign"][p] = m
        load[m] += 1
    while True:
        hi = max(live, key=lambda x: (load[x], x))
        lo = min(live, key=lambda x: (load[x], x))
        if load[hi] - load[lo] < 2 or not revocable[hi]:
            break
        p = revocable[hi].pop()
        plan["revoke"][p] = lo
        load[hi] -= 1
        load[lo] += 1
    return plan


class LeaseRunner:
    """Worker-side lease protocol driver for one StreamPipeline.

    ``sync()`` (throttled to ~ttl/4) renews, observes losses (buffered
    rows for a lost partition are DISCARDED — the next owner replays
    them from the table floor; keeping them would double-publish),
    hands off revoked partitions gracefully (flush → fenced final
    commit → release), and adopts assigned/orphaned partitions at
    their committed floors. ``push_commits()`` forwards the pipeline's
    floor movement through the fence after every step.
    """

    def __init__(self, table: LeaseTable, member: str, pipeline,
                 poll_s: "float | None" = None):
        self.table = table
        self.member = member
        self.pipe = pipeline
        self.poll_s = (max(0.05, table.ttl_s / 4.0)
                       if poll_s is None else float(poll_s))
        self._next_sync = 0.0
        self.epochs: dict[int, int] = {}
        self._pushed: dict[int, int] = {}
        self.stats = {"acquired": 0, "lost": 0, "revoked": 0,
                      "stale_commits": 0, "discarded_points": 0}

    def sync(self, force: bool = False) -> bool:
        """One membership round-trip; returns True if the owned set
        changed."""
        now = time.monotonic()
        if not force and now < self._next_sync:
            return False
        self._next_sync = now + self.poll_s
        view = self.table.renew(self.member)
        changed = False
        for p in [p for p in self.epochs if p not in view["owned"]]:
            self._drop(p)                     # lease lost: discard rows
            self.stats["lost"] += 1
            changed = True
        for p in view["revoke"]:
            if p in self.epochs:
                self._handoff(p)
                changed = True
        for p in view["assigned"] + view["orphans"]:
            epoch = self.table.acquire(self.member, p)
            if epoch is None:
                continue                      # raced another member
            self.pipe.adopt_partition(p, self.table.committed(p))
            self.epochs[p] = epoch
            self._pushed[p] = self.pipe.committed[p]
            self.stats["acquired"] += 1
            changed = True
        return changed

    def push_commits(self) -> None:
        """Forward pipeline floor movement through the epoch fence."""
        updates = {p: (e, int(self.pipe.committed[p]))
                   for p, e in self.epochs.items()
                   if int(self.pipe.committed[p]) > self._pushed[p]}
        if not updates:
            return
        try:
            self.table.commit_many(self.member, updates)
            bad: dict[int, str] = {}
        except StaleLeaseError as exc:
            bad = exc.partitions
        for p in updates:
            if p in bad:
                self._drop(p)
                self.stats["stale_commits"] += 1
            else:
                self._pushed[p] = updates[p][1]

    def _handoff(self, p: int) -> None:
        """Graceful revoke: flush the partition's rows through the
        matcher, push the final floor, release."""
        self.pipe.release_partition(p, flush=True)
        self.table.release(self.member, p, self.epochs[p],
                           floor=int(self.pipe.committed[p]))
        self.epochs.pop(p, None)
        self._pushed.pop(p, None)
        self.stats["revoked"] += 1

    def _drop(self, p: int) -> None:
        """Lost lease: drop the partition WITHOUT flushing — its
        unflushed rows replay at the new owner from the table floor;
        publishing them here would duplicate reports."""
        self.stats["discarded_points"] += self.pipe.release_partition(
            p, flush=False)
        self.epochs.pop(p, None)
        self._pushed.pop(p, None)

    def shutdown(self) -> None:
        """Graceful exit: hand off everything still held."""
        for p in sorted(self.epochs):
            self._handoff(p)

    def lag(self) -> int:
        """GLOBAL backlog: queue end offsets minus table floors over ALL
        partitions — the lease-mode drain condition (a worker owning
        nothing must not exit while other partitions still have
        uncommitted records that could rebalance onto it)."""
        floors = self.table.floors()
        return sum(max(0, self.pipe.queue.end_offset(p) - floors[p])
                   for p in range(self.table.num_partitions))
