"""Topology supervisor — N worker subprocesses run, watched, restarted,
and OBSERVED as one unit.

DISTRIBUTED.md's scale-out design ("several workers over one broker
directory, disjoint partition subsets") has always been spawnable by
hand; what never existed is the thing an operator actually runs: a
parent that owns the topology. This module is that parent:

  - spawns the members — N ``streaming.__main__`` worker subprocesses
    plus a fake datastore sink (``ReportSink``) and the supervisor's
    own WSGI observability face — as one unit with one workdir;
  - tails each member's spooled metrics/health snapshot
    (distributed/aggregate.py; workers write them atomically when
    ``RTPU_TOPO_SNAPSHOT_DIR`` is set — no inter-process HTTP, a wedged
    member can't stall the scrape);
  - detects member DEATH (a SIGKILL from an r9 fault plan, an OOM kill,
    a crash — any nonzero/signal exit while not asked to stop), counts
    it, stamps it into the topology event log
    (``topology_events.jsonl``), dumps ONE flight-recorder post-mortem
    per death transition (one event, one dump — the r15 rule), and
    restarts the member per policy (``max_restarts`` each);
  - serves ``/metrics`` (the fleet-wide merged exposition: counters
    summed, labeled series unioned, fixed-bucket histograms summed
    bucket-wise, gauges worker-labeled) and ``/health`` (per-member
    liveness, restart counts, snapshot lag) over stdlib WSGI.

Supervisor bookkeeping publishes into its OWN registry (``topo_*``
gauges/counters) which merges into the exposition as member
"supervisor", so the fleet view and the watcher's view arrive in one
scrape.

Elastic membership (round 23): with a ``lease_dir`` the supervisor
drives the partition-lease rebalancer (distributed/lease.py) from its
monitor loop — member join (``add_member``), leave (``remove_member``),
or lease expiry orphans partitions, and the planner reassigns them to
the least-loaded live workers. All lease-table I/O runs OUTSIDE the
supervisor locks (the table has its own leaf lock + flock).

SLO plane (round 24): the supervisor evaluates the SAME committed
:data:`~reporter_tpu.obs.slo.DEFAULT_SLOS` the workers do, but over the
r19 ``merge_exports`` document — burn is linear over counters/buckets,
so the topology-wide burn rate is one number equal to the per-worker
sum by construction. Its alert ledger is ``alerts.jsonl`` in the
workdir; ``/slo`` serves the full status and ``/health`` the roll-up.

Locking discipline (round 14): the member table rides
``supervisor.members``; the sink counter rides ``supervisor.sink``; the
event log rides the shared ``eventlog.append`` class (round 24 — the
one JSONL spelling, utils/eventlog.py). All three are LEAF locks —
spawning (``subprocess.Popen`` is a patched blocking entry point),
post-mortems, gauge publication, and snapshot merging all run OUTSIDE
them by construction, so the topology layer adds zero blocking-allow
entries to the concurrency contract.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any

from reporter_tpu.distributed import aggregate
from reporter_tpu.obs import slo as obs_slo
from reporter_tpu.utils import eventlog, locks, metrics, tracing

__all__ = ["MemberSpec", "Supervisor", "ReportSink", "worker_member"]

# env keys the supervisor sets for its workers (documented in README's
# env table; streaming/__main__.py reads them as CLI-flag twins)
ENV_SNAPSHOT_DIR = "RTPU_TOPO_SNAPSHOT_DIR"
ENV_SNAPSHOT_INTERVAL = "RTPU_TOPO_SNAPSHOT_INTERVAL_S"
ENV_MEMBER = "RTPU_TOPO_MEMBER"


@dataclasses.dataclass
class MemberSpec:
    """One supervised subprocess: the command line plus env overrides
    merged over the supervisor's base env at every (re)spawn."""

    name: str
    cmd: "list[str]"
    env: "dict[str, str] | None" = None


class _Member:
    """Runtime state of one member (guarded by supervisor.members)."""

    __slots__ = ("spec", "proc", "deaths", "restarts", "clean_exits",
                 "started_at", "stdout_tail", "exit_report", "stopping",
                 "respawning")

    def __init__(self, spec: MemberSpec):
        self.spec = spec
        self.proc: "subprocess.Popen | None" = None
        self.deaths = 0
        self.restarts = 0
        self.clean_exits = 0
        self.started_at = 0.0
        self.stdout_tail: "str" = ""
        self.exit_report: "dict | None" = None
        self.stopping = False
        # death claimed, replacement not yet spawned — drained() must
        # read this window as NOT drained
        self.respawning = False


class ReportSink:
    """The fake datastore of a topology: a threaded HTTP sink counting
    every POSTed report row (and keeping the multiset key the r9
    recovery accounting uses), so workers publish somewhere real
    without an external service. ``url`` is what DATASTORE_URL gets.
    THE one fake-datastore implementation (r19): bench.py's
    ``_report_sink`` delegates here — the multiset key and the
    ``t_first/t_last`` clock (``time.perf_counter``, diffable against
    the bench legs' own timestamps) must not fork."""

    def __init__(self):
        from collections import Counter
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._lock = locks.named_lock("supervisor.sink")
        self.reports: "Any" = Counter()
        self.rows = 0
        self.posts = 0
        self.t_first: "float | None" = None
        self.t_last: "float | None" = None
        sink = self

        class _H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    body = {}
                now = time.perf_counter()
                with sink._lock:
                    for r in body.get("reports", ()):
                        key = (r.get("id"), r.get("next_id"),
                               round(float(r.get("t0", 0.0)), 2),
                               round(float(r.get("t1", 0.0)), 2))
                        sink.reports[key] += 1
                        sink.rows += 1
                    sink.posts += 1
                    if sink.t_first is None:
                        sink.t_first = now
                    sink.t_last = now
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):      # keep supervisor output clean
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}/"

    def stats(self) -> dict:
        with self._lock:
            return {"rows": self.rows, "posts": self.posts,
                    "t_first": self.t_first, "t_last": self.t_last}

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def worker_member(name: str, tiles: str, broker_dir: str, workdir: str,
                  partitions: "list[int] | None" = None,
                  columnar: bool = False,
                  config: "str | None" = None,
                  exit_on_drain: bool = True,
                  extra_args: "list[str] | None" = None,
                  env: "dict[str, str] | None" = None,
                  lease_dir: "str | None" = None,
                  lease_ttl_s: "float | None" = None) -> MemberSpec:
    """MemberSpec for one ``streaming.__main__`` matcher worker — the
    standard member of a topology. Each worker gets its own checkpoint
    under the workdir (restarts replay from its committed offsets, the
    r9 recovery mechanism). With ``lease_dir`` the worker takes its
    partitions from the lease table instead of a static ``partitions``
    list (the two are mutually exclusive)."""
    if lease_dir and partitions is not None:
        raise ValueError("lease_dir and a static partitions list are "
                         "mutually exclusive (the lease table owns "
                         "assignment)")
    cmd = [sys.executable, "-m", "reporter_tpu.streaming",
           "--tiles", tiles, "--broker-dir", broker_dir,
           "--checkpoint", os.path.join(workdir, f"{name}.ckpt"),
           "--checkpoint-interval", "0.5", "--poll-interval", "0.01"]
    if columnar:
        cmd.append("--columnar")
    if config:
        cmd += ["--config", config]
    if exit_on_drain:
        cmd.append("--exit-on-drain")
    if partitions is not None:
        cmd += ["--partitions"] + [str(p) for p in partitions]
    if lease_dir:
        cmd += ["--lease-dir", lease_dir, "--member", name]
        if lease_ttl_s is not None:
            cmd += ["--lease-ttl", str(lease_ttl_s)]
    cmd += list(extra_args or ())
    return MemberSpec(name=name, cmd=cmd, env=env)


class Supervisor:
    """Spawn, watch, restart, aggregate. See the module docstring."""

    def __init__(self, members: "list[MemberSpec]", workdir: str,
                 restart: bool = True, max_restarts: int = 2,
                 poll_s: float = 0.05,
                 start_sink: bool = True,
                 base_env: "dict[str, str] | None" = None,
                 lease_dir: "str | None" = None,
                 rebalance_interval_s: float = 0.25):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.snapshot_dir = os.path.join(workdir, "snapshots")
        self.events_path = os.path.join(workdir, "topology_events.jsonl")
        self.restart = bool(restart)
        self.max_restarts = int(max_restarts)
        self.poll_s = float(poll_s)
        self._members_lock = locks.named_lock("supervisor.members")
        self._events = eventlog.EventLog(self.events_path)
        self._members: "dict[str, _Member]" = {
            s.name: _Member(s) for s in members}
        self._base_env = dict(base_env or {})
        self._stop = threading.Event()
        self._stopped = False
        self._monitor: "threading.Thread | None" = None
        self._http_server = None
        self.sink = ReportSink() if start_sink else None
        # the supervisor's own registry: merged into the exposition as
        # member "supervisor", so liveness/restart counters arrive in
        # the same scrape as the fleet series
        self.metrics = metrics.MetricsRegistry()
        self.started_at: "float | None" = None
        # Elastic membership (round 23): the lease table must already
        # exist (its creator fixes num_partitions); opening it here
        # fails fast on a misconfigured dir. All table I/O runs outside
        # the supervisor locks.
        self._lease_table = None
        self._rebalance_interval = float(rebalance_interval_s)
        self._last_rebalance = 0.0
        if lease_dir is not None:
            from reporter_tpu.distributed.lease import LeaseTable
            self._lease_table = LeaseTable(lease_dir)
        # Round-24 SLO plane: the same committed specs the workers run,
        # evaluated over the MERGED export — topology-wide burn is one
        # number. sample_gauges=False: members already folded their own
        # gauge levels into the synthetic sample counters, and the merge
        # carries them; sampling the worker-labeled merged gauges here
        # would double-count.
        self.alerts_path = os.path.join(workdir, "alerts.jsonl")
        self.slo: "obs_slo.SloEvaluator | None" = None
        if obs_slo.enabled():
            self.slo = obs_slo.SloEvaluator(
                self.metrics,
                source=lambda: self.merged_registry().export(),
                ledger=eventlog.EventLog(self.alerts_path),
                sample_gauges=False)

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "Supervisor":
        self.started_at = time.time()
        self._event("topology_start",
                    members=sorted(self._members),
                    restart=self.restart, max_restarts=self.max_restarts)
        for name in sorted(self._members):
            self._spawn(name, reason="start")
        self._publish_gauges()
        self._stop.clear()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="topology-supervisor")
        self._monitor.start()
        return self

    def _member_env(self, spec: MemberSpec) -> dict:
        env = dict(os.environ)
        # a `python -m reporter_tpu.streaming` member must import the
        # package REGARDLESS of the supervisor's cwd (found by the r19
        # CLI acceptance test running bench from a temp dir): prepend
        # the directory that contains this very package
        import reporter_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(reporter_tpu.__file__)))
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(
                                 os.pathsep)
        env[ENV_SNAPSHOT_DIR] = self.snapshot_dir
        env[ENV_MEMBER] = spec.name
        env.setdefault(ENV_SNAPSHOT_INTERVAL, "0.5")
        if self.sink is not None:
            # SET, not setdefault: when the supervisor owns a sink, an
            # inherited operator DATASTORE_URL must not silently
            # redirect the topology's reports to a REAL datastore
            # (base_env/spec.env below stay the deliberate overrides)
            env["DATASTORE_URL"] = self.sink.url
        env.update(self._base_env)
        env.update(spec.env or {})
        return env

    def _spawn(self, name: str, reason: str) -> None:
        """(Re)spawn one member. Popen is a patched blocking entry
        point (round 14) — it must never run under a named lock, so the
        table update happens after the process exists. A respawn that
        races stop() (the monitor mid-Popen while the caller tears
        down) must not leak a live worker nothing will ever terminate:
        the stopped flag is re-checked under the lock AFTER the Popen,
        and a loser child is killed instead of installed."""
        m = self._members[name]
        if self._stopped or m.stopping:
            with self._members_lock:
                m.respawning = False
            return
        proc = subprocess.Popen(
            m.spec.cmd, env=self._member_env(m.spec),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        with self._members_lock:
            if self._stopped or m.stopping:
                m.respawning = False
                install = False
            else:
                m.proc = proc
                m.started_at = time.time()
                m.respawning = False
                install = True
        if not install:
            proc.kill()
            proc.communicate()
            self._event("member_spawn_aborted", member=name,
                        reason="stopping")
            return
        self._event("member_spawn", member=name, pid=proc.pid,
                    reason=reason)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_s)

    def poll_once(self) -> None:
        """One supervision pass (the monitor thread's body, ALSO called
        directly by deterministic tests and the bench leg while the
        monitor runs): reap exits, classify death-vs-clean-exit,
        restart per policy, refresh gauges. Exits are CLAIMED under the
        members lock (``m.proc is proc`` then cleared) so two
        concurrent passes can never double-count one death, spawn two
        replacements onto the same partitions, or double-dump the
        post-mortem."""
        with self._members_lock:
            items = list(self._members.items())
        respawn: "list[str]" = []
        for name, m in items:
            proc = m.proc
            if proc is None or proc.poll() is None:
                continue
            died = proc.returncode != 0 and not m.stopping
            with self._members_lock:
                if m.proc is not proc:
                    continue            # another pass claimed this exit
                m.proc = None
                if died and self.restart \
                        and m.restarts < self.max_restarts:
                    m.respawning = True
            rc = proc.returncode
            tail = ""
            if proc.stdout is not None:
                try:
                    tail = proc.stdout.read() or ""
                except (OSError, ValueError):
                    tail = ""
                proc.stdout.close()
            report = _last_json_line(tail)
            with self._members_lock:
                m.stdout_tail = tail[-4096:]
                if report is not None:
                    m.exit_report = report
                if died:
                    m.deaths += 1
                    allow = m.respawning
                    if allow:
                        m.restarts += 1
                else:
                    m.clean_exits += 1
                    allow = False
            # event log + post-mortem + counters OUTSIDE the table lock
            if died:
                self.metrics.count("topo_deaths")
                self._event("member_death", member=name, pid=proc.pid,
                            returncode=rc, will_restart=allow,
                            uptime_s=round(time.time() - m.started_at, 3))
                # one death transition, one flight-recorder dump (the
                # r15 one-event-one-dump rule); bounded + no-op unless
                # tracing is configured with a dump dir
                tracing.post_mortem("worker_death", failing=name,
                                    member=name, returncode=rc)
                if allow:
                    respawn.append(name)
                else:
                    self._event("restart_budget_exhausted", member=name,
                                deaths=m.deaths, restarts=m.restarts)
            else:
                self._event("member_exit", member=name, pid=proc.pid,
                            returncode=rc)
        for name in respawn:
            self.metrics.count("topo_restarts")
            self._spawn(name, reason="restart")
        self._maybe_rebalance()
        if self.slo is not None:
            self.slo.tick()         # self-throttled; outside all locks
        self._publish_gauges()

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful teardown: SIGTERM members (their CLI checkpoints and
        drains on it), join, stop the monitor/sink/HTTP face.
        IDEMPOTENT — error-path finallys may call it after a normal
        stop: the repeat is a safe no-op that still leaves an audit
        event (round 23 satellite — silent no-ops hid double-teardown
        bugs)."""
        if self._stopped:
            self._event("stop_noop")
            return
        self._stopped = True
        with self._members_lock:
            items = list(self._members.items())
            for _, m in items:
                m.stopping = True
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=max(1.0, self.poll_s * 4))
        for name, m in items:
            proc = m.proc
            if proc is None:
                continue
            proc.terminate()
            try:
                out, _ = proc.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            report = _last_json_line(out or "")
            with self._members_lock:
                m.proc = None
                m.stdout_tail = (out or "")[-4096:]
                if report is not None:
                    m.exit_report = report
        self._event("topology_stop")
        if self._http_server is not None:
            self._http_server.shutdown()
            self._http_server.server_close()
            self._http_server = None
        if self.sink is not None:
            self.sink.close()

    # ---- elastic membership (round 23) -----------------------------------

    def add_member(self, spec: MemberSpec, reason: str = "join") -> None:
        """Join a new member to a RUNNING topology. With a lease table
        the newcomer heartbeats, the next rebalance pass revokes
        surplus partitions toward it, and it picks them up at their
        committed floors — scale-out under live load."""
        if self._stopped:
            raise RuntimeError("supervisor is stopped")
        with self._members_lock:
            if spec.name in self._members:
                raise ValueError(f"member {spec.name!r} already exists")
            self._members[spec.name] = _Member(spec)
        self._event("member_join", member=spec.name)
        self._spawn(spec.name, reason=reason)
        self._publish_gauges()

    def remove_member(self, name: str,
                      timeout: float = 30.0) -> "dict | None":
        """Graceful leave: SIGTERM the member (its CLI hands off leased
        partitions and checkpoints on it), wait, and let the normal
        claim path reap the exit. The member's history stays in the
        table. No-op (with an event) for an unknown name. Returns the
        member's exit report, if it printed one."""
        with self._members_lock:
            m = self._members.get(name)
            if m is not None:
                m.stopping = True
                proc = m.proc
        if m is None:
            self._event("member_remove_noop", member=name)
            return None
        self._event("member_leave", member=name)
        if proc is not None and proc.poll() is None:
            proc.terminate()
        self.wait_member(name, timeout=timeout)
        self.poll_once()
        self._publish_gauges()
        with self._members_lock:
            return m.exit_report

    def _maybe_rebalance(self) -> None:
        if self._lease_table is None or self._stopped:
            return
        now = time.monotonic()
        if now - self._last_rebalance < self._rebalance_interval:
            return
        self._last_rebalance = now
        self.rebalance_once()

    def rebalance_once(self) -> dict:
        """One planner pass over the lease table (public so tests and
        the bench leg can force one deterministically). The table
        transaction takes its own leaf lock and the planner is pure;
        the members lock is held only to snapshot the process table.
        Members whose heartbeat is older than 2× the lease TTL read as
        dead — and the supervisor's own process table SHORTENS that:
        a member it watched die stops receiving assignments
        immediately, not at heartbeat expiry."""
        table = self._lease_table
        if table is None:
            return {}
        from reporter_tpu.distributed.lease import plan_rebalance
        with self._members_lock:
            running = {name for name, m in self._members.items()
                       if m.proc is not None and m.proc.poll() is None}
        st = table.state()
        now = table.clock()
        orphans = sum(1 for ent in st["partitions"].values()
                      if ent["owner"] is None
                      or now > float(ent["expires"]))
        self.metrics.gauge("topo_lease_orphans", float(orphans))
        plan = plan_rebalance(st, now, member_ttl_s=table.ttl_s * 2.0,
                              running=running)
        if plan["assign"] or plan["revoke"] or plan["clear"]:
            table.apply_plan(plan)
        if plan["assign"] or plan["revoke"]:
            self.metrics.count("topo_rebalances")
            self._event(
                "rebalance",
                assign={str(p): m
                        for p, m in sorted(plan["assign"].items())},
                revoke={str(p): m
                        for p, m in sorted(plan["revoke"].items())})
        return plan

    # ---- chaos hooks -----------------------------------------------------

    def kill_member(self, name: str) -> "int | None":
        """A REAL SIGKILL (no drain, no checkpoint flush) — the bench
        topology leg's mid-soak fault. The monitor sees an unexpected
        death and runs the normal detect→count→post-mortem→restart
        path; nothing is pre-acknowledged here. Killing an unknown or
        already-exited member is a safe no-op that records an event
        (round 23 satellite)."""
        with self._members_lock:
            m = self._members.get(name)
            proc = m.proc if m is not None else None
        if proc is None or proc.poll() is not None:
            self._event("kill_noop", member=name)
            return None
        proc.kill()
        return proc.pid

    def wait_member(self, name: str, timeout: float = 60.0) -> bool:
        """Block until a member's process object exits (poll-based; the
        monitor thread still owns the reaping)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            with self._members_lock:
                m = self._members.get(name)
                proc = m.proc if m is not None else None
            if proc is None or proc.poll() is not None:
                return True
            time.sleep(0.01)
        return False

    def drained(self) -> bool:
        """Every member is done (no live process, no pending restart) —
        the topology's natural end under --exit-on-drain. A member
        whose death is claimed-but-not-respawned, or whose exited
        process hasn't been reaped yet but WILL be restarted, reads as
        NOT drained: a caller tearing down in that window would race
        the monitor's replacement spawn."""
        with self._members_lock:
            for m in self._members.values():
                if m.respawning:
                    return False
                proc = m.proc
                if proc is None:
                    continue
                if proc.poll() is None:
                    return False        # still running
                if proc.returncode != 0 and not m.stopping \
                        and self.restart \
                        and m.restarts < self.max_restarts:
                    return False        # unreaped death, restart pending
            return True

    # ---- observability ---------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        """Append one line to the topology event log (the r24 shared
        EventLog spelling: append+flush, torn-tail truncation at
        reopen)."""
        self._events.append({"t": round(time.time(), 3), "event": kind,
                             **fields})

    def events(self) -> "list[dict]":
        return self._events.read()

    def _publish_gauges(self) -> None:
        with self._members_lock:
            alive = sum(1 for m in self._members.values()
                        if m.proc is not None and m.proc.poll() is None)
            total = len(self._members)
        self.metrics.gauge("topo_members", total)
        self.metrics.gauge("topo_members_alive", alive)

    def exit_reports(self) -> "dict[str, dict | None]":
        """member → the last JSON line its most recent incarnation
        printed at exit (the worker CLI's stats report), or None while
        alive / when it died without one (a SIGKILLed member's report
        is its RESTARTED incarnation's)."""
        with self._members_lock:
            return {name: m.exit_report
                    for name, m in self._members.items()}

    def snapshots(self) -> "dict[str, dict]":
        return aggregate.load_dir(self.snapshot_dir)

    def merged_registry(self):
        """Fleet registry = member snapshots + the supervisor's own
        export (member "supervisor")."""
        snaps = self.snapshots()
        exports = {m: (doc.get("metrics") or {})
                   for m, doc in snaps.items()}
        exports["supervisor"] = self.metrics.export()
        return metrics.merge_exports(exports)

    def metrics_text(self) -> str:
        return self.merged_registry().render_prometheus()

    def health(self) -> dict:
        snaps = self.snapshots()
        members: "dict[str, dict]" = {}
        with self._members_lock:
            items = list(self._members.items())
        now = time.time()
        snap_health = aggregate.member_health(snaps, now=now)
        for name, m in items:
            proc = m.proc
            members[name] = {
                "alive": bool(proc is not None and proc.poll() is None),
                "pid": (proc.pid if proc is not None else None),
                "deaths": m.deaths,
                "restarts": m.restarts,
                "clean_exits": m.clean_exits,
                **snap_health.get(name, {"snapshot_age_s": None,
                                         "seq": None}),
            }
        out: "dict[str, Any]" = {
            "status": ("ok" if all(v["alive"] or v["clean_exits"]
                                   for v in members.values())
                       else "degraded"),
            "members": members,
            "deaths_total": int(self.metrics.value("topo_deaths")),
            "restarts_total": int(self.metrics.value("topo_restarts")),
            "uptime_seconds": (None if self.started_at is None
                               else round(now - self.started_at, 3)),
        }
        if self.sink is not None:
            out["sink"] = self.sink.stats()
        if self.slo is not None:
            out["slo"] = self.slo.health()
        return out

    # ---- WSGI face -------------------------------------------------------

    def wsgi(self, environ: dict, start_response):
        """The supervisor's observability endpoint: GET /metrics (the
        merged Prometheus exposition) and GET /health (liveness +
        restart counts + snapshot lag)."""
        path = environ.get("PATH_INFO", "/")
        if environ.get("REQUEST_METHOD") != "GET":
            return _respond(start_response, "405 Method Not Allowed",
                            b"{}", "application/json")
        if path == "/metrics":
            return _respond(start_response, "200 OK",
                            self.metrics_text().encode(),
                            "text/plain; version=0.0.4")
        if path == "/health":
            return _respond(start_response, "200 OK",
                            json.dumps(self.health()).encode(),
                            "application/json")
        if path == "/slo":
            body = (self.slo.status() if self.slo is not None
                    else {"enabled": False})
            return _respond(start_response, "200 OK",
                            json.dumps(body).encode(),
                            "application/json")
        return _respond(start_response, "404 Not Found", b"{}",
                        "application/json")

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the WSGI face on a daemon thread; returns the server
        (its bound port at ``server.server_address[1]``)."""
        from wsgiref.simple_server import (WSGIRequestHandler, WSGIServer,
                                           make_server)

        from socketserver import ThreadingMixIn

        class _Srv(ThreadingMixIn, WSGIServer):
            daemon_threads = True

        class _Quiet(WSGIRequestHandler):
            def log_message(self, *a):
                pass

        self._http_server = make_server(host, port, self.wsgi,
                                        server_class=_Srv,
                                        handler_class=_Quiet)
        threading.Thread(target=self._http_server.serve_forever,
                         daemon=True).start()
        return self._http_server


def _last_json_line(text: str) -> "dict | None":
    for line in reversed(text.strip().splitlines()):
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def _respond(start_response, status: str, body: bytes, ctype: str):
    start_response(status, [("Content-Type", ctype),
                            ("Content-Length", str(len(body)))])
    return [body]
