"""reporter_tpu — a TPU-native probe→OSMLR map-matching framework.

A ground-up re-design of the capabilities of Open Traffic Reporter
(burritojustice/reporter) plus the native Valhalla/Meili + OSMLR machinery it
drives (see SURVEY.md §0–§2; the reference mount was empty, so citations are to
SURVEY.md sections rather than file:line).

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

  service/    HTTP ``POST /report`` endpoint, per-uuid partial-trace cache,
              segment filter, datastore publisher            (reference L6/L4)
  streaming/  replayable ingest queue + staged worker pipeline (Kafka analog, L5)
  matcher/    ``SegmentMatcher`` backend boundary:
              ``matcher_backend={reference_cpu, jax}``        (reference L3)
  ops/        JAX kernels: vmapped point→polyline kNN, emission/transition,
              ``lax.scan`` Viterbi                            (reference L2, Meili)
  tiles/      offline tile compiler → flat padded device arrays; OSMLR
              chaining + association; reachability tables     (reference L1/L0)
  parallel/   ``jax.sharding`` Mesh: batch data-parallelism and multi-city
              tile sharding over ICI                          (replaces Kafka scale-out)
  netgen/     road-network sources: synthetic cities, OSM XML parser,
              probe-trace synthesis with ground truth
"""

__version__ = "0.1.0"


def __getattr__(name):  # lazy top-level API (avoids importing jax on
    _api = {            # package import; heavy modules load on first use)
        "Config": ("reporter_tpu.config", "Config"),
        "CompilerParams": ("reporter_tpu.config", "CompilerParams"),
        "MatcherParams": ("reporter_tpu.config", "MatcherParams"),
        "SegmentMatcher": ("reporter_tpu.matcher.api", "SegmentMatcher"),
        "MatchBatch": ("reporter_tpu.matcher.api", "MatchBatch"),
        "Trace": ("reporter_tpu.matcher.api", "Trace"),
        "TileSet": ("reporter_tpu.tiles.tileset", "TileSet"),
        "compile_network": ("reporter_tpu.tiles.compiler", "compile_network"),
        "plan_staging": ("reporter_tpu.tiles.capacity", "plan_staging"),
        "generate_city": ("reporter_tpu.netgen.synthetic", "generate_city"),
        "parse_osm_xml": ("reporter_tpu.netgen.osm_xml", "parse_osm_xml"),
        "make_app": ("reporter_tpu.service.app", "make_app"),
        "make_router": ("reporter_tpu.service.router", "make_router"),
        "make_fleet_router": ("reporter_tpu.fleet.router",
                              "make_fleet_router"),
        "FleetConfig": ("reporter_tpu.fleet.residency", "FleetConfig"),
        "MetroSLO": ("reporter_tpu.fleet.router", "MetroSLO"),
        "KafkaProbeConsumer": ("reporter_tpu.streaming.kafka_adapter",
                               "KafkaProbeConsumer"),
    }
    if name in _api:
        import importlib

        mod, attr = _api[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'reporter_tpu' has no attribute {name!r}")
