"""Data-parallel matcher: batch axis sharded over the mesh (config 3).

Tile arrays are replicated to every device once (they are read-only); each
batch dispatch shards traces across "dp" × "tile" as one flat data axis — no
cross-device communication in the forward match at all, which is exactly why
DP is the first-choice scaling axis for this workload (SURVEY.md §2.3).
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.match import MatchOutput, match_trace
from reporter_tpu.tiles.tileset import TileSet


def make_dp_matcher(mesh: Mesh, ts: TileSet, params: MatcherParams):
    """Returns fn(points [B,T,2], valid [B,T]) → MatchOutput, batch sharded
    over every mesh axis. B must be divisible by the mesh's device count
    (pad with valid=False rows on host)."""
    axes = tuple(mesh.axis_names)              # ("tile", "dp") or ("dp",)
    tables = jax.device_put(ts.device_tables(),
                            NamedSharding(mesh, P()))      # replicated
    batch_sh = NamedSharding(mesh, P(axes))    # shard B over all axes
    meta = ts.meta

    @functools.partial(jax.jit, in_shardings=(batch_sh, batch_sh),
                       out_shardings=batch_sh)
    def step(points, valid) -> MatchOutput:
        return jax.vmap(lambda p, v: match_trace(p, v, tables, meta, params))(
            points, valid)

    return step
