"""Data-parallel matcher: batch axis sharded over the mesh (config 3).

Tile arrays are replicated to every device once (they are read-only); each
batch dispatch shards traces across "dp" × "tile" as one flat data axis — no
cross-device communication in the forward match at all, which is exactly why
DP is the first-choice scaling axis for this workload (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.match import MatchOutput, match_traces
from reporter_tpu.parallel.compat import shard_map
from reporter_tpu.tiles.tileset import TileSet


def make_dp_matcher(mesh: Mesh, ts: TileSet, params: MatcherParams):
    """Returns fn(points [B,T,2], valid [B,T]) → MatchOutput, batch sharded
    over every mesh axis. B must be divisible by the mesh's device count
    (pad with valid=False rows on host).

    shard_map (not bare jit sharding): the dense candidate backend is a
    pallas custom call, which GSPMD has no partitioning rule for — under
    plain jit in_shardings it would be replicated (all-gather + redundant
    full-batch compute per device). shard_map runs the whole matcher
    per-shard on the local batch slice, which is the intended semantics:
    zero cross-device communication in the forward match.
    """
    axes = tuple(mesh.axis_names)              # ("tile", "dp") or ("dp",)
    # replicated to every device — stage only the layout this platform's
    # candidate backend sweeps (cell_pack is ~1 GB at bayarea-xl scale)
    tables = jax.device_put(ts.device_tables(params.candidate_backend),
                            NamedSharding(mesh, P()))
    meta = ts.meta

    local = shard_map(
        lambda p, v, tbl: match_traces(p, v, tbl, meta, params),
        mesh=mesh,
        in_specs=(P(axes), P(axes), jax.tree.map(lambda _: P(), tables)),
        out_specs=P(axes),
        check_vma=False,   # same constant-carry caveat as multimetro
    )

    @jax.jit
    def step(points, valid) -> MatchOutput:
        return local(points, valid, tables)

    return step
