"""Mesh construction helpers.

One place decides how physical devices become logical axes:

  ("dp",)          — pure data parallelism (BASELINE config 3)
  ("tile", "dp")   — metro shards × data parallelism (BASELINE config 4)

On a real v5e-8 slice the axes ride ICI; under
``--xla_force_host_platform_device_count=N`` the same code runs on virtual
CPU devices (SURVEY.md §4), which is how tests and the driver's multichip
dry-run validate shardings without 8 chips.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(tile: int = 1, dp: int | None = None,
              devices=None) -> Mesh:
    """Build a ("tile", "dp") mesh over ``tile * dp`` devices.

    dp=None uses all remaining devices. tile=1 degenerates to data-parallel
    only (the "tile" axis still exists, size 1, so downstream sharding specs
    are uniform).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if dp is None:
        if len(devices) % tile:
            raise ValueError(
                f"{len(devices)} devices not divisible by tile={tile}")
        dp = len(devices) // tile
    need = tile * dp
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(tile, dp)
    return Mesh(arr, ("tile", "dp"))
