"""Multi-host bootstrap — the process-group half of DISTRIBUTED.md.

The reference scales out as independent Kafka consumers; the TPU build
scales out as JAX processes whose devices join ONE global mesh (DISTRIBUTED
.md "Multi-host"): `jax.distributed.initialize()` per host, then the same
`parallel.mesh.make_mesh` axes — `jax.devices()` spans every host's chips
after initialization, so the sharded programs in `parallel/` run unchanged.

This module is the bootstrap seam: explicit args, or environment variables
(the k8s/compose shape — each replica gets the same manifest plus its
ordinal):

  REPORTER_TPU_COORDINATOR    host:port of process 0 (e.g. "tpu-0:8476")
  REPORTER_TPU_NUM_PROCESSES  total process count
  REPORTER_TPU_PROCESS_ID     this process's ordinal (0-based)

Single-process (none of the above set) is a no-op — the local devices
already form the whole mesh. tests/test_parallel.py exercises the real
single-process initialize() path in a subprocess (coordinator service,
client handshake, mesh over the global device list); multi-host needs real
DCN and is design-validated only (STATUS.md limitation).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("reporter_tpu.multihost")


def initialize_multihost(coordinator: "str | None" = None,
                         num_processes: "int | None" = None,
                         process_id: "int | None" = None) -> bool:
    """Join (or host) the JAX process group; True iff initialized.

    Falls back to REPORTER_TPU_* env vars for unset args. Returns False in
    single-process mode (nothing to join). Must run before the first
    device query in the process (jax.distributed's own requirement).
    """
    env = os.environ
    coordinator = coordinator or env.get("REPORTER_TPU_COORDINATOR") or None
    if num_processes is None and env.get("REPORTER_TPU_NUM_PROCESSES"):
        num_processes = int(env["REPORTER_TPU_NUM_PROCESSES"])
    if process_id is None and env.get("REPORTER_TPU_PROCESS_ID"):
        process_id = int(env["REPORTER_TPU_PROCESS_ID"])

    if coordinator is None:
        # Half-configured is the dangerous state: any group-shaped setting
        # without a coordinator means a typoed manifest, and silently
        # booting N disjoint single-process meshes would hide it.
        if num_processes not in (None, 1) or process_id is not None:
            raise ValueError(
                f"num_processes={num_processes} / process_id={process_id} "
                "but no coordinator address (set REPORTER_TPU_COORDINATOR "
                "on every process)")
        return False
    # jax can infer num_processes/process_id from TPU pod metadata, but
    # this deployment shape has none (remote-attached chips / CPU hosts) —
    # require both explicitly so a mis-templated manifest fails HERE with
    # a clear message, not deep inside the JAX handshake.
    if num_processes is None or process_id is None:
        raise ValueError(
            "coordinator set but num_processes/process_id missing (set "
            "REPORTER_TPU_NUM_PROCESSES and the per-replica "
            "REPORTER_TPU_PROCESS_ID)")

    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("joined process group: process %d/%d via %s",
             jax.process_index(), jax.process_count(), coordinator)
    return True


def shutdown_multihost() -> None:
    """Leave the process group (idempotent; no-op when never joined)."""
    import jax

    try:
        jax.distributed.shutdown()
    except RuntimeError:
        pass  # never initialized
