"""Segment-sharded candidate search — the framework's TP analog.

SURVEY.md §2.3 marks tensor parallelism "not needed; optional sharded kNN
reduce over ICI if a metro's edge set exceeds one core's HBM". This is
that option: the Morton-blocked segment table (seg_pack + seg_feat
columns + their bboxes) is sharded over a mesh axis, every device sweeps
its shard of the map against the FULL point batch, and the per-shard top-K candidate lists
are all-gathered over ICI and merged with the same distinct-edge K-merge
the dense kernel uses per block. Viterbi then runs data-parallel on the
merged candidates (reach tables replicated — node-keyed [N, M] and small
relative to shape data).

Segments of one edge may straddle a shard boundary; the merge dedupes by
edge id keeping the closer projection with the dense kernel's own
distance-tie resolution (``_select_topk``), so results are bit-identical
to the unsharded sweep — including at exact ties (test-asserted).

Collective traffic per batch: one all-gather of [shards, B·T, K] candidate
triples over ICI — bytes ≈ shards × points × K × 12, tiny next to the
sharded HBM win (each device holds 1/shards of the map).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.candidates import CandidateSet
from reporter_tpu.parallel.compat import shard_map
from reporter_tpu.ops.dense_candidates import (
    _SBLK,
    _select_topk,
    build_seg_pack,
    find_candidates_dense,
)
from reporter_tpu.ops.hmm import viterbi_decode_batched
from reporter_tpu.ops.match import MatchOutput
from reporter_tpu.tiles.tileset import TileSet


class ShardedTables(NamedTuple):
    seg_pack: jnp.ndarray    # [8, S_pad] — sharded over columns
    seg_bbox: jnp.ndarray    # [nblocks, 4] — sharded over rows
    seg_sub: jnp.ndarray     # [nblocks, nsub*4] — sharded over rows
    seg_feat: jnp.ndarray    # [8, S_pad] MXU feature rows — sharded over
    #                          columns in lockstep with seg_pack
    edge_len: jnp.ndarray    # replicated
    reach_row: jnp.ndarray   # replicated (edge → governing reach row)
    reach_to: jnp.ndarray
    reach_dist: jnp.ndarray


def shard_tables(mesh: Mesh, ts: TileSet, axis: str = "tile",
                 ) -> ShardedTables:
    """Pad the segment table to shards × block multiples and device_put with
    the column dimension sharded over ``axis``."""
    n = mesh.shape[axis]
    sp = build_seg_pack(ts.seg_a, ts.seg_b, ts.seg_edge, ts.seg_off,
                        ts.seg_len)
    spad = sp.pack.shape[1]
    per = -(-spad // (n * _SBLK)) * _SBLK          # columns per shard
    total = per * n
    pack = np.full((sp.pack.shape[0], total), np.int32(-1).view(np.float32),
                   np.float32)
    pack[:, :spad] = sp.pack
    bbox = np.full((total // _SBLK, 4), np.nan, np.float32)
    bbox[:sp.bbox.shape[0]] = sp.bbox
    sub = np.full((total // _SBLK, sp.sub.shape[1]), np.nan, np.float32)
    sub[:sp.sub.shape[0]] = sp.sub
    # feature rows pad in whole blocks whose NaN sub quads gate them off
    # before the matmul — BIG fill keeps a stray read conservative
    feat = np.full((sp.feat.shape[0], total), np.float32(1e30), np.float32)
    feat[:, :spad] = sp.feat

    return ShardedTables(
        seg_pack=jax.device_put(jnp.asarray(pack),
                                NamedSharding(mesh, P(None, axis))),
        seg_bbox=jax.device_put(jnp.asarray(bbox),
                                NamedSharding(mesh, P(axis))),
        seg_sub=jax.device_put(jnp.asarray(sub),
                               NamedSharding(mesh, P(axis))),
        seg_feat=jax.device_put(jnp.asarray(feat),
                                NamedSharding(mesh, P(None, axis))),
        edge_len=jax.device_put(jnp.asarray(ts.edge_len),
                                NamedSharding(mesh, P())),
        reach_row=jax.device_put(jnp.asarray(ts.edge_reach_row),
                                 NamedSharding(mesh, P())),
        reach_to=jax.device_put(jnp.asarray(ts.reach_to),
                                NamedSharding(mesh, P())),
        reach_dist=jax.device_put(jnp.asarray(ts.reach_dist),
                                  NamedSharding(mesh, P())),
    )


def _merge_topk(edge, dist, off, k: int):
    """Merge gathered per-shard K-lists: fields [shards, N, K] → [N, K].
    Delegates to the dense kernel's ``_select_topk`` so the distinct-edge
    K-merge and its distance-tie resolution (smallest tied edge id, then
    its lowest tied offset) are ONE implementation: exact node-distance
    ties at high-degree junctions can span shard boundaries, and any
    drift here would let the sharded path pick a different tied edge than
    the single-device sweep (test-asserted bit-identical)."""
    s, n, kk = edge.shape
    e = jnp.moveaxis(edge, 0, 1).reshape(n, s * kk)
    d = jnp.moveaxis(dist, 0, 1).reshape(n, s * kk)
    o = jnp.moveaxis(off, 0, 1).reshape(n, s * kk)
    d = jnp.where(e >= 0, d, jnp.float32(1e30))
    md, me, mo = _select_topk(d, e, o, k)
    return me, md, mo


def make_sharded_matcher(mesh: Mesh, ts: TileSet, params: MatcherParams,
                         axis: str = "tile"):
    """fn(points [B,T,2], valid [B,T]) → MatchOutput with the segment table
    sharded over ``axis`` (map-capacity scaling) and the batch replicated
    on that axis. Compose with batch sharding over the other mesh axes
    externally if desired."""
    tables = shard_tables(mesh, ts, axis)
    radius, k = params.search_radius, params.max_candidates

    def local(points, valid, seg_pack, seg_bbox, seg_sub, seg_feat,
              edge_len, reach_row, reach_to, reach_dist):
        B, T = points.shape[:2]
        flat = find_candidates_dense(
            points.reshape(B * T, 2),
            (seg_pack, seg_bbox, seg_sub, seg_feat),
            radius, k, valid=valid.reshape(B * T),
            subcull=getattr(params, "sweep_subcull", True),
            lowp=getattr(params, "sweep_lowp", "off"),
            mxu=getattr(params, "sweep_mxu", False))
        # all-gather each shard's K-list over ICI, then K-merge
        ge = jax.lax.all_gather(flat.edge, axis)        # [shards, N, K]
        gd = jax.lax.all_gather(flat.dist, axis)
        go = jax.lax.all_gather(flat.offset, axis)
        me, md, mo = _merge_topk(ge, gd, go, k)
        cands = CandidateSet(edge=me.reshape(B, T, k),
                             offset=mo.reshape(B, T, k),
                             dist=md.reshape(B, T, k),
                             valid=(me >= 0).reshape(B, T, k))
        vit = viterbi_decode_batched(
            cands, points, valid,
            {"edge_len": edge_len, "reach_row": reach_row,
             "reach_to": reach_to, "reach_dist": reach_dist},
            params.sigma_z, params.beta, params.max_route_distance_factor,
            params.breakage_distance, params.backward_slack,
            params.interpolation_distance)
        return MatchOutput(edge=vit.edge, offset=vit.offset,
                           chain_start=vit.chain_start, matched=vit.matched)

    other = [a for a in mesh.axis_names if a != axis]
    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(*other) if other else P(), P(*other) if other else P(),
                  P(None, axis), P(axis), P(axis), P(None, axis),
                  P(), P(), P(), P()),
        out_specs=P(*other) if other else P(),
        check_vma=False,
    )

    @jax.jit
    def step(points, valid) -> MatchOutput:
        return sharded(points, valid, tables.seg_pack, tables.seg_bbox,
                       tables.seg_sub, tables.seg_feat, tables.edge_len,
                       tables.reach_row, tables.reach_to, tables.reach_dist)

    return step
