"""Scale-out: device meshes, sharded matchers, multi-metro dispatch.

The reference scales out with Kafka partitions × consumer-group workers and a
thread pool in the HTTP service (SURVEY.md §2.3). The TPU-native mapping:

  data parallelism   → batch axis sharded over the mesh's "dp" axis
                       (BASELINE configs 2–3)
  sharded-state (EP) → each shard of the "tile" axis holds a different
                       metro's tile arrays; probes are dispatched to their
                       metro's shard, MoE-style (BASELINE config 4)
  collectives        → XLA psum over ICI for cross-shard aggregation
                       (per-segment histograms), not NCCL/MPI

No NCCL/Kafka translation: shardings are declared with jax.sharding and XLA
inserts the collectives.
"""

from reporter_tpu.parallel.mesh import make_mesh
from reporter_tpu.parallel.dp import make_dp_matcher
from reporter_tpu.parallel.dp_e2e import DpWireMatcher
from reporter_tpu.parallel.sharded_candidates import make_sharded_matcher
from reporter_tpu.parallel.multimetro import (
    MetroBatch,
    StackedTiles,
    dispatch_traces,
    make_multimetro_matcher,
    stack_tilesets,
)

__all__ = [
    "DpWireMatcher",
    "make_sharded_matcher",
    "make_mesh",
    "make_dp_matcher",
    "MetroBatch",
    "StackedTiles",
    "dispatch_traces",
    "make_multimetro_matcher",
    "stack_tilesets",
]
