"""Mesh-aware wire backend — the multi-device PRODUCT path (configs 3–4).

Round 4 proved the sharded *kernel step* (parallel/dp, multimetro,
sharded_candidates); this module carries the sharding into the deployable
pipeline: ``SegmentMatcher(ts, mesh=mesh)`` routes every device dispatch in
``_submit_many`` through a :class:`DpWireMatcher`, whose jitted programs are
``shard_map`` wrappings of the SAME undecorated wire bodies
(ops.match.wire_from_*) the single-device path jits — wire packing included.
Everything downstream (harvest, unpack, native C++ walk, columnar
MatchBatch, report build, service layers) is byte-stream work on the SAME
wire format, so the sharded product is bit-identical to single-device by
construction (test-asserted in tests/test_parallel.py).

Sharding layout (SURVEY.md §2.3 DP row): batch rows sharded over every mesh
axis flattened into one data axis; tile tables replicated (read-only,
staged once at construction). Zero cross-device communication per dispatch
— the forward match is embarrassingly data-parallel, which is why DP is the
first-choice scaling axis for this workload. shard_map rather than jit
in_shardings because the dense candidate backend is a pallas custom call
GSPMD cannot partition (see parallel/dp.py).

Batches whose row count is not a device-count multiple are padded with
zero-length (all-invalid) rows on submit; the harvest side slices wires
back to the real row count, so callers never see the padding.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.match import wire_from_f32, wire_from_q8, wire_from_q16
from reporter_tpu.parallel.compat import shard_map
from reporter_tpu.tiles.tileset import TileSet

_IMPLS = {"f32": wire_from_f32, "q16": wire_from_q16, "q8": wire_from_q8}
_NARGS = {"f32": 2, "q16": 3, "q8": 3}


def data_pspec(mesh: Mesh) -> P:
    """THE data-parallel PartitionSpec: leading dim sharded over every
    mesh axis flattened into one logical data axis. One spelling, every
    mesh consumer (wire dispatch below, the backfill aggregate scatter in
    ops/aggregate.py) — two spellings would let a placement drift."""
    return P(tuple(mesh.axis_names))


def flat_device_count(mesh: Mesh) -> int:
    """Total devices under the flattened data axis (== rows per padded
    dispatch block)."""
    return int(np.prod(tuple(mesh.shape.values())))


def mesh_wire_fn(mesh: Mesh, kind: str, meta, params: MatcherParams,
                 spec: "tuple | None", tables_pytree, has_acc: bool):
    """``jit(shard_map(wire_from_<kind>))`` over ``mesh`` — THE product-
    path program builder. One spelling, two callers: DpWireMatcher's
    dispatch cache below, and the device-contract audit
    (analysis/device_contract.py), which abstractly traces the same
    callable so the audited mesh program can never drift from the served
    one. ``tables_pytree`` only shapes the replicated in_specs tree —
    ShapeDtypeStructs work as well as placed arrays."""
    impl = _IMPLS[kind]
    nargs = _NARGS[kind]
    data = data_pspec(mesh)                  # rows over ALL mesh axes
    tbl_specs = jax.tree.map(lambda _: P(), tables_pytree)

    if has_acc:
        def local(*a):
            *ins, acc, tbl = a
            return impl(*ins, tbl, meta, params, acc, spec)
        in_specs = (data,) * nargs + (data, tbl_specs)
    else:
        def local(*a):
            *ins, tbl = a
            return impl(*ins, tbl, meta, params, None, spec)
        in_specs = (data,) * nargs + (tbl_specs,)

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=data,
        check_vma=False))   # same constant-carry caveat as parallel/dp


class DpWireMatcher:
    """Duck-type of matcher.api._LocalWire: f32/q16/q8 entries taking host
    numpy arrays, returning an inflight device wire array (padded rows
    possible — harvest slices to the caller's row count)."""

    def __init__(self, mesh: Mesh, ts: TileSet, params: MatcherParams,
                 spec: "tuple | None"):
        self.mesh = mesh
        self.ndev = flat_device_count(mesh)
        self.meta = ts.meta
        self.params = params
        self.spec = spec
        # replicated once; stage only the resolved backend's layout (the
        # unused index is the largest table at metro scale)
        self.tables = jax.device_put(
            ts.device_tables(params.candidate_backend),
            NamedSharding(mesh, P()))
        self._fns: dict = {}

    # ---- public entries (same shapes as the single-device jits) ---------

    def f32(self, pts, lens, acc):
        return self._dispatch("f32", (pts, lens), acc)

    def q16(self, pts_q, origins, lens, acc):
        return self._dispatch("q16", (pts_q, origins, lens), acc)

    def q8(self, deltas_q, origins, lens, acc):
        return self._dispatch("q8", (deltas_q, origins, lens), acc)

    # ---- internals -------------------------------------------------------

    def _dispatch(self, kind: str, arrays, acc):
        B = arrays[0].shape[0]
        pad = (-B) % self.ndev
        if pad:
            arrays = tuple(
                np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                for a in arrays)
            if acc is not None:
                acc = np.concatenate(
                    [acc, np.ones((pad,) + acc.shape[1:], acc.dtype)])
        fn = self._fn(kind, acc is not None)
        args = [jnp.asarray(a) for a in arrays]
        if acc is not None:
            args.append(jnp.asarray(acc))
        return fn(*args, self.tables)

    def _fn(self, kind: str, has_acc: bool):
        """Cached mesh_wire_fn — one program per (entry kind, accuracy
        presence); shapes recompile inside the jit cache."""
        key = (kind, has_acc)
        cached = self._fns.get(key)
        if cached is None:
            cached = self._fns[key] = mesh_wire_fn(
                self.mesh, kind, self.meta, self.params, self.spec,
                self.tables, has_acc)
        return cached
