"""jax version compat for the mesh product path.

``jax.shard_map`` graduated from ``jax.experimental.shard_map`` with a
kwarg rename on the way (``check_rep`` → ``check_vma``). Every module in
parallel/ imports :func:`shard_map` from here so one shim covers both
generations: on a jax new enough to carry the top-level alias we use it
untouched; otherwise the experimental entry point is wrapped to accept
the modern ``check_vma`` spelling. Call sites always pass mesh/specs as
keywords and only ever set ``check_vma`` — the one kwarg whose name
moved — so the wrapper stays this small.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)
