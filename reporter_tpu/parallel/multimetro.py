"""Multi-metro tile sharding (BASELINE config 4: SF + NYC + LA on one mesh).

The reference's analog is sharded-by-key state: each Kafka partition's worker
owns its vehicles (SURVEY.md §2.3 "EP"). Here each shard of the mesh's
"tile" axis owns whole metros: every metro's tile arrays are padded to a
common shape, stacked on a leading metro axis, and sharded over "tile";
probes are dispatched to their metro's shard on host (the MoE-style router).
Inside shard_map each shard matches only its own metros' probes — zero
cross-shard traffic in the matcher — and a per-segment observation histogram
is psum'd over the "dp" axis (the ICI collective; SURVEY.md §2.3
"Collective/comm backend").
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.candidates import GridMeta
from reporter_tpu.ops.match import MatchOutput, match_traces
from reporter_tpu.parallel.compat import shard_map
from reporter_tpu.tiles.tileset import TileSet

_PAD_VALUES: dict[str, Any] = {
    # padded cell rows are never gathered (indices clip to the metro's own
    # gw/gh), but fill them with the bitcast of edge=-1 anyway so a stray
    # gather could only ever produce an invalid candidate
    "cell_pack": np.int32(-1).view(np.float32),
    # the dense sweep DOES visit padding columns: edge = bitcast(-1) marks
    # them invalid (other components become NaN, which the kernel's
    # where(valid) masking discards before any reduction)
    "seg_pack": np.int32(-1).view(np.float32),
    # NaN bboxes never overlap anything → padded blocks are never selected
    "seg_bbox": np.float32(np.nan),
    # same rule for the in-kernel sub-block quads (rows pad in sync with
    # seg_bbox: whole _SBLK blocks)
    "seg_sub": np.float32(np.nan),
    # MXU feature rows pad in whole blocks too; those blocks' NaN seg_sub
    # quads gate them off before the matmul ever reads these — any fill
    # works, BIG in the F slot's spirit keeps a stray read conservative
    "seg_feat": np.float32(1e30),
    "reach_to": -1,          # no reachable target
    "reach_dist": np.float32(np.inf),
    "edge_osmlr": -1,
    # lengths / offsets: zero is safe, padded ids above make sure padded
    # rows are never selected as real candidates
}


class StackedTiles(NamedTuple):
    """All metros' device tables, shape-padded and stacked on axis 0."""

    tables: dict[str, jnp.ndarray]   # each [M, ...]
    names: tuple[str, ...]
    cell_size: float
    index_radius: float              # uniform grid registration dilation
    num_osmlr: tuple[int, ...]       # real OSMLR row count per metro
    osmlr_pad: int                   # padded G (histogram width)


def _pad_to(arr: np.ndarray, shape: tuple[int, ...], fill) -> np.ndarray:
    out = np.full(shape, fill, dtype=arr.dtype)
    out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


def stack_tilesets(tilesets: Sequence[TileSet]) -> StackedTiles:
    """Pad every metro's device tables to common shapes and stack them.

    Requires uniform compiler cell_size and index_radius (static kernel
    parameters); grid origin/dims vary per metro and ride along as traced
    scalars.
    """
    # NOTE: stacking is POSITIONAL — duplicate names are legal here (the
    # mesh suites stack two differently-seeded "tiny" metros), but any
    # name-keyed consumer (dispatch_traces, MetroRouter, the fleet
    # registry) requires unique names and checks its own.
    cell_sizes = {ts.meta.cell_size for ts in tilesets}
    if len(cell_sizes) != 1:
        raise ValueError(f"metros compiled with differing cell_size: {cell_sizes}")
    radii = {ts.meta.index_radius for ts in tilesets}
    if len(radii) != 1:
        raise ValueError(f"metros compiled with differing index_radius: {radii}")
    caps = {ts.grid.shape[1] for ts in tilesets}
    if len(caps) != 1:
        # Capacity auto-sizes per content (the compiler doubles it on
        # overflow, e.g. organic cores), so metros legitimately differ.
        # Tail-pad the narrower GRIDS with -1 first: cell_pack rows are
        # component-major [8*C] and could not be padded after packing,
        # but device_tables builds the pack FROM ts.grid, so widening the
        # grid up front yields a uniform pack layout for free.
        import dataclasses

        cap = max(caps)
        tilesets = [ts if ts.grid.shape[1] == cap else dataclasses.replace(
            ts, grid=_pad_to(ts.grid, (ts.grid.shape[0], cap), -1))
            for ts in tilesets]

    host_tables = []
    for ts in tilesets:
        # host_tables, not device_tables: the pad-and-stack below is host
        # numpy, so staging per-metro jnp arrays first would round-trip
        # every table through the device (and on a remote-attached chip,
        # through the link) just to pull it straight back
        t = dict(ts.host_tables())
        t["grid_ox"] = np.float32(ts.meta.grid_origin[0])
        t["grid_oy"] = np.float32(ts.meta.grid_origin[1])
        t["grid_gw"] = np.int32(ts.meta.grid_dims[0])
        t["grid_gh"] = np.int32(ts.meta.grid_dims[1])
        host_tables.append(t)

    keys = host_tables[0].keys()
    stacked: dict[str, jnp.ndarray] = {}
    for k in keys:
        arrs = [t[k] for t in host_tables]
        shape = tuple(max(a.shape[d] for a in arrs)
                      for d in range(arrs[0].ndim))
        fill = _PAD_VALUES.get(k, 0)
        stacked[k] = jnp.asarray(np.stack(
            [_pad_to(a, shape, fill) for a in arrs]))

    num_osmlr = tuple(len(ts.osmlr_id) for ts in tilesets)
    return StackedTiles(
        tables=stacked,
        names=tuple(ts.name for ts in tilesets),
        cell_size=float(cell_sizes.pop()),
        index_radius=float(radii.pop()),
        num_osmlr=num_osmlr,
        osmlr_pad=max(num_osmlr),
    )


def make_multimetro_matcher(mesh: Mesh, stacked: StackedTiles,
                            params: MatcherParams):
    """Build the sharded step: fn(points [M,B,T,2], valid [M,B,T]) →
    (MatchOutput [M,B,T], hist [M, G]).

    M (metro count) must be divisible by the mesh's "tile" axis; B by "dp".
    ``hist`` counts matched-point observations per OSMLR row, summed over the
    whole "dp" axis on device (psum over ICI) — the seed of the streaming
    speed-histogram path (BASELINE config 5).
    """
    if params.search_radius > stacked.index_radius:
        raise ValueError(
            f"search_radius ({params.search_radius}) exceeds index_radius "
            f"({stacked.index_radius})")
    n_tile = mesh.shape["tile"]
    if len(stacked.names) % n_tile:
        raise ValueError(
            f"{len(stacked.names)} metros not divisible by tile axis {n_tile}")

    cell_size = stacked.cell_size
    index_radius = stacked.index_radius
    gmax = stacked.osmlr_pad
    tables = jax.device_put(
        stacked.tables,
        NamedSharding(mesh, P("tile")))     # metro axis sharded, rest local

    def per_metro(pts, val, tbl):
        gm = GridMeta(ox=tbl["grid_ox"], oy=tbl["grid_oy"],
                      cell_size=cell_size, gw=tbl["grid_gw"],
                      gh=tbl["grid_gh"], index_radius=index_radius)
        out = match_traces(pts, val, tbl, gm, params)
        rows = jnp.where(out.matched,
                         tbl["edge_osmlr"][jnp.maximum(out.edge, 0)], -1)
        ok = (rows >= 0).reshape(-1)
        hist = jnp.zeros((gmax,), jnp.int32).at[
            jnp.maximum(rows, 0).reshape(-1)].add(ok.astype(jnp.int32))
        return out, hist

    def local_step(points, valid, tbl):
        # points [m_local, b_local, T, 2]; tbl leaves [m_local, ...]
        out, hist = jax.vmap(per_metro)(points, valid, tbl)
        hist = jax.lax.psum(hist, "dp")     # full counts on every dp shard
        return out, hist

    tbl_specs = jax.tree.map(lambda _: P("tile"), tables)
    # check_vma off: the Viterbi scan seeds its carry from constants, which
    # the varying-manual-axes checker rejects inside shard_map even though
    # the computation is per-shard correct (constants are trivially varying).
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("tile", "dp"), P("tile", "dp"), tbl_specs),
        out_specs=(P("tile", "dp"), P("tile")),
        check_vma=False,
    )

    @jax.jit
    def step(points, valid):
        return sharded(points, valid, tables)

    return step


class MetroBatch(NamedTuple):
    """Host-side dispatch result: device inputs + scatter-back indices."""

    points: np.ndarray               # f32 [M, B, T, 2]
    valid: np.ndarray                # bool [M, B, T]
    # [metro][slot] → (caller job idx, chunk start within the job, length);
    # over-bucket jobs occupy several consecutive slots (chunked like
    # matcher.api._decode_many — each chunk is an independent HMM).
    index: list[list[tuple[int, int, int]]]


def dispatch_traces(names: Sequence[str],
                    jobs: Sequence[tuple[str, np.ndarray]],
                    dp: int, bucket: int) -> MetroBatch:
    """Route (metro, points[T,2]) jobs into padded [M, B, T] device arrays.

    Jobs longer than ``bucket`` are split into consecutive chunks (one slot
    each). B is the max per-metro slot count, rounded up to
    dp × next-power-of-two so repeat dispatches reuse a small set of compiled
    shapes instead of recompiling per load level; T pads to ``bucket``.
    """
    if len(set(names)) != len(names):
        # the slot map below is name-keyed: duplicates would merge two
        # stack rows' traffic into whichever row iterates last
        raise ValueError(f"duplicate metro names: {list(names)}")
    by_metro: dict[str, list[tuple[int, int, int]]] = {n: [] for n in names}
    for j, (metro, xy) in enumerate(jobs):
        if metro not in by_metro:
            raise KeyError(f"unknown metro {metro!r}; have {list(names)}")
        for lo in range(0, max(len(xy), 1), bucket):
            by_metro[metro].append((j, lo, min(bucket, len(xy) - lo)))

    load = max((len(v) for v in by_metro.values()), default=1)
    # lint: allow[jit-shape-len] 2026-08-04 the pow2 ladder IS the bound
    # here: B takes log2(max load) distinct values per dp, and stack
    # dispatch is the offline/test path (the serving face buckets via
    # the scheduler's fixed _TRACE_RUNGS instead)
    B = dp * (1 << max(0, (load + dp - 1) // dp - 1).bit_length())
    M = len(names)
    points = np.zeros((M, B, bucket, 2), np.float32)
    valid = np.zeros((M, B, bucket), bool)
    index: list[list[tuple[int, int, int]]] = []
    for m, name in enumerate(names):
        slots = []
        for slot, (j, lo, t) in enumerate(by_metro[name]):
            xy = jobs[j][1]
            points[m, slot, :t] = xy[lo:lo + t]
            valid[m, slot, :t] = True
            slots.append((j, lo, t))
        index.append(slots)
    return MetroBatch(points=points, valid=valid, index=index)
