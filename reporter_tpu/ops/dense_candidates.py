"""Dense candidate search: a Pallas TPU sweep kernel, zero gathers.

Replaces the grid-gather candidate path (ops/candidates.py) on TPU. The
grid path is the idiomatic CPU design (Meili's CandidateGridQuery,
SURVEY.md §2.2 "Candidate search"): hash into a cell, inspect only local
segments. On TPU that turns into a data-dependent row gather per probe
point, and XLA lowers those to serialized dynamic-slices — measured ~3.3 µs
per row on v5e, 80 ms for a 24k-point batch, dominating the whole matcher.

The TPU-first formulation inverts it: stream *segment blocks* past each
*point chunk* and keep a running top-K of distinct edges in VMEM scratch.
All regular VPU work — no gathers, no data-dependent addressing, nothing
for the compiler to serialize.

Three levels of work avoidance keep it output-sensitive:

1. **Spatial blocks** — build_seg_pack sorts segments by Morton code of
   their midpoint, so each SBLK-column block covers a compact region, and
   records per-block bboxes.
2. **Block culling (scalar prefetch)** — before the kernel, a tiny jnp
   pre-pass intersects each point-chunk's (sub-)bboxes with the block
   bboxes and emits a per-chunk id list with the relevant (hit) blocks
   first. The segment BlockSpec's index_map reads the prefetched list, so
   only relevant blocks are ever DMA'd. The list keeps full nblocks width
   (completeness by construction — no truncation); pad slots repeat the
   previous id, which skips both the re-fetch (equal consecutive indices)
   and, via the in-kernel `fresh` predicate, all the VPU work.
3. **Early-out** — a block whose segments all miss the radius skips the
   top-K selection entirely (`pl.when` on the block-min distance).

Output contract matches ops.candidates.find_candidates_trace: top-K
*distinct* edges per point, each edge represented by its closest
projection (Meili keeps one candidate per edge).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from reporter_tpu.ops.candidates import CandidateSet

BIG = 1e30  # python float: pallas kernels reject captured jnp constants

try:  # pallas lowers on TPU backends; keep CPU-only envs importable
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

# seg_pack component rows
SP_AX, SP_AY, SP_BX, SP_BY, SP_OFF, SP_LEN, SP_EDGE, SP_SPARE = range(8)
SP_NCOMP = 8

# seg_feat component rows (round 13, the MXU coarse pass): per-column
# quadratic-form coefficients such that, for a point recentered on the
# column's SUB-slice center q = p - c,
#   A*qx^2 + B*qy^2 + C*qx*qy + D*qx + E*qy + F
#     == squared distance from p to the segment's INFINITE line,
# a lower bound on the exact point-to-segment distance (the clamp to the
# endpoints only ever moves the closest point further away). One
# [P, 8] @ [8, subw] dot per surviving slice evaluates the whole slice's
# coarse distances on the MXU. Rows CX/CY carry the slice center the
# coefficients were recentered on — the kernel reads it from HERE, never
# recomputes it, so host/device center drift is impossible by
# construction. Padding columns carry F = BIG (coarse distance BIG →
# never admit a pair on their own).
SF_A, SF_B, SF_C, SF_D, SF_E, SF_F, SF_CX, SF_CY = range(8)
SF_NCOMP = 8

# Conservative margin of the MXU coarse test, RELATIVE to the squared
# clamp-box scale: XLA TPU may serve even an f32-input matmul with
# bf16-multiply passes (precision=DEFAULT), so the margin assumes
# bf16-grade operand rounding (2^-9 relative) for BOTH dtypes — the
# worst-case term-sum bound is ~9*s^2 * 2^-8 ≈ s^2 * 2^-4.8; 2^-4 gives
# ~1.8x headroom over that already-unattainable joint worst case, and
# tests/test_dense_candidates.py fuzzes the bound with emulated bf16
# rounding. An absolute 0.5 m^2 slack covers the tiny-scale regime.
_MXU_REL_MARGIN = 0.0625
_MXU_ABS_MARGIN = 0.5

# interpret mode: run the kernel through the pallas interpreter on any
# backend — slow, for debugging kernel logic without TPU access
# (env_flag = THE boolean env parse, round-14 env-flag lint)
from reporter_tpu.utils.tracing import env_flag as _env_flag

_INTERPRET = _env_flag(os.environ.get("RTPU_PALLAS_INTERPRET"))

_P = 256          # points per chunk: halves the (chunks x blocks) launch
#                   grid vs 128 — measured ~2/5/9% faster on sf/organic/xl
#                   (interleaved A/B, round 4); 512 loses (looser bboxes)
_SBLK = int(os.environ.get("RTPU_SBLK", "512"))
#                   segment columns per block (small: culling granularity;
#                   512 re-validated post-narrow-grid — the env override
#                   exists for interleaved A/B tuning, results are exact
#                   at any block size since the merge is order-independent)
_SUB = int(os.environ.get("RTPU_SUB", "128"))
#                   sub-block columns for the IN-KERNEL second culling
#                   level (round 8): each DMA'd _SBLK block is tested per
#                   _SUB-column lane-width slice against the chunk's
#                   actual points (exact point-vs-bbox distance, tighter
#                   than the host pre-pass's chunk-bbox test), and the
#                   pair geometry + top-K selection run only on slices
#                   that can hold an in-radius pair. Must divide _SBLK.
_NSUB = 8         # chunk sub-bboxes — 32 points per sub-bbox, the same
#                   culling tightness as the old 128/4 (results identical)
_NJ_CAP = 128     # narrow-grid width DEFAULT rung: max culled blocks per
#                   chunk before the sweep falls back to the full-width
#                   launch grid (Morton-sorted fleet chunks typically hit
#                   ~6-11 blocks; the cap kills the per-slot launch
#                   overhead that cost bayarea-xl ~45% of its dispatch at
#                   1184 blocks). Round 17: callers may override per
#                   dispatch via find_candidates_dense(nj_cap=...) —
#                   MatcherParams.sweep_nj_cap, restricted to the
#                   config.SWEEP_NJ_CAP_RUNGS ladder so the compiled-
#                   shape universe stays manifest-pinned; this module
#                   constant is the rung served when no param rides in
#                   (and the compile-manifest's committed default).
SPLIT_LEN = 256.0  # long-segment pre-split span (shared with tiles/capacity)


class SegPack(NamedTuple):
    """Device-side dense segment table (spatially blocked)."""

    pack: np.ndarray   # f32 [8, S_pad] component rows, Morton-sorted columns
    bbox: np.ndarray   # f32 [nblocks, 4] per-block (xmin, ymin, xmax, ymax)
    sub: "np.ndarray | None" = None
    #                  # f32 [nblocks, (SBLK/SUB)*4] per-SUB-slice bboxes
    #                  # (xmin, ymin, xmax, ymax quads; NaN = empty slice)
    #                  # — the in-kernel second culling level; None on
    #                  # packs built before round 8 (kernel falls back to
    #                  # whole-block sweeps)
    feat: "np.ndarray | None" = None
    #                  # f32 [8, S_pad] per-column MXU feature rows (SF_*)
    #                  # — the round-13 matmul-form coarse pass; None on
    #                  # packs built before round 13 (mxu=True then raises
    #                  # rather than silently measuring f32 against itself)


def _morton(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Interleave 16-bit quantized coords → 32-bit Morton keys."""

    def spread(v):
        v = v.astype(np.uint64)
        v = (v | (v << 16)) & np.uint64(0x0000FFFF0000FFFF)
        v = (v | (v << 8)) & np.uint64(0x00FF00FF00FF00FF)
        v = (v | (v << 4)) & np.uint64(0x0F0F0F0F0F0F0F0F)
        v = (v | (v << 2)) & np.uint64(0x3333333333333333)
        v = (v | (v << 1)) & np.uint64(0x5555555555555555)
        return v

    return spread(x) | (spread(y) << np.uint64(1))


def _split_long_segments(seg_a, seg_b, seg_edge, seg_off, seg_len,
                         lmax: float):
    """Tile segments longer than ``lmax`` into collinear sub-spans.

    A 2 km rural edge is ONE line segment; its bbox inflates whichever
    Morton block it lands in until half the metro's chunks "hit" that
    block (organic/xl tiles carry many such edges — grid tiles none).
    Sub-spans tile the segment exactly: min distance over pieces equals
    distance to the whole segment, and offabs composes via the piece's
    off0, so candidates are unchanged (to f32 rounding at the seams) —
    only the culling gets tighter."""
    long_i = np.nonzero(seg_len > lmax)[0]
    if not len(long_i):
        return seg_a, seg_b, seg_edge, seg_off, seg_len
    keep = np.ones(len(seg_len), bool)
    keep[long_i] = False
    # grouped formulation (xl-scale tiles can carry tens of thousands of
    # long rural edges; a per-edge Python loop is real time on one core):
    # piece r of parent i spans fractions [r/n_i, (r+1)/n_i]
    n = np.ceil(seg_len[long_i] / lmax).astype(np.int64)
    parent = np.repeat(long_i, n)                      # [N] parent index
    r = np.arange(len(parent)) - np.repeat(np.cumsum(n) - n, n)
    nn = np.repeat(n, n).astype(np.float64)
    f0 = (r / nn)[:, None]
    f1 = ((r + 1) / nn)[:, None]
    d = seg_b[parent] - seg_a[parent]
    pb_long = seg_a[parent] + d * f1
    # the final piece ends at the ORIGINAL endpoint bit-for-bit: junction
    # nodes are segment endpoints, and an a+(b-a)*1.0 ulp there would
    # break the exact d=0 ties the cross-backend tie-break relies on
    last = (r + 1) == nn.astype(np.int64)
    pb_long[last] = seg_b[parent[last]]
    return (np.concatenate([seg_a[keep],
                            seg_a[parent] + d * f0]).astype(np.float32),
            np.concatenate([seg_b[keep], pb_long]).astype(np.float32),
            np.concatenate([seg_edge[keep], seg_edge[parent]]),
            np.concatenate([seg_off[keep], seg_off[parent]
                            + seg_len[parent] * f0[:, 0]]).astype(np.float32),
            np.concatenate([seg_len[keep], seg_len[parent]
                            * (f1 - f0)[:, 0]]).astype(np.float32))


def packed_columns(seg_len: np.ndarray, block: int = _SBLK,
                   split_len: float = SPLIT_LEN) -> int:
    """Post-split padded column count of build_seg_pack's layout — the
    shape math tiles/capacity needs WITHOUT rebuilding the Morton pack
    (~seconds at 0.6M segments on one core). Must mirror
    _split_long_segments' piece count exactly."""
    s = len(seg_len)
    if split_len and s:
        long = seg_len > split_len
        s = int(s - long.sum()
                + np.ceil(seg_len[long] / split_len).sum())
    return max(block, -(-s // block) * block)


def build_seg_pack(seg_a: np.ndarray, seg_b: np.ndarray, seg_edge: np.ndarray,
                   seg_off: np.ndarray, seg_len: np.ndarray,
                   block: int = _SBLK, split_len: float = SPLIT_LEN) -> SegPack:
    """Morton-sort segments, pack [8, S_pad] f32 component rows (edge ids
    bitcast into a row), record per-block bboxes. Padding columns carry
    edge = -1 → permanently invalid; padding blocks carry NaN bboxes →
    never selected by the culling pre-pass. Segments longer than
    ``split_len`` are tiled into sub-spans first so no block bbox is
    inflated by one long edge (_split_long_segments)."""
    if split_len and len(seg_len):
        seg_a, seg_b, seg_edge, seg_off, seg_len = _split_long_segments(
            seg_a, seg_b, seg_edge, seg_off, seg_len, split_len)
    s = len(seg_edge)
    spad = max(block, ((s + block - 1) // block) * block)

    mid = (seg_a + seg_b) * 0.5 if s else np.zeros((0, 2))
    if s:
        lo = mid.min(0)
        span = np.maximum(mid.max(0) - lo, 1e-6)
        q = np.minimum((mid - lo) / span * 65535.0, 65535.0).astype(np.uint32)
        order = np.argsort(_morton(q[:, 0], q[:, 1]), kind="stable")
    else:
        order = np.arange(0)
    a, b = seg_a[order], seg_b[order]

    pack = np.zeros((SP_NCOMP, spad), np.float32)
    pack[SP_AX, :s] = a[:, 0]
    pack[SP_AY, :s] = a[:, 1]
    pack[SP_BX, :s] = b[:, 0]
    pack[SP_BY, :s] = b[:, 1]
    pack[SP_OFF, :s] = seg_off[order]
    pack[SP_LEN, :s] = seg_len[order]
    edge = np.full(spad, -1, np.int32)
    edge[:s] = seg_edge[order]
    pack[SP_EDGE] = edge.view(np.float32)

    nblocks = spad // block
    bbox = np.full((nblocks, 4), np.nan, np.float32)
    for blk in range(nblocks):
        sl = slice(blk * block, min((blk + 1) * block, s))
        if sl.start >= s:
            break
        xs = np.concatenate([a[sl, 0], b[sl, 0]])
        ys = np.concatenate([a[sl, 1], b[sl, 1]])
        bbox[blk] = (xs.min(), ys.min(), xs.max(), ys.max())

    # Per-SUB-slice bboxes (round 8, the in-kernel second culling level).
    # Padding columns (>= s) are excluded; slices with no real column get
    # NaN quads, which every comparison in the kernel rejects. Vectorized:
    # the padding tail is contiguous, so per-column extrema with +-inf
    # sentinels reduce correctly and the all-pad slices are masked after.
    nsub = block // _SUB if _SUB and block % _SUB == 0 else 1
    subw = block // nsub
    real = np.arange(spad) < s
    big = np.float32(np.inf)
    cxmin = np.where(real, np.minimum(pack[SP_AX], pack[SP_BX]), big)
    cymin = np.where(real, np.minimum(pack[SP_AY], pack[SP_BY]), big)
    cxmax = np.where(real, np.maximum(pack[SP_AX], pack[SP_BX]), -big)
    cymax = np.where(real, np.maximum(pack[SP_AY], pack[SP_BY]), -big)
    quads = np.stack([cxmin.reshape(-1, subw).min(1),
                      cymin.reshape(-1, subw).min(1),
                      cxmax.reshape(-1, subw).max(1),
                      cymax.reshape(-1, subw).max(1)], axis=1)
    quads[~real.reshape(-1, subw).any(1)] = np.nan
    quads = quads.astype(np.float32)
    sub = quads.reshape(nblocks, nsub * 4)

    # Per-column MXU feature rows (round 13): quadratic expansion of the
    # point-to-LINE squared distance, recentered on each column's slice
    # center. Coefficients are computed in f64 and stored f32 (host
    # rounding ≪ the kernel's bf16-grade margin); the CENTER itself rides
    # rows SF_CX/SF_CY so the kernel and the builder can never disagree
    # on it. Padding columns get zero coefficients + F = BIG → their
    # coarse distance is BIG and can never keep a slice alive by itself.
    centers = np.stack([(quads[:, 0] + quads[:, 2]) * np.float32(0.5),
                        (quads[:, 1] + quads[:, 3]) * np.float32(0.5)],
                       axis=1)                         # f32 [nslices, 2]
    c64 = np.repeat(centers, subw, axis=0).astype(np.float64)  # [spad, 2]
    a64 = np.stack([pack[SP_AX], pack[SP_AY]], 1).astype(np.float64)
    b64 = np.stack([pack[SP_BX], pack[SP_BY]], 1).astype(np.float64)
    d64 = b64 - a64
    w = 1.0 / np.maximum((d64 * d64).sum(1), 1e-12)    # same eps as the
    #                                                    exact geometry
    e64 = a64 - c64
    g = e64[:, 0] * d64[:, 1] - e64[:, 1] * d64[:, 0]  # e x d
    feat = np.zeros((SF_NCOMP, spad), np.float32)
    feat[SF_A] = np.where(real, d64[:, 1] ** 2 * w, 0.0)
    feat[SF_B] = np.where(real, d64[:, 0] ** 2 * w, 0.0)
    feat[SF_C] = np.where(real, -2.0 * d64[:, 0] * d64[:, 1] * w, 0.0)
    feat[SF_D] = np.where(real, -2.0 * g * d64[:, 1] * w, 0.0)
    feat[SF_E] = np.where(real, 2.0 * g * d64[:, 0] * w, 0.0)
    feat[SF_F] = np.where(real, g * g * w, BIG)
    feat[SF_CX] = c64[:, 0]
    feat[SF_CY] = c64[:, 1]
    return SegPack(pack=pack, bbox=bbox, sub=sub, feat=feat)


def cull_radius(radius: float) -> float:
    """The sub-slice cull's statically dilated radius: absorbs f32
    rounding of the point-to-bbox lower bound so the in-kernel cull can
    never drop a pair the exact r2 test would keep. ONE definition —
    bench's host-side culling-stats replication imports it, so the
    reported pair counts stay exactly what the kernel computes even if
    this is retuned."""
    return float(radius) * 1.0005 + 0.01


def _block_geometry(px, py, seg):
    """Distances/offsets of a [P,1] point column against a [8,SBLK] segment
    block. Returns (d2 [P,SBLK], edge [P,SBLK] i32, offabs [P,SBLK]).
    Shared by the pallas kernel and the jnp fallback."""
    ax = seg[SP_AX:SP_AX + 1, :]
    ay = seg[SP_AY:SP_AY + 1, :]
    bx = seg[SP_BX:SP_BX + 1, :]
    by = seg[SP_BY:SP_BY + 1, :]
    off0 = seg[SP_OFF:SP_OFF + 1, :]
    slen = seg[SP_LEN:SP_LEN + 1, :]
    edge = jax.lax.bitcast_convert_type(seg[SP_EDGE:SP_EDGE + 1, :], jnp.int32)

    abx = bx - ax
    aby = by - ay
    denom = jnp.maximum(abx * abx + aby * aby, 1e-12)
    t = jnp.clip(((px - ax) * abx + (py - ay) * aby) / denom, 0.0, 1.0)
    dx = px - (ax + t * abx)
    dy = py - (ay + t * aby)
    d2 = dx * dx + dy * dy
    offabs = off0 + t * slen
    return d2, jnp.broadcast_to(edge, d2.shape), offabs


def _select_topk(d2, edge, offabs, k: int):
    """K passes of (pick global min lane, extract fields, kill same-edge).

    d2 [P, C] (BIG = invalid), edge i32 [P, C], offabs [P, C] →
    (d2 [P, K], edge [P, K], offabs [P, K]); scans C columns K times, all
    lane-parallel VPU work. Same algorithm as candidates._topk_distinct_edges
    but extraction by masked reduction instead of argmin+gather (in-kernel
    gathers would reintroduce the serialization this kernel removes).

    Distance TIES break toward the smallest edge id — the same order the
    grid backend (cell rows in segment-index order, argmin keeps the
    first) and the CPU oracle (stable sort over the segment arrays)
    resolve them. Morton sorting permutes this kernel's scan order, so a
    first-lane tie-break would pick a DIFFERENT tied candidate than the
    other two backends; at organic degree-5/6 junctions several edges
    tie at exactly the node distance and K fills up, which made the
    divergence visible as ~2% phantom oracle disagreement (round 4).
    Edge-id ties also make the block-merge order-independent.
    """
    P, C = d2.shape
    big_e = jnp.int32(2 ** 31 - 1)
    outs_d, outs_e, outs_o = [], [], []
    for _ in range(k):
        m = jnp.min(d2, axis=1, keepdims=True)                     # [P,1]
        tied = d2 == m
        pick_e = jnp.min(jnp.where(tied, edge, big_e), axis=1)     # [P]
        # the picked edge IS the reduction result — no lane extraction
        # pass needed; offset = the edge's lowest tied projection (same
        # as the oracle's stable first-segment pick: segment order is
        # increasing offset within an edge). Three column reductions per
        # step vs the old first-lane scheme's four.
        sel = tied & (edge == pick_e[:, None])
        o_k = jnp.min(jnp.where(sel, offabs, BIG), axis=1)
        ok = m[:, 0] < BIG
        outs_d.append(m[:, 0])
        outs_e.append(jnp.where(ok, pick_e, -1))
        outs_o.append(jnp.where(ok, o_k, 0.0))
        d2 = jnp.where((edge == pick_e[:, None]) & ok[:, None], BIG, d2)
    return (jnp.stack(outs_d, 1), jnp.stack(outs_e, 1), jnp.stack(outs_o, 1))


def _sweep_kernel(ids_ref, pts_ref, seg_ref, edge_out, off_out, dist_out,
                  d2_s, edge_s, off_s, *, r2: float, k: int, nj: int):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        d2_s[:] = jnp.full_like(d2_s, BIG)
        edge_s[:] = jnp.full_like(edge_s, -1)
        off_s[:] = jnp.zeros_like(off_s)

    # Padded id-list slots repeat the previous id: the pipeline skips the
    # re-DMA on equal consecutive block indices, and `fresh` skips ALL the
    # VPU work, so non-hit grid steps cost only the program launch.
    fresh = (j == 0) | (ids_ref[i, j] != ids_ref[i, jnp.maximum(j - 1, 0)])

    @pl.when(fresh)
    def _():
        d2, edge, offabs = _block_geometry(pts_ref[:, 0:1], pts_ref[:, 1:2],
                                           seg_ref[:])
        d2 = jnp.where((edge >= 0) & (d2 <= r2), d2, BIG)

        # bbox culling is conservative — blocks with zero in-radius hits
        # still skip the (much heavier) top-K selection machinery
        @pl.when(jnp.min(d2) < BIG)
        def _():
            bd, be, bo = _select_topk(d2, edge, offabs, k)         # [P,K]
            md, me, mo = _select_topk(
                jnp.concatenate([d2_s[:], bd], axis=1),
                jnp.concatenate([edge_s[:], be], axis=1),
                jnp.concatenate([off_s[:], bo], axis=1), k)
            d2_s[:] = md
            edge_s[:] = me
            off_s[:] = mo

    @pl.when(j == nj - 1)
    def _():
        md = d2_s[:]
        edge_out[:] = edge_s[:]
        off_out[:] = off_s[:]
        dist_out[:] = jnp.where(md < BIG,
                                jnp.sqrt(jnp.maximum(md, 0.0)), BIG)


def _sweep_kernel_sub(ids_ref, pts_ref, seg_ref, sub_ref, *rest,
                      r2: float, rc2: float, radius: float, k: int, nj: int,
                      nsub: int, subw: int, lowp: str, mxu: bool = False):
    """Two-level sweep (round 8). Per ``subw``-column slice of the DMA'd
    block: (1) an exact point-vs-slice-bbox distance test (min over the
    chunk's actual points — tighter than the host pre-pass's chunk-bbox
    overlap) gates all pair work; (2) the top-K update is ONE fused
    _select_topk over the [P, subw + k] concat of the slice's distances
    with the running scratch. The old shape selected over the full
    _SBLK-wide block and then merged [P, 2k] — ~4x the selection
    reductions when a single slice holds every in-radius pair, and the
    roofline says selection roughly doubles effective sweep cost.

    ``mxu=True`` (round 13) inserts a matmul-form coarse pair pass per
    surviving slice: point features [P, 8] (quadratic expansion of the
    recentered, clamp-boxed point coordinates) against the staged
    per-column coefficient rows [8, subw] — ONE dot on the MXU whose
    output is each pair's squared point-to-LINE distance, a lower bound
    on the exact point-to-segment distance. Exact f32 geometry +
    selection run only when some coarse distance admits an in-radius
    pair within a conservative margin (bf16-grade operand rounding is
    assumed for BOTH matmul dtypes — see _MXU_REL_MARGIN), so results
    stay bit-identical to every other kernel arm by construction.
    ``lowp`` selects the matmul operand dtype ("bf16" = native MXU
    width, "off" = f32 operands).

    ``lowp="bf16"`` WITHOUT mxu keeps the round-8 VPU filter: a
    recentered bf16 coarse pair pass per surviving slice (a 16-ulp bound
    on the recentered coordinate magnitude plus 0.5 m slack), same
    conservative-refinement contract.

    Exactness of the culling: slice bboxes are built from the same f32
    endpoint values the geometry reads, the point-to-bbox distance is a
    lower bound on every point-to-segment distance in the slice, and
    ``rc2`` carries a small static dilation over ``r2`` to absorb f32
    rounding of the bound itself — so no in-radius pair is ever skipped.
    """
    if mxu:
        (feat_ref, edge_out, off_out, dist_out, d2_s, edge_s, off_s) = rest
    else:
        feat_ref = None
        (edge_out, off_out, dist_out, d2_s, edge_s, off_s) = rest
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        d2_s[:] = jnp.full_like(d2_s, BIG)
        edge_s[:] = jnp.full_like(edge_s, -1)
        off_s[:] = jnp.zeros_like(off_s)

    # same launch-skip discipline as _sweep_kernel: padded id slots repeat
    # the previous id, so non-hit grid steps cost only the program launch
    fresh = (j == 0) | (ids_ref[i, j] != ids_ref[i, jnp.maximum(j - 1, 0)])

    @pl.when(fresh)
    def _():
        px = pts_ref[:, 0:1]
        py = pts_ref[:, 1:2]
        sb = sub_ref[:]                                    # [1, nsub*4]
        for s in range(nsub):                              # static unroll
            lox = sb[0:1, 4 * s + 0:4 * s + 1]             # [1, 1] each
            loy = sb[0:1, 4 * s + 1:4 * s + 2]
            hix = sb[0:1, 4 * s + 2:4 * s + 3]
            hiy = sb[0:1, 4 * s + 3:4 * s + 4]
            dx = jnp.maximum(jnp.maximum(lox - px, px - hix), 0.0)
            dy = jnp.maximum(jnp.maximum(loy - py, py - hiy), 0.0)
            bb2 = dx * dx + dy * dy                        # [P, 1]

            # NaN quads (all-padding slices) compare False -> skipped
            @pl.when(jnp.min(bb2) <= rc2)
            def _(s=s, lox=lox, loy=loy, hix=hix, hiy=hiy):
                seg = seg_ref[:, s * subw:(s + 1) * subw]

                def exact():
                    d2, edge, offabs = _block_geometry(px, py, seg)
                    d2 = jnp.where((edge >= 0) & (d2 <= r2), d2, BIG)

                    @pl.when(jnp.min(d2) < BIG)
                    def _():
                        md, me, mo = _select_topk(
                            jnp.concatenate([d2_s[:], d2], axis=1),
                            jnp.concatenate([edge_s[:], edge], axis=1),
                            jnp.concatenate([off_s[:], offabs], axis=1), k)
                        d2_s[:] = md
                        edge_s[:] = me
                        off_s[:] = mo

                if mxu:
                    # MXU coarse pass: evaluate every pair's squared
                    # point-to-LINE distance as one [P, 8] x [8, subw]
                    # dot over the staged quadratic coefficients. The
                    # point is recentered on the SAME center the
                    # coefficients were built with (read from the
                    # feature rows — never recomputed) and clamped into
                    # the slice bbox dilated by ~radius: the box contains
                    # every segment of the slice, so projecting the
                    # point into it never increases its distance to
                    # them, and the clamp bounds every matmul operand by
                    # the slice extent instead of the chunk's spread
                    # (the r8 bf16-filter argument, verbatim).
                    feat = feat_ref[:, s * subw:(s + 1) * subw]
                    cx = feat[SF_CX:SF_CX + 1, 0:1]    # [1, 1] each
                    cy = feat[SF_CY:SF_CY + 1, 0:1]
                    mx = jnp.float32(radius) * 1.001 + 0.5
                    exm = (hix - lox) * 0.5 + mx
                    eym = (hiy - loy) * 0.5 + mx
                    qx = jnp.clip(px - cx, -exm, exm)  # [P, 1]
                    qy = jnp.clip(py - cy, -eym, eym)
                    one = jnp.ones_like(qx)
                    zero = jnp.zeros_like(qx)
                    pf = jnp.concatenate(
                        [qx * qx, qy * qy, qx * qy, qx, qy, one,
                         zero, zero], axis=1)          # [P, 8]
                    # rows SF_CX/SF_CY multiply the two zero point
                    # features — exactly 0 contribution at any rounding
                    if lowp == "bf16":
                        lhs = pf.astype(jnp.bfloat16)
                        rhs = feat.astype(jnp.bfloat16)
                    else:
                        lhs, rhs = pf, feat
                    d2m = jax.lax.dot_general(
                        lhs, rhs, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)  # [P, subw]
                    scale = jnp.maximum(exm, eym)
                    thr = (jnp.float32(r2)
                           + scale * scale * jnp.float32(_MXU_REL_MARGIN)
                           + jnp.float32(_MXU_ABS_MARGIN))

                    @pl.when(jnp.min(d2m) <= jnp.min(thr))
                    def _():
                        exact()
                elif lowp != "bf16":
                    exact()
                else:
                    # recenter on the slice bbox AND clamp every operand
                    # into the bbox dilated by ~radius: the slice's real
                    # endpoints already lie inside (unchanged); far-away
                    # chunk points and zero-padding columns clamp to the
                    # boundary. Projection onto a convex set containing
                    # the slice's segments never increases the distance
                    # to them, so the coarse test stays conservative —
                    # and the bf16 error scale is bounded by the SLICE
                    # extent + radius instead of the whole chunk's
                    # spread (unclamped, a 2 km trace chunk inflated the
                    # margin until the filter stopped culling anything)
                    mx = jnp.float32(radius) * 1.001 + 0.5
                    cx = (lox + hix) * 0.5
                    cy = (loy + hiy) * 0.5
                    ex = (hix - lox) * 0.5 + mx            # [1, 1]
                    ey = (hiy - loy) * 0.5 + mx
                    pxc = jnp.clip(px - cx, -ex, ex)
                    pyc = jnp.clip(py - cy, -ey, ey)
                    axc = jnp.clip(seg[SP_AX:SP_AX + 1, :] - cx, -ex, ex)
                    ayc = jnp.clip(seg[SP_AY:SP_AY + 1, :] - cy, -ey, ey)
                    bxc = jnp.clip(seg[SP_BX:SP_BX + 1, :] - cx, -ex, ex)
                    byc = jnp.clip(seg[SP_BY:SP_BY + 1, :] - cy, -ey, ey)
                    scale = jnp.maximum(ex, ey)            # |coord| bound
                    bf = jnp.bfloat16
                    pxl, pyl = pxc.astype(bf), pyc.astype(bf)
                    axl, ayl = axc.astype(bf), ayc.astype(bf)
                    abx = bxc.astype(bf) - axl
                    aby = byc.astype(bf) - ayl
                    den = jnp.maximum(abx * abx + aby * aby,
                                      jnp.asarray(1e-12, bf))
                    t = jnp.clip(((pxl - axl) * abx + (pyl - ayl) * aby)
                                 / den,
                                 jnp.asarray(0.0, bf), jnp.asarray(1.0, bf))
                    dxl = pxl - (axl + t * abx)
                    dyl = pyl - (ayl + t * aby)
                    d2c = (dxl * dxl + dyl * dyl).astype(jnp.float32)
                    # conservative inflation: bf16 rounds each operand to
                    # <= scale * 2^-9 absolute error and the ~10-op chain
                    # accumulates a few ulps more — scale * 2^-4 (6.25%)
                    # is ~16x that bound, plus a 0.5 m absolute slack for
                    # the tiny-coordinate regime
                    rl = (jnp.float32(radius) + scale * jnp.float32(0.0625)
                          + jnp.float32(0.5))              # [1, 1]

                    @pl.when(jnp.min(d2c) <= jnp.min(rl * rl))
                    def _():
                        exact()

    @pl.when(j == nj - 1)
    def _():
        md = d2_s[:]
        edge_out[:] = edge_s[:]
        off_out[:] = off_s[:]
        dist_out[:] = jnp.where(md < BIG,
                                jnp.sqrt(jnp.maximum(md, 0.0)), BIG)


def _chunk_block_ids(pts, valid, bbox, radius: float, nchunks: int):
    """Culling pre-pass: ([nchunks, nblocks] i32 block ids to visit,
    [nchunks] i32 hit counts).

    pts f32 [nchunks*P, 2] (already padded), valid bool [nchunks*P].
    Each chunk is split into _NSUB consecutive sub-ranges; a block is a hit
    if its (radius-dilated) bbox overlaps any sub-range's bbox. Hits are
    listed first (ascending id); the tail repeats the last hit so the
    kernel skips both the DMA and all compute for those slots.
    """
    sub = pts.reshape(nchunks * _NSUB, _P // _NSUB, 2)
    v = valid.reshape(nchunks * _NSUB, _P // _NSUB, 1)
    big = jnp.float32(BIG)
    lo = jnp.min(jnp.where(v, sub, big), axis=1)        # [nc*NSUB, 2]
    hi = jnp.max(jnp.where(v, sub, -big), axis=1)
    lo = lo - radius
    hi = hi + radius

    bxmin, bymin, bxmax, bymax = (bbox[:, 0], bbox[:, 1], bbox[:, 2],
                                  bbox[:, 3])
    hit = ((bxmin[None, :] <= hi[:, 0:1]) & (bxmax[None, :] >= lo[:, 0:1]) &
           (bymin[None, :] <= hi[:, 1:2]) & (bymax[None, :] >= lo[:, 1:2]))
    hit = hit.reshape(nchunks, _NSUB, -1).any(axis=1)   # [nchunks, nblocks]

    nblocks = hit.shape[1]
    ids = jnp.arange(nblocks, dtype=jnp.int32)[None, :]
    key = jnp.where(hit, ids, nblocks + ids)            # hits sort first
    order = jnp.sort(key, axis=1)                       # [nchunks, nblocks]
    is_hit = order < nblocks
    hit_id = jnp.where(is_hit, order, 0)
    # pad slots ← running last hit (cummax works since ids ascend); the
    # list keeps FULL width nblocks, so no hit is ever dropped — sparsity
    # is recovered by the narrow-grid truncation in _dense_pallas (exact
    # whenever hits fit _NJ_CAP — the counts returned here prove it) and
    # in-kernel by the `fresh` skip
    padded = jax.lax.cummax(jnp.where(is_hit, hit_id, -1), axis=1)
    # dtype pinned: a bool jnp.sum accumulates in the DEFAULT int width,
    # which under x64 silently widens to i64 (device-contract x64 audit)
    return (jnp.maximum(padded, 0).astype(jnp.int32),
            jnp.sum(hit, axis=1, dtype=jnp.int32))


def _dense_pallas(points, valid, seg_pack: "SegPack | tuple", radius: float,
                  k: int, subcull: bool = True, lowp: str = "off",
                  mxu: bool = False, nj_cap: "int | None" = None):
    # resolved at CALL time so the interpret-parity tests' module-global
    # monkeypatch keeps working; params-driven callers pass the rung
    nj_cap = _NJ_CAP if nj_cap is None else int(nj_cap)
    pack, bbox = seg_pack[0], seg_pack[1]
    sub = seg_pack[2] if len(seg_pack) > 2 else None
    feat = seg_pack[3] if len(seg_pack) > 3 else None
    use_sub = bool(subcull) and sub is not None
    if lowp == "bf16" and not use_sub and not mxu:
        # only the two-level kernel implements the low-precision pass;
        # silently running plain f32 would let an A/B "bf16 arm" measure
        # f32 against itself (the config layer raises the same way)
        raise ValueError(
            "lowp='bf16' requires the two-level kernel: subcull=True and "
            "a seg_pack built with sub quads")
    use_mxu = bool(mxu)
    if use_mxu and (not use_sub or feat is None):
        # same discipline: an "mxu arm" that silently fell back to the
        # plain two-level kernel would A/B-measure an arm against itself
        raise ValueError(
            "mxu=True requires the two-level kernel (subcull=True) and a "
            "seg_pack built with feat rows (round 13 build_seg_pack)")
    n = points.shape[0]
    spad = pack.shape[1]
    nchunks = max(1, (n + _P - 1) // _P)
    npad = nchunks * _P
    pts = jnp.pad(points, ((0, npad - n), (0, 0)))
    val = jnp.pad(valid, (0, npad - n))
    # neutralize invalid points (zeros would drag chunk bboxes to origin):
    # replace with the chunk's masked mean so they cull like their chunk
    chunks = pts.reshape(nchunks, _P, 2)
    vc = val.reshape(nchunks, _P, 1)
    # dtype pinned (see _chunk_block_ids): the default-int bool sum would
    # also drag the mean's division up to f64 under x64
    cnt = jnp.maximum(jnp.sum(vc, axis=1, dtype=jnp.int32), 1)
    mean = jnp.sum(jnp.where(vc, chunks, 0.0), axis=1) / cnt
    pts = jnp.where(vc, chunks, mean[:, None, :]).reshape(npad, 2)

    ids, nhits = _chunk_block_ids(pts, val, bbox, radius, nchunks)

    if use_sub:
        nsub4 = int(sub.shape[1])
        nsub = nsub4 // 4
        subw = _SBLK // nsub
        rc = cull_radius(radius)
    r2 = float(radius) * float(radius)

    def call(ids_g, pts_g, nj):
        nc = ids_g.shape[0]
        in_specs = [
            pl.BlockSpec((_P, 2), lambda i, j, ids: (i, 0)),
            pl.BlockSpec((SP_NCOMP, _SBLK),
                         lambda i, j, ids: (0, ids[i, j])),
        ]
        inputs = [ids_g, pts_g, pack]
        if use_sub:
            in_specs.append(
                pl.BlockSpec((1, nsub4), lambda i, j, ids: (ids[i, j], 0)))
            inputs.append(sub)
            if use_mxu:
                # feature rows ride the same per-block DMA discipline as
                # the segment pack (equal consecutive ids skip the fetch)
                in_specs.append(
                    pl.BlockSpec((SF_NCOMP, _SBLK),
                                 lambda i, j, ids: (0, ids[i, j])))
                inputs.append(feat)
            kern = functools.partial(
                _sweep_kernel_sub, r2=r2, rc2=rc * rc, radius=float(radius),
                k=k, nj=nj, nsub=nsub, subw=subw, lowp=lowp, mxu=use_mxu)
        else:
            kern = functools.partial(_sweep_kernel, r2=r2, k=k, nj=nj)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nc, nj),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((_P, k), lambda i, j, ids: (i, 0)),
                pl.BlockSpec((_P, k), lambda i, j, ids: (i, 0)),
                pl.BlockSpec((_P, k), lambda i, j, ids: (i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((_P, k), jnp.float32),
                pltpu.VMEM((_P, k), jnp.int32),
                pltpu.VMEM((_P, k), jnp.float32),
            ],
        )
        return pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((nc * _P, k), jnp.int32),
                jax.ShapeDtypeStruct((nc * _P, k), jnp.float32),
                jax.ShapeDtypeStruct((nc * _P, k), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(*inputs)

    def sweep(ids_w):
        """Full sweep at one static id-list width. The grid dim must
        equal the id-list width or the kernel reads the scalar ref out of
        bounds. The prefetched id list lives in SMEM (~1 MB), lane-padded
        to 128 columns — cap chunks per pallas_call and sequence groups
        (XLA pipelines consecutive custom calls)."""
        nj = ids_w.shape[1]
        _, maxc = prefetch_group_cap(nj)
        if nchunks <= maxc:
            # tuple(): the narrow/full cond branches can take different
            # chunking paths here, and lax.cond requires identical output
            # containers — don't rely on pallas_call's own return type
            return tuple(call(ids_w, pts, nj))
        parts = []
        for lo in range(0, nchunks, maxc):
            hi = min(nchunks, lo + maxc)
            parts.append(call(ids_w[lo:hi], pts[lo * _P:hi * _P], nj))
        return tuple(jnp.concatenate(xs, axis=0) for xs in zip(*parts))

    # Narrow-grid launch (round-5 xl attribution): the full-width grid
    # runs nblocks steps per chunk and big metros pay megasteps of empty
    # launches — bayarea-xl's 1184-block table spent ~45% of its dispatch
    # on culled slots (~85 ns each). Hits sort first, so truncating the
    # id list to nj_cap columns is EXACT whenever every chunk hits at
    # most nj_cap blocks (typical max is tens; the culling stats prove
    # it per dispatch) — one traced cond falls back to the full-width
    # sweep for the rare spread-out batch.
    if ids.shape[1] > nj_cap:
        edge, off, dist = jax.lax.cond(
            jnp.max(nhits) <= nj_cap,
            lambda: sweep(ids[:, :nj_cap]),
            lambda: sweep(ids))
    else:
        edge, off, dist = sweep(ids)
    return edge[:n], off[:n], dist[:n]


def _dense_jnp(points, seg_pack, radius: float, k: int):
    """Reference path (CPU tests, multichip dry-runs, interpret debugging):
    full sweep, no culling — identical output, blocked over points to bound
    the [P, S] temporary."""
    pack = seg_pack[0] if isinstance(seg_pack, (tuple, SegPack)) else seg_pack
    n = points.shape[0]
    # own chunk size, decoupled from the pallas launch-grid tuning (_P):
    # this path's [P, S] f32 temporary is ~P*606k*4 B at xl scale on the
    # one-core CPU host, so keep P at the memory-bounding 128
    P = 128
    nchunks = max(1, (n + P - 1) // P)
    npad = nchunks * P
    pts = jnp.pad(points, ((0, npad - n), (0, 0))).reshape(nchunks, P, 2)
    r2 = radius * radius

    def chunk(p):
        d2, edge, offabs = _block_geometry(p[:, 0:1], p[:, 1:2], pack)
        d2 = jnp.where((edge >= 0) & (d2 <= r2), d2, BIG)
        return _select_topk(d2, edge, offabs, k)

    d2c, ec, oc = jax.lax.map(chunk, pts)
    d2c = d2c.reshape(npad, k)[:n]
    dist = jnp.where(d2c < BIG, jnp.sqrt(jnp.maximum(d2c, 0.0)), BIG)
    return ec.reshape(npad, k)[:n], oc.reshape(npad, k)[:n], dist


# SMEM budget of one pallas_call's scalar-prefetch id list: the whole
# [nc, nj] i32 array is prefetched, lane-padded to 128 columns, and SMEM
# is ~1 MB per core — the 512 KB self-cap leaves headroom for the grid
# indices and compiler-managed scalars. ONE definition: the launcher's
# chunk grouping below and the static device-contract audit
# (analysis/device_contract.py) must bound the same bytes.
SMEM_PREFETCH_BUDGET = 512 * 1024
SMEM_LANE_PAD = 128


def prefetch_group_cap(nj: int) -> "tuple[int, int]":
    """(lane-padded id-list columns, max chunks per pallas_call) for an
    id list ``nj`` wide — the shape math that keeps every grouped
    scalar-prefetch launch inside ``SMEM_PREFETCH_BUDGET``."""
    padded_cols = ((nj + SMEM_LANE_PAD - 1) // SMEM_LANE_PAD) * SMEM_LANE_PAD
    return padded_cols, max(1, SMEM_PREFETCH_BUDGET // (padded_cols * 4))


def prefetch_smem_bytes(nchunks: int, nj: int) -> int:
    """Static SMEM footprint bound of the id list for ONE grouped launch
    over ``nchunks`` point chunks at id-list width ``nj`` (the audit's
    closed form; the launcher never exceeds it by construction)."""
    padded_cols, maxc = prefetch_group_cap(nj)
    return min(nchunks, maxc) * padded_cols * 4


_FORCE_PALLAS_TRACE = 0


@contextlib.contextmanager
def pallas_trace_override():
    """Audit hook (analysis/device_contract.py): make ``_use_pallas()``
    answer True on a CPU host so ``jax.make_jaxpr`` traces the ACTUAL
    kernel program — abstract eval only, nothing is lowered or run."""
    global _FORCE_PALLAS_TRACE
    _FORCE_PALLAS_TRACE += 1
    try:
        yield
    finally:
        _FORCE_PALLAS_TRACE -= 1


def _use_pallas() -> bool:
    if _INTERPRET or _FORCE_PALLAS_TRACE:
        return pl is not None
    return pl is not None and jax.default_backend() != "cpu"


def find_candidates_dense(points, seg_pack, radius: float,
                          max_candidates: int,
                          valid=None, subcull: bool = True,
                          lowp: str = "off",
                          mxu: bool = False,
                          nj_cap: "int | None" = None) -> CandidateSet:
    """points f32 [N, 2] → CandidateSet with [N, K] fields (flat batch).

    seg_pack: a SegPack (or (pack, bbox[, sub[, feat]]) tuple of
    arrays). valid (bool [N], optional) marks padding points — they
    still produce (ignored) rows but are excluded from the culling
    bboxes. Uses the pallas sweep on accelerators, the jnp full sweep on
    CPU backends.

    subcull enables the in-kernel sub-block culling + fused narrow top-K
    (round 8; needs the pack's ``sub`` quads — silently falls back to the
    whole-block kernel without them). mxu=True (round 13) runs the
    matmul-form coarse pair pass on the MXU per surviving slice (needs
    the pack's ``feat`` rows — raises without them); lowp="bf16" then
    selects bf16 matmul operands. lowp="bf16" without mxu keeps the
    round-8 VPU coarse pair filter. Every combination is bit-identical
    to the whole-block kernel and the jnp reference by construction
    (interpret-mode test-asserted): coarse passes only ever SKIP
    provably-out-of-radius work, refinement is exact f32.

    nj_cap (round 17): the narrow-grid launch width rung
    (MatcherParams.sweep_nj_cap; None = this module's _NJ_CAP default).
    Exact at any width — the lax.cond full-width fallback is unchanged —
    so the per-metro autotuner may select it freely.
    """
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    if _use_pallas():
        edge, off, dist = _dense_pallas(points, valid, seg_pack, radius,
                                        max_candidates, subcull=subcull,
                                        lowp=lowp, mxu=mxu, nj_cap=nj_cap)
    else:
        edge, off, dist = _dense_jnp(points, seg_pack, radius, max_candidates)
    return CandidateSet(edge=edge, offset=off, dist=dist, valid=edge >= 0)
