"""Candidate search: vmapped point→polyline kNN over the spatial grid.

Replaces Meili's CandidateGridQuery (SURVEY.md §2.2 "Candidate search" —
valhalla/meili/candidate_search, UNVERIFIED): instead of a per-point hash-grid
walk with pointer chasing, every query gathers its OWN grid cell's row —
registration was dilated by index_radius offline (tiles/compiler._build_grid),
so that one row already contains every segment within
search_radius <= index_radius — computes point→segment distances for all C
registered line segments at once on the VPU, and selects the K nearest
*distinct edges* with a fixed-K argmin scan. All shapes static, fully
vmappable over points and traces.

Memory layout matters more than FLOPs here: all per-segment data
(endpoints, offset, length, owning edge) is pre-fused into ``cell_pack``
rows (tiles/tileset.build_cell_pack), so each query issues ONE contiguous
row-gather of [8C] floats. The naive formulation (id grid + six
data-dependent scalar gathers over global segment arrays, 3×3 cell
neighborhood) ran ~40× slower on TPU: gathers of lone f32 elements
serialize, and 9 row-gathers per point beat the HBM access pattern to
death. Offline dilation trades registrations for exactly one contiguous
row read per point.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from reporter_tpu.tiles.tileset import (
    PACK_AX, PACK_AY, PACK_BX, PACK_BY, PACK_EDGE, PACK_LEN, PACK_NCOMP,
    PACK_OFF, TileMeta)

# "infinity" that survives subtraction without NaNs. A numpy scalar, NOT a
# jnp array: materializing a device array at import time initializes the
# XLA backend, which breaks jax.distributed.initialize() for any process
# that imports this package before joining the process group
# (parallel/multihost.py). Behaves identically inside jitted code.
BIG = np.float32(1e30)


class GridMeta(NamedTuple):
    """Grid geometry as scalars — static Python floats for the single-metro
    path, or traced jnp scalars when each shard of a sharded mesh carries a
    different metro's grid (parallel/multimetro.py). ``cell_size`` and
    ``index_radius`` must stay static either way: the coverage check against
    search_radius happens at trace time."""

    ox: Any          # grid origin x (cell (0,0) lower-left)
    oy: Any          # grid origin y
    cell_size: float
    gw: Any          # grid width in cells
    gh: Any          # grid height in cells
    index_radius: float  # registration dilation the grid was built with


def as_grid_meta(meta: "TileMeta | GridMeta") -> GridMeta:
    if isinstance(meta, GridMeta):
        return meta
    return GridMeta(ox=meta.grid_origin[0], oy=meta.grid_origin[1],
                    cell_size=meta.cell_size,
                    gw=meta.grid_dims[0], gh=meta.grid_dims[1],
                    index_radius=meta.index_radius)


class CandidateSet(NamedTuple):
    """Top-K candidate edges per trace point (fixed shapes, -1/BIG padded)."""

    edge: jnp.ndarray    # i32 [T, K] candidate directed-edge id, -1 invalid
    offset: jnp.ndarray  # f32 [T, K] distance along edge of the projection (m)
    dist: jnp.ndarray    # f32 [T, K] euclidean point→edge distance (m)
    valid: jnp.ndarray   # bool [T, K]


def _point_segment_dist(px, py, ax, ay, bx, by):
    """Device mirror of geometry.point_segment_project (distance + t).

    Componentwise (structure-of-arrays) on purpose: stacking xy into a
    trailing size-2 axis would tile terribly on TPU (lane dim padded 2→128);
    with flat [n] operands everything rides the VPU at full width.
    """
    abx = bx - ax
    aby = by - ay
    denom = jnp.maximum(abx * abx + aby * aby, 1e-12)
    t = jnp.clip(((px - ax) * abx + (py - ay) * aby) / denom, 0.0, 1.0)
    dx = px - (ax + t * abx)
    dy = py - (ay + t * aby)
    d = jnp.sqrt(dx * dx + dy * dy)
    return d, t, jnp.sqrt(denom)


def gather_cell_pack(pt, cell_pack, meta: "TileMeta | GridMeta"):
    """Fused segment data for the grid cell containing ``pt``.

    Returns (ax, ay, bx, by, off, slen, edge), each [C]; edge = -1 marks
    padding slots. Registration dilation guarantees this one row covers the
    whole search ball. Out-of-grid points clip to the nearest boundary cell,
    whose dilated registrations cover the first index_radius beyond the
    edge; anything farther is correctly rejected by the distance test.
    Out-of-range rows of a *padded* cell_pack (multimetro stacking pads
    every metro's grid to the same cell count) are never touched: indices
    are clipped to the metro's own gw/gh.
    """
    gm = as_grid_meta(meta)
    cx = jnp.floor((pt[0] - gm.ox) / gm.cell_size).astype(jnp.int32)
    cy = jnp.floor((pt[1] - gm.oy) / gm.cell_size).astype(jnp.int32)
    cell = (jnp.clip(cx, 0, gm.gw - 1) * gm.gh
            + jnp.clip(cy, 0, gm.gh - 1))
    row = cell_pack[cell].reshape(PACK_NCOMP, -1)        # [NCOMP, C]
    edge = jax.lax.bitcast_convert_type(row[PACK_EDGE], jnp.int32)
    return (row[PACK_AX], row[PACK_AY], row[PACK_BX], row[PACK_BY],
            row[PACK_OFF], row[PACK_LEN], edge)


def _topk_distinct_edges(seg_edges, dists, ts, k: int):
    """K nearest distinct edges from per-segment distances.

    seg_edges i32 [C], dists f32 [C] (BIG = invalid), ts f32 [C] projection
    parameter. K sequential argmin steps; after picking an edge every segment
    of that edge is masked, so each edge appears at most once (Meili keeps one
    candidate per edge — the closest projection).
    """

    def step(d, _):
        i = jnp.argmin(d)
        best = d[i]
        e = seg_edges[i]
        picked_valid = best < BIG
        d = jnp.where(seg_edges == e, BIG, d)
        return d, (jnp.where(picked_valid, e, -1), best, jnp.where(picked_valid, i, 0),
                   picked_valid)

    _, (edges, best_d, idx, ok) = jax.lax.scan(step, dists, None, length=k)
    return edges, best_d, idx, ts[idx], ok


def find_candidates(pt, tables, meta: "TileMeta | GridMeta",
                    search_radius: float, max_candidates: int):
    """Candidates for ONE point. vmap over T (and again over batch) upstream.

    tables: dict from TileSet.device_tables().
    Returns (edge [K], offset [K], dist [K], valid [K]).
    """
    ax, ay, bx, by, off0, slen, seg_edge = gather_cell_pack(
        pt, tables["cell_pack"], meta)                           # each [C]
    d, t, _ = _point_segment_dist(pt[0], pt[1], ax, ay, bx, by)
    seg_valid = (seg_edge >= 0) & (d <= search_radius)
    d = jnp.where(seg_valid, d, BIG)

    edges, best_d, idx, t_at, ok = _topk_distinct_edges(
        seg_edge, d, t, max_candidates)
    off = off0[idx] + t_at * slen[idx]
    return CandidateSet(
        edge=edges.astype(jnp.int32),
        offset=jnp.where(ok, off, 0.0).astype(jnp.float32),
        dist=jnp.where(ok, best_d, BIG).astype(jnp.float32),
        valid=ok,
    )


def find_candidates_trace(points, tables, meta: "TileMeta | GridMeta",
                          search_radius: float,
                          max_candidates: int) -> CandidateSet:
    """[T, 2] points → CandidateSet with [T, K] fields."""
    return jax.vmap(
        lambda p: find_candidates(p, tables, meta, search_radius, max_candidates)
    )(points)
