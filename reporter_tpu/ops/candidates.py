"""Candidate search: vmapped point→polyline kNN over the spatial grid.

Replaces Meili's CandidateGridQuery (SURVEY.md §2.2 "Candidate search" —
valhalla/meili/candidate_search, UNVERIFIED): instead of a per-point hash-grid
walk with pointer chasing, every query gathers a fixed 3×3 neighborhood of
grid cells (cell_size >= search_radius guarantees coverage, see
config.Config.validate), computes point→segment distances for all 9·C
registered line segments at once on the VPU, and selects the K nearest
*distinct edges* with a fixed-K argmin scan. All shapes static, fully
vmappable over points and traces.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from reporter_tpu.tiles.tileset import TileMeta

BIG = jnp.float32(1e30)   # "infinity" that survives subtraction without NaNs


class GridMeta(NamedTuple):
    """Grid geometry as scalars — static Python floats for the single-metro
    path, or traced jnp scalars when each shard of a sharded mesh carries a
    different metro's grid (parallel/multimetro.py). ``cell_size`` must stay
    static either way: the 3×3-gather coverage check against search_radius
    happens at trace time."""

    ox: Any          # grid origin x (cell (0,0) lower-left)
    oy: Any          # grid origin y
    cell_size: float
    gw: Any          # grid width in cells
    gh: Any          # grid height in cells


def as_grid_meta(meta: "TileMeta | GridMeta") -> GridMeta:
    if isinstance(meta, GridMeta):
        return meta
    return GridMeta(ox=meta.grid_origin[0], oy=meta.grid_origin[1],
                    cell_size=meta.cell_size,
                    gw=meta.grid_dims[0], gh=meta.grid_dims[1])


class CandidateSet(NamedTuple):
    """Top-K candidate edges per trace point (fixed shapes, -1/BIG padded)."""

    edge: jnp.ndarray    # i32 [T, K] candidate directed-edge id, -1 invalid
    offset: jnp.ndarray  # f32 [T, K] distance along edge of the projection (m)
    dist: jnp.ndarray    # f32 [T, K] euclidean point→edge distance (m)
    valid: jnp.ndarray   # bool [T, K]


def _point_segment_dist(px, py, ax, ay, bx, by):
    """Device mirror of geometry.point_segment_project (distance + t).

    Componentwise (structure-of-arrays) on purpose: stacking xy into a
    trailing size-2 axis would tile terribly on TPU (lane dim padded 2→128);
    with flat [n] operands everything rides the VPU at full width.
    """
    abx = bx - ax
    aby = by - ay
    denom = jnp.maximum(abx * abx + aby * aby, 1e-12)
    t = jnp.clip(((px - ax) * abx + (py - ay) * aby) / denom, 0.0, 1.0)
    dx = px - (ax + t * abx)
    dy = py - (ay + t * aby)
    d = jnp.sqrt(dx * dx + dy * dy)
    return d, t, jnp.sqrt(denom)


def gather_cell_segments(pt, grid, meta: "TileMeta | GridMeta"):
    """Segment ids registered in the 3×3 cell neighborhood of ``pt``.

    Returns i32 [9*C]; -1 entries are padding or out-of-bounds cells.
    Out-of-range cell rows of a *padded* grid (multimetro stacking pads every
    metro's grid to the same cell count) are never touched: indices are
    clipped to the metro's own gw/gh and masked by in_bounds.
    """
    gm = as_grid_meta(meta)
    gw, gh = gm.gw, gm.gh
    ox, oy = gm.ox, gm.oy
    cx = jnp.floor((pt[0] - ox) / gm.cell_size).astype(jnp.int32)
    cy = jnp.floor((pt[1] - oy) / gm.cell_size).astype(jnp.int32)
    dx = jnp.array([-1, -1, -1, 0, 0, 0, 1, 1, 1], jnp.int32)
    dy = jnp.array([-1, 0, 1, -1, 0, 1, -1, 0, 1], jnp.int32)
    xs = cx + dx
    ys = cy + dy
    in_bounds = (xs >= 0) & (xs < gw) & (ys >= 0) & (ys < gh)
    cells = jnp.clip(xs, 0, gw - 1) * gh + jnp.clip(ys, 0, gh - 1)
    segs = grid[cells]                                   # [9, C]
    segs = jnp.where(in_bounds[:, None], segs, -1)
    return segs.reshape(-1)


def _topk_distinct_edges(seg_edges, dists, ts, k: int):
    """K nearest distinct edges from per-segment distances.

    seg_edges i32 [S9], dists f32 [S9] (BIG = invalid), ts f32 [S9] projection
    parameter. K sequential argmin steps; after picking an edge every segment
    of that edge is masked, so each edge appears at most once (Meili keeps one
    candidate per edge — the closest projection).
    """

    def step(d, _):
        i = jnp.argmin(d)
        best = d[i]
        e = seg_edges[i]
        picked_valid = best < BIG
        d = jnp.where(seg_edges == e, BIG, d)
        return d, (jnp.where(picked_valid, e, -1), best, jnp.where(picked_valid, i, 0),
                   picked_valid)

    _, (edges, best_d, idx, ok) = jax.lax.scan(step, dists, None, length=k)
    return edges, best_d, idx, ts[idx], ok


def find_candidates(pt, tables, meta: "TileMeta | GridMeta",
                    search_radius: float, max_candidates: int):
    """Candidates for ONE point. vmap over T (and again over batch) upstream.

    tables: dict from TileSet.device_tables().
    Returns (edge [K], offset [K], dist [K], valid [K]).
    """
    segs = gather_cell_segments(pt, tables["grid"], meta)        # [9C]
    safe = jnp.maximum(segs, 0)
    ax = tables["seg_ax"][safe]
    ay = tables["seg_ay"][safe]
    bx = tables["seg_bx"][safe]
    by = tables["seg_by"][safe]
    d, t, seg_norm = _point_segment_dist(pt[0], pt[1], ax, ay, bx, by)
    seg_valid = (segs >= 0) & (d <= search_radius)
    d = jnp.where(seg_valid, d, BIG)
    seg_edge = jnp.where(segs >= 0, tables["seg_edge"][safe], -1)

    edges, best_d, idx, t_at, ok = _topk_distinct_edges(
        seg_edge, d, t, max_candidates)
    off = tables["seg_off"][safe[idx]] + t_at * seg_norm[idx]
    return CandidateSet(
        edge=edges.astype(jnp.int32),
        offset=jnp.where(ok, off, 0.0).astype(jnp.float32),
        dist=jnp.where(ok, best_d, BIG).astype(jnp.float32),
        valid=ok,
    )


def find_candidates_trace(points, tables, meta: "TileMeta | GridMeta",
                          search_radius: float,
                          max_candidates: int) -> CandidateSet:
    """[T, 2] points → CandidateSet with [T, K] fields."""
    return jax.vmap(
        lambda p: find_candidates(p, tables, meta, search_radius, max_candidates)
    )(points)
