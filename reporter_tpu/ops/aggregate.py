"""Fixed-grid device aggregation scatter (backfill, round 20; mesh r21).

Generalizes ``streaming/histogram.py``'s scatter discipline — an i32
device accumulator updated by ONE jit'd scatter-add with a FIXED update
batch shape (the r12 lesson: jit TRACE+LOWER is per process per shape and
not covered by the persistent compile cache, so a shape-varying scatter
drops ~150 ms of trace cost into whichever measured wave first hits a new
cap) — from the histogram's [rows, bins] 2-D grid to an arbitrary FLAT
grid. Callers (backfill/aggregate.py) own the host-side binning that
turns an observation into a flat cell index; this module owns only the
device residency + chunked padded scatter, so every backfill aggregate
(speed × time-of-day histogram, next-segment turn counts) rides the same
audited kernel instead of growing one scatter per grid shape.

Mesh sharding (round 21): ``FixedGridCounts(size, mesh=...)`` keeps a
PER-DEVICE partial grid ([ndev, size], leading dim sharded over the
flattened data axis — the same ``dp_e2e.data_pspec`` spelling the wire
dispatch uses) and scatters each device's slice of the index stream into
its own partial with zero cross-device communication; the partials are
merged BUCKET-WISE (i32 sum over the shard axis — addition of unit
increments commutes, so the merged grid is bit-identical to single-device
accumulation, the r19 fixed-grid merge discipline) at ``snapshot()``,
which is already the ONE harvest/checkpoint readback. The mesh program is
built by ``mesh_scatter_fn`` — one spelling, two callers: the add() path
below and the device-contract jaxpr audit (analysis/device_contract.py),
so the audited mesh scatter can never drift from the served one.

The numpy reference accumulation lives here too: the device scatter must
stay bit-equal to it over the same index stream (property-tested across
chunk boundaries and the pad path in tests/test_backfill.py — mesh and
single-device — and re-asserted on every bench composite's
``detail.backfill`` leg).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# ONE update-batch shape for the jit'd scatter, same value and same
# reason as SpeedHistogram._CAP: updates pad to it, bigger batches chunk
# through it, and the executable compiles once in the warm-up chunk.
# The mesh path scatters _CAP indices PER SHARD (one [ndev, _CAP] block
# per dispatch), so its effective chunk is ndev × _CAP — still one
# compiled shape per process per mesh.
_CAP = 4096


def _scatter_body(grid, idx, ok):
    # dtype pinned exactly like histogram._accumulate: the bool cast
    # materializes the update in i32 regardless of x64 mode (the
    # device-contract x64 audit covers this jaxpr too).
    upd = ok.astype(jnp.int32)
    return grid.at[jnp.maximum(idx, 0)].add(upd)


# the single-device executable keeps its r20 spelling (jit + donated
# grid); the mesh program wraps the SAME body so the two paths cannot
# fork semantically
_scatter_add = jax.jit(_scatter_body, donate_argnums=(0,))


def mesh_scatter_fn(mesh):
    """``jit(shard_map(_scatter_body))`` over ``mesh`` — THE mesh scatter
    program builder. One spelling, two callers: FixedGridCounts' mesh
    path and the device-contract audit, which abstractly traces the same
    callable so the audited program can never drift from the served one.
    Operands are [ndev, size] / [ndev, _CAP] / [ndev, _CAP] with the
    leading dim sharded over the flattened data axis; each device updates
    ONLY its own partial row — no collective in the jaxpr."""
    from reporter_tpu.parallel.compat import shard_map
    from reporter_tpu.parallel.dp_e2e import data_pspec

    from jax.sharding import PartitionSpec as P

    shard = P(tuple(data_pspec(mesh))[0], None)

    def local(grid, idx, ok):
        return _scatter_body(grid[0], idx[0], ok[0])[None]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(shard,) * 3, out_specs=shard,
        check_vma=False),   # same constant-carry caveat as parallel/dp
        donate_argnums=(0,))


class FixedGridCounts:
    """i32 flat [size] device counts; add() scatters host-binned flat
    cell indices. Out-of-range / negative indices are masked (counted in
    the return value as rejected), never clamped into a real cell.

    ``mesh``: shard the accumulator per-device ([ndev, size] partials,
    round-robin index blocks) — snapshot() merges bucket-wise, bit-
    identical to the single-device grid over the same stream."""

    def __init__(self, size: int, mesh=None):
        self.size = int(size)
        assert 0 < self.size < 2 ** 31, self.size   # i32 index space
        self.mesh = mesh
        if mesh is None:
            self.ndev = 1
            self._grid = jnp.zeros(self.size, jnp.int32)
            self._mesh_fn = None
        else:
            from reporter_tpu.parallel.dp_e2e import flat_device_count

            self.ndev = flat_device_count(mesh)
            self._grid = self._place(
                np.zeros((self.ndev, self.size), np.int32))
            self._mesh_fn = mesh_scatter_fn(mesh)

    def _place(self, arr2d: np.ndarray):
        from reporter_tpu.parallel.dp_e2e import data_pspec

        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = P(tuple(data_pspec(self.mesh))[0], None)
        return jax.device_put(jnp.asarray(arr2d),
                              NamedSharding(self.mesh, shard))

    def add(self, idx: np.ndarray) -> int:
        """One observation per flat index; returns the accepted count."""
        if len(idx) == 0:
            return 0
        idx = np.asarray(idx, np.int64)
        ok = (idx >= 0) & (idx < self.size)
        idx32 = np.where(ok, idx, -1).astype(np.int32)
        step = self.ndev * _CAP
        for lo in range(0, len(idx32), step):
            i = idx32[lo:lo + step]
            o = ok[lo:lo + step]
            pad = step - len(i)
            if pad:
                i = np.pad(i, (0, pad))
                o = np.pad(o, (0, pad))
            if self.mesh is None:
                self._grid = _scatter_add(self._grid, jnp.asarray(i),
                                          jnp.asarray(o))
            else:
                self._grid = self._mesh_fn(
                    self._grid,
                    jnp.asarray(i.reshape(self.ndev, _CAP)),
                    jnp.asarray(o.reshape(self.ndev, _CAP)))
        return int(ok.sum())

    def snapshot(self) -> np.ndarray:
        """Host copy (the ONE readback — harvest/checkpoint only). On a
        mesh this is the bucket-wise merge: per-device partials summed in
        i32 (unit increments commute, so the merged grid is bit-identical
        to single-device accumulation — wrap semantics included)."""
        if self.mesh is None:
            return np.asarray(self._grid)
        return np.asarray(self._grid).sum(axis=0, dtype=np.int32)

    def load(self, grid: np.ndarray) -> None:
        grid = np.asarray(grid).reshape(-1)
        assert grid.shape == (self.size,), (grid.shape, self.size)
        if self.mesh is None:
            self._grid = jnp.asarray(grid.astype(np.int32))
            return
        # checkpointed grids are the MERGED form; resume places the whole
        # restored grid in partial row 0 (rows are partials, not owners —
        # any distribution summing to the grid is equivalent)
        arr = np.zeros((self.ndev, self.size), np.int32)
        arr[0] = grid.astype(np.int32)
        self._grid = self._place(arr)


def reference_counts(size: int, idx: np.ndarray) -> np.ndarray:
    """Numpy reference of the device accumulation: what a FixedGridCounts
    snapshot must equal bit-for-bit after add(idx) from zero state."""
    grid = np.zeros(int(size), np.int32)
    idx = np.asarray(idx, np.int64)
    ok = (idx >= 0) & (idx < size)
    np.add.at(grid, idx[ok], np.int32(1))
    return grid
