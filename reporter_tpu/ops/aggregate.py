"""Fixed-grid device aggregation scatter (backfill, round 20).

Generalizes ``streaming/histogram.py``'s scatter discipline — an i32
device accumulator updated by ONE jit'd scatter-add with a FIXED update
batch shape (the r12 lesson: jit TRACE+LOWER is per process per shape and
not covered by the persistent compile cache, so a shape-varying scatter
drops ~150 ms of trace cost into whichever measured wave first hits a new
cap) — from the histogram's [rows, bins] 2-D grid to an arbitrary FLAT
grid. Callers (backfill/aggregate.py) own the host-side binning that
turns an observation into a flat cell index; this module owns only the
device residency + chunked padded scatter, so every backfill aggregate
(speed × time-of-day histogram, next-segment turn counts) rides the same
audited kernel instead of growing one scatter per grid shape.

The numpy reference accumulation lives here too: the device scatter must
stay bit-equal to it over the same index stream (property-tested across
chunk boundaries and the pad path in tests/test_backfill.py, and
re-asserted on every bench composite's ``detail.backfill`` leg).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

# ONE update-batch shape for the jit'd scatter, same value and same
# reason as SpeedHistogram._CAP: updates pad to it, bigger batches chunk
# through it, and the executable compiles once in the warm-up chunk.
_CAP = 4096


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_add(grid, idx, ok):
    # dtype pinned exactly like histogram._accumulate: the bool cast
    # materializes the update in i32 regardless of x64 mode (the
    # device-contract x64 audit covers this jaxpr too).
    upd = ok.astype(jnp.int32)
    return grid.at[jnp.maximum(idx, 0)].add(upd)


class FixedGridCounts:
    """i32 flat [size] device counts; add() scatters host-binned flat
    cell indices. Out-of-range / negative indices are masked (counted in
    the return value as rejected), never clamped into a real cell."""

    def __init__(self, size: int):
        self.size = int(size)
        assert 0 < self.size < 2 ** 31, self.size   # i32 index space
        self._grid = jnp.zeros(self.size, jnp.int32)

    def add(self, idx: np.ndarray) -> int:
        """One observation per flat index; returns the accepted count."""
        if len(idx) == 0:
            return 0
        idx = np.asarray(idx, np.int64)
        ok = (idx >= 0) & (idx < self.size)
        idx32 = np.where(ok, idx, -1).astype(np.int32)
        for lo in range(0, len(idx32), _CAP):
            i = idx32[lo:lo + _CAP]
            o = ok[lo:lo + _CAP]
            pad = _CAP - len(i)
            if pad:
                i = np.pad(i, (0, pad))
                o = np.pad(o, (0, pad))
            self._grid = _scatter_add(self._grid, jnp.asarray(i),
                                      jnp.asarray(o))
        return int(ok.sum())

    def snapshot(self) -> np.ndarray:
        """Host copy (the ONE readback — harvest/checkpoint only)."""
        return np.asarray(self._grid)

    def load(self, grid: np.ndarray) -> None:
        grid = np.asarray(grid).reshape(-1)
        assert grid.shape == (self.size,), (grid.shape, self.size)
        self._grid = jnp.asarray(grid.astype(np.int32))


def reference_counts(size: int, idx: np.ndarray) -> np.ndarray:
    """Numpy reference of the device accumulation: what a FixedGridCounts
    snapshot must equal bit-for-bit after add(idx) from zero state."""
    grid = np.zeros(int(size), np.int32)
    idx = np.asarray(idx, np.int64)
    ok = (idx >= 0) & (idx < size)
    np.add.at(grid, idx[ok], np.int32(1))
    return grid
