"""HMM emission/transition costs and Viterbi decode as a `lax.scan`.

Replaces Meili's ViterbiSearch + per-pair Dijkstra routing (SURVEY.md §2.2
"HMM Viterbi decode" / "Inter-candidate routing", valhalla/meili — UNVERIFIED
paths): the data-dependent label-set Dijkstra of the reference's hot loop is
replaced by a gather into offline reach tables (tiles/reach.py), so one
Viterbi time-step is pure dense arithmetic over a [K, K] transition block —
scan-friendly, vmappable over a batch of traces, no host round-trips.

Cost model (negative log-likelihood up to constants, matching Meili's):
  emission(c)      = dist(point, c)^2 / (2 * sigma_z^2)
  transition(c→c') = |route_dist(c, c') − gc_dist| / beta
with transitions disallowed when no route exists within the reach radius or
the route detour exceeds ``max_route_distance_factor``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from reporter_tpu.ops.candidates import BIG, CandidateSet


class ViterbiResult(NamedTuple):
    choice: jnp.ndarray       # i32 [T] chosen candidate slot per point, -1 unmatched
    edge: jnp.ndarray         # i32 [T] chosen edge id, -1 unmatched
    offset: jnp.ndarray       # f32 [T] offset along chosen edge (m)
    chain_start: jnp.ndarray  # bool [T] True where a new HMM chain begins
    matched: jnp.ndarray      # bool [T]


def route_distance(e1, off1, e2, off2, tables, backward_slack: float = 10.0):
    """Network distance from candidate (e1, off1) to candidate (e2, off2).

    Broadcasts over leading dims. Uses the reach tables: end-of-e1→start-of-e2
    plus the remainders on both end edges; the same-edge forward case is a
    plain offset difference. Same-edge projections that move *backwards* by
    less than ``backward_slack`` (GPS jitter between close samples) count as
    zero forward progress instead of a full graph loop. BIG when unreachable
    within the reach radius.
    """
    edge_len = tables["edge_len"]
    reach_row = tables["reach_row"]    # i32 [E] — edge → reach row (node
                                       # rows; private rows for restricted
                                       # from-edges, tiles/reach.py)
    reach_to = tables["reach_to"]      # [R, M]
    reach_dist = tables["reach_dist"]  # [R, M]

    e1s = jnp.maximum(e1, 0)
    e2s = jnp.maximum(e2, 0)
    n1 = reach_row[e1s]
    row_to = reach_to[n1]              # [..., M]
    row_d = reach_dist[n1]
    hit = row_to == e2s[..., None]
    gap = jnp.min(jnp.where(hit, row_d, BIG), axis=-1)
    cross = (edge_len[e1s] - off1) + gap + off2

    same = (e1 == e2) & (off2 >= off1 - backward_slack)
    direct = jnp.maximum(off2 - off1, 0.0)
    route = jnp.where(same, jnp.minimum(direct, cross), cross)
    return jnp.where((e1 >= 0) & (e2 >= 0), route, BIG)


def transition_costs(cands_t: CandidateSet, cands_u: CandidateSet, gc, tables,
                     beta: float, max_route_factor: float,
                     backward_slack: float = 10.0):
    """[K, K] transition cost block from point t's candidates to point u's.

    gc: scalar straight-line distance between the two measurements.
    """
    e1, o1 = cands_t.edge, cands_t.offset
    e2, o2 = cands_u.edge, cands_u.offset
    route = route_distance(e1[:, None], o1[:, None], e2[None, :], o2[None, :],
                           tables, backward_slack)
    cost = jnp.abs(route - gc) / beta
    # Detour guard: route much longer than the crow flies ⇒ disallowed
    # (Meili's max_route_distance_factor). The +10 m floor keeps near-zero gc
    # pairs (stopped vehicle) from disallowing everything.
    allowed = (route < BIG) & (route <= max_route_factor * gc + 10.0)
    allowed &= cands_t.valid[:, None] & cands_u.valid[None, :]
    return jnp.where(allowed, cost, BIG)


def emission_costs(cands: CandidateSet, sigma_z: float):
    """[T, K] emission cost; BIG for invalid candidates."""
    c = cands.dist ** 2 / (2.0 * sigma_z ** 2)
    return jnp.where(cands.valid, c, BIG)


def _keep_mask_batched(pts, vp, interp_distance: float):
    """Batch-last keep mask: pts [T, 2, B], vp [T, B] → bool [T, B]."""
    if interp_distance <= 0.0:
        return vp
    d2_min = jnp.float32(interp_distance) ** 2

    def step(carry, x):
        last_pt, any_kept = carry
        pt, v = x
        d2 = jnp.sum((pt - last_pt) ** 2, axis=0)       # [B]
        keep = v & (~any_kept | (d2 >= d2_min))
        return (jnp.where(keep[None, :], pt, last_pt), any_kept | keep), keep

    B = vp.shape[1]
    (_, _), keep = jax.lax.scan(
        step, (pts[0], jnp.zeros((B,), bool)), (pts, vp))
    return keep


def viterbi_decode_batched(cands: CandidateSet, points, valid_pt, tables,
                           sigma_z: float, beta: float,
                           max_route_factor: float, breakage_distance: float,
                           backward_slack: float = 10.0,
                           interpolation_distance: float = 0.0,
                           ) -> ViterbiResult:
    """Whole-batch Viterbi: cands fields [B, T, K], points [B, T, 2],
    valid_pt [B, T] → ViterbiResult fields [B, T].

    Semantically identical to vmap(viterbi_decode) (tests assert bit
    equality) but laid out **batch-last** internally: the scan carries
    [K, B] tensors and each step's K×K transition block is [K, K, B], so
    the batch rides the TPU lane dimension at full width. The vmapped form
    puts K (=8) on lanes — 8/128 occupancy — and measured ~3 ms per scan
    step of almost no arithmetic; batch-last recovers the width.
    """
    B, T, K = cands.edge.shape
    ce = jnp.moveaxis(cands.edge, 0, -1)                # [T, K, B]
    co = jnp.moveaxis(cands.offset, 0, -1)
    cd = jnp.moveaxis(cands.dist, 0, -1)
    cv = jnp.moveaxis(cands.valid, 0, -1)
    pts = jnp.moveaxis(points, 0, -1)                   # [T, 2, B]
    vp = valid_pt.T                                     # [T, B]

    em = jnp.where(cv, cd ** 2 / (2.0 * sigma_z ** 2), BIG)   # [T, K, B]
    keep = _keep_mask_batched(pts, vp, interpolation_distance)
    active = keep & jnp.any(cv, axis=1)                 # [T, B]
    identity_bp = jnp.broadcast_to(
        jnp.arange(K, dtype=jnp.int32)[:, None], (K, B))
    k_iota = jnp.arange(K, dtype=jnp.int32)

    edge_len = tables["edge_len"]
    reach_row = tables["reach_row"]
    reach_to = tables["reach_to"]
    reach_dist = tables["reach_dist"]

    def trans_block(pe, po, pv, e, o, v, gc):
        """[K, K, B] transition costs (mirror of transition_costs)."""
        e1 = jnp.maximum(pe, 0)                         # [K, B]
        e2 = jnp.maximum(e, 0)
        n1 = reach_row[e1]                              # edge → reach row
        rows_to = reach_to[n1]                          # [K, B, M]
        rows_d = reach_dist[n1]
        hit = rows_to[:, None] == e2[None, :, :, None]  # [K, K, B, M]
        gap = jnp.min(jnp.where(hit, rows_d[:, None], BIG), axis=-1)
        cross = (edge_len[e1] - po)[:, None] + gap + o[None, :]
        same = ((pe[:, None] == e[None, :])
                & (o[None, :] >= po[:, None] - backward_slack))
        direct = jnp.maximum(o[None, :] - po[:, None], 0.0)
        route = jnp.where(same, jnp.minimum(direct, cross), cross)
        route = jnp.where((pe[:, None] >= 0) & (e[None, :] >= 0), route, BIG)
        cost = jnp.abs(route - gc) / beta
        allowed = (route < BIG) & (route <= max_route_factor * gc + 10.0)
        allowed &= pv[:, None] & v[None, :]
        return jnp.where(allowed, cost, BIG)

    def step(carry, inp):
        score, prev_pt, prev_any, pe, po, pv = carry
        em_t, pt, act_t, e, o, v = inp

        gc = jnp.sqrt(jnp.sum((pt - prev_pt) ** 2, axis=0))     # [B]
        trans = trans_block(pe, po, pv, e, o, v, gc)            # [K, K, B]
        trans = jnp.where(gc <= breakage_distance, trans, BIG)

        via = score[:, None] + trans
        # index dtype pinned: jnp.argmin indexes in the DEFAULT int width
        # (i64 under x64) — lax.argmin with an explicit index_dtype is
        # the same op with the width pinned (device-contract x64 audit)
        best_prev = jax.lax.argmin(via, 0, jnp.int32)           # [K, B]
        best_cost = jnp.min(via, axis=0)
        connected = best_cost < BIG

        broken = ~jnp.any(connected, axis=0) | ~prev_any        # [B]
        new_score = jnp.where(broken[None, :], em_t,
                              jnp.where(connected, best_cost + em_t, BIG))
        backptr = jnp.where(broken[None, :] | ~connected, -1, best_prev)

        act = act_t[None, :]
        score_out = jnp.where(act, new_score, score)
        new_carry = (score_out,
                     jnp.where(act, pt, prev_pt),
                     act_t | prev_any,
                     jnp.where(act, e, pe),
                     jnp.where(act, o, po),
                     jnp.where(act, v, pv))
        emit = (score_out,
                jnp.where(act, backptr, identity_bp),
                act_t & broken)
        return new_carry, emit

    init = (jnp.full((K, B), BIG, jnp.float32), pts[0],
            jnp.zeros((B,), bool),
            jnp.full((K, B), -1, jnp.int32),
            jnp.zeros((K, B), jnp.float32),
            jnp.zeros((K, B), bool))
    xs = (em, pts, active, ce, co, cv)
    _, (scores, backptrs, started) = jax.lax.scan(step, init, xs)

    # ---- backtrack (reverse scan; see viterbi_decode for the invariant) --
    def back(carry, inp):
        nxt_choice, nxt_started = carry                 # [B]
        score_t, bp_next, act_t, started_t = inp
        sel = k_iota[:, None] == jnp.maximum(nxt_choice, 0)[None, :]
        # dtype pinned: integer jnp.sum accumulates in the DEFAULT int
        # width, which under x64 silently widens the scan carry to i64
        # (the device-contract x64 audit traces exactly this)
        prop = jnp.sum(jnp.where(sel, bp_next, 0), axis=0, dtype=jnp.int32)
        prop = jnp.where(nxt_choice >= 0, prop, -1)
        own = jax.lax.argmin(score_t, 0, jnp.int32)   # index dtype pinned
        own = jnp.where(jnp.min(score_t, axis=0) < BIG, own, -1)
        terminal = nxt_started | (nxt_choice < 0)
        choice_t = jnp.where(terminal, own, prop)
        out = jnp.where(act_t, choice_t, -1)
        return (choice_t, started_t), out

    bp_above = jnp.concatenate(
        [backptrs[1:], jnp.full((1, K, B), -1, jnp.int32)])
    rev = (scores[::-1], bp_above[::-1], active[::-1], started[::-1])
    _, choices_rev = jax.lax.scan(
        back, (jnp.full((B,), -1, jnp.int32), jnp.ones((B,), bool)), rev)
    choice = choices_rev[::-1]                          # [T, B]

    safe = jnp.maximum(choice, 0)
    matched = choice >= 0
    sel = k_iota[None, :, None] == safe[:, None, :]     # [T, K, B]
    edge = jnp.where(matched,
                     jnp.sum(jnp.where(sel, ce, 0), axis=1, dtype=jnp.int32),
                     -1)
    offset = jnp.where(matched, jnp.sum(jnp.where(sel, co, 0.0), axis=1), 0.0)

    # interpolated points ride the matched path (see viterbi_decode)
    interp = vp & ~keep

    def fill(carry, x):
        pe_, po_, pok = carry                           # [B]
        e, o, m, ip = x
        use = ip & pok & ~m
        e2 = jnp.where(use, pe_, e)
        o2 = jnp.where(use, po_, o)
        new = (jnp.where(m, e, pe_), jnp.where(m, o, po_), pok | m)
        return new, (e2, o2, m | use)

    _, (edge, offset, matched) = jax.lax.scan(
        fill, (jnp.full((B,), -1, jnp.int32), jnp.zeros((B,), jnp.float32),
               jnp.zeros((B,), bool)),
        (edge, offset, matched, interp))

    return ViterbiResult(
        choice=choice.T.astype(jnp.int32),
        edge=edge.T.astype(jnp.int32),
        offset=offset.T,
        chain_start=started.T,
        matched=matched.T,
    )


def interpolation_keep_mask(points, valid_pt, interp_distance: float):
    """bool [T]: False for points within ``interp_distance`` of the last
    kept point — Meili's input interpolation (such points ride the matched
    path instead of voting in the HMM; SURVEY.md §2.2 map-matcher row).
    Sequential by definition (distance to the last KEPT point), so a small
    lax.scan over T; vmap over traces upstream."""
    if interp_distance <= 0.0:
        return valid_pt
    d2_min = jnp.float32(interp_distance) ** 2

    def step(carry, x):
        last_pt, any_kept = carry
        pt, v = x
        d2 = jnp.sum((pt - last_pt) ** 2)
        keep = v & (~any_kept | (d2 >= d2_min))
        return (jnp.where(keep, pt, last_pt), any_kept | keep), keep

    (_, _), keep = jax.lax.scan(
        step, (points[0], jnp.bool_(False)), (points, valid_pt))
    return keep


def _forward_lattice(cands: CandidateSet, points, valid_pt, keep, tables,
                     sigma_z: float, beta: float, max_route_factor: float,
                     breakage_distance: float, backward_slack: float):
    """Forward Viterbi pass of ONE trace → (scores [T,K], backptrs [T,K],
    started [T], active [T]). Shared by viterbi_decode (best path) and
    viterbi_topk_paths (K-best terminal completions)."""
    T, K = cands.edge.shape
    em = emission_costs(cands, sigma_z)                     # [T, K]
    active = keep & jnp.any(cands.valid, axis=1)            # [T]
    identity_bp = jnp.arange(K, dtype=jnp.int32)

    def slot_view(t_idx):
        return CandidateSet(edge=cands.edge[t_idx], offset=cands.offset[t_idx],
                            dist=cands.dist[t_idx], valid=cands.valid[t_idx])

    def step(carry, inp):
        score, prev_pt, prev_any, prev_idx = carry
        em_t, pt, act_t, t_idx = inp

        gc = jnp.sqrt(jnp.sum((pt - prev_pt) ** 2))
        trans = transition_costs(slot_view(prev_idx), slot_view(t_idx), gc,
                                 tables, beta, max_route_factor,
                                 backward_slack)                   # [K, K]
        trans = jnp.where(gc <= breakage_distance, trans, BIG)

        via = score[:, None] + trans
        best_prev = jnp.argmin(via, axis=0).astype(jnp.int32)       # [K]
        best_cost = jnp.min(via, axis=0)
        connected = best_cost < BIG

        broken = ~jnp.any(connected) | ~prev_any
        new_score = jnp.where(broken, em_t,
                              jnp.where(connected, best_cost + em_t, BIG))
        backptr = jnp.where(broken | ~connected, -1, best_prev)

        score_out = jnp.where(act_t, new_score, score)
        new_carry = (score_out,
                     jnp.where(act_t, pt, prev_pt),
                     act_t | prev_any,
                     jnp.where(act_t, t_idx, prev_idx))
        emit = (score_out,
                jnp.where(act_t, backptr, identity_bp),
                act_t & broken)
        return new_carry, emit

    init = (jnp.full((K,), BIG, jnp.float32), points[0], jnp.bool_(False),
            jnp.int32(0))
    xs = (em, points, active, jnp.arange(T, dtype=jnp.int32))
    _, (scores, backptrs, started) = jax.lax.scan(step, init, xs)
    return scores, backptrs, started, active


def viterbi_decode(cands: CandidateSet, points, valid_pt, tables,
                   sigma_z: float, beta: float, max_route_factor: float,
                   breakage_distance: float,
                   backward_slack: float = 10.0,
                   interpolation_distance: float = 0.0) -> ViterbiResult:
    """Viterbi over the candidate lattice of ONE trace.

    points: f32 [T, 2] (for gc distances); valid_pt: bool [T] padding mask.
    Chain breakage: when consecutive points are farther apart than
    ``breakage_distance`` or no transition is allowed, the chain restarts at
    the new point, mirroring Meili's broken-path behavior. Inactive points
    (padding, interpolated, or no candidate in radius) pass the carry
    through untouched with identity backpointers, so chains connect across
    them.
    """
    T, K = cands.edge.shape
    keep = interpolation_keep_mask(points, valid_pt, interpolation_distance)
    scores, backptrs, started, active = _forward_lattice(
        cands, points, valid_pt, keep, tables, sigma_z, beta,
        max_route_factor, breakage_distance, backward_slack)

    # ---- backtrack (reverse scan) ---------------------------------------
    # carry = (slot chosen at the level just above, propagated down through
    # identity backpointers at inactive levels; started flag of that level).
    # A level is a chain terminal when the level above started a new chain
    # (or there is no level above): re-seed from its own score argmin — at
    # inactive levels the passed-through score is exactly the final score of
    # the last active point below, so re-seeding there is correct too.
    def back(carry, inp):
        nxt_choice, nxt_started = carry
        score_t, bp_next, act_t, started_t = inp
        prop = jnp.where(nxt_choice >= 0,
                         bp_next[jnp.maximum(nxt_choice, 0)], -1)
        own = jnp.argmin(score_t).astype(jnp.int32)
        own = jnp.where(score_t[own] < BIG, own, -1)
        terminal = nxt_started | (nxt_choice < 0)
        choice_t = jnp.where(terminal, own, prop)
        out = jnp.where(act_t, choice_t, -1)
        return (choice_t, started_t), out

    bp_above = jnp.concatenate([backptrs[1:], jnp.full((1, K), -1, jnp.int32)])
    rev = (scores[::-1], bp_above[::-1], active[::-1], started[::-1])
    _, choices_rev = jax.lax.scan(back, (jnp.int32(-1), jnp.bool_(True)), rev)
    choice = choices_rev[::-1]

    safe = jnp.maximum(choice, 0)
    matched = choice >= 0
    t_ar = jnp.arange(T)
    edge = jnp.where(matched, cands.edge[t_ar, safe], -1).astype(jnp.int32)
    offset = jnp.where(matched, cands.offset[t_ar, safe], 0.0)

    # Interpolated points (valid but not voting) ride the matched path:
    # inherit the last matched point's (edge, offset), as Meili interpolates
    # skipped input points onto the route. Padding stays unmatched.
    interp = valid_pt & ~keep

    def fill(carry, x):
        pe, po, pok = carry
        e, o, m, ip = x
        use = ip & pok & ~m
        e2 = jnp.where(use, pe, e)
        o2 = jnp.where(use, po, o)
        m2 = m | use
        new = (jnp.where(m, e, pe), jnp.where(m, o, po), pok | m)
        return new, (e2, o2, m2)

    _, (edge, offset, matched) = jax.lax.scan(
        fill, (jnp.int32(-1), jnp.float32(0.0), jnp.bool_(False)),
        (edge, offset, matched, interp))

    return ViterbiResult(
        choice=choice.astype(jnp.int32),
        edge=edge,
        offset=offset,
        chain_start=started,
        matched=matched,
    )


def viterbi_topk_paths(cands: CandidateSet, points, valid_pt, tables,
                       sigma_z: float, beta: float, max_route_factor: float,
                       breakage_distance: float,
                       backward_slack: float = 10.0,
                       interpolation_distance: float = 0.0):
    """K-best path interpretations of ONE trace (Meili's TopKSearch analog,
    SURVEY.md §2.2 HMM row).

    Ranks the final chain's K terminal candidates by accumulated cost and
    backtracks each one; earlier chains keep their best path. (Meili
    enumerates alternates by penalized re-search over the whole lattice;
    terminal completion is the standard single-pass K-best Viterbi
    approximation — alternates differ in the suffix, which for map matching
    is where the ambiguity that TopK serves lives: parallel roads at the
    trace's end.) tests/test_topk_oracle.py pins this contract against an
    exact list-Viterbi: rank 0 is the global optimum, every alternate is
    the exact optimal completion for its terminal, and true K-best
    dominates the returned ranking element-wise.

    Returns (choice [K, T] i32 candidate slots (-1 unmatched), score [K]
    f32 accumulated cost, valid [K] bool), ranked best-first.
    """
    T, K = cands.edge.shape
    keep = interpolation_keep_mask(points, valid_pt, interpolation_distance)
    scores, backptrs, started, active = _forward_lattice(
        cands, points, valid_pt, keep, tables, sigma_z, beta,
        max_route_factor, breakage_distance, backward_slack)

    final = scores[-1]                                   # [K]
    order = jnp.argsort(final).astype(jnp.int32)         # best-first slots
    rank_score = final[order]
    rank_valid = rank_score < BIG

    def back_one(slot):
        # Same reverse scan as viterbi_decode, but the level above T-1 is
        # pinned to `slot`: bp row of all-slot + non-terminal carry makes
        # the last level choose `slot`, propagated down through inactive
        # levels by the identity backpointers.
        def back(carry, inp):
            nxt_choice, nxt_started = carry
            score_t, bp_next, act_t, started_t = inp
            prop = jnp.where(nxt_choice >= 0,
                             bp_next[jnp.maximum(nxt_choice, 0)], -1)
            own = jnp.argmin(score_t).astype(jnp.int32)
            own = jnp.where(score_t[own] < BIG, own, -1)
            terminal = nxt_started | (nxt_choice < 0)
            choice_t = jnp.where(terminal, own, prop)
            out = jnp.where(act_t, choice_t, -1)
            return (choice_t, started_t), out

        bp_above = jnp.concatenate(
            [backptrs[1:], jnp.broadcast_to(slot, (1, K)).astype(jnp.int32)])
        rev = (scores[::-1], bp_above[::-1], active[::-1], started[::-1])
        _, choices_rev = jax.lax.scan(
            back, (slot.astype(jnp.int32), jnp.bool_(False)), rev)
        return choices_rev[::-1]

    choices = jax.vmap(back_one)(order)                  # [K, T]
    choices = jnp.where(rank_valid[:, None], choices, -1)
    return choices, rank_score, rank_valid


def viterbi_kbest_paths(cands: CandidateSet, points, valid_pt, tables,
                        sigma_z: float, beta: float, max_route_factor: float,
                        breakage_distance: float,
                        backward_slack: float = 10.0,
                        interpolation_distance: float = 0.0,
                        num_paths: int = 4):
    """EXACT K-best paths of ONE trace's final chain (list Viterbi).

    Where viterbi_topk_paths returns the optimal completion per terminal
    candidate (alternates can only differ in the suffix), this carries the
    top ``num_paths`` path costs PER LATTICE STATE through the scan — the
    textbook list-Viterbi / parallel-list decoder, which on TPU is just
    one more vectorized axis: the carry is [K, R] instead of [K], the
    per-step reduction a lax.top_k over the (prev candidate × rank)
    axis. Exactness (scores AND paths, against an independent numpy
    list-Viterbi oracle) is asserted by tests/test_topk_oracle.py.

    Alternate ranks share the convention of viterbi_topk_paths: earlier
    chains keep their single best path; ranks enumerate the final chain's
    K globally-best paths, not per-terminal completions.

    Returns (choice [R, T] i32 candidate slots (-1 unmatched), score [R]
    f32, valid [R] bool), ranked best-first.
    """
    T, K = cands.edge.shape
    R = int(num_paths)
    keep = interpolation_keep_mask(points, valid_pt, interpolation_distance)
    em = emission_costs(cands, sigma_z)                     # [T, K]
    active = keep & jnp.any(cands.valid, axis=1)            # [T]
    # flat (candidate, rank) coding: state s = c * R + r
    identity_bp = jnp.arange(K * R, dtype=jnp.int32).reshape(K, R)

    def slot_view(t_idx):
        return CandidateSet(edge=cands.edge[t_idx], offset=cands.offset[t_idx],
                            dist=cands.dist[t_idx], valid=cands.valid[t_idx])

    def step(carry, inp):
        score, prev_pt, prev_any, prev_idx = carry          # score [K, R]
        em_t, pt, act_t, t_idx = inp

        gc = jnp.sqrt(jnp.sum((pt - prev_pt) ** 2))
        trans = transition_costs(slot_view(prev_idx), slot_view(t_idx), gc,
                                 tables, beta, max_route_factor,
                                 backward_slack)             # [K, K]
        trans = jnp.where(gc <= breakage_distance, trans, BIG)

        # via[(cp, r), c] = score[cp, r] + trans[cp, c]; top-R smallest per
        # c. Ties resolve by ascending flat index — the same (cp, r)
        # enumeration order the numpy oracle's stable sort uses.
        via = (score[:, :, None] + trans[:, None, :]).reshape(K * R, K)
        vals, idxs = jax.lax.top_k(-via.T, R)                # [K(c), R]
        best_cost = -vals                                    # ascending
        connected = best_cost < BIG
        broken = ~jnp.any(connected) | ~prev_any

        restart = jnp.concatenate(
            [em_t[:, None], jnp.full((K, R - 1), BIG, em_t.dtype)], axis=1)
        new_score = jnp.where(broken, restart,
                              jnp.where(connected,
                                        best_cost + em_t[:, None], BIG))
        backptr = jnp.where(broken | ~connected, -1, idxs.astype(jnp.int32))

        score_out = jnp.where(act_t, new_score, score)
        new_carry = (score_out,
                     jnp.where(act_t, pt, prev_pt),
                     act_t | prev_any,
                     jnp.where(act_t, t_idx, prev_idx))
        emit = (score_out,
                jnp.where(act_t, backptr, identity_bp),
                act_t & broken)
        return new_carry, emit

    init = (jnp.full((K, R), BIG, jnp.float32), points[0], jnp.bool_(False),
            jnp.int32(0))
    xs = (em, points, active, jnp.arange(T, dtype=jnp.int32))
    _, (scores, backptrs, started) = jax.lax.scan(step, init, xs)
    # scores [T, K, R], backptrs [T, K, R] (flat-coded), started [T]

    final = scores[-1].reshape(K * R)
    order = jnp.argsort(final)[:R].astype(jnp.int32)         # best R states
    rank_score = final[order]
    rank_valid = rank_score < BIG

    def back_one(state):                                     # flat (c, r)
        def back(carry, inp):
            nxt_state, nxt_started = carry
            score_t, bp_next, act_t, started_t = inp
            safe = jnp.maximum(nxt_state, 0)
            prop = jnp.where(nxt_state >= 0,
                             bp_next.reshape(K * R)[safe], -1)
            # chain boundary: earlier chains keep their single best path
            own = jnp.argmin(score_t.reshape(K * R)).astype(jnp.int32)
            own = jnp.where(score_t.reshape(K * R)[own] < BIG, own, -1)
            terminal = nxt_started | (nxt_state < 0)
            state_t = jnp.where(terminal, own, prop)
            out = jnp.where(act_t, state_t, -1)
            return (state_t, started_t), out

        bp_above = jnp.concatenate(
            [backptrs[1:],
             jnp.broadcast_to(state, (1, K, R)).astype(jnp.int32)])
        rev = (scores[::-1], bp_above[::-1], active[::-1], started[::-1])
        _, states_rev = jax.lax.scan(
            back, (state.astype(jnp.int32), jnp.bool_(False)), rev)
        states = states_rev[::-1]
        return jnp.where(states >= 0, states // R, -1)       # slot per point

    choices = jax.vmap(back_one)(order)                      # [R, T]
    choices = jnp.where(rank_valid[:, None], choices, -1)
    return choices, rank_score, rank_valid
