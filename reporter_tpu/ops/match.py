"""Fused per-trace match pipeline and its batched (vmapped) form.

This is the device program that replaces the region between
``segment_matcher.Match(`` and the edge walk in the reference's call stack
(SURVEY.md §3.5): candidates → emission/transition → Viterbi, all under one
`jit`, vmapped across a batch of padded traces. Host code (matcher/) turns
the per-point (edge, offset) output into OSMLR segment reports.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.candidates import find_candidates_trace
from reporter_tpu.ops.hmm import viterbi_decode
from reporter_tpu.tiles.tileset import TileMeta


class MatchOutput(NamedTuple):
    """Per-point match result (fixed [.., T] shapes; -1 = unmatched)."""

    edge: jnp.ndarray         # i32 [.., T]
    offset: jnp.ndarray       # f32 [.., T]
    chain_start: jnp.ndarray  # bool [.., T]
    matched: jnp.ndarray      # bool [.., T]


def match_trace(points, valid_pt, tables, meta,
                params: MatcherParams) -> MatchOutput:
    """Match ONE padded trace: points f32 [T, 2], valid_pt bool [T].

    meta: TileMeta (static) or ops.candidates.GridMeta (scalars, possibly
    traced — the multimetro sharded path).
    """
    if params.search_radius > meta.index_radius:
        # Trace-time check (both are static): the single-cell gather only
        # covers the registration dilation, so a radius beyond index_radius
        # silently drops roads.
        raise ValueError(
            f"search_radius ({params.search_radius}) exceeds tile "
            f"index_radius ({meta.index_radius}); recompile tiles with "
            "index_radius >= radius")
    cands = find_candidates_trace(
        points, tables, meta, params.search_radius, params.max_candidates)
    vit = viterbi_decode(
        cands, points, valid_pt, tables,
        params.sigma_z, params.beta, params.max_route_distance_factor,
        params.breakage_distance, params.backward_slack)
    return MatchOutput(edge=vit.edge, offset=vit.offset,
                       chain_start=vit.chain_start, matched=vit.matched)


@functools.partial(jax.jit, static_argnames=("meta", "params"))
def match_batch(points, valid_pt, tables: dict[str, Any], meta: TileMeta,
                params: MatcherParams) -> MatchOutput:
    """Match a batch: points f32 [B, T, 2], valid_pt bool [B, T].

    meta and params are hashable statics — one compilation per (T, K, tile
    geometry, param set), then every batch reuses the executable
    (SURVEY.md §7.5 "jit persistence").
    """
    return jax.vmap(lambda p, v: match_trace(p, v, tables, meta, params))(
        points, valid_pt)
