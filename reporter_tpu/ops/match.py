"""Fused per-trace match pipeline and its batched (vmapped) form.

This is the device program that replaces the region between
``segment_matcher.Match(`` and the edge walk in the reference's call stack
(SURVEY.md §3.5): candidates → emission/transition → Viterbi, all under one
`jit`, vmapped across a batch of padded traces. Host code (matcher/) turns
the per-point (edge, offset) output into OSMLR segment reports.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from reporter_tpu.config import MatcherParams
from reporter_tpu.ops.candidates import CandidateSet, find_candidates_trace
from reporter_tpu.ops.dense_candidates import find_candidates_dense
from reporter_tpu.ops.hmm import viterbi_decode_batched
from reporter_tpu.tiles.tileset import TileMeta


class MatchOutput(NamedTuple):
    """Per-point match result (fixed [.., T] shapes; -1 = unmatched)."""

    edge: jnp.ndarray         # i32 [.., T]
    offset: jnp.ndarray       # f32 [.., T]
    chain_start: jnp.ndarray  # bool [.., T]
    matched: jnp.ndarray      # bool [.., T]


def _check_grid_coverage(params: MatcherParams, meta) -> None:
    if params.search_radius > meta.index_radius:
        # Trace-time check (both are static): the single-cell gather only
        # covers the registration dilation, so a radius beyond index_radius
        # silently drops roads. (Dense backend sweeps everything — exempt.)
        raise ValueError(
            f"search_radius ({params.search_radius}) exceeds tile "
            f"index_radius ({meta.index_radius}); recompile tiles with "
            "index_radius >= radius")


def batch_candidates(points, valid_pt, tables, meta,
                     params: MatcherParams) -> CandidateSet:
    """Candidates for a batch of traces: points f32 [B, T, 2] → [B, T, K].

    Backend dispatch (params.candidate_backend is static):
      dense — ONE pallas sweep over the flattened [B*T] point batch (the
              kernel amortizes its segment-block DMA across every trace);
      grid  — per-point cell-row gather, vmapped per trace.
    """
    B, T = points.shape[:2]
    backend = params.candidate_backend
    if backend == "auto":
        # trace-time resolution: the sweep wins ~50x on accelerators, the
        # gather wins ~50x on CPU (XLA CPU gathers are cheap; an O(S)
        # sweep per chunk is not)
        backend = "grid" if jax.default_backend() == "cpu" else "dense"
    if backend == "dense":
        flat = find_candidates_dense(
            points.reshape(B * T, 2),
            (tables["seg_pack"], tables["seg_bbox"],
             tables.get("seg_sub"), tables.get("seg_feat")),
            params.search_radius, params.max_candidates,
            valid=valid_pt.reshape(B * T),
            subcull=getattr(params, "sweep_subcull", True),
            lowp=getattr(params, "sweep_lowp", "off"),
            mxu=getattr(params, "sweep_mxu", False),
            nj_cap=getattr(params, "sweep_nj_cap", None))
        return CandidateSet(*(x.reshape(B, T, -1) for x in flat))
    if backend != "grid":
        raise ValueError(
            f"unknown candidate_backend {params.candidate_backend!r}; "
            "use 'auto', 'dense' or 'grid'")
    _check_grid_coverage(params, meta)
    return jax.vmap(lambda p: find_candidates_trace(
        p, tables, meta, params.search_radius, params.max_candidates))(points)


def match_trace(points, valid_pt, tables, meta,
                params: MatcherParams) -> MatchOutput:
    """Match ONE padded trace: points f32 [T, 2], valid_pt bool [T].

    meta: TileMeta (static) or ops.candidates.GridMeta (scalars, possibly
    traced — the multimetro sharded path).
    """
    out = match_traces(points[None], valid_pt[None], tables, meta, params)
    return MatchOutput(*(x[0] for x in out))


def match_traces(points, valid_pt, tables, meta,
                 params: MatcherParams, acc_scale=None) -> MatchOutput:
    """Match a batch (not jitted — compose under jit/vmap/shard_map):
    points f32 [B, T, 2], valid_pt bool [B, T].

    acc_scale f32 [B, T] (optional): per-point GPS-accuracy emission
    scaling. Meili scales the emission sigma by each point's reported
    accuracy; since emission = d²/(2σ²) = (d·σ_z/σ)²/(2σ_z²), scaling the
    candidate DISTANCES by σ_z/σ_point implements per-point σ without
    touching the cost model or the wire format (scaling is uniform within
    a point, so top-K candidate selection is unchanged).
    """
    cands = batch_candidates(points, valid_pt, tables, meta, params)
    if acc_scale is not None:
        cands = cands._replace(dist=cands.dist * acc_scale[..., None])
    vit = viterbi_decode_batched(
        cands, points, valid_pt, tables,
        params.sigma_z, params.beta, params.max_route_distance_factor,
        params.breakage_distance, params.backward_slack,
        params.interpolation_distance)
    return MatchOutput(edge=vit.edge, offset=vit.offset,
                       chain_start=vit.chain_start, matched=vit.matched)


@functools.partial(jax.jit, static_argnames=("meta", "params"))
def match_batch(points, valid_pt, tables: dict[str, Any], meta: TileMeta,
                params: MatcherParams) -> MatchOutput:
    """Match a batch: points f32 [B, T, 2], valid_pt bool [B, T].

    meta and params are hashable statics — one compilation per (T, K, tile
    geometry, param set), then every batch reuses the executable
    (SURVEY.md §7.5 "jit persistence").
    """
    return match_traces(points, valid_pt, tables, meta, params)


# Wire format (match_batch_wire): ONE array so the decode result crosses
# the device→host link as a single transfer. Three layouts, chosen
# statically from the tile (unpack_wire dispatches on lane count/dtype):
#   compact u16 [B, 2, T]  — metros ≤ _COMPACT_WIRE_EDGES edges:
#     lane 0 offset (0.25 m fixed point), lane 1 id(14)|start|matched
#   packed  u32 [B, 1, T]  — bigger metros whenever wire_spec() accepts:
#     offset(ob) | edge(30-ob) | start<<30 | matched<<31 (same bytes as
#     compact; -33% vs the 3-lane fallback on the readback-bound path)
#   full    u16 [B, 3, T]  — the fallback (multi-km edges at ~0.5M ids):
#     lane 0 offset, lane 1 id low 16, lane 2 id hi(13)|start|matched
OFFSET_QUANTUM = 0.25


def wire_from_f32(points, lengths, tables: dict[str, Any], meta: TileMeta,
                  params: MatcherParams, acc_scale=None, spec=None):
    """points f32 [B, T, 2], lengths i32 [B] (valid prefix per trace) →
    u16 [B, 2|3, T] wire array; unpack with unpack_wire(). acc_scale: see
    match_traces (None traces a separate, scale-free executable, so
    accuracy-less batches pay nothing). Undecorated body: jit via
    match_batch_wire, or wrap in shard_map (parallel/dp_e2e) — the SAME
    device program serves both so the sharded product path cannot drift."""
    T = points.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]
    out = match_traces(points, valid, tables, meta, params, acc_scale)
    return _pack_wire(out, tables["edge_len"].shape[0], spec)


def wire_from_q16(points_q, origins, lengths, tables: dict[str, Any],
                  meta: TileMeta, params: MatcherParams, acc_scale=None,
                  spec=None):
    """Quantized-input variant: points_q i16 [B, T, 2] are 0.25 m
    fixed-point offsets from per-trace origins f32 [B, 2] (host→device
    bytes halve vs f32; 0.125 m quantization ≪ sigma_z). Traces spanning
    beyond ±8.19 km of their origin don't fit i16 — the host batcher
    (matcher/api._decode_many) falls back to the f32 entry for those."""
    T = points_q.shape[1]
    points = origins[:, None, :] + points_q.astype(jnp.float32) * jnp.float32(
        OFFSET_QUANTUM)
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]
    out = match_traces(points, valid, tables, meta, params, acc_scale)
    return _pack_wire(out, tables["edge_len"].shape[0], spec)


def wire_from_q8(deltas_q, origins, lengths, tables: dict[str, Any],
                 meta: TileMeta, params: MatcherParams,
                 acc_scale=None, spec=None):
    """Delta-quantized input: deltas_q i8 [B, T, 2] are the per-step
    DIFFERENCES of the i16 0.25 m quanta (first step 0 — the origin is
    the first point). Integer cumsum reconstructs the i16 absolutes
    EXACTLY, so this path is bit-identical to match_batch_wire_q on every
    valid point at half the host→device bytes — consecutive GPS points
    at 1 Hz move well under the ±31.75 m an i8 delta can express; the
    host batcher zeroes pad-region deltas (padded positions sit at the
    last valid point, mask-excluded) and falls back to i16 when a real
    step doesn't fit."""
    q = jnp.cumsum(deltas_q.astype(jnp.int32), axis=1)
    points = origins[:, None, :] + q.astype(jnp.float32) * jnp.float32(
        OFFSET_QUANTUM)
    T = deltas_q.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]
    out = match_traces(points, valid, tables, meta, params, acc_scale)
    return _pack_wire(out, tables["edge_len"].shape[0], spec)


match_batch_wire = functools.partial(
    jax.jit, static_argnames=("meta", "params", "spec"))(wire_from_f32)
match_batch_wire_q = functools.partial(
    jax.jit, static_argnames=("meta", "params", "spec"))(wire_from_q16)
match_batch_wire_q8 = functools.partial(
    jax.jit, static_argnames=("meta", "params", "spec"))(wire_from_q8)


# Compact 2-lane format: metros under _COMPACT_WIRE_EDGES directed edges
# (most single-city tiles — sf's 5.3k qualifies, bayarea's 54k does not)
# fit the edge id in 14 bits, so lane 1 carries id | start | matched and
# lane 0 the offset — one third fewer device→host bytes on exactly the
# link-bound path. The format is chosen statically from the edge count
# (tables shape → trace-time constant); unpack_wire dispatches on the
# lane-count axis, so every consumer handles both.
_COMPACT_WIRE_EDGES = 1 << 14


def wire_spec(num_edges: int, max_edge_len: float) -> "tuple | None":
    """Packed-u32 wire layout for metros past the compact-u16 range, or
    None where the 3-lane u16 fallback must carry the result.

    Layout: offset quanta in the low ``ob`` bits, edge id in the next
    30-ob bits, chain_start at 30, matched at 31 — ONE u32 lane instead
    of three u16 lanes (-33% of the device→host bytes that bound big-
    metro decode; the downlink streams ~11 MB/s). ``ob`` shrinks as the
    edge count grows; the offset quantum is max(0.25 m, Lmax/(2^ob-1)),
    and when that would exceed 0.5 m (multi-km edges on a ~500k-edge
    tile) the format is rejected (None) rather than degrading offsets."""
    if num_edges <= _COMPACT_WIRE_EDGES:
        return None                      # compact u16 is already 4 B/pt
    eb = max(15, int(np.ceil(np.log2(max(num_edges, 2)))))
    ob = 30 - eb
    if ob < 8:
        return None
    q = max(OFFSET_QUANTUM, float(max_edge_len) / ((1 << ob) - 1))
    return (ob, q) if q <= 0.5 else None


def _pack_wire(out: MatchOutput, num_edges: int,
               spec: "tuple | None" = None):
    edge = jnp.maximum(out.edge, 0).astype(jnp.uint32)
    if spec is not None and num_edges > _COMPACT_WIRE_EDGES:
        ob, q = spec
        off_q = jnp.clip(jnp.round(out.offset / q),
                         0, (1 << ob) - 1).astype(jnp.uint32)
        w = (off_q | (edge << ob)
             | (out.chain_start.astype(jnp.uint32) << 30)
             | (out.matched.astype(jnp.uint32) << 31))
        return w[:, None, :]
    off_q = jnp.clip(jnp.round(out.offset / OFFSET_QUANTUM), 0, 65535)
    w0 = off_q.astype(jnp.uint16)
    if num_edges <= _COMPACT_WIRE_EDGES:
        w1 = (edge & 0x3FFF
              | (out.chain_start.astype(jnp.uint32) << 14)
              | (out.matched.astype(jnp.uint32) << 15)).astype(jnp.uint16)
        return jnp.stack([w0, w1], axis=1)
    w1 = (edge & 0xFFFF).astype(jnp.uint16)
    w2 = ((edge >> 16) & 0x1FFF
          | (out.chain_start.astype(jnp.uint32) << 14)
          | (out.matched.astype(jnp.uint32) << 15)).astype(jnp.uint16)
    return jnp.stack([w0, w1, w2], axis=1)


def unpack_wire(wire, spec: "tuple | None" = None) -> tuple[Any, Any, Any]:
    """numpy unpack: u16 [B, 2|3, T] (or packed u32 [B, 1, T] with its
    ``spec`` from wire_spec) → (edges i32 [B,T] with -1 unmatched,
    offsets f32 [B,T], chain_starts bool [B,T])."""
    if wire.dtype == np.uint32:             # packed u32: off | edge | s | m
        if spec is None:
            raise ValueError(
                "unpack_wire: uint32 wire requires the wire_spec it was "
                "packed with (pass spec=wire_spec(...) from the matcher)")
        ob, q = spec
        w = np.asarray(wire[:, 0], np.int64)
        matched = (w >> 31) & 1
        edges = np.where(matched == 1,
                         (w >> ob) & ((1 << (30 - ob)) - 1), -1)
        starts = ((w >> 30) & 1).astype(bool)
        offsets = ((w & ((1 << ob) - 1)) * q).astype(np.float32)
        return edges.astype(np.int32), offsets, starts
    w0 = wire[:, 0].astype(np.int64)
    w1 = wire[:, 1].astype(np.int64)
    if wire.shape[1] == 2:                  # compact: id(14) | start | matched
        matched = (w1 >> 15) & 1
        edges = np.where(matched == 1, w1 & 0x3FFF, -1)
        starts = ((w1 >> 14) & 1).astype(bool)
    else:
        w2 = wire[:, 2].astype(np.int64)
        matched = (w2 >> 15) & 1
        edges = np.where(matched == 1, w1 | ((w2 & 0x1FFF) << 16), -1)
        starts = ((w2 >> 14) & 1).astype(bool)
    offsets = (w0 * OFFSET_QUANTUM).astype(np.float32)
    return edges.astype(np.int32), offsets, starts
