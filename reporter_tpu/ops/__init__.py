"""Device-side matcher kernels (JAX).

TPU-native replacement for the online math inside Valhalla/Meili
(SURVEY.md §2.2): candidate search → `candidates`, emission/transition +
Viterbi → `hmm`, fused per-trace pipeline → `match`.
"""

from reporter_tpu.ops.candidates import CandidateSet, find_candidates
from reporter_tpu.ops.hmm import viterbi_decode
from reporter_tpu.ops.dense_candidates import find_candidates_dense
from reporter_tpu.ops.match import match_batch, match_trace, match_traces

__all__ = [
    "CandidateSet",
    "find_candidates",
    "find_candidates_dense",
    "viterbi_decode",
    "match_batch",
    "match_trace",
    "match_traces",
]
