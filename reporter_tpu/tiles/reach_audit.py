"""Reach-table truncation audit.

The node-keyed [N, M] reach tables (tiles/reach.py; the row governing
transitions out of edge e is row ``edge_dst[e]``) keep only the M nearest
targets within ``reach_radius`` of each node; everything else is treated as
unreachable by the device transition model (ops/hmm.route_distance). This
module measures what that approximation actually costs on a workload: for
every consecutive candidate pair the HMM would consider, compare the exact
bounded-Dijkstra verdict (the Meili-semantics oracle, cpu_reference) with
the table verdict and count the transitions the table wrongly rejects.

Pair-level misses overstate the harm (Viterbi only needs *a* good path),
so step-level misses — transitions where the table rejects every candidate
pair the oracle accepts, forcing a spurious chain break — are reported
too. SURVEY §7 "hard part 1"; VERDICT r1 "What's weak" item 2.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from reporter_tpu.config import MatcherParams
from reporter_tpu.matcher import cpu_reference
from reporter_tpu.tiles.tileset import TileSet


@dataclass
class ReachAudit:
    """Counts from one audit run (see audit_reach)."""

    pairs_considered: int = 0      # candidate pairs with gc <= breakage
    pairs_accepted_exact: int = 0  # exact route exists & passes detour guard
    pairs_missed: int = 0          # accepted by exact, rejected by table
    steps_considered: int = 0      # consecutive active point pairs
    steps_accepted_exact: int = 0  # steps where exact accepts >= 1 pair
    steps_missed: int = 0          # exact accepts >= 1 pair, table accepts 0
    missed_gaps: list = field(default_factory=list)   # required end->start gap
    truncated_nodes: int = 0
    coverage_radii: np.ndarray | None = None  # per-node D_M (inf if untruncated)

    @property
    def pair_miss_rate(self) -> float:
        return self.pairs_missed / max(self.pairs_accepted_exact, 1)

    @property
    def step_miss_rate(self) -> float:
        return self.steps_missed / max(self.steps_accepted_exact, 1)

    def summary(self) -> dict:
        gaps = np.asarray(self.missed_gaps, np.float64)
        cov = self.coverage_radii
        fin = cov[np.isfinite(cov)] if cov is not None else np.empty(0)
        return {
            "pairs_considered": self.pairs_considered,
            "pairs_accepted_exact": self.pairs_accepted_exact,
            "pairs_missed": self.pairs_missed,
            "pair_miss_rate": round(self.pair_miss_rate, 5),
            "steps_considered": self.steps_considered,
            "steps_accepted_exact": self.steps_accepted_exact,
            "steps_missed": self.steps_missed,
            "step_miss_rate": round(self.step_miss_rate, 5),
            "missed_gap_m": {
                "min": round(float(gaps.min()), 1) if len(gaps) else None,
                "p50": round(float(np.median(gaps)), 1) if len(gaps) else None,
                "max": round(float(gaps.max()), 1) if len(gaps) else None,
            },
            "truncated_nodes": int(self.truncated_nodes),
            "node_coverage_m": {
                "min": round(float(fin.min()), 1) if len(fin) else None,
                "p50": round(float(np.median(fin)), 1) if len(fin) else None,
            },
        }


def node_coverage_radii(ts: TileSet) -> np.ndarray:
    """Per-node truncation coverage D_M: network distance of the FARTHEST
    kept reach target (the radius beyond which the table is blind), +inf
    when the row is not full (nothing was cut). Schema-4 rows are laid out
    by target id, not distance, so take a masked max — the valid prefix is
    contiguous but unordered in distance."""
    full = ts.reach_to[:, -1] >= 0          # [N] row is full ⇒ maybe cut
    far = np.where(ts.reach_to >= 0, ts.reach_dist, -np.inf).max(axis=1)
    return np.where(full, far, np.inf)


def audit_reach(ts: TileSet, traces_xy: list[np.ndarray],
                params: MatcherParams | None = None,
                dij_cache: cpu_reference.DijkstraCache | None = None,
                ) -> ReachAudit:
    """Audit reach-table misses over a list of [T, 2] float traces.

    Mirrors the device transition model's acceptance rule
    (ops/hmm.trans_block): a pair is accepted when a route exists and
    route <= max_route_distance_factor * gc + 10. Same-edge pairs moving
    FORWARD (within backward_slack) are exact by construction on the device
    (offset arithmetic, no table) and are skipped; same-edge BACKWARD pairs
    beyond the slack need a loop entry (e → its own start) in the reach row
    and are audited like any cross-edge pair.
    """
    params = params or MatcherParams()
    cache = dij_cache or cpu_reference.DijkstraCache()
    audit = ReachAudit()
    audit.truncated_nodes = int(ts.stats.get("reach_truncated_nodes", 0))
    audit.coverage_radii = node_coverage_radii(ts)

    reach_to = ts.reach_to
    reach_dist = ts.reach_dist
    edge_len = ts.edge_len

    for xy in traces_xy:
        xy = np.asarray(xy, np.float64)
        T = len(xy)
        cands = [cpu_reference.find_candidates_cpu(ts, xy[t], params)
                 for t in range(T)]
        keep = cpu_reference.interpolation_keep(
            xy, params.interpolation_distance)
        act = [t for t in range(T) if keep[t] and cands[t]]
        for prev_t, t in zip(act, act[1:]):
            gc = float(np.linalg.norm(xy[t] - xy[prev_t]))
            if gc > params.breakage_distance:
                continue
            limit = params.max_route_distance_factor * gc + 10.0
            bound = cpu_reference.viterbi_bound(gc, params)
            audit.steps_considered += 1
            step_exact = step_table = 0
            for cj in cands[prev_t]:
                reached = None
                row_to = row_d = None
                for ck in cands[t]:
                    if (cj.edge == ck.edge
                            and ck.offset >= cj.offset
                            - params.backward_slack):
                        continue   # same-edge forward: exact on device
                    audit.pairs_considered += 1
                    if reached is None:
                        reached = cache.reached(ts, cj.edge, bound)
                    hit = reached.get(ck.edge)
                    if hit is None:
                        continue
                    route = ((float(edge_len[cj.edge]) - cj.offset)
                             + hit[0] + ck.offset)
                    if route > limit:
                        continue
                    audit.pairs_accepted_exact += 1
                    step_exact += 1
                    if row_to is None:
                        u = int(ts.edge_reach_row[cj.edge])
                        row_to = reach_to[u]
                        row_d = reach_dist[u]
                    idx = np.nonzero(row_to == ck.edge)[0]
                    gap_t = float(row_d[idx[0]]) if len(idx) else np.inf
                    route_t = ((float(edge_len[cj.edge]) - cj.offset)
                               + gap_t + ck.offset)
                    if np.isfinite(gap_t) and route_t <= limit:
                        step_table += 1
                    else:
                        audit.pairs_missed += 1
                        audit.missed_gaps.append(hit[0])
            if step_exact:
                audit.steps_accepted_exact += 1
                if step_table == 0:
                    audit.steps_missed += 1
    return audit


def main(argv: list[str] | None = None) -> None:
    """CLI: python -m reporter_tpu.tiles.reach_audit [city] [n_traces]."""
    import json
    import sys

    from reporter_tpu.config import CompilerParams
    from reporter_tpu.netgen.synthetic import generate_city
    from reporter_tpu.netgen.traces import synthesize_fleet
    from reporter_tpu.tiles.compiler import compile_network

    args = list(sys.argv[1:] if argv is None else argv)
    city = args[0] if args else "sf"
    n = int(args[1]) if len(args) > 1 else 50
    ts = compile_network(generate_city(city), CompilerParams())
    fleet = synthesize_fleet(ts, n, num_points=120, seed=7)
    audit = audit_reach(ts, [p.xy for p in fleet])
    print(json.dumps({"city": city, "n_traces": n, **audit.summary()}))


if __name__ == "__main__":
    main()
