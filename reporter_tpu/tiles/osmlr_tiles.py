"""Binary OSMLR segment tiles — the protobuf-tile publication format.

The reference publishes OSMLR as protobuf segment tiles (SURVEY.md §2.2
"OSMLR segments + association": ~1 km linear references shipped as .osmlr
protobuf files that datastore consumers resolve segment ids against).
GeoJSON (tiles/osmlr_export.py) covers human/GIS consumers; this module
is the compact machine format, written with the SAME hand-rolled protobuf
wire primitives as the OSM PBF codec (netgen/pbf.py — varints, zigzag,
length-delimited fields; no protobuf dependency).

Message shape (field numbers, all length-delimited unless noted):

  Tile:    1 name (string)   2 repeated Segment
  Segment: 1 id (varint)     2 length_cm (varint)
           3 packed way_ids (zigzag delta)
           4 packed lons 1e-7 deg (zigzag delta)   5 packed lats (same)

Delta-coded fixed-point coordinates make a metro's segment geometry a
few bytes per point, like the real OSMLR tiles (and DenseNodes in PBF).
Round-trip is exact at 1e-7 degrees (~1 cm) — read_osmlr_tile returns
what write_osmlr_tile saw, asserted by tests/test_osmlr_tiles.py.
"""

from __future__ import annotations


from reporter_tpu.netgen.pbf import (_field, _fields, _ld, _packed,
                                     _packed_varints, _read_varint, _varint)
from reporter_tpu.netgen.pbf import _delta_decode
from reporter_tpu.tiles.osmlr_export import osmlr_features
from reporter_tpu.tiles.tileset import TileSet

_MAGIC = b"OSMLRT01"          # file magic + format version
_COORD_SCALE = 1e7            # 1e-7 deg fixed point (~1 cm)


def write_osmlr_tile(ts: TileSet, path: str) -> int:
    """Serialize the tileset's OSMLR segments; returns the segment count.

    Geometry/way membership comes from osmlr_features — the same
    drive-order edge stitching the GeoJSON export publishes, so the two
    formats can never disagree about a segment's shape."""
    segments = []
    for feat in osmlr_features(ts):
        props = feat["properties"]
        lons = [int(round(lo * _COORD_SCALE))
                for lo, _ in feat["geometry"]["coordinates"]]
        lats = [int(round(la * _COORD_SCALE))
                for _, la in feat["geometry"]["coordinates"]]
        body = (_field(1, 0, _varint(int(feat["id"])))
                + _field(2, 0, _varint(int(round(
                    props["length_m"] * 100))))
                + _packed(3, props["way_ids"], signed=True, delta=True)
                + _packed(4, lons, signed=True, delta=True)
                + _packed(5, lats, signed=True, delta=True))
        segments.append(_ld(2, body))
    payload = _ld(1, ts.name.encode()) + b"".join(segments)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(_varint(len(payload)))
        f.write(payload)
    return len(segments)


def read_osmlr_tile(path: str) -> dict:
    """Parse a tile written by write_osmlr_tile →
    {"name": ..., "segments": [{"id", "length_m", "way_ids",
    "coordinates": [(lon, lat)...]}, ...]}."""
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:len(_MAGIC)] != _MAGIC:
        raise ValueError(f"{path}: not an OSMLR tile (bad magic)")
    n, i = _read_varint(blob, len(_MAGIC))
    payload = blob[i:i + n]
    if len(payload) != n:
        # a short slice would parse silently into a partial/garbled tile
        raise ValueError(f"{path}: truncated OSMLR tile "
                         f"({len(payload)} of {n} payload bytes)")
    name = ""
    segments = []
    for no, wt, v in _fields(payload):
        if no == 1 and wt == 2:
            name = v.decode()
        elif no == 2 and wt == 2:
            seg: dict = {"way_ids": [], "coordinates": []}
            lons = lats = None
            for sno, swt, sv in _fields(v):
                if sno == 1 and swt == 0:
                    seg["id"] = sv
                elif sno == 2 and swt == 0:
                    seg["length_m"] = sv / 100.0
                elif sno == 3 and swt == 2:
                    seg["way_ids"] = _delta_decode(
                        _packed_varints(sv, signed=True))
                elif sno == 4 and swt == 2:
                    lons = _delta_decode(_packed_varints(sv, signed=True))
                elif sno == 5 and swt == 2:
                    lats = _delta_decode(_packed_varints(sv, signed=True))
            if lons is not None and lats is not None:
                seg["coordinates"] = [
                    (lo / _COORD_SCALE, la / _COORD_SCALE)
                    for lo, la in zip(lons, lats)]
            segments.append(seg)
    return {"name": name, "segments": segments}
