"""Reachability tables: bounded all-pairs-nearby network distances.

This is the TPU-first answer to SURVEY.md §7's hardest part, "transition costs
without Dijkstra": Meili runs a label-set Dijkstra between candidate pairs at
match time (SURVEY.md §2.2 "Inter-candidate routing" — the dominant cost of
the reference's hot loop, §3.1). A data-dependent priority queue cannot run on
the MXU, so we move the graph search OFFLINE: for every directed edge ``e``,
precompute the network distance from the END of ``e`` to the START of every
edge reachable within ``radius`` meters, keep the ``M`` nearest, and store
them as fixed-shape tables. At match time a transition cost is then a
gather + compare — exactly what the TPU is good at. ``reach_next``
(first edge of each path) lets the host reconstruct full paths after Viterbi
by repeated next-hop lookup, replacing Meili's edge walk.

Tables are keyed by NODE ([N, M]): every in-edge of a node shares one target
row, so the row for edge ``e`` is ``reach_*[edge_dst[e]]`` (one extra tiny
gather on device). Node-keying cuts the footprint ~E/N (≈3×) versus the
per-edge broadcast, which is what makes a wide M (deep truncation coverage —
see tiles/reach_audit.py) affordable at metro scale.

A C++ builder (native/reach.cc) accelerates this for large metros; this module
is the reference implementation and fallback.
"""

from __future__ import annotations

import heapq

import numpy as np


def node_dijkstra(
    u: int,
    node_out: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
) -> dict[int, tuple[float, int]]:
    """Single-source bounded Dijkstra over nodes.

    Returns {node v: (dist(u→v), first_edge_id on a shortest path)}; u itself
    maps to (0.0, -1).
    """
    dist: dict[int, float] = {u: 0.0}
    first: dict[int, int] = {u: -1}
    pq: list[tuple[float, int]] = [(0.0, u)]
    while pq:
        d, v = heapq.heappop(pq)
        if d > dist.get(v, np.inf):
            continue
        for e in node_out[v]:
            if e < 0:
                break
            w = int(edge_dst[e])
            nd = d + float(edge_len[e])
            if nd <= radius and nd < dist.get(w, np.inf):
                dist[w] = nd
                first[w] = int(e) if v == u else first[v]
                heapq.heappush(pq, (nd, w))
    return {v: (dist[v], first[v]) for v in dist}


def build_reach_tables(
    node_out: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
    max_targets: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Build (reach_to, reach_dist, reach_next, truncated_nodes); tables are
    each [N, max_targets], keyed by node.

    For node u, targets are out-edges e' of every node v with
    d(u, v) <= radius; reach_dist = d(u, src(e')), reach_next = first edge of
    the u→v path (or e' itself when v == u, i.e. e' directly follows an
    in-edge of u). Rows are sorted by distance; -1/inf padded. The row that
    governs transitions out of edge e is row edge_dst[e].
    """
    num_nodes = len(node_out)
    reach_to = np.full((num_nodes, max_targets), -1, dtype=np.int32)
    reach_dist = np.full((num_nodes, max_targets), np.inf, dtype=np.float32)
    reach_next = np.full((num_nodes, max_targets), -1, dtype=np.int32)

    truncated = 0
    for u in range(num_nodes):
        reached = node_dijkstra(u, node_out, edge_dst, edge_len, radius)
        tos: list[int] = []
        dists: list[float] = []
        nexts: list[int] = []
        for v, (d, fe) in reached.items():
            for e2 in node_out[v]:
                if e2 < 0:
                    break
                tos.append(int(e2))
                dists.append(d)
                nexts.append(int(e2) if v == u else fe)
        if not tos:
            continue
        order = np.lexsort((np.asarray(tos), np.asarray(dists)))
        if len(order) > max_targets:
            truncated += 1
            order = order[:max_targets]
        k = len(order)
        reach_to[u, :k] = np.asarray(tos, np.int32)[order]
        reach_dist[u, :k] = np.asarray(dists, np.float32)[order]
        reach_next[u, :k] = np.asarray(nexts, np.int32)[order]

    return reach_to, reach_dist, reach_next, truncated


def reach_lookup(reach_to: np.ndarray, reach_dist: np.ndarray,
                 edge_dst: np.ndarray, e1: int, e2: int) -> float:
    """Network distance end-of-e1 → start-of-e2, inf if outside the table."""
    u = int(edge_dst[e1])
    row = reach_to[u]
    hit = np.nonzero(row == e2)[0]
    return float(reach_dist[u, hit[0]]) if len(hit) else float(np.inf)
