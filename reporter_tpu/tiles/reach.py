"""Reachability tables: bounded all-pairs-nearby network distances.

This is the TPU-first answer to SURVEY.md §7's hardest part, "transition costs
without Dijkstra": Meili runs a label-set Dijkstra between candidate pairs at
match time (SURVEY.md §2.2 "Inter-candidate routing" — the dominant cost of
the reference's hot loop, §3.1). A data-dependent priority queue cannot run on
the MXU, so we move the graph search OFFLINE: for every directed edge ``e``,
precompute the network distance from the END of ``e`` to the START of every
edge reachable within ``radius`` meters, keep the ``M`` nearest, and store
them as fixed-shape tables. At match time a transition cost is then a
gather + compare — exactly what the TPU is good at. ``reach_next``
(first edge of each path) lets the host reconstruct full paths after Viterbi
by repeated next-hop lookup, replacing Meili's edge walk.

Tables are keyed by NODE ([N, M]): every in-edge of a node shares one target
row, so the row for edge ``e`` is ``reach_*[edge_reach_row[e]]`` (one extra
tiny gather on device). Node-keying cuts the footprint ~E/N (≈3×) versus the
per-edge broadcast, which is what makes a wide M (deep truncation coverage —
see tiles/reach_audit.py) affordable at metro scale.

Turn restrictions (banned from-edge → to-edge pairs at a node) make
reachability depend on the ARRIVING edge, not just the node. Rather than
falling back to per-edge rows everywhere, restriction from-edges get
PRIVATE rows appended after the N node rows (``build_reach_tables_restricted``)
and ``edge_reach_row`` points them there; every other edge keeps its node
row. All searches on a restricted tile run in EDGE space (label = edge) so
paths *through* a restricted node also respect its bans. Unrestricted
tiles keep the plain node-space build (bit-identical to the native C++
builder, which handles only that case).

A C++ builder (native/reach.cc) accelerates this for large metros; this module
is the reference implementation and fallback.
"""

from __future__ import annotations

import heapq

import numpy as np


def node_dijkstra(
    u: int,
    node_out: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
) -> dict[int, tuple[float, int]]:
    """Single-source bounded Dijkstra over nodes.

    Returns {node v: (dist(u→v), first_edge_id on a shortest path)}; u itself
    maps to (0.0, -1).
    """
    dist: dict[int, float] = {u: 0.0}
    first: dict[int, int] = {u: -1}
    pq: list[tuple[float, int]] = [(0.0, u)]
    while pq:
        d, v = heapq.heappop(pq)
        if d > dist.get(v, np.inf):
            continue
        for e in node_out[v]:
            if e < 0:
                break
            w = int(edge_dst[e])
            nd = d + float(edge_len[e])
            if nd <= radius and nd < dist.get(w, np.inf):
                dist[w] = nd
                first[w] = int(e) if v == u else first[v]
                heapq.heappush(pq, (nd, w))
    return {v: (dist[v], first[v]) for v in dist}


def build_reach_tables(
    node_out: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
    max_targets: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Build (reach_to, reach_dist, reach_next, truncated_nodes); tables are
    each [N, max_targets], keyed by node.

    For node u, targets are out-edges e' of every node v with
    d(u, v) <= radius; reach_dist = d(u, src(e')), reach_next = first edge of
    the u→v path (or e' itself when v == u, i.e. e' directly follows an
    in-edge of u). The nearest max_targets by (dist, id) are kept, then laid
    out ascending by target id (schema-4 invariant — the native walker
    binary-searches rows); -1/inf padded. The row that governs transitions
    out of edge e is row edge_dst[e].
    """
    num_nodes = len(node_out)
    reach_to = np.full((num_nodes, max_targets), -1, dtype=np.int32)
    reach_dist = np.full((num_nodes, max_targets), np.inf, dtype=np.float32)
    reach_next = np.full((num_nodes, max_targets), -1, dtype=np.int32)

    truncated = 0
    for u in range(num_nodes):
        reached = node_dijkstra(u, node_out, edge_dst, edge_len, radius)
        tos: list[int] = []
        dists: list[float] = []
        nexts: list[int] = []
        for v, (d, fe) in reached.items():
            for e2 in node_out[v]:
                if e2 < 0:
                    break
                tos.append(int(e2))
                dists.append(d)
                nexts.append(int(e2) if v == u else fe)
        if not tos:
            continue
        tos_a = np.asarray(tos)
        order = np.lexsort((tos_a, np.asarray(dists)))
        if len(order) > max_targets:
            truncated += 1
            order = order[:max_targets]
        order = order[np.argsort(tos_a[order], kind="stable")]
        k = len(order)
        reach_to[u, :k] = np.asarray(tos, np.int32)[order]
        reach_dist[u, :k] = np.asarray(dists, np.float32)[order]
        reach_next[u, :k] = np.asarray(nexts, np.int32)[order]

    return reach_to, reach_dist, reach_next, truncated


def edge_space_targets(
    seeds: list[int],
    node_out: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
    banned: set[tuple[int, int]],
) -> dict[int, tuple[float, int, int]]:
    """Bounded Dijkstra over EDGES: {edge e': (dist to start of e', seed
    edge beginning the path, previous edge on the path — -1 for seeds)}.
    Seeds start at dist 0 (their own start). Expansion e → e2 at dst(e) is
    skipped when (e, e2) is banned, so paths through restricted nodes stay
    legal no matter the source. Shared by the reach-table builder and the
    CPU oracle (matcher/cpu_reference) so the two can never diverge on ban
    semantics — the <5% disagreement gate depends on that.
    """
    dist: dict[int, float] = {}
    first: dict[int, int] = {}
    prev: dict[int, int] = {}
    pq: list[tuple[float, int]] = []
    for e in seeds:
        if 0.0 < dist.get(e, np.inf):
            dist[e] = 0.0
            first[e] = e
            prev[e] = -1
            heapq.heappush(pq, (0.0, e))
    while pq:
        d, e = heapq.heappop(pq)
        if d > dist.get(e, np.inf):
            continue
        nd = d + float(edge_len[e])
        if nd > radius:
            continue
        v = int(edge_dst[e])
        for e2 in node_out[v]:
            if e2 < 0:
                break
            e2 = int(e2)
            if (e, e2) in banned:
                continue
            if nd < dist.get(e2, np.inf):
                dist[e2] = nd
                first[e2] = first[e]
                prev[e2] = e
                heapq.heappush(pq, (nd, e2))
    return {e: (dist[e], first[e], prev[e]) for e in dist}


def _pack_rows(targets: dict[int, tuple[float, int, int]], seeds: set[int],
               max_targets: int,
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, bool]:
    """Keep the nearest max_targets by (dist, edge id), then lay the kept
    entries out sorted by TARGET EDGE ID: the native walker binary-searches
    rows by target (route_between in walker.cc), so ascending ids are a
    schema invariant (tileset schema 4). Next-hop is the target itself for
    direct successors (seed edges), else the path's first edge."""
    tos = np.fromiter(targets.keys(), np.int64, len(targets))
    dists = np.asarray([targets[int(e)][0] for e in tos])
    nexts = np.asarray([int(e) if int(e) in seeds else targets[int(e)][1]
                        for e in tos], np.int32)
    order = np.lexsort((tos, dists))
    cut = len(order) > max_targets
    order = order[:max_targets]
    order = order[np.argsort(tos[order], kind="stable")]
    return (tos[order].astype(np.int32), dists[order].astype(np.float32),
            nexts[order], cut)


def build_reach_tables_restricted(
    node_out: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_len: np.ndarray,
    radius: float,
    max_targets: int,
    banned_pairs: "np.ndarray | list[tuple[int, int]]",
    base: "tuple[np.ndarray, np.ndarray, np.ndarray] | None" = None,
    node_xy: "np.ndarray | None" = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int, np.ndarray]:
    """Ban-aware build: (reach_to, reach_dist, reach_next, truncated,
    edge_reach_row). Rows are [N + F, max_targets]: node rows first, then
    one private row per restriction from-edge (ascending edge id);
    edge_reach_row[e] picks the row governing transitions out of e.

    With ``base`` (the unrestricted node rows, e.g. from the multithreaded
    native builder) and ``node_xy``, only AFFECTED node rows are recomputed
    in Python edge space: nodes within straight-line ``radius`` of a ban's
    via node (network distance ≥ euclidean, so this ball is a conservative
    superset of every row a ban could change). Restrictions are sparse in
    real extracts, so this keeps metro compiles on the fast path. The
    returned ``truncated`` stat then counts rows at capacity (a superset
    of truly-truncated rows — diagnostic only).
    """
    banned = {(int(a), int(b)) for a, b in banned_pairs}
    from_edges = sorted({a for a, _ in banned})
    num_nodes = len(node_out)
    rows = num_nodes + len(from_edges)
    reach_to = np.full((rows, max_targets), -1, dtype=np.int32)
    reach_dist = np.full((rows, max_targets), np.inf, dtype=np.float32)
    reach_next = np.full((rows, max_targets), -1, dtype=np.int32)
    exact_cut = 0

    if base is not None:
        reach_to[:num_nodes] = base[0]
        reach_dist[:num_nodes] = base[1]
        reach_next[:num_nodes] = base[2]

    if base is not None and node_xy is not None:
        via = np.asarray(sorted({int(edge_dst[a]) for a, _ in banned}))
        # Running min over via nodes: O(N) memory (an [N, V, 2] broadcast
        # would peak at tens of GB on a metro extract with thousands of
        # restrictions — the exact compiles this fast path exists for).
        d2_min = np.full(len(node_xy), np.inf)
        for v in via:
            dv = node_xy - node_xy[int(v)]
            np.minimum(d2_min, (dv * dv).sum(-1), out=d2_min)
        affected = np.nonzero(d2_min <= radius * radius)[0]
    else:
        affected = np.arange(num_nodes)

    def fill(row: int, seeds: list[int]) -> None:
        nonlocal exact_cut
        reach_to[row] = -1
        reach_dist[row] = np.inf
        reach_next[row] = -1
        targets = edge_space_targets(seeds, node_out, edge_dst, edge_len,
                                     radius, banned)
        if not targets:
            return
        tos, dists, nexts, cut = _pack_rows(targets, set(seeds), max_targets)
        exact_cut += bool(cut)
        reach_to[row, :len(tos)] = tos
        reach_dist[row, :len(tos)] = dists
        reach_next[row, :len(tos)] = nexts

    for u in affected:
        fill(int(u), [int(e) for e in node_out[u] if e >= 0])
    edge_reach_row = edge_dst.astype(np.int32).copy()
    for i, e_f in enumerate(from_edges):
        u = int(edge_dst[e_f])
        seeds = [int(e) for e in node_out[u]
                 if e >= 0 and (e_f, int(e)) not in banned]
        fill(num_nodes + i, seeds)
        edge_reach_row[e_f] = num_nodes + i
    if base is not None and len(affected) < num_nodes:
        truncated = int((reach_to[:, -1] >= 0).sum())   # rows at capacity
    else:
        truncated = exact_cut
    return reach_to, reach_dist, reach_next, truncated, edge_reach_row


def reach_lookup(reach_to: np.ndarray, reach_dist: np.ndarray,
                 edge_reach_row: np.ndarray, e1: int, e2: int) -> float:
    """Network distance end-of-e1 → start-of-e2, inf if outside the table."""
    u = int(edge_reach_row[e1])
    row = reach_to[u]
    hit = np.nonzero(row == e2)[0]
    return float(reach_dist[u, hit[0]]) if len(hit) else float(np.inf)
