"""Tile compiler: RoadNetwork → TileSet.

One offline pass replacing the reference's whole L0 pipeline (SURVEY.md §3.4):

  valhalla_build_tiles  → directed-edge/node arrays + shape decomposition
  osmlr generation      → directional segment chaining (~1 km target length)
  associate_segments    → edge→OSMLR row + offset arrays
  (new, TPU-first)      → padded spatial grid over line segments, and
                          reachability tables (tiles/reach.py) that replace
                          match-time Dijkstra with offline precompute

Everything downstream is fixed-shape: the matcher never touches the
RoadNetwork again.
"""

from __future__ import annotations

import time

import numpy as np

from reporter_tpu.config import CompilerParams
from reporter_tpu.geometry import lonlat_to_xy
from reporter_tpu.netgen.network import ACCESS_AUTO, RoadNetwork
from reporter_tpu.tiles.tileset import TileMeta, TileSet


def _build_edges(net: RoadNetwork, node_xy: np.ndarray, origin: np.ndarray):
    """Directed edges + per-edge polylines from ways."""
    src: list[int] = []
    dst: list[int] = []
    way: list[int] = []
    speed: list[float] = []
    shapes: list[np.ndarray] = []          # per-edge [k>=2, 2] xy polyline
    fwd_of_leg: dict[tuple[int, int], int] = {}   # (way_idx, leg) → fwd edge id
    rev_of_leg: dict[tuple[int, int], int] = {}

    for wi, w in enumerate(net.ways):
        for leg in range(len(w.nodes) - 1):
            a, b = w.nodes[leg], w.nodes[leg + 1]
            mid_ll = w.geometry.get(leg)
            if mid_ll is not None and len(mid_ll):
                mid = lonlat_to_xy(mid_ll, origin)
                poly = np.vstack([node_xy[a][None], mid, node_xy[b][None]])
            else:
                poly = np.vstack([node_xy[a][None], node_xy[b][None]])
            fwd_of_leg[(wi, leg)] = len(src)
            src.append(a); dst.append(b); way.append(w.way_id); speed.append(w.speed_mps)
            shapes.append(poly.astype(np.float32))
            if not w.oneway:
                rev_of_leg[(wi, leg)] = len(src)
                src.append(b); dst.append(a); way.append(w.way_id); speed.append(w.speed_mps)
                shapes.append(poly[::-1].astype(np.float32))

    E = len(src)
    edge_opp = np.full(E, -1, dtype=np.int32)
    for key, f in fwd_of_leg.items():
        r = rev_of_leg.get(key)
        if r is not None:
            edge_opp[f] = r
            edge_opp[r] = f
    return (
        np.asarray(src, np.int32), np.asarray(dst, np.int32),
        np.asarray(way, np.int64), np.asarray(speed, np.float32),
        shapes, edge_opp, fwd_of_leg, rev_of_leg,
    )


def _chain_osmlr(net: RoadNetwork, edge_len: np.ndarray,
                 edge_src: np.ndarray, edge_dst: np.ndarray,
                 edge_opp: np.ndarray, fwd_of_leg, rev_of_leg,
                 max_len: float):
    """Directional OSMLR chaining with cross-way continuation.

    Real OSMLR merges short ways into ~1 km linear references (SURVEY.md
    §2.2 "OSMLR segments"): a residential street mapped as five OSM ways
    is still ONE segment. Rules, mirroring that behavior:

      1. within a way, consecutive legs always chain (a way may pass
         through intersections);
      2. across a way boundary, the chain continues iff the joint node has
         geometric degree 2 (exactly two incident undirected legs) — i.e.
         the road merely changes way id there, nothing joins or leaves;
      3. chains split greedily into chunks of ≤ ``max_len`` meters.

    Stable ids pack (first edge's way_id << 20) | (direction << 19) | chunk,
    where ``chunk`` counts chunks per (way_id, direction) base in first-edge
    order — deterministic for a given network, and unchanged from the
    round-1 scheme for chains that do not cross ways. Every directed edge
    belongs to exactly one chain; pure cycles (a block perimeter of
    degree-2 corners) start at their lowest edge id.
    """
    E = len(edge_len)
    edge_osmlr = np.full(E, -1, dtype=np.int32)
    edge_osmlr_off = np.zeros(E, dtype=np.float32)
    osmlr_ids: list[int] = []
    osmlr_lens: list[float] = []

    # edge → (way index, leg, direction); direction 1 = against the way
    edge_leg: dict[int, tuple[int, int, int]] = {}
    for (wi, leg), e in fwd_of_leg.items():
        edge_leg[e] = (wi, leg, 0)
    for (wi, leg), e in rev_of_leg.items():
        edge_leg[e] = (wi, leg, 1)

    # geometric node degree = number of incident undirected legs
    num_nodes = net.num_nodes
    node_deg = np.zeros(num_nodes, dtype=np.int32)
    for (wi, leg), e in fwd_of_leg.items():
        node_deg[edge_src[e]] += 1
        node_deg[edge_dst[e]] += 1

    out_edges: dict[int, list[int]] = {}
    for e in range(E):
        out_edges.setdefault(int(edge_src[e]), []).append(e)

    def succ(e: int) -> int | None:
        wi, leg, d = edge_leg[e]
        nxt = (fwd_of_leg.get((wi, leg + 1)) if d == 0
               else rev_of_leg.get((wi, leg - 1)))
        if nxt is not None:
            return nxt                      # rule 1: same way continues
        u = int(edge_dst[e])
        if node_deg[u] != 2:
            return None                     # junction: chain ends
        cands = [x for x in out_edges.get(u, ())
                 if x != e and x != int(edge_opp[e])]
        return cands[0] if len(cands) == 1 else None

    preds = set()
    for e in range(E):
        s = succ(e)
        if s is not None:
            preds.add(s)

    def walk(start: int, visited: np.ndarray) -> list[int]:
        chain = []
        e = start
        while e is not None and not visited[e]:
            visited[e] = True
            chain.append(e)
            e = succ(e)
        return chain

    visited = np.zeros(E, dtype=bool)
    chains: list[list[int]] = []
    for e in range(E):                      # chain heads first…
        if e not in preds and not visited[e]:
            chains.append(walk(e, visited))
    for e in range(E):                      # …then pure cycles
        if not visited[e]:
            chains.append(walk(e, visited))

    chunk_counter: dict[tuple[int, int], int] = {}
    for chain in chains:                    # chains are in first-edge order
        wi, _, d = edge_leg[chain[0]]
        base = (net.ways[wi].way_id, d)
        cur: list[int] = []
        cur_len = 0.0

        def flush() -> None:
            nonlocal cur, cur_len
            if not cur:
                return
            chunk = chunk_counter.get(base, 0)
            chunk_counter[base] = chunk + 1
            row = len(osmlr_ids)
            osmlr_ids.append((base[0] << 20) | (base[1] << 19) | chunk)
            off = 0.0
            for e in cur:
                edge_osmlr[e] = row
                edge_osmlr_off[e] = off
                off += float(edge_len[e])
            osmlr_lens.append(off)
            cur = []
            cur_len = 0.0

        for e in chain:
            if cur and cur_len + float(edge_len[e]) > max_len:
                flush()
            cur.append(e)
            cur_len += float(edge_len[e])
        flush()

    return (edge_osmlr, edge_osmlr_off,
            np.asarray(osmlr_ids, np.int64), np.asarray(osmlr_lens, np.float32))


def _full_graph_osmlr(full_net: RoadNetwork, sub_net: RoadNetwork,
                      sub_E: int, sub_fwd, sub_rev, max_len: float):
    """OSMLR association computed on the FULL (all-mode) network, mapped
    onto a mode subgraph's edges.

    The reference associates OSMLR segments ONCE for all modes (osmlr +
    valhalla_associate_segments run on the full graph; SURVEY.md §2.2), so
    a road's segment id is identical whether a car or a bike report names
    it. Chaining on the subgraph instead would move chain boundaries
    wherever mode filtering changes a junction's degree. Mapping key is
    (way_id, leg, direction) — leg structure is mode-invariant
    (RoadNetwork.for_mode never re-splits ways). Direction-less edges the
    full graph lacks (a pedestrian walking a one-way street backwards)
    stay internal (-1): directional OSMLR refs have no counter-flow id in
    the reference either.
    """
    # Memo key = content fingerprint, not identity: callers mutate nets in
    # place between compiles (add_random_restrictions, test fixtures), and
    # an identity-keyed memo would silently serve a stale association.
    fp = (max_len, full_net.fingerprint())
    cached = getattr(full_net, "_osmlr_assoc", None)
    if cached is not None and cached[0] == fp:
        f_osmlr, f_off, ids, lens, by_key = cached[1]
    else:
        origin = full_net.origin()
        node_xy = lonlat_to_xy(full_net.node_lonlat,
                               origin).astype(np.float32)
        (fsrc, fdst, _fway, _fspeed, fshapes, fopp,
         f_fwd, f_rev) = _build_edges(full_net, node_xy, origin)
        # polyline lengths directly — the full segment decompose would
        # build and discard the whole kNN index just for this column
        f_edge_len = np.asarray(
            [float(np.linalg.norm(np.diff(p, axis=0), axis=1).sum())
             for p in fshapes], np.float32)
        f_osmlr, f_off, ids, lens = _chain_osmlr(
            full_net, f_edge_len, fsrc, fdst, fopp, f_fwd, f_rev, max_len)
        by_key = {}
        for (wi, leg), e in f_fwd.items():
            by_key[(full_net.ways[wi].way_id, leg, 0)] = e
        for (wi, leg), e in f_rev.items():
            by_key[(full_net.ways[wi].way_id, leg, 1)] = e
        # one association per full net content serves every mode compile
        full_net._osmlr_assoc = (
            fp, (f_osmlr, f_off, ids, lens, by_key))

    edge_osmlr = np.full(sub_E, -1, dtype=np.int32)
    edge_osmlr_off = np.zeros(sub_E, dtype=np.float32)
    for legs, d in ((sub_fwd, 0), (sub_rev, 1)):
        for (wi, leg), e in legs.items():
            fe = by_key.get((sub_net.ways[wi].way_id, leg, d))
            if fe is not None:   # None: e.g. a pedestrian's counter-flow
                #                  edge on a one-way — no directional ref
                edge_osmlr[e] = f_osmlr[fe]
                edge_osmlr_off[e] = f_off[fe]
    return edge_osmlr, edge_osmlr_off, ids, lens


def _decompose_segments(shapes: list[np.ndarray]):
    """Edge polylines → flat line-segment arrays (the kNN index unit)."""
    seg_a, seg_b, seg_edge, seg_off = [], [], [], []
    edge_len = np.zeros(len(shapes), dtype=np.float32)
    for e, poly in enumerate(shapes):
        off = 0.0
        for i in range(len(poly) - 1):
            a, b = poly[i], poly[i + 1]
            L = float(np.linalg.norm(b - a))
            if L <= 1e-6:
                continue
            seg_a.append(a); seg_b.append(b); seg_edge.append(e); seg_off.append(off)
            off += L
        edge_len[e] = off
    seg_a = np.asarray(seg_a, np.float32).reshape(-1, 2)
    seg_b = np.asarray(seg_b, np.float32).reshape(-1, 2)
    seg_len = np.linalg.norm(seg_b - seg_a, axis=1).astype(np.float32)
    return (seg_a, seg_b, np.asarray(seg_edge, np.int32),
            np.asarray(seg_off, np.float32), seg_len, edge_len)


def _build_grid(seg_a: np.ndarray, seg_b: np.ndarray, cell_size: float,
                capacity: int, index_radius: float, use_native: bool = False):
    """Padded uniform grid over line segments, dilated by ``index_radius``.

    A segment is registered in every cell within ``index_radius`` of its
    bbox. That trades offline registrations (and HBM rows) for the matcher's
    memory-access pattern: a query point reads exactly ONE cell row — its
    own — and is guaranteed to see every segment within
    search_radius <= index_radius. (The earlier design registered only
    overlapped cells and gathered a 3×3 neighborhood per point; the 9-row
    gather was the single most expensive memory access in the whole match
    pipeline on TPU.)"""
    smin = np.minimum(seg_a, seg_b) - index_radius
    smax = np.maximum(seg_a, seg_b) + index_radius
    lo = smin.min(axis=0) - 1.0
    hi = smax.max(axis=0) + 1.0
    gw = max(1, int(np.ceil((hi[0] - lo[0]) / cell_size)))
    gh = max(1, int(np.ceil((hi[1] - lo[1]) / cell_size)))
    if use_native:
        try:
            from reporter_tpu.tiles.native import build_grid_native

            # The native kernel boxes min/max of the two endpoint arrays it is
            # given, so passing the dilated corners registers dilated bboxes.
            out = build_grid_native(smin, smax, lo, cell_size, gw, gh,
                                    capacity)
            if out is not None:
                grid, overflow = out
                return grid, (gw, gh), lo.astype(np.float64), overflow
        except ImportError:
            pass
    grid = np.full((gw * gh, capacity), -1, dtype=np.int32)
    counts = np.zeros(gw * gh, dtype=np.int32)
    overflow = 0

    c0 = np.floor((smin - lo) / cell_size).astype(np.int64)
    c1 = np.floor((smax - lo) / cell_size).astype(np.int64)
    c0 = np.clip(c0, 0, [gw - 1, gh - 1])
    c1 = np.clip(c1, 0, [gw - 1, gh - 1])
    for s in range(len(seg_a)):
        for cx in range(c0[s, 0], c1[s, 0] + 1):
            for cy in range(c0[s, 1], c1[s, 1] + 1):
                cell = cx * gh + cy
                if counts[cell] < capacity:
                    grid[cell, counts[cell]] = s
                    counts[cell] += 1
                else:
                    overflow += 1
    return grid, (gw, gh), lo.astype(np.float64), overflow


def _build_node_out(num_nodes: int, edge_src: np.ndarray):
    order = np.argsort(edge_src, kind="stable")
    degree = np.bincount(edge_src, minlength=num_nodes)
    dmax = max(1, int(degree.max()) if len(degree) else 1)
    node_out = np.full((num_nodes, dmax), -1, dtype=np.int32)
    fill = np.zeros(num_nodes, dtype=np.int32)
    for e in order:
        u = edge_src[e]
        node_out[u, fill[u]] = e
        fill[u] += 1
    return node_out


def compile_network(net: RoadNetwork, params: CompilerParams | None = None,
                    mode: "str | None" = None) -> TileSet:
    """Compile a RoadNetwork into a device-ready TileSet.

    ``mode`` ("auto" / "bicycle" / "foot") compiles the tileset over that
    mode's legal subgraph (RoadNetwork.for_mode — the per-mode costing
    boundary, SURVEY.md §2.1): candidate tables, reach routing, and OSMLR
    chains are then all consistent with what the mode may travel. None
    keeps the network as-is when every way is drivable (synthetic cities
    default to all-access ways, so None and "auto" compile identically
    there) — but a MIXED network compiled with mode=None falls back to
    the auto subgraph, with a warning: the legacy unqualified API means
    "the drivable graph", and must not let cars match onto footpaths.
    Networks already filtered by for_mode (net.mode set), and networks
    with no drivable ways at all, always compile as-is — but note an
    as-is compile of a pre-filtered subgraph chains OSMLR on the SUBGRAPH
    (ids are subgraph-local): deployments that join segments across modes
    must compile via compile_network(full_net, mode=...) so every mode
    shares the full-graph association below.

    OSMLR association for mode tilesets is computed on the FULL (all
    modes) network and mapped onto the subgraph (_full_graph_osmlr), so a
    road's segment id is identical across modes — the reference runs
    osmlr + valhalla_associate_segments once for all modes, and
    cross-mode segment joins in the datastore depend on it."""
    params = params or CompilerParams()
    full_net = net
    if (mode is None and net.mode is None
            and any(not (w.access_mask & ACCESS_AUTO) for w in net.ways)
            and any(w.access_mask & ACCESS_AUTO for w in net.ways)):
        # (a net with NO drivable ways at all compiles as-is: the caller
        # built a non-auto graph on purpose, and an auto subgraph of it
        # would be empty)
        # Legacy drivable-only semantics: the parsers keep bike/foot-only
        # ways in the RoadNetwork (access bits) since the per-mode split,
        # so an unqualified compile of a mixed network must not let cars
        # match onto footpaths. Routing through the auto subgraph also
        # keeps name-keyed artifacts unambiguous: one name, one content.
        import warnings

        warnings.warn(
            f"network {net.name!r} contains non-drivable ways; "
            "compiling the auto subgraph (pass mode=... to silence)",
            stacklevel=2)
        mode = "auto"
    if mode is not None:
        net = net.for_mode(mode)
    if net.num_nodes == 0 or not net.ways:
        raise ValueError(
            f"RoadNetwork {net.name!r} has no drivable ways/nodes; nothing to compile")
    t0 = time.time()
    # Mode compiles project with the FULL net's origin: the mapped OSMLR
    # offsets/lengths are measured in that frame, and the walker compares
    # them against subgraph edge lengths with 1 m absolute tolerances —
    # two equirectangular frames (cos-lat scaling) would drift past that
    # on metro-scale bbox shifts.
    origin = (full_net if mode is not None else net).origin()
    node_xy = lonlat_to_xy(net.node_lonlat, origin).astype(np.float32)

    (edge_src, edge_dst, edge_way, edge_speed,
     shapes, edge_opp, fwd_of_leg, rev_of_leg) = _build_edges(net, node_xy, origin)

    seg_a, seg_b, seg_edge, seg_off, seg_len, edge_len = _decompose_segments(shapes)

    if mode is not None:
        # mode tilesets share ONE full-graph OSMLR association, so a
        # road's segment id is identical across modes (_full_graph_osmlr)
        edge_osmlr, edge_osmlr_off, osmlr_id, osmlr_len = _full_graph_osmlr(
            full_net, net, len(edge_len), fwd_of_leg, rev_of_leg,
            params.osmlr_max_length)
    else:
        edge_osmlr, edge_osmlr_off, osmlr_id, osmlr_len = _chain_osmlr(
            net, edge_len, edge_src, edge_dst, edge_opp, fwd_of_leg,
            rev_of_leg, params.osmlr_max_length)

    # Auto-size the grid capacity: irregular topologies (organic cores,
    # real OSM downtowns) can exceed the default segments-per-cell, and an
    # overflowed cell silently hides candidates from the grid backend and
    # the CPU oracle. Doubling until clean costs only offline time and
    # (cells × capacity × 4 B) of a table the dense path never stages.
    capacity = params.cell_capacity
    while True:
        grid, grid_dims, grid_origin, overflow = _build_grid(
            seg_a, seg_b, params.cell_size, capacity,
            params.index_radius, use_native=params.use_native)
        if not overflow or capacity >= 1024:
            break
        capacity *= 2

    node_out = _build_node_out(net.num_nodes, edge_src)

    banned_pairs = _resolve_restrictions(net, edge_src, edge_dst, edge_way,
                                         node_out)

    (reach_to, reach_dist, reach_next, reach_truncated,
     edge_reach_row) = _build_reach(
        node_out, edge_src, edge_dst, edge_len, node_xy, banned_pairs, params)

    if overflow:
        import warnings

        warnings.warn(
            f"{net.name}: spatial grid dropped {overflow} segment "
            f"registrations even at the auto-sizing ceiling "
            f"(cell_capacity={capacity}, started at {params.cell_capacity});"
            " candidate search may miss roads in dense cells — shrink "
            "cell_size or thin the network", stacklevel=2)

    meta = TileMeta(
        grid_origin=(float(grid_origin[0]), float(grid_origin[1])),
        cell_size=float(params.cell_size),
        grid_dims=grid_dims,
        origin_lonlat=(float(origin[0]), float(origin[1])),
        index_radius=float(params.index_radius),
    )
    ts = TileSet(
        name=net.name, meta=meta,
        node_xy=node_xy, node_out=node_out,
        edge_src=edge_src, edge_dst=edge_dst, edge_len=edge_len,
        edge_way=edge_way, edge_speed=edge_speed, edge_opp=edge_opp,
        edge_osmlr=edge_osmlr, edge_osmlr_off=edge_osmlr_off,
        osmlr_id=osmlr_id, osmlr_len=osmlr_len,
        seg_a=seg_a, seg_b=seg_b, seg_edge=seg_edge, seg_off=seg_off, seg_len=seg_len,
        grid=grid,
        reach_to=reach_to, reach_dist=reach_dist, reach_next=reach_next,
        edge_reach_row=edge_reach_row,
        ban_from=banned_pairs[:, 0].copy() if len(banned_pairs)
        else np.zeros(0, np.int32),
        ban_to=banned_pairs[:, 1].copy() if len(banned_pairs)
        else np.zeros(0, np.int32),
        stats={
            "nodes": int(net.num_nodes), "edges": int(len(edge_len)),
            "line_segments": int(len(seg_a)), "osmlr_segments": int(len(osmlr_id)),
            "grid_cells": int(grid_dims[0] * grid_dims[1]),
            "grid_overflow": int(overflow),
            "reach_truncated_nodes": int(reach_truncated),
            "restrictions": len(net.restrictions),
            "banned_turn_pairs": int(len(banned_pairs)),
            **({"mode": mode} if mode is not None else {}),
            "compile_seconds": round(time.time() - t0, 3),
        },
    )
    return ts


def _resolve_restrictions(net: RoadNetwork, edge_src, edge_dst, edge_way,
                          node_out) -> np.ndarray:
    """TurnRestrictions (way ids + via node) → banned directed-edge pairs
    [B, 2]. ``no_*`` bans the named (from, to) pairs; ``only_*`` bans every
    OTHER exit from the from-edge at the via node. Unresolvable relations
    (way not incident to the via node in the needed direction) are dropped
    with a warning, like the reference's graph builder does."""
    banned: set[tuple[int, int]] = set()
    dropped = 0
    if not net.restrictions:
        return np.zeros((0, 2), np.int32)
    by_way: dict[int, list[int]] = {}
    for e, w in enumerate(edge_way):
        by_way.setdefault(int(w), []).append(e)
    for r in net.restrictions:
        u = int(r.via_node)
        from_edges = [e for e in by_way.get(r.from_way, ())
                      if int(edge_dst[e]) == u]
        to_edges = {e for e in by_way.get(r.to_way, ())
                    if int(edge_src[e]) == u}
        if not from_edges or not to_edges:
            dropped += 1
            continue
        outs = [int(e) for e in node_out[u] if e >= 0]
        for ef in from_edges:
            if r.mandatory:
                banned.update((ef, x) for x in outs if x not in to_edges)
            else:
                banned.update((ef, int(t)) for t in to_edges)
    if dropped:
        import warnings

        warnings.warn(f"{net.name}: dropped {dropped} unresolvable turn "
                      "restrictions", stacklevel=2)
    if not banned:
        return np.zeros((0, 2), np.int32)
    return np.asarray(sorted(banned), np.int32)


def _node_space_reach(node_out, edge_src, edge_dst, edge_len,
                      params: CompilerParams):
    """Unrestricted node rows: native C++ builder when available, Python
    fallback (bit-identical). Returns (to, dist, next, truncated)."""
    if params.use_native:
        try:
            from reporter_tpu.tiles.native import build_reach_native

            out = build_reach_native(
                node_out, edge_src, edge_dst, edge_len,
                params.reach_radius, params.reach_max)
            if out is not None:
                return out
        except ImportError:
            pass
    from reporter_tpu.tiles.reach import build_reach_tables

    return build_reach_tables(
        node_out, edge_src, edge_dst, edge_len,
        params.reach_radius, params.reach_max)


def _build_reach(node_out, edge_src, edge_dst, edge_len, node_xy,
                 banned_pairs, params: CompilerParams):
    """Reach tables + edge→row map. The fast (native) node-space build
    always runs; tiles with turn restrictions then recompute only the
    ban-affected ball of node rows + the private from-edge rows in the
    Python edge-space builder (restrictions are sparse, so metro compiles
    stay on the multithreaded path)."""
    base = _node_space_reach(node_out, edge_src, edge_dst, edge_len, params)
    if not len(banned_pairs):
        return (*base, edge_dst.astype(np.int32).copy())
    from reporter_tpu.tiles.reach import build_reach_tables_restricted

    return build_reach_tables_restricted(
        node_out, edge_src, edge_dst, edge_len,
        params.reach_radius, params.reach_max, banned_pairs,
        base=base[:3], node_xy=node_xy)
