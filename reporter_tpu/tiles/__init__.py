"""Offline tile pipeline: RoadNetwork → flat, padded, TPU-resident arrays.

TPU-native replacement for the reference's L1/L0 (SURVEY.md §1): Valhalla's
baldr graph tiles + mjolnir tile build + OSMLR generation/association. Instead
of pointer-rich C++ tiles read at match time, everything the online matcher
needs is compiled offline into fixed-shape arrays that live in HBM.
"""

from reporter_tpu.tiles.tileset import TileSet, TileMeta
from reporter_tpu.tiles.compiler import compile_network

__all__ = ["TileSet", "TileMeta", "compile_network"]
