"""OSMLR segment export — the published segment-definition artifact.

The reference's OSMLR project publishes segment definitions as geometry
tiles (SURVEY.md §2.2 "OSMLR segments + association": "~1 km stable
segments (protobuf tiles…)"), which is how datastore consumers resolve a
report's ``segment_id`` back to a place on the map. This module produces
the same artifact from a compiled TileSet: one GeoJSON Feature per OSMLR
segment, geometry stitched from the member edges' line segments in drive
order, properties carrying the stable id, length, and source way ids.

    python -m reporter_tpu.tiles osmlr metro.npz -o segments.geojson
"""

from __future__ import annotations

import json

import numpy as np

from reporter_tpu.geometry import xy_to_lonlat
from reporter_tpu.tiles.tileset import TileSet


def osmlr_features(ts: TileSet) -> "list[dict]":
    """GeoJSON Features (LineString per OSMLR segment), id order."""
    # member edges per row, ordered by their offset within the segment
    edges_of: dict[int, list[tuple[float, int]]] = {}
    for e in range(ts.num_edges):
        row = int(ts.edge_osmlr[e])
        if row >= 0:
            edges_of.setdefault(row, []).append(
                (float(ts.edge_osmlr_off[e]), e))

    # line segments per edge: _decompose_segments already emits them
    # grouped by edge in increasing seg_off order, so a single forward
    # pass groups them — no argsort (single-core host, S can be millions)
    segs_of: dict[int, list[int]] = {}
    for s in range(len(ts.seg_edge)):
        segs_of.setdefault(int(ts.seg_edge[s]), []).append(s)

    origin = np.asarray(ts.meta.origin_lonlat)
    feats: list[dict] = []
    for row in range(len(ts.osmlr_id)):
        members = sorted(edges_of.get(row, ()))
        if not members:
            continue
        pts_xy: list = []
        way_ids: list[int] = []
        for _, e in members:
            w = int(ts.edge_way[e])
            if not way_ids or way_ids[-1] != w:
                way_ids.append(w)
            for s in segs_of.get(e, ()):
                ax, ay = float(ts.seg_a[s, 0]), float(ts.seg_a[s, 1])
                # consecutive seg_b/seg_a pairs are bit-identical f32 by
                # construction — exact compare, no tolerance scaling
                if not pts_xy or pts_xy[-1] != (ax, ay):
                    pts_xy.append((ax, ay))
                pts_xy.append((float(ts.seg_b[s, 0]),
                               float(ts.seg_b[s, 1])))
        if len(pts_xy) < 2:
            # all member edges were sub-epsilon (skipped by the segment
            # decomposer): nothing drawable — a <2-point LineString is
            # invalid GeoJSON, so skip the row rather than abort
            continue
        lonlat = xy_to_lonlat(np.asarray(pts_xy, np.float64), origin)
        feats.append({
            "type": "Feature",
            "id": int(ts.osmlr_id[row]),
            "geometry": {
                "type": "LineString",
                "coordinates": [[round(float(lo), 7), round(float(la), 7)]
                                for lo, la in lonlat],
            },
            "properties": {
                "osmlr_id": int(ts.osmlr_id[row]),
                "length_m": round(float(ts.osmlr_len[row]), 2),
                "way_ids": way_ids,
                "num_edges": len(members),
            },
        })
    return feats


def export_osmlr_geojson(ts: TileSet, path: str) -> int:
    """Write the FeatureCollection; returns the feature count."""
    feats = osmlr_features(ts)
    with open(path, "w") as f:
        json.dump({"type": "FeatureCollection",
                   "name": f"{ts.name}-osmlr",
                   "features": feats}, f)
    return len(feats)
