"""TileSet — the compiled, device-ready road graph for one metro.

Replaces the online role of Valhalla's GraphTile/GraphReader (SURVEY.md §2.2
"Graph tiles"): no pointer chasing, no tile fetch — every array is flat,
fixed-dtype, padded with sentinels, and can be staged to TPU HBM once and
reused across every match batch.

Array glossary (sizes: N nodes, E directed edges, S line segments, G OSMLR
segments, C grid cell capacity, M reach-table width):

  node_xy        f32 [N,2]   node position, tile-local meters
  node_out       i32 [N,D]   outgoing directed-edge ids, -1 padded
  edge_src/dst   i32 [E]     endpoint node ids
  edge_len       f32 [E]     polyline length (m)
  edge_way       i64 [E]     source way id (OSM way analog)
  edge_speed     f32 [E]     free-flow speed (m/s)
  edge_opp       i32 [E]     opposite directed edge, -1 if one-way
  edge_osmlr     i32 [E]     OSMLR table row, -1 if unassociated
  edge_osmlr_off f32 [E]     meters from OSMLR segment start to edge start
  osmlr_id       i64 [G]     stable OSMLR segment id
  osmlr_len      f32 [G]     full segment length (m)
  seg_a/seg_b    f32 [S,2]   line-segment endpoints (edge shapes decomposed)
  seg_edge       i32 [S]     owning directed edge
  seg_off        f32 [S]     distance along edge at seg_a
  seg_len        f32 [S]     |seg_b - seg_a|
  grid           i32 [ncells,C]  line-segment ids per spatial cell, -1 padded
  reach_to       i32 [R,M]   nearby reachable target edges, -1 padded
  reach_dist     f32 [R,M]   network distance row-source → start-of-target (m)
  reach_next     i32 [R,M]   first edge of that path (next-hop, for host walk)
  edge_reach_row i32 [E]     reach row governing transitions out of edge e
  ban_from/ban_to i32 [B]    banned turn pairs (from edge → to edge)

Reach tables are node-keyed: R = N rows, edge_reach_row[e] == edge_dst[e]
(all in-edges of a node share targets), ~3× smaller than a per-edge
broadcast — which pays for a wide M (tiles/reach_audit.py measures what
truncation would cost). Turn restrictions add private ban-aware rows for
their from-edges (R = N + F) and repoint edge_reach_row there
(tiles/reach.py).

Device-side the grid + per-segment arrays are fused into ``cell_pack``
(build_cell_pack below): one f32 [ncells, 8*C] row per cell holding every
registered segment's geometry inline, so candidate search is a single
contiguous row-gather instead of six dependent scalar gathers (the latter are
catastrophic on TPU — gathers of single f32 elements run near one element per
cycle, and dominated the whole match pipeline before this layout).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, NamedTuple

import numpy as np

# cell_pack component slots (axis 1 of the [ncells, NCOMP, C] layout)
PACK_AX, PACK_AY, PACK_BX, PACK_BY = 0, 1, 2, 3
PACK_OFF, PACK_LEN, PACK_EDGE, PACK_SPARE = 4, 5, 6, 7
PACK_NCOMP = 8

# Version of the staged device-table LAYOUT (the member set host_tables
# builds). Bumped whenever a table is added/changed so a host_tables
# dict pinned BEFORE the change (the fleet cold tier keeps them for the
# process lifetime; external callers may cache them) fails loudly at
# restage time instead of shipping an incomplete layout to the kernel.
#   v2 (round 13): + seg_feat (MXU quadratic feature rows) next to the
#   round-8 seg_sub quads. Pre-tag dicts (≤ r12) carry no tag at all.
#   v3 (round 17): + tuned_plan (matcher/autotune.py — the per-metro
#   self-tuned dispatch plan as an i32[5] vector; host_tables stamps the
#   static default, the tuner or the on-disk plan cache overwrites it at
#   staging time). Rides the dense layout only — the grid backend has no
#   kernel arms to tune.
STAGED_LAYOUT_VERSION = 3

# every SegPack member the dense layout must stage as of this version —
# check_staged_layout cross-checks the member set, not just the tag, so
# a hand-assembled dict can't pass with a fresh tag and a stale layout
_DENSE_LAYOUT_KEYS = ("seg_pack", "seg_bbox", "seg_sub", "seg_feat")


def check_staged_layout(tables) -> None:
    """Assert a staged-tables dict was built by THIS code version's
    ``host_tables``/``device_tables``. Called at every staging seam that
    accepts a pre-built dict (SegmentMatcher(staged_tables=...),
    restage_tables — the fleet promotion path): a dict built before a
    layout change would otherwise reach the kernel missing a table (or
    carrying a stale one) and fail as garbage three layers down."""
    v = None
    if hasattr(tables, "get"):
        v = tables.get("staged_layout")
    if v is None:
        raise ValueError(
            "staged tables carry no staged_layout version tag — built "
            "before the versioned staging layout (round 13); rebuild the "
            "dict with TileSet.host_tables()/device_tables()")
    # value check only on host-backed tags: reading a device-resident
    # scalar back would cost a link RTT on the fleet promote path (the
    # axon tunnel, CLAUDE.md) for a dict that was device_put from a
    # host dict any host-side seam already vetted. The key-presence and
    # member-set checks below are free and cover the realistic stale
    # case (pre-tag dicts have no key at all).
    if isinstance(v, (int, np.integer, np.ndarray)):
        if int(v) != STAGED_LAYOUT_VERSION:
            raise ValueError(
                f"staged tables are layout v{int(v)}, this code stages "
                f"v{STAGED_LAYOUT_VERSION} — rebuild the dict with "
                "TileSet.host_tables()/device_tables()")
    if "seg_pack" in tables:
        missing = [k for k in _DENSE_LAYOUT_KEYS if k not in tables]
        # tuned_plan (layout v3) rides the dense layout too, but stays
        # OUT of _DENSE_LAYOUT_KEYS: it is plan metadata, not a swept
        # table, and the staged-layout lint's "members stage together"
        # rule must not force every sweep consumer to name it
        if "tuned_plan" not in tables:
            missing.append("tuned_plan")
        if missing:
            raise ValueError(
                f"staged dense layout is missing {missing} despite a "
                f"current version tag — rebuild with TileSet.host_tables()")


def build_cell_pack(grid: np.ndarray, seg_a: np.ndarray, seg_b: np.ndarray,
                    seg_edge: np.ndarray, seg_off: np.ndarray,
                    seg_len: np.ndarray) -> np.ndarray:
    """Fuse grid + segment SoA arrays into one gatherable f32 row per cell.

    Layout [ncells, NCOMP * C], component-major (all C ax values, then all C
    ay values, …) so the device kernel reshapes to [NCOMP, C] and slices.
    Edge ids ride along bitcast int32→float32 (exact round-trip via
    lax.bitcast_convert_type); empty slots carry edge = -1.
    """
    ncells, cap = grid.shape
    safe = np.maximum(grid, 0)
    empty = grid < 0
    pack = np.zeros((ncells, PACK_NCOMP, cap), np.float32)
    pack[:, PACK_AX] = seg_a[:, 0][safe]
    pack[:, PACK_AY] = seg_a[:, 1][safe]
    pack[:, PACK_BX] = seg_b[:, 0][safe]
    pack[:, PACK_BY] = seg_b[:, 1][safe]
    pack[:, PACK_OFF] = seg_off[safe]
    pack[:, PACK_LEN] = seg_len[safe]
    edge = np.where(empty, np.int32(-1), seg_edge[safe]).astype(np.int32)
    pack[:, PACK_EDGE] = edge.view(np.float32)
    for comp in (PACK_AX, PACK_AY, PACK_BX, PACK_BY, PACK_OFF, PACK_LEN):
        pack[:, comp][empty] = 0.0
    return pack.reshape(ncells, PACK_NCOMP * cap)


_ARRAY_FIELDS = (
    "node_xy", "node_out",
    "edge_src", "edge_dst", "edge_len", "edge_way", "edge_speed", "edge_opp",
    "edge_osmlr", "edge_osmlr_off",
    "osmlr_id", "osmlr_len",
    "seg_a", "seg_b", "seg_edge", "seg_off", "seg_len",
    "grid",
    "reach_to", "reach_dist", "reach_next", "edge_reach_row",
    "ban_from", "ban_to",
)


class TileMeta(NamedTuple):
    """Static (trace-time-constant) grid/projection metadata."""

    grid_origin: tuple[float, float]   # xy of cell (0, 0) lower-left corner
    cell_size: float
    grid_dims: tuple[int, int]         # (gw, gh); grid array is [gw*gh, C]
    origin_lonlat: tuple[float, float]
    index_radius: float                # grid registration dilation (m); the
                                       # single-cell gather covers any
                                       # search_radius <= this


@dataclass
class TileSet:
    name: str
    meta: TileMeta
    node_xy: np.ndarray
    node_out: np.ndarray
    edge_src: np.ndarray
    edge_dst: np.ndarray
    edge_len: np.ndarray
    edge_way: np.ndarray
    edge_speed: np.ndarray
    edge_opp: np.ndarray
    edge_osmlr: np.ndarray
    edge_osmlr_off: np.ndarray
    osmlr_id: np.ndarray
    osmlr_len: np.ndarray
    seg_a: np.ndarray
    seg_b: np.ndarray
    seg_edge: np.ndarray
    seg_off: np.ndarray
    seg_len: np.ndarray
    grid: np.ndarray
    reach_to: np.ndarray
    reach_dist: np.ndarray
    reach_next: np.ndarray
    edge_reach_row: np.ndarray
    ban_from: np.ndarray
    ban_to: np.ndarray
    stats: dict[str, Any] = field(default_factory=dict)

    _ban_set_cache: "set[tuple[int, int]] | None" = field(
        default=None, repr=False, compare=False)

    @property
    def ban_set(self) -> set[tuple[int, int]]:
        """Banned (from_edge, to_edge) pairs as a set (lazy; oracle + audit)."""
        if self._ban_set_cache is None:
            object.__setattr__(self, "_ban_set_cache",
                               {(int(a), int(b)) for a, b
                                in zip(self.ban_from, self.ban_to)})
        return self._ban_set_cache

    @property
    def num_edges(self) -> int:
        return int(len(self.edge_len))

    @property
    def num_nodes(self) -> int:
        return int(len(self.node_xy))

    # ---- persistence ----------------------------------------------------

    def save(self, path: str) -> None:
        import json

        if not path.endswith(".npz"):
            path += ".npz"  # savez appends it; normalize so load(path) matches
        payload = {f: getattr(self, f) for f in _ARRAY_FIELDS}
        payload["_meta"] = np.frombuffer(
            json.dumps({"name": self.name, "meta": list(self.meta),
                        "stats": self.stats,
                        # schema 4: reach rows laid out ascending by
                        # target edge id (binary-searchable) on top of
                        # schema 3's node-keyed rows + edge_reach_row
                        # indirection + banned turn pairs
                        "schema": 4}).encode(),
            dtype=np.uint8,
        )
        np.savez_compressed(path, **payload)

    @classmethod
    def load(cls, path: str) -> "TileSet":
        import json

        if not path.endswith(".npz"):
            path += ".npz"
        with np.load(path) as z:
            raw = json.loads(bytes(z["_meta"]).decode())
            if raw.get("schema", 1) != 4:
                raise ValueError(
                    f"{path}: tileset schema {raw.get('schema', 1)} predates "
                    "the id-sorted reach rows (binary-searched by the "
                    "native walker); recompile with compile_network()")
            arrays = {f: z[f] for f in _ARRAY_FIELDS}
        if len(raw["meta"]) != len(TileMeta._fields):
            raise ValueError(
                f"{path}: tileset metadata has {len(raw['meta'])} fields, "
                f"expected {len(TileMeta._fields)} — written by an older tile "
                "compiler; recompile the network with compile_network()")
        go, cs, gd, ol, ir = raw["meta"]
        meta = TileMeta(tuple(go), float(cs), tuple(gd), tuple(ol), float(ir))
        return cls(name=raw["name"], meta=meta, stats=raw.get("stats", {}),
                   **arrays)

    # ---- device staging --------------------------------------------------

    def host_tables(self, candidate_backend: str = "both",
                    ) -> dict[str, np.ndarray]:
        """The staged device layouts as plain HOST numpy arrays — the
        shared builder behind ``device_tables`` (jnp view of the same
        dict), the multimetro NaN-pad stack (parallel/multimetro.py,
        which pads these before any device placement), and the fleet
        residency manager's cold tier (fleet/residency.py pins this
        dict in host RAM so an evicted metro re-promotes with one
        ``jax.device_put`` instead of rebuilding cell_pack/seg_pack —
        the build, not the transfer, dominates staging cost at metro
        scale).

        ``candidate_backend`` prunes the candidate-search layout staged:
        "dense" skips cell_pack (the grid backend's [C, 8*cap] f32 fusion
        — by far the largest table at metro scale: ~1.06 GB for
        bayarea-xl vs ~39 MB of seg_pack + seg_feat), "grid" skips the
        seg_pack/bbox/sub/feat layout,
        "auto" resolves like ops.match.batch_candidates (grid on CPU,
        dense on accelerators), "both" stages everything (multimetro
        stacking and tests that flip backends per matcher)."""
        import logging

        from reporter_tpu.ops.dense_candidates import build_seg_pack

        if candidate_backend == "auto":
            import jax

            candidate_backend = ("grid" if jax.default_backend() == "cpu"
                                 else "dense")
        if candidate_backend not in ("dense", "grid", "both"):
            # a typo would silently stage BOTH layouts, defeating the
            # pruning — mirror ops/match.batch_candidates' strictness
            raise ValueError(
                f"unknown candidate_backend {candidate_backend!r}; "
                "use 'auto', 'dense', 'grid' or 'both'")

        # The u16 result wire format carries offsets in 0.25 m fixed point
        # (ops/match.py OFFSET_QUANTUM): edges longer than 16.4 km would
        # clamp. Real road edges are far shorter (OSMLR chains target 1 km),
        # so surface the anomaly instead of silently corrupting offsets.
        max_len = float(self.edge_len.max()) if len(self.edge_len) else 0.0
        if max_len > 16000.0:
            logging.getLogger("reporter_tpu.tiles").warning(
                "tileset %s has an edge of %.0f m — offsets beyond 16383 m "
                "clamp in the u16 wire format; split such edges upstream",
                self.name, max_len)

        # Two candidate-search layouts ride to HBM: cell_pack (grid backend —
        # one contiguous [8C] row-gather per point, see build_cell_pack) and
        # seg_pack + seg_bbox (dense backend — Morton-blocked [8, S]
        # component rows swept by the pallas kernel with bbox culling, no
        # gathers at all; ops/dense_candidates.py). The id-only grid and
        # per-segment SoA arrays stay host-side.
        out: dict[str, np.ndarray] = {
            # layout version tag (check_staged_layout): a 0-d i32 that
            # rides the dict everywhere — through device_put (fleet
            # promotions), the multimetro stack, and the wire entries
            # (unused dynamic leaf) — so a pinned dict from an older
            # layout can never silently restage
            "staged_layout": np.int32(STAGED_LAYOUT_VERSION),
            "edge_len": np.asarray(self.edge_len),
            "reach_row": np.asarray(self.edge_reach_row),
            "edge_osmlr": np.asarray(self.edge_osmlr),
            "reach_to": np.asarray(self.reach_to),
            "reach_dist": np.asarray(self.reach_dist),
        }
        if candidate_backend != "dense":
            out["cell_pack"] = build_cell_pack(
                self.grid, self.seg_a, self.seg_b, self.seg_edge,
                self.seg_off, self.seg_len)
        if candidate_backend != "grid":
            sp = build_seg_pack(self.seg_a, self.seg_b, self.seg_edge,
                                self.seg_off, self.seg_len)
            out["seg_pack"] = np.asarray(sp.pack)
            out["seg_bbox"] = np.asarray(sp.bbox)
            # per-sub-block bbox quads: the kernel's in-block second
            # culling level (round 8) — tiny next to seg_pack
            out["seg_sub"] = np.asarray(sp.sub)
            # per-column MXU feature rows: the matmul-form coarse pass
            # (round 13) — same [8, S_pad] footprint as seg_pack
            out["seg_feat"] = np.asarray(sp.feat)
            # per-metro dispatch plan (round 17, layout v3): the static
            # default here; the autotuner / on-disk plan cache overwrite
            # this host leaf at staging time (matcher/autotune.py). An
            # unused wire argument on device — a plan change can never
            # change wire bytes.
            from reporter_tpu.matcher.autotune import default_plan_array
            out["tuned_plan"] = default_plan_array()
        return out

    def device_tables(self, candidate_backend: str = "both",
                      ) -> dict[str, Any]:
        """``host_tables`` as a plain dict pytree of jnp arrays
        (HBM-resident after first use) — what SegmentMatcher stages."""
        import jax.numpy as jnp

        return {k: jnp.asarray(v)
                for k, v in self.host_tables(candidate_backend).items()}

    def hbm_bytes(self) -> int:
        return int(sum(getattr(self, f).nbytes for f in _ARRAY_FIELDS))
