"""numpy-facing wrappers over the native (C++) tile-compiler kernels.

The tile compiler calls these when ``CompilerParams.use_native`` is set;
each returns None when the native library is unavailable so the caller can
fall back to the pure-Python builders (tiles/reach.py, compiler._build_grid).
Output parity with those builders is exact and tested (tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np


def _as_c(arr: np.ndarray, dtype) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=dtype)


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_reach_native(node_out: np.ndarray, edge_src: np.ndarray,
                       edge_dst: np.ndarray, edge_len: np.ndarray,
                       radius: float, max_targets: int,
                       ) -> "tuple[np.ndarray, np.ndarray, np.ndarray, int] | None":
    """Native twin of tiles.reach.build_reach_tables (same signature/output)."""
    from reporter_tpu.native import lib

    if lib is None:
        return None
    num_nodes, deg = node_out.shape
    node_out = _as_c(node_out, np.int32)
    edge_dst = _as_c(edge_dst, np.int32)
    edge_len = _as_c(edge_len, np.float32)
    # node-keyed rows (the row for edge e is row edge_dst[e])
    reach_to = np.full((num_nodes, max_targets), -1, dtype=np.int32)
    reach_dist = np.full((num_nodes, max_targets), np.inf, dtype=np.float32)
    reach_next = np.full((num_nodes, max_targets), -1, dtype=np.int32)
    n_threads = int(os.environ.get("REPORTER_TPU_NATIVE_THREADS", "0"))
    truncated = lib.reporter_build_reach(
        _ptr(node_out, ctypes.c_int32), num_nodes, deg,
        _ptr(edge_dst, ctypes.c_int32), _ptr(edge_len, ctypes.c_float),
        float(radius), int(max_targets), n_threads,
        _ptr(reach_to, ctypes.c_int32), _ptr(reach_dist, ctypes.c_float),
        _ptr(reach_next, ctypes.c_int32))
    return reach_to, reach_dist, reach_next, int(truncated)


def build_grid_native(seg_a: np.ndarray, seg_b: np.ndarray,
                      lo: np.ndarray, cell_size: float,
                      gw: int, gh: int, capacity: int,
                      ) -> "tuple[np.ndarray, int] | None":
    """Native twin of the grid-fill loop in tiles.compiler._build_grid."""
    from reporter_tpu.native import lib

    if lib is None:
        return None
    ax = _as_c(seg_a[:, 0], np.float32)
    ay = _as_c(seg_a[:, 1], np.float32)
    bx = _as_c(seg_b[:, 0], np.float32)
    by = _as_c(seg_b[:, 1], np.float32)
    grid = np.full((gw * gh, capacity), -1, dtype=np.int32)
    counts = np.zeros(gw * gh, dtype=np.int32)
    overflow = lib.reporter_build_grid(
        _ptr(ax, ctypes.c_float), _ptr(ay, ctypes.c_float),
        _ptr(bx, ctypes.c_float), _ptr(by, ctypes.c_float), len(ax),
        float(lo[0]), float(lo[1]), float(cell_size),
        int(gw), int(gh), int(capacity),
        _ptr(grid, ctypes.c_int32), _ptr(counts, ctypes.c_int32))
    return grid, int(overflow)
