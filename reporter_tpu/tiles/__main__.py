"""Tile-pipeline CLI — the valhalla_build_tiles / osmlr / associate analog.

The reference's offline pipeline is three C++ CLI tools chained by scripts
(SURVEY.md §3.4): build routable tiles, generate OSMLR segments, write the
edge↔segment association back. Here the whole chain is one compiler pass
(tiles/compiler.compile_network does graph + OSMLR chaining + association +
grid + reach tables), so the CLI surface is:

    python -m reporter_tpu.tiles build --osm map.osm.xml -o metro.npz
    python -m reporter_tpu.tiles synth --city sf -o sf.npz
    python -m reporter_tpu.tiles info metro.npz

Compiled .npz tilesets load with TileSet.load() and stage straight to HBM
via TileSet.device_tables().
"""

from __future__ import annotations

import argparse
import json
import sys


def _params(args: argparse.Namespace):
    from reporter_tpu.config import CompilerParams

    kw = {}
    for f in ("cell_size", "cell_capacity", "index_radius", "reach_radius",
              "reach_max", "osmlr_max_length"):
        v = getattr(args, f, None)
        if v is not None:
            kw[f] = v
    if getattr(args, "no_native", False):
        kw["use_native"] = False
    return CompilerParams(**kw)


def _add_compiler_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("-o", "--output", required=True, help="output .npz path")
    p.add_argument("--cell-size", dest="cell_size", type=float)
    p.add_argument("--cell-capacity", dest="cell_capacity", type=int)
    p.add_argument("--index-radius", dest="index_radius", type=float)
    p.add_argument("--reach-radius", dest="reach_radius", type=float)
    p.add_argument("--reach-max", dest="reach_max", type=int)
    p.add_argument("--osmlr-max-length", dest="osmlr_max_length", type=float)
    p.add_argument("--no-native", dest="no_native", action="store_true",
                   help="force the pure-Python reach/grid builders")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m reporter_tpu.tiles")
    sub = ap.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="compile an OSM extract (XML or PBF)")
    b.add_argument("--osm", required=True,
                   help="OSM file (.osm/.xml or .osm.pbf/.pbf)")
    b.add_argument("--name", default=None, help="tileset name")
    b.add_argument("--mode", default="auto",
                   choices=("auto", "bicycle", "foot"),
                   help="compile this mode's legal subgraph (default auto; "
                        "parsers keep every mode's ways, so one deployment "
                        "builds one tileset per served mode)")
    _add_compiler_flags(b)

    from reporter_tpu.netgen.synthetic import CITY_PRESETS

    s = sub.add_parser("synth", help="compile a synthetic city")
    s.add_argument("--city", default="sf",
                   help="|".join(CITY_PRESETS) + " (netgen/synthetic.py)")
    s.add_argument("--seed", type=int, default=0)
    _add_compiler_flags(s)

    i = sub.add_parser("info", help="print a compiled tileset's stats")
    i.add_argument("path")

    g = sub.add_parser("osmlr",
                       help="export OSMLR segment definitions (GeoJSON, "
                            "or the compact binary tile with --binary)")
    g.add_argument("path", help="compiled tileset .npz")
    g.add_argument("-o", "--output", required=True,
                   help="output .geojson (or .osmlr with --binary) path")
    g.add_argument("--binary", action="store_true",
                   help="write the protobuf-wire binary segment tile "
                        "(tiles/osmlr_tiles.py) instead of GeoJSON")

    c = sub.add_parser("convert", help="convert an OSM XML extract to PBF")
    c.add_argument("xml", help="input .osm/.xml file")
    c.add_argument("pbf", help="output .osm.pbf path")
    c.add_argument("--raw", action="store_true",
                   help="write uncompressed blobs (debugging)")

    args = ap.parse_args(argv)

    if args.cmd == "osmlr":
        from reporter_tpu.tiles.tileset import TileSet

        ts = TileSet.load(args.path)
        if args.binary:
            from reporter_tpu.tiles.osmlr_tiles import write_osmlr_tile

            n = write_osmlr_tile(ts, args.output)
        else:
            from reporter_tpu.tiles.osmlr_export import export_osmlr_geojson

            n = export_osmlr_geojson(ts, args.output)
        print(json.dumps({"written": args.output, "segments": n}))
        return 0

    if args.cmd == "convert":
        from reporter_tpu.netgen.osm_xml import xml_elements
        from reporter_tpu.netgen.pbf import write_osm_pbf

        node_pos, ways, relations = xml_elements(args.xml)
        write_osm_pbf(args.pbf, node_pos, ways, relations,
                      compress=not args.raw)
        print(json.dumps({"written": args.pbf, "nodes": len(node_pos),
                          "ways": len(ways), "relations": len(relations)}))
        return 0

    if args.cmd == "info":
        from reporter_tpu.tiles.tileset import TileSet

        ts = TileSet.load(args.path)
        print(json.dumps({
            "name": ts.name,
            "nodes": ts.num_nodes,
            "edges": ts.num_edges,
            "line_segments": int(len(ts.seg_edge)),
            "osmlr_segments": int(len(ts.osmlr_id)),
            "grid_cells": int(ts.grid.shape[0]),
            "hbm_bytes": ts.hbm_bytes(),
            "meta": {"cell_size": ts.meta.cell_size,
                     "grid_dims": list(ts.meta.grid_dims),
                     "index_radius": ts.meta.index_radius},
            "stats": ts.stats,
        }, indent=2))
        return 0

    from reporter_tpu.tiles.compiler import compile_network

    if args.cmd == "build":
        name = args.name or args.osm.rsplit("/", 1)[-1].split(".")[0]
        if args.osm.endswith(".pbf"):
            from reporter_tpu.netgen.pbf import parse_osm_pbf

            net = parse_osm_pbf(args.osm, name=name)
        else:
            from reporter_tpu.netgen.osm_xml import parse_osm_xml

            net = parse_osm_xml(args.osm, name=name)
    else:
        from reporter_tpu.netgen.synthetic import generate_city

        net = generate_city(args.city, seed=args.seed)

    ts = compile_network(net, _params(args),
                         mode=getattr(args, "mode", None))
    ts.save(args.output)
    print(json.dumps({"written": args.output, "name": ts.name,
                      "stats": ts.stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
