"""Per-chip HBM capacity planning — the SURVEY §7 "HBM budget" hard part.

Decides, for a compiled TileSet and a device memory budget, whether the
matcher stages the whole map replicated (the fast path: zero collectives
in candidate search) or must shard the segment table over a mesh axis
(parallel/sharded_candidates — per-shard sweeps + one ICI all-gather
K-merge per batch).

Plans over the bytes the dense (TPU) path actually stages
(TileSet.device_tables(candidate_backend="dense")): the Morton-blocked
seg_pack + bboxes, the per-edge arrays, and the node-keyed reach tables.
The grid backend's cell_pack fusion — the largest table at metro scale
(~1.06 GB for bayarea-xl) — is a CPU-backend layout and is no longer
staged on accelerators.

The measured envelope (bayarea-xl, 484,713 directed edges / 606,010 line
segments): a few hundred bytes per directed edge, dominated by the reach
rows — so one 16 GB v5e chip holds tens of millions of directed edges
replicated, an order of magnitude past any US metro. Segment sharding is
the continental-scale rung; past ITS crossover the reach share itself
outgrows the budget, and the answer is metro sharding
(parallel/multimetro) or a narrower reach_max, which the error message
says. bench.py's `xl` block records the live numbers each round.
"""

from __future__ import annotations

from typing import NamedTuple

from reporter_tpu.tiles.tileset import TileSet

# Conservative default budget for one v5e chip: 16 GB HBM minus compiler
# workspace, activation buffers, and the wire/infeed arrays.
DEFAULT_HBM_BUDGET = 12 * 1024**3

class StagingPlan(NamedTuple):
    strategy: str          # "replicated" | "segment-sharded"
    shards: int            # mesh extent needed on the sharding axis (1 ⇒
    #                        replicated)
    table_bytes: int       # dense-path staged bytes, unsharded
    shardable_bytes: int   # the segment-table share (what sharding divides)
    fixed_bytes: int       # replicated share (reach + per-edge arrays)
    budget_bytes: int
    bytes_per_edge: float  # table_bytes / directed edges
    edge_capacity: int     # directed edges that fit replicated in budget

    def to_json(self) -> dict:
        return {**self._asdict(),
                "bytes_per_edge": round(self.bytes_per_edge, 1)}


def dense_staged_bytes(ts: TileSet) -> tuple[int, int]:
    """(shardable, fixed) HBM bytes for the dense path's device tables.

    shardable — seg_pack + seg_feat [8, S] f32 each + per-block bboxes,
    what parallel/sharded_candidates.shard_tables splits over the mesh;
    fixed — per-edge arrays + node-keyed reach rows, replicated by design
    (every shard's Viterbi needs them).

    Byte-EXACTNESS of this formula against what ``host_tables`` actually
    builds is CI-pinned (analysis/compile_manifest.hbm_findings, the
    round-16 device-contract gate): a formula that drifts from the
    staged layout under-plans silently — the fleet ledger
    (fleet/residency.py) meters real nbytes, but planning decisions
    ride this math.
    """
    from reporter_tpu.ops.dense_candidates import (_SBLK, _SUB, SF_NCOMP,
                                                   SP_NCOMP, packed_columns)

    # exact shape math for build_seg_pack's layout ([SP_NCOMP, S_pad] f32
    # pack + the round-13 [SF_NCOMP, S_pad] f32 MXU feature rows +
    # [S_pad/_SBLK, 4] f32 block bboxes + the per-sub-block quads
    # [S_pad/_SBLK, (SBLK/SUB)*4]) — computing it beats REBUILDING the
    # Morton pack (~seconds at 0.6M segments on a one-core host).
    # packed_columns accounts for the long-segment pre-split at the
    # shared dense_candidates.SPLIT_LEN (the pack holds MORE columns than
    # ts.seg_edge on tiles with long segments).
    spad = packed_columns(ts.seg_len)
    nsub = _SBLK // _SUB if _SUB and _SBLK % _SUB == 0 else 1
    shardable = ((SP_NCOMP + SF_NCOMP) * spad
                 + (spad // _SBLK) * 4 * (1 + nsub)) * 4
    fixed = int(ts.edge_len.nbytes + ts.edge_reach_row.nbytes
                + ts.edge_osmlr.nbytes + ts.reach_to.nbytes
                + ts.reach_dist.nbytes)
    return shardable, fixed


def plan_staging(ts: TileSet, budget_bytes: int = DEFAULT_HBM_BUDGET,
                 ) -> StagingPlan:
    """Staging plan for one device (or one shard axis of a mesh).

    Raises when even a fully-sharded layout cannot fit (the replicated
    reach/edge share alone over budget) — at that scale shard by metro
    (parallel/multimetro) or narrow reach_max instead.
    """
    shardable, fixed = dense_staged_bytes(ts)
    total = shardable + fixed
    per_edge = total / max(ts.num_edges, 1)
    capacity = int(budget_bytes / per_edge) if per_edge else 0
    if total <= budget_bytes:
        return StagingPlan("replicated", 1, total, shardable, fixed,
                           int(budget_bytes), per_edge, capacity)
    headroom = budget_bytes - fixed
    if headroom <= 0:
        raise ValueError(
            f"tileset {ts.name!r}: replicated share {fixed} B alone "
            f"exceeds the {budget_bytes} B budget — segment sharding "
            "cannot help; shard by metro (parallel/multimetro) or shrink "
            "reach_max/grid capacity")
    shards = -(-shardable // headroom)          # ceil division
    return StagingPlan("segment-sharded", int(shards), total, shardable,
                       fixed, int(budget_bytes), per_edge, capacity)
