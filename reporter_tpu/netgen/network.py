"""RoadNetwork — the intermediate representation between data sources and the
tile compiler.

Plays the role of the parsed-OSM stage inside the reference's offline pipeline
(SURVEY.md §3.4: OSM extract → valhalla_build_tiles → graph tiles): sources
(synthetic generator, OSM XML parser) produce a RoadNetwork; the compiler
(reporter_tpu.tiles.compiler) lowers it to flat device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


# Per-mode access bits (Valhalla's kAutoAccess/kBicycleAccess/kPedestrian
# analog, SURVEY.md §2.1 "mode costing"): a Way carries the set of modes
# allowed on it; compile_network(..., mode=...) builds a tileset over one
# mode's subgraph.
ACCESS_AUTO = 1
ACCESS_BICYCLE = 2
ACCESS_FOOT = 4
ACCESS_ALL = ACCESS_AUTO | ACCESS_BICYCLE | ACCESS_FOOT
MODE_BITS = {"auto": ACCESS_AUTO, "bicycle": ACCESS_BICYCLE,
             "foot": ACCESS_FOOT}


@dataclass
class Way:
    """A travelable way: an ordered chain of node indices, optionally with
    intermediate shape geometry per leg (lonlat points strictly between the
    leg's endpoint nodes)."""

    way_id: int
    nodes: list[int]                     # indices into RoadNetwork.node_lonlat
    oneway: bool = False
    name: str = ""
    speed_mps: float = 13.4              # free-flow speed, ~30 mph default
    # leg index i (between nodes[i] and nodes[i+1]) → [k, 2] lonlat shape points
    geometry: dict[int, np.ndarray] = field(default_factory=dict)
    access_mask: int = ACCESS_ALL        # OR of ACCESS_* bits


@dataclass
class TurnRestriction:
    """An OSM turn restriction with a via NODE (the overwhelmingly common
    form; via-way restrictions are out of scope and dropped by parsers).

    ``kind`` keeps the OSM vocabulary: prohibitory ``no_*`` (that one turn
    is banned) or mandatory ``only_*`` (every OTHER turn from from_way at
    the via node is banned). The compiler resolves ways to directed edges,
    so a PBF reader producing these same records slots straight in.
    """

    from_way: int                        # OSM way id the vehicle arrives on
    via_node: int                        # node index into node_lonlat
    to_way: int                          # OSM way id of the (dis)allowed exit
    kind: str = "no_turn"                # "no_*" or "only_*"

    @property
    def mandatory(self) -> bool:
        return self.kind.startswith("only_")


@dataclass
class RoadNetwork:
    """Graph-agnostic road network: nodes in lon/lat + ways."""

    node_lonlat: np.ndarray              # [N, 2] float64 (lon, lat) degrees
    ways: list[Way]
    name: str = "net"
    restrictions: list[TurnRestriction] = field(default_factory=list)
    # set by for_mode: marks this network as one mode's subgraph, so
    # compile_network knows an unqualified compile of it is deliberate
    mode: "str | None" = None

    @property
    def num_nodes(self) -> int:
        return int(len(self.node_lonlat))

    def bbox(self) -> tuple[np.ndarray, np.ndarray]:
        lo = self.node_lonlat.min(axis=0)
        hi = self.node_lonlat.max(axis=0)
        return lo, hi

    def origin(self) -> np.ndarray:
        lo, hi = self.bbox()
        return (lo + hi) / 2.0

    def fingerprint(self) -> int:
        """Content crc of the graph (node positions, way topology and
        attributes, per-leg geometry, restrictions) — the shared key for
        content-addressed caches (the compiler's full-graph OSMLR memo,
        bench tile/fleet caches). A generator or mutation that changes
        anything the compiler reads must change this value."""
        import zlib

        crc = zlib.crc32(np.ascontiguousarray(self.node_lonlat).tobytes())
        words: list[int] = []
        for w in self.ways:
            words.extend((w.way_id, len(w.nodes), int(w.oneway),
                          w.access_mask, int(w.speed_mps * 100)))
            words.extend(w.nodes)
            for leg in sorted(w.geometry):
                words.append(leg)
                crc = zlib.crc32(np.ascontiguousarray(
                    w.geometry[leg], np.float64).tobytes(), crc)
        for r in self.restrictions:
            words.extend((r.from_way, r.via_node, r.to_way,
                          zlib.crc32(r.kind.encode())))
        return zlib.crc32(np.asarray(words, np.int64).tobytes(), crc)

    def for_mode(self, mode: str) -> "RoadNetwork":
        """The mode's legal subgraph: ways whose access_mask includes
        ``mode``, restrictions filtered to surviving ways. Pedestrians
        ignore oneway (Valhalla pedestrian costing parity): the foot view
        clears it, so both directed edges exist. Node array is shared
        (ids stay stable); ways are shallow-rebuilt only where changed.

        This is the per-mode costing boundary (SURVEY.md §2.1): one
        compile per served mode, so the matcher's tables — candidates,
        reach routing, OSMLR chains — are all consistent with what that
        mode may drive. Fixed tables per mode beat per-query masking on
        TPU: the sweep scans fewer segments instead of filtering more.
        """
        import dataclasses

        bit = MODE_BITS.get(mode)
        if bit is None:
            raise ValueError(f"unknown mode {mode!r}; "
                             f"one of {sorted(MODE_BITS)}")
        ways = [w for w in self.ways if w.access_mask & bit]
        if mode == "foot":
            # pedestrians walk one-way streets both directions, and turn
            # restrictions do not bind them
            ways = [w if not w.oneway
                    else dataclasses.replace(w, oneway=False) for w in ways]
            restrictions = []
        else:
            keep = {w.way_id for w in ways}
            restrictions = [r for r in self.restrictions
                            if r.from_way in keep and r.to_way in keep]
        # Compact nodes to those the kept ways reference: reach tables are
        # one row PER NODE, so orphans from other modes' ways would cost
        # real table memory downstream.
        used: dict[int, int] = {}
        for w in ways:
            for nd in w.nodes:
                if nd not in used:
                    used[nd] = len(used)
        if len(used) != self.num_nodes:
            node_lonlat = self.node_lonlat[list(used)]   # insertion order
            ways = [dataclasses.replace(
                w, nodes=[used[nd] for nd in w.nodes]) for w in ways]
            restrictions = [dataclasses.replace(r, via_node=used[r.via_node])
                            for r in restrictions if r.via_node in used]
        else:
            node_lonlat = self.node_lonlat
        suffix = "" if mode == "auto" else f"-{mode}"
        return RoadNetwork(node_lonlat=node_lonlat, ways=ways,
                           name=f"{self.name}{suffix}",
                           restrictions=restrictions, mode=mode)
