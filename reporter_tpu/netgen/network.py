"""RoadNetwork — the intermediate representation between data sources and the
tile compiler.

Plays the role of the parsed-OSM stage inside the reference's offline pipeline
(SURVEY.md §3.4: OSM extract → valhalla_build_tiles → graph tiles): sources
(synthetic generator, OSM XML parser) produce a RoadNetwork; the compiler
(reporter_tpu.tiles.compiler) lowers it to flat device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class Way:
    """A drivable way: an ordered chain of node indices, optionally with
    intermediate shape geometry per leg (lonlat points strictly between the
    leg's endpoint nodes)."""

    way_id: int
    nodes: list[int]                     # indices into RoadNetwork.node_lonlat
    oneway: bool = False
    name: str = ""
    speed_mps: float = 13.4              # free-flow speed, ~30 mph default
    # leg index i (between nodes[i] and nodes[i+1]) → [k, 2] lonlat shape points
    geometry: dict[int, np.ndarray] = field(default_factory=dict)


@dataclass
class TurnRestriction:
    """An OSM turn restriction with a via NODE (the overwhelmingly common
    form; via-way restrictions are out of scope and dropped by parsers).

    ``kind`` keeps the OSM vocabulary: prohibitory ``no_*`` (that one turn
    is banned) or mandatory ``only_*`` (every OTHER turn from from_way at
    the via node is banned). The compiler resolves ways to directed edges,
    so a PBF reader producing these same records slots straight in.
    """

    from_way: int                        # OSM way id the vehicle arrives on
    via_node: int                        # node index into node_lonlat
    to_way: int                          # OSM way id of the (dis)allowed exit
    kind: str = "no_turn"                # "no_*" or "only_*"

    @property
    def mandatory(self) -> bool:
        return self.kind.startswith("only_")


@dataclass
class RoadNetwork:
    """Graph-agnostic road network: nodes in lon/lat + ways."""

    node_lonlat: np.ndarray              # [N, 2] float64 (lon, lat) degrees
    ways: list[Way]
    name: str = "net"
    restrictions: list[TurnRestriction] = field(default_factory=list)

    @property
    def num_nodes(self) -> int:
        return int(len(self.node_lonlat))

    def bbox(self) -> tuple[np.ndarray, np.ndarray]:
        lo = self.node_lonlat.min(axis=0)
        hi = self.node_lonlat.max(axis=0)
        return lo, hi

    def origin(self) -> np.ndarray:
        lo, hi = self.bbox()
        return (lo + hi) / 2.0
