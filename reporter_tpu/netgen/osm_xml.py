"""OSM XML parser → RoadNetwork (+ the element→graph builder PBF shares).

Capability-parity stand-in for the front of the reference's offline pipeline
(SURVEY.md §3.4: OSM extract → valhalla_build_tiles). Supports the subset
needed to build a drivable graph: <node> elements and <way> elements tagged
``highway=*`` from a drivable whitelist, with ``oneway`` and ``maxspeed``
handling, plus ``type=restriction`` relations. ``build_network`` is the
format-independent half: netgen/pbf.py decodes .osm.pbf into the same raw
elements and builds through it, so both formats produce identical graphs.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from reporter_tpu.geometry import lonlat_to_xy
from reporter_tpu.netgen.network import (ACCESS_ALL, ACCESS_AUTO,
                                         ACCESS_BICYCLE, ACCESS_FOOT,
                                         RoadNetwork, TurnRestriction, Way)

DRIVABLE_HIGHWAY = {
    "motorway", "trunk", "primary", "secondary", "tertiary", "unclassified",
    "residential", "service", "motorway_link", "trunk_link", "primary_link",
    "secondary_link", "tertiary_link", "living_street",
}

# highway classes that only exist for non-auto modes (kept in the
# RoadNetwork with the matching access bits; the auto compile filters
# them out via RoadNetwork.for_mode)
_MODE_ONLY_HIGHWAY = {
    "cycleway": ACCESS_BICYCLE | ACCESS_FOOT,
    "footway": ACCESS_FOOT,
    "pedestrian": ACCESS_FOOT,
    "steps": ACCESS_FOOT,
    "path": ACCESS_FOOT | ACCESS_BICYCLE,
    # track: agricultural lanes — bike/foot by default here (the pre-mode
    # parser never compiled them for autos; motor_vehicle=yes opts in)
    "track": ACCESS_FOOT | ACCESS_BICYCLE,
}

# classes where non-motor modes are off by DEFAULT (tag overrides apply)
_AUTO_ONLY_HIGHWAY = {"motorway", "motorway_link", "trunk", "trunk_link"}

# Access values that exclude a mode (Valhalla costing analog, SURVEY.md
# §3.4). Checked most-specific-first per the OSM access hierarchy — each
# mode has its own override chain.
_NO_ACCESS = {"no", "private", "agricultural", "forestry", "delivery",
              "emergency", "military"}

_MODE_TAG_CHAIN = {
    ACCESS_AUTO: ("motor_vehicle", "vehicle", "access"),
    ACCESS_BICYCLE: ("bicycle", "vehicle", "access"),
    ACCESS_FOOT: ("foot", "access"),
}


def _access_mask(tags: "dict[str, str]") -> int:
    """Per-mode access bits for a way, from its highway class default +
    the OSM access-tag hierarchy (most specific key wins per mode)."""
    hw = tags.get("highway", "")
    if hw in _MODE_ONLY_HIGHWAY:
        default = _MODE_ONLY_HIGHWAY[hw]
    elif hw in _AUTO_ONLY_HIGHWAY:
        default = ACCESS_AUTO
    elif hw in DRIVABLE_HIGHWAY:
        default = ACCESS_ALL
    else:
        return 0
    mask = 0
    for bit, chain in _MODE_TAG_CHAIN.items():
        allowed = bool(default & bit)
        for key in chain:
            v = tags.get(key)
            if v is not None:
                allowed = v not in _NO_ACCESS
                break                 # most specific key decides
        if allowed:
            mask |= bit
    return mask

_DEFAULT_SPEED = {  # m/s by highway class
    "motorway": 29.0, "trunk": 24.5, "primary": 17.9, "secondary": 15.6,
    "tertiary": 13.4, "residential": 11.2, "service": 6.7, "living_street": 4.5,
    # non-auto classes: free-flow for their primary mode
    "cycleway": 5.6, "footway": 1.4, "pedestrian": 1.4, "steps": 0.7,
    "path": 2.8, "track": 8.3,
}

# Interior shape runs longer than this split into separate legs/edges:
# keeps edge offsets far inside the u16 wire range (16.4 km) and candidate
# search output well-conditioned on rural roads with distant junctions.
_MAX_LEG_LENGTH = 5000.0  # meters


def _speed_mps(tags: dict[str, str]) -> float:
    ms = tags.get("maxspeed", "")
    try:
        if ms.endswith("mph"):
            return float(ms[:-3].strip()) * 0.44704
        if ms:
            return float(ms) / 3.6
    except ValueError:
        pass
    hw = tags.get("highway", "")
    return _DEFAULT_SPEED.get(hw.removesuffix("_link"), 13.4)


def xml_elements(source: str):
    """Raw OSM elements off an XML document (path or XML string):
    (node_pos {id: (lon, lat)}, ways [(id, refs, tags)...], relations
    [(tags, [(role, member type, ref)...])...]) — build_network's input
    shape, also what netgen/pbf.write_osm_pbf serializes."""
    if source.lstrip().startswith("<"):
        root = ET.fromstring(source)
    else:
        root = ET.parse(source).getroot()

    node_pos: dict[int, tuple[float, float]] = {}
    for nd in root.iter("node"):
        node_pos[int(nd.get("id"))] = (float(nd.get("lon")), float(nd.get("lat")))

    raw_ways = [(int(w.get("id")),
                 [int(nd.get("ref")) for nd in w.findall("nd")],
                 {t.get("k"): t.get("v") for t in w.findall("tag")})
                for w in root.iter("way")]

    raw_relations = []
    for rel in root.iter("relation"):
        tags = {t.get("k"): t.get("v") for t in rel.findall("tag")}
        members = [(m.get("role"), m.get("type"), int(m.get("ref")))
                   for m in rel.findall("member")]
        raw_relations.append((tags, members))
    return node_pos, raw_ways, raw_relations


def parse_osm_xml(source: str, name: str = "osm") -> RoadNetwork:
    """Parse an .osm XML document (path or XML string) into a RoadNetwork."""
    return build_network(*xml_elements(source), name)


def build_network(
    node_pos: "dict[int, tuple[float, float]]",
    raw_ways: "list[tuple[int, list[int], dict[str, str]]]",
    raw_relations: "list[tuple[dict[str, str], list[tuple[str, str, int]]]]",
    name: str = "osm",
) -> RoadNetwork:
    """Raw OSM elements → RoadNetwork (shared by the XML and PBF parsers).

    node_pos: osm node id → (lon, lat); raw_ways: (way id, node refs,
    tags); raw_relations: (tags, [(role, member type, ref)...]).
    """
    # Corrupt extracts can carry coordinates outside the WGS84 domain;
    # projecting them would silently warp the local metric (cos-lat goes
    # negative past the pole). Treat such nodes as absent — ways route
    # around them exactly like dangling refs — and say so.
    bad = [nid for nid, (lon, lat) in node_pos.items()
           if not (-180.0 <= lon <= 180.0 and -90.0 <= lat <= 90.0)]
    if bad:
        import warnings

        warnings.warn(
            f"extract {name!r}: dropped {len(bad)} node(s) with "
            f"out-of-range coordinates (e.g. id {bad[0]})", stacklevel=3)
        # drop into a local copy — the caller's dict must survive intact
        # (callers reuse parsed elements across build_network calls)
        node_pos = dict(node_pos)
        for nid in bad:
            del node_pos[nid]

    drivable: list[tuple[int, list[int], dict[str, str], int]] = []
    for way_id, refs, tags in raw_ways:
        mask = _access_mask(tags)
        if not mask:
            continue
        refs = [r for r in refs if r in node_pos]
        # Real extracts contain duplicate consecutive refs — and distinct
        # ids digitized at the SAME position; either way the hop would
        # become a zero-length edge, which the compiler forbids
        # (edge_len > 0), so drop the repeated ref.
        refs = [r for i, r in enumerate(refs)
                if i == 0 or (r != refs[i - 1]
                              and node_pos[r] != node_pos[refs[i - 1]])]
        if len(refs) >= 2:
            drivable.append((way_id, refs, tags, mask))
    raw_ways = drivable

    # Graph simplification (what valhalla_build_tiles does with OSM shape
    # nodes): only JUNCTION nodes become graph nodes — way endpoints,
    # nodes shared between drivable ways (or revisited within one), and
    # restriction via nodes. Interior degree-2 refs are curve shape, not
    # topology; they collapse into per-leg edge geometry (Way.geometry →
    # the compiler's per-edge polylines), which keeps node/edge counts —
    # and with them reach tables and HMM transition work — proportional
    # to the road TOPOLOGY instead of to how smoothly the mapper drew the
    # curves. Collapsed runs split at _MAX_LEG_LENGTH so edge offsets
    # stay far inside the u16 wire range.
    ref_count: dict[int, int] = {}
    junction: set[int] = set()
    for _, refs, _, _ in raw_ways:
        junction.add(refs[0])
        junction.add(refs[-1])
        for r in refs:
            n = ref_count.get(r, 0) + 1
            ref_count[r] = n
            if n >= 2:
                junction.add(r)
    for tags, members in raw_relations:
        if tags.get("type") == "restriction":
            for role, mtype, ref in members:
                if role == "via" and mtype == "node":
                    junction.add(ref)

    def leg_split(refs: list[int]):
        """Split one way's refs at junctions (and length caps) into legs:
        (junction refs, {leg index: interior lonlat array}). Lengths come
        from geometry.lonlat_to_xy — the same local metric the compiler
        measures edges in."""
        ll = np.asarray([node_pos[r] for r in refs], np.float64)
        step = np.hypot(*np.diff(lonlat_to_xy(ll, ll[0]), axis=0).T)
        nodes = [refs[0]]
        geometry: dict[int, np.ndarray] = {}
        interior: list[tuple[float, float]] = []
        acc = 0.0
        for j, r in enumerate(refs[1:]):
            acc += float(step[j])
            if r in junction or acc >= _MAX_LEG_LENGTH or r == refs[-1]:
                if interior:
                    geometry[len(nodes) - 1] = np.asarray(interior,
                                                          np.float64)
                nodes.append(r)
                interior = []
                acc = 0.0
            else:
                interior.append(node_pos[r])
        return nodes, geometry

    # Keep only junction nodes; remap to dense indices.
    used: dict[int, int] = {}
    split_ways: list[tuple[int, list[int], dict, dict[str, str], int]] = []
    for way_id, refs, tags, mask in raw_ways:
        nodes, geometry = leg_split(refs)
        split_ways.append((way_id, nodes, geometry, tags, mask))
        for r in nodes:
            if r not in used:
                used[r] = len(used)
    lonlat = np.zeros((len(used), 2), dtype=np.float64)
    for osm_id, idx in used.items():
        lonlat[idx] = node_pos[osm_id]

    ways: list[Way] = []
    drivable_way_ids = set()
    for way_id, refs, geometry, tags, mask in split_ways:
        ow = tags.get("oneway", "no") in ("yes", "true", "1")
        nodes = [used[r] for r in refs]
        if tags.get("oneway") == "-1":
            nodes = nodes[::-1]
            ow = True
            # leg i of the reversed way is original leg L-1-i, driven
            # backwards — reverse its interior points too
            L = len(refs) - 1
            geometry = {L - 1 - i: g[::-1] for i, g in geometry.items()}
        ways.append(
            Way(way_id=way_id, nodes=nodes, oneway=ow, geometry=geometry,
                name=tags.get("name", ""), speed_mps=_speed_mps(tags),
                access_mask=mask)
        )
        drivable_way_ids.add(way_id)

    # Turn restrictions: relations tagged type=restriction with way/from,
    # node/via, way/to members (SURVEY.md §3.4 — Valhalla's complex
    # restrictions; via-WAY relations are rare and dropped here).
    restrictions: list[TurnRestriction] = []
    for tags, members in raw_relations:
        if tags.get("type") != "restriction":
            continue
        kind = tags.get("restriction", "")
        if not (kind.startswith("no_") or kind.startswith("only_")):
            continue
        frm = via = to = None
        for role, mtype, ref in members:
            if role == "from" and mtype == "way":
                frm = ref
            elif role == "via" and mtype == "node":
                via = ref
            elif role == "to" and mtype == "way":
                to = ref
        if (frm in drivable_way_ids and to in drivable_way_ids
                and via in used):
            restrictions.append(TurnRestriction(
                from_way=frm, via_node=used[via], to_way=to, kind=kind))

    return RoadNetwork(node_lonlat=lonlat, ways=ways, name=name,
                       restrictions=restrictions)
