"""Minimal OSM XML parser → RoadNetwork.

Capability-parity stand-in for the front of the reference's offline pipeline
(SURVEY.md §3.4: OSM extract → valhalla_build_tiles). Supports the subset
needed to build a drivable graph: <node> elements and <way> elements tagged
``highway=*`` from a drivable whitelist, with ``oneway`` and ``maxspeed``
handling. PBF input is out of scope (no protobuf OSM fixtures available here);
the XML path exercises the same compiler.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from reporter_tpu.netgen.network import RoadNetwork, TurnRestriction, Way

DRIVABLE_HIGHWAY = {
    "motorway", "trunk", "primary", "secondary", "tertiary", "unclassified",
    "residential", "service", "motorway_link", "trunk_link", "primary_link",
    "secondary_link", "tertiary_link", "living_street",
}

_DEFAULT_SPEED = {  # m/s by highway class
    "motorway": 29.0, "trunk": 24.5, "primary": 17.9, "secondary": 15.6,
    "tertiary": 13.4, "residential": 11.2, "service": 6.7, "living_street": 4.5,
}


def _speed_mps(tags: dict[str, str]) -> float:
    ms = tags.get("maxspeed", "")
    try:
        if ms.endswith("mph"):
            return float(ms[:-3].strip()) * 0.44704
        if ms:
            return float(ms) / 3.6
    except ValueError:
        pass
    hw = tags.get("highway", "")
    return _DEFAULT_SPEED.get(hw.removesuffix("_link"), 13.4)


def parse_osm_xml(source: str, name: str = "osm") -> RoadNetwork:
    """Parse an .osm XML document (path or XML string) into a RoadNetwork."""
    if source.lstrip().startswith("<"):
        root = ET.fromstring(source)
    else:
        root = ET.parse(source).getroot()

    node_pos: dict[int, tuple[float, float]] = {}
    for nd in root.iter("node"):
        node_pos[int(nd.get("id"))] = (float(nd.get("lon")), float(nd.get("lat")))

    raw_ways: list[tuple[int, list[int], dict[str, str]]] = []
    for w in root.iter("way"):
        tags = {t.get("k"): t.get("v") for t in w.findall("tag")}
        if tags.get("highway") not in DRIVABLE_HIGHWAY:
            continue
        refs = [int(nd.get("ref")) for nd in w.findall("nd")]
        refs = [r for r in refs if r in node_pos]
        # Real extracts contain duplicate consecutive refs; they would become
        # zero-length edges, which the compiler forbids (edge_len > 0).
        refs = [r for i, r in enumerate(refs) if i == 0 or r != refs[i - 1]]
        if len(refs) >= 2:
            raw_ways.append((int(w.get("id")), refs, tags))

    # Keep only nodes referenced by drivable ways; remap to dense indices.
    used: dict[int, int] = {}
    for _, refs, _ in raw_ways:
        for r in refs:
            if r not in used:
                used[r] = len(used)
    lonlat = np.zeros((len(used), 2), dtype=np.float64)
    for osm_id, idx in used.items():
        lonlat[idx] = node_pos[osm_id]

    ways: list[Way] = []
    drivable_way_ids = set()
    for way_id, refs, tags in raw_ways:
        ow = tags.get("oneway", "no") in ("yes", "true", "1")
        nodes = [used[r] for r in refs]
        if tags.get("oneway") == "-1":
            nodes = nodes[::-1]
            ow = True
        ways.append(
            Way(way_id=way_id, nodes=nodes, oneway=ow,
                name=tags.get("name", ""), speed_mps=_speed_mps(tags))
        )
        drivable_way_ids.add(way_id)

    # Turn restrictions: <relation> tagged type=restriction with way/from,
    # node/via, way/to members (SURVEY.md §3.4 — Valhalla's complex
    # restrictions; via-WAY relations are rare and dropped here).
    restrictions: list[TurnRestriction] = []
    for rel in root.iter("relation"):
        tags = {t.get("k"): t.get("v") for t in rel.findall("tag")}
        if tags.get("type") != "restriction":
            continue
        kind = tags.get("restriction", "")
        if not (kind.startswith("no_") or kind.startswith("only_")):
            continue
        frm = via = to = None
        for m in rel.findall("member"):
            role, mtype = m.get("role"), m.get("type")
            ref = int(m.get("ref"))
            if role == "from" and mtype == "way":
                frm = ref
            elif role == "via" and mtype == "node":
                via = ref
            elif role == "to" and mtype == "way":
                to = ref
        if (frm in drivable_way_ids and to in drivable_way_ids
                and via in used):
            restrictions.append(TurnRestriction(
                from_way=frm, via_node=used[via], to_way=to, kind=kind))

    return RoadNetwork(node_lonlat=lonlat, ways=ways, name=name,
                       restrictions=restrictions)
