"""OSM PBF reader/writer → RoadNetwork (no protobuf library needed).

Closes the reference pipeline's real input format (SURVEY.md §3.4: planet
extracts are .osm.pbf; the reference feeds them to valhalla_build_tiles).
The PBF container is small enough to decode by hand — protobuf wire format
(varints, zigzag, length-delimited fields) over a blob framing:

  file   := ( u32be len | BlobHeader | Blob )*
  BlobHeader := { 1: type "OSMHeader"|"OSMData", 3: datasize }
  Blob       := { 1: raw bytes | 3: zlib_data bytes, 2: raw_size }
  OSMHeader  → HeaderBlock { 4: required_features*, 5: optional_features* }
  OSMData    → PrimitiveBlock {
      1: stringtable { 1: bytes* },  2: PrimitiveGroup*,
      17: granularity (=100), 19: lat_offset (=0), 20: lon_offset (=0) }
  PrimitiveGroup := { 1: Node*, 2: DenseNodes, 3: Way*, 4: Relation* }
  DenseNodes := { 1: ids sint64 packed Δ, 8/9: lat/lon sint64 packed Δ,
                  10: keys_vals int32 packed (0-terminated per node) }
  Way        := { 1: id, 2/3: keys/vals uint32 packed, 8: refs sint64 packed Δ }
  Relation   := { 1: id, 2/3: keys/vals, 8: roles_sid packed,
                  9: memids sint64 packed Δ, 10: types packed (0/1/2) }

Coordinates decode as 1e-9 * (offset + granularity * raw) degrees.

The writer exists for fixtures AND as a real tool: it turns any element set
(e.g. a synthetic city) into a spec-conformant .osm.pbf, which is how the
round-trip tests prove the reader against the XML parser byte-for-byte
(tests/test_pbf.py). Both parsers feed osm_xml.build_network, so a .pbf and
an equivalent .osm compile to identical tilesets.
"""

from __future__ import annotations

import struct
import zlib

from reporter_tpu.netgen.network import RoadNetwork
from reporter_tpu.netgen.osm_xml import build_network

_MEMBER_TYPES = ("node", "way", "relation")   # Relation.MemberType enum


# ---- protobuf wire primitives ------------------------------------------


def _read_varint(buf: bytes, i: int) -> "tuple[int, int]":
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Yield (field_no, wire_type, value): ints for wiretype 0, bytes for 2,
    raw u64/u32 for 1/5 (unused by OSM but skipped correctly)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack_from("<I", buf, i)[0]
            i += 4
        elif wt == 1:
            v = struct.unpack_from("<Q", buf, i)[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _packed_varints(data: bytes, signed: bool = False) -> "list[int]":
    out, i = [], 0
    while i < len(data):
        v, i = _read_varint(data, i)
        out.append(_zigzag(v) if signed else v)
    return out


def _delta_decode(vals: "list[int]") -> "list[int]":
    acc, out = 0, []
    for v in vals:
        acc += v
        out.append(acc)
    return out


# ---- reader -------------------------------------------------------------


def _blob_payload(blob: bytes) -> bytes:
    raw = zdata = None
    for field, _, v in _fields(blob):
        if field == 1:
            raw = v
        elif field == 3:
            zdata = v
    if raw is not None:
        return raw
    if zdata is not None:
        return zlib.decompress(zdata)
    raise ValueError("Blob carries neither raw nor zlib_data "
                     "(lzma/zstd blobs unsupported)")


def _iter_blobs(path: str):
    with open(path, "rb") as f:
        while True:
            hdr_len = f.read(4)
            if len(hdr_len) < 4:
                return
            header = f.read(struct.unpack(">I", hdr_len)[0])
            btype, datasize = "", 0
            for field, _, v in _fields(header):
                if field == 1:
                    btype = v.decode()
                elif field == 3:
                    datasize = v
            yield btype, _blob_payload(f.read(datasize))


def _parse_dense(data: bytes, node_pos, gran, lat_off, lon_off):
    ids = lats = lons = ()
    for field, _, v in _fields(data):
        if field == 1:
            ids = _delta_decode(_packed_varints(v, signed=True))
        elif field == 8:
            lats = _delta_decode(_packed_varints(v, signed=True))
        elif field == 9:
            lons = _delta_decode(_packed_varints(v, signed=True))
    for nid, la, lo in zip(ids, lats, lons):
        node_pos[nid] = (1e-9 * (lon_off + gran * lo),
                         1e-9 * (lat_off + gran * la))


def _tags(keys, vals, strings) -> "dict[str, str]":
    return {strings[k]: strings[v] for k, v in zip(keys, vals)}


def parse_osm_pbf(path: str, name: str = "osm") -> RoadNetwork:
    """Parse an .osm.pbf file into a RoadNetwork (same graph as the XML
    parser produces for an equivalent extract)."""
    node_pos: dict[int, tuple[float, float]] = {}
    raw_ways: list = []
    raw_relations: list = []

    for btype, payload in _iter_blobs(path):
        if btype == "OSMHeader":
            for field, _, v in _fields(payload):
                if field == 4:            # required_features
                    feat = v.decode()
                    if feat not in ("OsmSchema-V0.6", "DenseNodes"):
                        raise ValueError(
                            f"unsupported required feature: {feat!r}")
            continue
        if btype != "OSMData":
            continue                      # per spec: skip unknown blob types

        strings: list[str] = []
        groups: list[bytes] = []
        gran, lat_off, lon_off = 100, 0, 0
        for field, _, v in _fields(payload):
            if field == 1:
                strings = [s.decode("utf-8")
                           for _, _, s in _fields(v)]
            elif field == 2:
                groups.append(v)
            elif field == 17:
                gran = v
            elif field == 19:
                lat_off = v
            elif field == 20:
                lon_off = v

        for group in groups:
            for field, _, v in _fields(group):
                if field == 2:            # DenseNodes
                    _parse_dense(v, node_pos, gran, lat_off, lon_off)
                elif field == 1:          # plain Node
                    nid = la = lo = 0
                    for f2, _, v2 in _fields(v):
                        if f2 == 1:
                            nid = _zigzag(v2)
                        elif f2 == 8:
                            la = _zigzag(v2)
                        elif f2 == 9:
                            lo = _zigzag(v2)
                    node_pos[nid] = (1e-9 * (lon_off + gran * lo),
                                     1e-9 * (lat_off + gran * la))
                elif field == 3:          # Way
                    wid, keys, vals, refs = 0, (), (), ()
                    for f2, _, v2 in _fields(v):
                        if f2 == 1:
                            wid = v2
                        elif f2 == 2:
                            keys = _packed_varints(v2)
                        elif f2 == 3:
                            vals = _packed_varints(v2)
                        elif f2 == 8:
                            refs = _delta_decode(
                                _packed_varints(v2, signed=True))
                    raw_ways.append((wid, list(refs),
                                     _tags(keys, vals, strings)))
                elif field == 4:          # Relation
                    keys, vals, roles, memids, types = (), (), (), (), ()
                    for f2, _, v2 in _fields(v):
                        if f2 == 2:
                            keys = _packed_varints(v2)
                        elif f2 == 3:
                            vals = _packed_varints(v2)
                        elif f2 == 8:
                            roles = _packed_varints(v2)
                        elif f2 == 9:
                            memids = _delta_decode(
                                _packed_varints(v2, signed=True))
                        elif f2 == 10:
                            types = _packed_varints(v2)
                    members = [(strings[r], _MEMBER_TYPES[t], m)
                               for r, m, t in zip(roles, memids, types)]
                    raw_relations.append((_tags(keys, vals, strings),
                                          members))

    return build_network(node_pos, raw_ways, raw_relations, name)


# ---- writer -------------------------------------------------------------


def _varint(v: int) -> bytes:
    if v < 0:
        # Python's arbitrary-precision ints would loop forever below;
        # negative values must be zigzag-encoded by the caller.
        raise ValueError(f"negative varint {v}: field needs zigzag encoding")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_enc(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def _field(no: int, wt: int, payload: bytes) -> bytes:
    return _varint(no << 3 | wt) + payload


def _ld(no: int, payload: bytes) -> bytes:          # length-delimited
    return _field(no, 2, _varint(len(payload)) + payload)


def _packed(no: int, vals, signed=False, delta=False) -> bytes:
    if not vals:
        return b""
    if delta:
        vals = [vals[0]] + [b - a for a, b in zip(vals, vals[1:])]
    body = b"".join(_varint(_zigzag_enc(v) if signed else v) for v in vals)
    return _ld(no, body)


class _StringTable:
    """Index 0 is reserved empty per spec; strings dedupe to one index."""

    def __init__(self):
        self._idx = {"": 0}
        self.strings = [""]

    def __call__(self, s: str) -> int:
        if s not in self._idx:
            self._idx[s] = len(self.strings)
            self.strings.append(s)
        return self._idx[s]

    def encode(self) -> bytes:
        return _ld(1, b"".join(_ld(1, s.encode("utf-8"))
                               for s in self.strings))


def _write_blob(out, btype: str, payload: bytes, compress: bool) -> None:
    if compress:
        blob = (_field(2, 0, _varint(len(payload)))
                + _ld(3, zlib.compress(payload)))
    else:
        blob = _ld(1, payload)
    header = _ld(1, btype.encode()) + _field(3, 0, _varint(len(blob)))
    out.write(struct.pack(">I", len(header)))
    out.write(header)
    out.write(blob)


def write_osm_pbf(
    path: str,
    node_pos: "dict[int, tuple[float, float]]",
    ways: "list[tuple[int, list[int], dict[str, str]]]",
    relations: "list[tuple[dict[str, str], list[tuple[str, str, int]]]]" = (),
    granularity: int = 100,
    compress: bool = True,
) -> None:
    """Write elements as a spec-conformant .osm.pbf (one PrimitiveBlock).

    Inputs mirror build_network's: node_pos {id: (lon, lat)}, ways
    (id, refs, tags), relations (tags, [(role, member type, ref)...]).
    """
    st = _StringTable()
    group = bytearray()

    ids = sorted(node_pos)
    # Round-to-nearest grid unit (not floor): halves the quantization
    # error and avoids a systematic south-west bias for negative coords.
    lat_raw = [round(node_pos[n][1] * 1e9 / granularity) for n in ids]
    lon_raw = [round(node_pos[n][0] * 1e9 / granularity) for n in ids]
    dense = (_packed(1, ids, signed=True, delta=True)
             + _packed(8, lat_raw, signed=True, delta=True)
             + _packed(9, lon_raw, signed=True, delta=True))
    group += _ld(2, dense)

    for wid, refs, tags in ways:
        body = (_field(1, 0, _varint(wid))
                + _packed(2, [st(k) for k in tags])
                + _packed(3, [st(v) for v in tags.values()])
                + _packed(8, list(refs), signed=True, delta=True))
        group += _ld(3, body)

    for i, (tags, members) in enumerate(relations):
        body = (_field(1, 0, _varint(i + 1))
                + _packed(2, [st(k) for k in tags])
                + _packed(3, [st(v) for v in tags.values()])
                + _packed(8, [st(role) for role, _, _ in members])
                + _packed(9, [m for _, _, m in members],
                          signed=True, delta=True)
                + _packed(10, [_MEMBER_TYPES.index(t)
                               for _, t, _ in members]))
        group += _ld(4, body)

    block = st.encode() + _ld(2, bytes(group))
    if granularity != 100:
        block += _field(17, 0, _varint(granularity))

    header_block = (_ld(4, b"OsmSchema-V0.6") + _ld(4, b"DenseNodes"))
    with open(path, "wb") as f:
        _write_blob(f, "OSMHeader", header_block, compress)
        _write_blob(f, "OSMData", block, compress)
