"""Organic (non-grid) synthetic metro generator.

The grid generator (netgen/synthetic.py) produces near-uniform degree-4
topology with ~120 m edges — which plausibly flatters Morton-block
culling, reach-table coverage, and HMM disambiguation (VERDICT r3
"irregular-geometry evidence"). This generator builds the opposite: a
radial city the shape real metros take,

  - node density falling off from a dense core to a sparse fringe, with
    angular "district" lobes (not rotationally uniform);
  - street topology from a Delaunay triangulation thinned by a
    radius-dependent length cap plus random pruning — mixed node degrees
    (3-way junctions dominate, like real cities), edge lengths from
    ~30 m downtown to ~2 km rural, nothing axis-aligned;
  - streets chained into multi-junction WAYS by straightest-continuation
    (the way named roads thread a city), so OSMLR segments span
    intersections like the reference's ~1 km references do;
  - ring + radial arterials SNAPPED onto existing streets (faster
    speeds, the way avenues emerge from a street fabric);
  - a limited-access highway spine crossing the metro: its own curved
    polyline, connected to the fabric only at ramp nodes, geometrically
    CROSSING many streets without sharing a node (overpasses);
  - cul-de-sac stubs (dead ends, the reach-table worst case);
  - one-ways and curved edge geometry like the grid generator.

Everything downstream (compiler, matcher, fleets) is source-agnostic, so
the organic tile drops into the bench/audit harness unchanged.
"""

from __future__ import annotations

import numpy as np

from reporter_tpu.netgen.network import RoadNetwork, Way

# speeds by road class (m/s)
_SPEED_LOCAL = 11.2
_SPEED_ARTERIAL = 17.9
_SPEED_SPINE = 29.0
_SPEED_RAMP = 13.4
_SPEED_STUB = 6.7


def _sample_nodes(rng: np.random.Generator, radius: float, core_scale: float,
                  n_candidates: int, dedupe_m: float) -> np.ndarray:
    """Poisson-like node cloud with 1/(1+(r/r0)^2) radial falloff and
    3-lobed angular districts; pairs closer than ``dedupe_m`` merged
    (keeps every edge length above the grid index's comfort floor and the
    core density inside cell_capacity)."""
    from scipy.spatial import cKDTree

    pts = rng.uniform(-radius, radius, size=(n_candidates, 2))
    r = np.linalg.norm(pts, axis=1)
    th = np.arctan2(pts[:, 1], pts[:, 0])
    density = 1.0 / (1.0 + (r / core_scale) ** 2)
    density *= np.clip(1.0 + 0.45 * np.cos(3.0 * th + 0.7), 0.1, None)
    keep = (r <= radius) & (rng.random(n_candidates) < density)
    pts = pts[keep]
    tree = cKDTree(pts)
    drop = np.zeros(len(pts), bool)
    for i, j in sorted(tree.query_pairs(dedupe_m)):
        if not drop[i] and not drop[j]:
            drop[max(i, j)] = True
    return pts[~drop]


def _street_edges(rng: np.random.Generator, pts: np.ndarray,
                  radius: float) -> np.ndarray:
    """Thinned Delaunay edges [K, 2]: a radius-dependent length cap (short
    blocks downtown, multi-km roads at the fringe), random pruning for
    mixed degrees, and the Delaunay MST kept unconditionally so the
    street fabric stays one connected component."""
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import minimum_spanning_tree
    from scipy.spatial import Delaunay

    tri = Delaunay(pts)
    e = np.vstack([tri.simplices[:, [0, 1]], tri.simplices[:, [1, 2]],
                   tri.simplices[:, [2, 0]]])
    e = np.unique(np.sort(e, axis=1), axis=0)
    length = np.linalg.norm(pts[e[:, 0]] - pts[e[:, 1]], axis=1)

    mst = minimum_spanning_tree(coo_matrix(
        (length, (e[:, 0], e[:, 1])), shape=(len(pts), len(pts)))).tocoo()
    mst_keys = set(zip(*np.sort(np.vstack([mst.row, mst.col]), axis=0)))

    mid_r = np.linalg.norm((pts[e[:, 0]] + pts[e[:, 1]]) / 2.0, axis=1)
    max_len = 90.0 + 0.24 * mid_r
    keep = length <= max_len
    # prune preferentially the longer edges so junction degrees mix 3/4/5
    keep &= rng.random(len(e)) > 0.22 * (0.5 + length / max_len)
    keep |= np.fromiter(((a, b) in mst_keys for a, b in e), bool, len(e))
    return e[keep]


def _chain_ways(rng: np.random.Generator, pts: np.ndarray,
                edges: np.ndarray, arterial: np.ndarray,
                ) -> "list[tuple[list[int], bool]]":
    """Group street edges into multi-node way chains by straightest
    continuation within the same class (arterial/local): at each junction
    a chain continues onto the unvisited same-class edge that deviates
    least, if it deviates under ~50° — the way a named road threads
    junctions. Every edge lands in exactly one chain."""
    adj: dict[int, list[tuple[int, int]]] = {}
    for k, (a, b) in enumerate(edges):
        adj.setdefault(int(a), []).append((k, int(b)))
        adj.setdefault(int(b), []).append((k, int(a)))
    visited = np.zeros(len(edges), bool)

    def _extend(chain: list[int], cls: bool) -> None:
        while True:
            prev, cur = chain[-2], chain[-1]
            d0 = pts[cur] - pts[prev]
            d0 /= max(float(np.linalg.norm(d0)), 1e-9)
            best, best_cos = None, 0.64           # cos 50°
            for k2, other in adj.get(cur, ()):
                if visited[k2] or arterial[k2] != cls or other == prev:
                    continue
                d1 = pts[other] - pts[cur]
                d1 = d1 / max(float(np.linalg.norm(d1)), 1e-9)
                c = float(d0 @ d1)
                if c > best_cos:
                    best, best_cos = (k2, other), c
            if best is None:
                return
            visited[best[0]] = True
            chain.append(best[1])

    chains: list[tuple[list[int], bool]] = []
    order = rng.permutation(len(edges))
    for k in order:
        if visited[k]:
            continue
        visited[k] = True
        chain = [int(edges[k, 0]), int(edges[k, 1])]
        _extend(chain, bool(arterial[k]))
        chain.reverse()
        _extend(chain, bool(arterial[k]))
        chains.append((chain, bool(arterial[k])))
    return chains


def generate_organic_city(name: str = "organic", seed: int = 11,
                          radius: float = 9000.0, core_scale: float = 1800.0,
                          n_candidates: int = 150000,
                          center_lonlat: "tuple[float, float]" = (-122.27,
                                                                  37.80),
                          ) -> RoadNetwork:
    """Generate an organic metro RoadNetwork (~15k nodes / ~55k directed
    edges after compilation at the defaults)."""
    from reporter_tpu.geometry import xy_to_lonlat

    rng = np.random.default_rng(seed)
    pts = _sample_nodes(rng, radius, core_scale, n_candidates, dedupe_m=32.0)
    edges = _street_edges(rng, pts, radius)

    r = np.linalg.norm(pts, axis=1)

    # ---- arterial classification (snapped onto existing streets) --------
    ring_radii = (1300.0, 2800.0, 4400.0)
    spoke_angles = rng.uniform(0.0, 2 * np.pi, size=7)
    a, b = edges[:, 0], edges[:, 1]
    is_ring = np.zeros(len(edges), bool)
    for rr in ring_radii:
        tol = 0.06 * rr + 60.0
        is_ring |= (np.abs(r[a] - rr) < tol) & (np.abs(r[b] - rr) < tol)
    is_spoke = np.zeros(len(edges), bool)
    for ang in spoke_angles:
        d = np.array([np.cos(ang), np.sin(ang)])
        ca = np.abs(pts[a] @ np.array([-d[1], d[0]]))
        cb = np.abs(pts[b] @ np.array([-d[1], d[0]]))
        on = (ca < 90.0) & (cb < 90.0) & (pts[a] @ d > 0) & (pts[b] @ d > 0)
        is_spoke |= on & (r[a] < 0.8 * radius)
    arterial = is_ring | is_spoke

    # ---- ways: straightest-continuation chains --------------------------
    chains = _chain_ways(rng, pts, edges, arterial)

    extra_xy: list[np.ndarray] = []      # spine/ramp/stub nodes appended
    ways: list[Way] = []
    way_id = 1

    def _xy_of(idx: int) -> np.ndarray:
        return pts[idx] if idx < len(pts) else extra_xy[idx - len(pts)]

    def _add_way(nodes: list[int], speed: float, nm: str,
                 oneway: bool, curved: bool = True) -> None:
        nonlocal way_id
        geometry: dict[int, np.ndarray] = {}
        if curved:
            # bow ~25% of long-enough legs (curved roads, like the grid gen)
            for leg in range(len(nodes) - 1):
                if rng.random() >= 0.25:
                    continue
                pa, pb = _xy_of(nodes[leg]), _xy_of(nodes[leg + 1])
                d = pb - pa
                n = float(np.linalg.norm(d))
                if n < 60.0:
                    continue
                perp = np.array([-d[1], d[0]]) / n
                mid = (pa + pb) / 2.0 + perp * rng.uniform(0.04, 0.1) * n
                geometry[leg] = xy_to_lonlat(
                    mid[None, :], np.asarray(center_lonlat, np.float64))
        ways.append(Way(way_id=way_id, nodes=nodes, oneway=oneway, name=nm,
                        speed_mps=speed, geometry=geometry))
        way_id += 1

    for chain, art in chains:
        if art:
            _add_way(chain, _SPEED_ARTERIAL, "avenue", False)
        else:
            _add_way(chain, _SPEED_LOCAL, "st",
                     bool(rng.random() < 0.22))

    # ---- highway spine (limited access, crosses streets as overpasses) --
    ang = rng.uniform(0.0, np.pi)
    d = np.array([np.cos(ang), np.sin(ang)])
    perp = np.array([-d[1], d[0]])
    spine_nodes: list[int] = []
    s = -radius * 0.98
    while s < radius * 0.98:
        off = 1200.0 * np.sin(s / radius * 2.2) + rng.normal(0.0, 60.0)
        p = s * d + off * perp
        if np.linalg.norm(p) < radius:
            spine_nodes.append(len(pts) + len(extra_xy))
            extra_xy.append(p)
        s += rng.uniform(600.0, 1400.0)      # long legs (0.6–1.4 km)
    if len(spine_nodes) >= 2:
        _add_way(spine_nodes, _SPEED_SPINE, "spine", False, curved=False)
        from scipy.spatial import cKDTree

        tree = cKDTree(pts)
        for sn in spine_nodes[::3]:          # a ramp every ~3 km
            p = _xy_of(sn)
            dists, nears = tree.query(p, k=4)
            # prefer a ramp with some length to it; fall back to the
            # closest street node rather than leaving the spine orphaned
            ok = [int(n) for dd, n in zip(dists, nears)
                  if 40.0 <= dd < 1500.0]
            target = ok[0] if ok else (int(nears[0])
                                       if dists[0] < 1500.0 else None)
            if target is not None:
                _add_way([sn, target], _SPEED_RAMP, "ramp", False,
                         curved=False)

    # ---- cul-de-sacs ----------------------------------------------------
    n_stub = max(1, len(pts) // 18)
    anchors = rng.choice(len(pts), size=n_stub, replace=False)
    for u in anchors:
        ang = rng.uniform(0.0, 2 * np.pi)
        stub = pts[u] + np.array([np.cos(ang), np.sin(ang)]) \
            * rng.uniform(40.0, 150.0)
        sid = len(pts) + len(extra_xy)
        extra_xy.append(stub)
        _add_way([int(u), sid], _SPEED_STUB, "cul", False, curved=False)

    all_xy = np.vstack([pts, np.asarray(extra_xy).reshape(-1, 2)]) \
        if extra_xy else pts
    node_ll = xy_to_lonlat(all_xy, np.asarray(center_lonlat, np.float64))
    return RoadNetwork(node_lonlat=node_ll, ways=ways, name=name)
