"""Synthetic metro generator.

The environment has no network access and no OSM extracts, so benchmark
cities ("sf", "nyc", "la" — BASELINE.md configs 2–4) are generated
deterministically: a jittered street grid with one-ways, occasional missing
blocks, diagonal avenues, and curved edge geometry. The generator emits a
RoadNetwork; everything downstream (compiler, matcher) is source-agnostic, so
real OSM data can be swapped in through ``netgen.osm_xml`` unchanged.
"""

from __future__ import annotations

import numpy as np

from reporter_tpu.netgen.network import (ACCESS_BICYCLE, ACCESS_FOOT,
                                         RoadNetwork, TurnRestriction, Way)

# name → (seed, nx, ny); sizes tuned so "sf" compiles in seconds and the trio
# gives a meaningfully sharded multi-city set (BASELINE config 4).
CITY_PRESETS: dict[str, tuple[int, int, int]] = {
    "tiny": (7, 6, 6),
    "sf": (1, 40, 40),
    "nyc": (2, 56, 36),
    "la": (3, 48, 48),
    # metro-scale tile set (BASELINE config 3 "Bay-Area tiles in HBM"):
    # ~16k intersections, ~54k directed edges after interior-node
    # simplification (the compiled count STATUS/bench quote), ~17 km a side
    "bayarea": (4, 128, 128),
    # realistic-scale HBM stressor (SURVEY §7 "HBM budget"): ~147k
    # intersections, ~0.5M directed edges, ~46 km a side — several GB of
    # reach/grid/shape tables, the real Bay Area's order of magnitude
    "bayarea-xl": (5, 384, 384),
}

_CITY_CENTERS = {
    "tiny": (-122.45, 37.77),
    "sf": (-122.4194, 37.7749),
    "nyc": (-73.9857, 40.7484),
    "la": (-118.2437, 34.0522),
    "bayarea": (-122.2711, 37.8044),
    "bayarea-xl": (-122.2711, 37.8044),
}


def generate_city(
    name: str = "tiny",
    *,
    nx: int | None = None,
    ny: int | None = None,
    seed: int | None = None,
    spacing: float = 120.0,
    jitter: float = 12.0,
    p_missing_block: float = 0.06,
    p_oneway: float = 0.25,
    p_curved: float = 0.25,
    center: "tuple[float, float] | None" = None,
) -> RoadNetwork:
    """Generate a deterministic synthetic city RoadNetwork.

    Streets run east-west, avenues north-south, on a jittered grid with
    ``spacing`` meters between intersections. Some whole-block legs are
    removed, some ways are one-way, some legs get curved shape geometry, and a
    pair of diagonal boulevards crosses the grid.

    ``center`` overrides the (lon, lat) city center. Names outside
    ``_CITY_CENTERS`` all share one default center, so a fleet of
    generated metros would otherwise stack on the same patch of planet —
    geo routing (service/router.py bbox dispatch, the fleet bench's N
    synthetic metros) needs disjoint bboxes.
    """
    if name in ("organic", "organic-xl"):
        if center is not None:
            raise ValueError("center does not apply to the organic "
                             "generator; its centers are fixed")
        # irregular radial metros (VERDICT r3: non-grid topology evidence);
        # live in netgen/organic.py — same RoadNetwork contract. The -xl
        # variant (~32k nodes / ~152k directed edges) carries the
        # irregular structure to several times metro scale.
        if (nx, ny) != (None, None) or (spacing, jitter) != (120.0, 12.0) \
                or (p_missing_block, p_oneway, p_curved) != (0.06, 0.25,
                                                             0.25):
            raise ValueError(
                "grid parameters don't apply to the organic generator; "
                "call netgen.organic.generate_organic_city directly")
        from reporter_tpu.netgen.organic import generate_organic_city

        if name == "organic-xl":
            return generate_organic_city(
                name, seed=seed if seed is not None else 12,
                radius=16000.0, core_scale=2800.0, n_candidates=420000)
        return generate_organic_city(name, seed=seed if seed is not None
                                     else 11)
    preset = CITY_PRESETS.get(name)
    if preset is not None:
        pseed, pnx, pny = preset
        seed = pseed if seed is None else seed
        nx = pnx if nx is None else nx
        ny = pny if ny is None else ny
    if nx is None or ny is None or seed is None:
        raise ValueError(f"unknown city {name!r}; pass nx/ny/seed explicitly")

    rng = np.random.default_rng(seed)
    lon0, lat0 = (center if center is not None
                  else _CITY_CENTERS.get(name, (-122.0, 37.0)))

    # Node grid in local meters, centered at 0.
    xs = (np.arange(nx) - (nx - 1) / 2.0) * spacing
    ys = (np.arange(ny) - (ny - 1) / 2.0) * spacing
    gx, gy = np.meshgrid(xs, ys, indexing="ij")
    xy = np.stack([gx, gy], axis=-1)                     # [nx, ny, 2]
    xy = xy + rng.normal(0.0, jitter, size=xy.shape)

    # meters → lonlat around the city center (inverse equirectangular).
    from reporter_tpu.geometry import xy_to_lonlat

    node_lonlat = xy_to_lonlat(xy.reshape(-1, 2), np.array([lon0, lat0]))
    node_index = np.arange(nx * ny).reshape(nx, ny)

    removed = rng.random((nx, ny, 2)) < p_missing_block  # [.., 0]=east leg, [.., 1]=north leg

    ways: list[Way] = []
    way_id = 1

    def add_chain(chain: list[int], oneway: bool, name_: str, speed: float) -> None:
        nonlocal way_id
        if len(chain) < 2:
            return
        w = Way(way_id=way_id, nodes=chain, oneway=oneway, name=name_, speed_mps=speed)
        # Curved geometry on a fraction of legs: a midpoint pushed perpendicular.
        for i in range(len(chain) - 1):
            if rng.random() < p_curved:
                a = node_lonlat[chain[i]]
                b = node_lonlat[chain[i + 1]]
                mid = (a + b) / 2.0
                d = b - a
                perp = np.array([-d[1], d[0]])
                n = np.linalg.norm(perp)
                if n > 0:
                    # ~8 m lateral bow (in degree-space via local scaling of the leg itself)
                    bow = rng.uniform(0.05, 0.12)
                    mid = mid + perp * bow
                    w.geometry[i] = mid[None, :]
        ways.append(w)
        way_id += 1

    # Streets (constant j, varying i): break chains at removed east-legs.
    for j in range(ny):
        chain: list[int] = [int(node_index[0, j])]
        for i in range(nx - 1):
            if removed[i, j, 0]:
                add_chain(chain, rng.random() < p_oneway, f"st_{j}", 13.4)
                chain = [int(node_index[i + 1, j])]
            else:
                chain.append(int(node_index[i + 1, j]))
        add_chain(chain, rng.random() < p_oneway, f"st_{j}", 13.4)

    # Avenues (constant i, varying j): break chains at removed north-legs.
    for i in range(nx):
        chain = [int(node_index[i, 0])]
        for j in range(ny - 1):
            if removed[i, j, 1]:
                add_chain(chain, rng.random() < p_oneway, f"av_{i}", 13.4)
                chain = [int(node_index[i, j + 1])]
            else:
                chain.append(int(node_index[i, j + 1]))
        add_chain(chain, rng.random() < p_oneway, f"av_{i}", 13.4)

    # Two diagonal boulevards (two-way, faster).
    k = min(nx, ny)
    add_chain([int(node_index[t, t]) for t in range(k)], False, "diag_ne", 17.9)
    add_chain([int(node_index[t, ny - 1 - t]) for t in range(min(nx, ny))], False, "diag_se", 17.9)

    return RoadNetwork(node_lonlat=node_lonlat, ways=ways, name=name)


def assign_mode_access(net: RoadNetwork, seed: int = 21,
                       p_bike_only: float = 0.08,
                       p_foot_only: float = 0.05) -> RoadNetwork:
    """Give a synthetic (all-access) city a realistic mode mix: a fraction
    of ways become bike-only "cycleways" and foot-only "footpaths" (with
    matching free-flow speeds), the rest stay all-access. Mutates and
    returns ``net``; name gains ``+m`` so content-keyed caches split the
    variant. The result is the fixture for per-mode compiles
    (compile_network(net, mode=...)) at bench scale."""
    rng = np.random.default_rng(seed)
    for w in net.ways:
        u = rng.random()
        if u < p_bike_only:
            w.access_mask = ACCESS_BICYCLE | ACCESS_FOOT
            w.speed_mps = 5.6
        elif u < p_bike_only + p_foot_only:
            w.access_mask = ACCESS_FOOT
            w.speed_mps = 1.4
    if not net.name.endswith("+m"):
        net.name = f"{net.name}+m"
    return net


def add_random_restrictions(net: RoadNetwork, fraction: float = 0.08,
                            seed: int = 99) -> RoadNetwork:
    """Inject ``no_turn`` restrictions at ~``fraction`` of real junctions.

    Gives synthetic cities a realistic turn-restriction density (the
    reference's graphs carry OSM `restriction` relations; see
    tiles/compiler._resolve_restrictions for the banned-pair lowering).
    Candidate junctions are nodes where ≥2 distinct ways cross and ≥2
    distinct ways leave; the ban always leaves the arriving vehicle another
    exit (continuing on its own way, or a third way) — a restriction forces
    a detour, never a dead end. Mutates and returns ``net`` (name gains a
    ``+r`` suffix so tile caches key the variant separately).
    """
    rng = np.random.default_rng(seed)
    # node → ways that can ARRIVE at it / LEAVE it (oneway-aware)
    arrive: dict[int, list] = {}
    leave: dict[int, list] = {}
    for w in net.ways:
        for i, nd in enumerate(w.nodes):
            if i > 0 or not w.oneway:
                arrive.setdefault(nd, []).append(w)
            if i < len(w.nodes) - 1 or not w.oneway:
                leave.setdefault(nd, []).append(w)
    junctions = [nd for nd in arrive
                 if len({w.way_id for w in leave.get(nd, [])}) >= 2]
    junctions.sort()
    n_pick = int(round(len(junctions) * fraction))
    for nd in rng.permutation(np.asarray(junctions))[:n_pick]:
        nd = int(nd)
        dst_ids = sorted({w.way_id for w in leave[nd]})
        # the banned exit must leave the arriving vehicle another way out
        src = [w for w in arrive[nd]
               if w.way_id in dst_ids and len(dst_ids) >= 2]
        if not src:
            continue
        fw = src[rng.integers(len(src))]
        to_choices = [d for d in dst_ids if d != fw.way_id]
        if not to_choices:
            continue
        tw = to_choices[rng.integers(len(to_choices))]
        net.restrictions.append(TurnRestriction(
            from_way=fw.way_id, via_node=nd, to_way=int(tw),
            kind="no_turn"))
    if net.restrictions and not net.name.endswith("+r"):
        net.name = f"{net.name}+r"
    return net
