"""Road-network sources: synthetic cities, OSM XML parsing, probe synthesis."""

from reporter_tpu.netgen.network import RoadNetwork, Way
from reporter_tpu.netgen.synthetic import generate_city
from reporter_tpu.netgen.osm_xml import parse_osm_xml

__all__ = ["RoadNetwork", "Way", "generate_city", "parse_osm_xml"]
