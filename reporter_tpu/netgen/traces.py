"""GPS probe-trace synthesis with ground truth.

The reference's tests replay canned real-city GPS fixtures and assert segment
ids (SURVEY.md §4 "golden segment-ID tests"). With no real extracts available,
we synthesize probes instead — a random drive on the compiled graph, sampled
at fixed dt with Gaussian GPS noise — and keep the ground-truth edge/OSMLR
sequence, which is *stronger* than golden files: accuracy is measured against
truth, and golden tests pin the matcher output for fixed seeds.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from reporter_tpu.geometry import xy_to_lonlat
from reporter_tpu.tiles.tileset import TileSet


@dataclass
class Probe:
    """One synthetic vehicle trace."""

    uuid: str
    lonlat: np.ndarray        # [T, 2] noisy (lon, lat)
    xy: np.ndarray            # [T, 2] noisy local meters
    times: np.ndarray         # [T] seconds (epoch-less)
    true_edges: np.ndarray    # [T] ground-truth edge id per sample
    true_offsets: np.ndarray  # [T] ground-truth distance along edge (m)
    path_edges: np.ndarray    # full driven edge sequence

    def to_report_json(self) -> dict:
        """The reference's /report request shape (SURVEY.md §3.1)."""
        return {
            "uuid": self.uuid,
            "trace": [
                {"lat": float(la), "lon": float(lo), "time": float(t)}
                for (lo, la), t in zip(self.lonlat, self.times)
            ],
        }


class _EdgeShapeCache:
    """seg arrays grouped by edge, so sampling is O(1)-ish per lookup."""

    def __init__(self, ts: TileSet):
        order = np.argsort(ts.seg_edge, kind="stable")
        self.seg_by_edge_start = np.searchsorted(
            ts.seg_edge[order], np.arange(ts.num_edges))
        self.seg_by_edge_end = np.searchsorted(
            ts.seg_edge[order], np.arange(ts.num_edges), side="right")
        self.order = order
        self.ts = ts

    def point_at(self, e: int, off: float) -> np.ndarray:
        ts = self.ts
        sl = self.order[self.seg_by_edge_start[e]:self.seg_by_edge_end[e]]
        offs = ts.seg_off[sl]
        i = int(np.searchsorted(offs, off, side="right") - 1)
        i = max(0, min(i, len(sl) - 1))
        s = sl[i]
        t = np.clip((off - ts.seg_off[s]) / max(ts.seg_len[s], 1e-6), 0.0, 1.0)
        return ts.seg_a[s] + t * (ts.seg_b[s] - ts.seg_a[s])


def random_walk_edges(
    ts: TileSet, rng: np.random.Generator, target_length: float,
    start_edge: int | None = None,
    ban: "set[tuple[int, int]] | None" = None,
) -> list[int]:
    """A plausible drive: follow graph connectivity, avoid immediate U-turns
    when an alternative exists, and never take a banned turn (``ban`` is the
    tile's (from_edge, to_edge) set — restricted tiles get LEGAL fleets, the
    way real probes drive)."""
    e = int(rng.integers(ts.num_edges)) if start_edge is None else int(start_edge)
    path = [e]
    total = float(ts.edge_len[e])
    while total < target_length:
        u = int(ts.edge_dst[e])
        outs = [int(x) for x in ts.node_out[u] if x >= 0]
        if ban:
            outs = [x for x in outs if (e, x) not in ban]
        if not outs:
            break
        non_uturn = [x for x in outs if x != int(ts.edge_opp[e])]
        choices = non_uturn if non_uturn else outs
        e = int(choices[rng.integers(len(choices))])
        path.append(e)
        total += float(ts.edge_len[e])
    return path


def synthesize_probe(
    ts: TileSet,
    seed: int = 0,
    *,
    num_points: int = 120,
    dt: float = 1.0,
    speed_mps: float | None = None,
    gps_sigma: float = 5.0,
    uuid: str | None = None,
    shape_cache: "_EdgeShapeCache | None" = None,
    ban: "set[tuple[int, int]] | None" = None,
) -> Probe:
    """Drive a random path and sample noisy GPS points along it."""
    rng = np.random.default_rng(seed)
    speed = float(speed_mps if speed_mps is not None else rng.uniform(7.0, 16.0))
    need = speed * dt * (num_points + 2)
    path = random_walk_edges(ts, rng, need, ban=ban)
    cache = shape_cache if shape_cache is not None else _EdgeShapeCache(ts)

    cum = np.concatenate([[0.0], np.cumsum(ts.edge_len[path].astype(np.float64))])
    xs, true_e, true_off = [], [], []
    for i in range(num_points):
        s = min(i * dt * speed, cum[-1] - 1e-3)
        k = int(np.searchsorted(cum, s, side="right") - 1)
        k = max(0, min(k, len(path) - 1))
        off = s - cum[k]
        xs.append(cache.point_at(path[k], off))
        true_e.append(path[k])
        true_off.append(off)

    xy_true = np.asarray(xs, dtype=np.float64)
    noise = rng.normal(0.0, gps_sigma, size=xy_true.shape)
    xy = xy_true + noise
    lonlat = xy_to_lonlat(xy, np.asarray(ts.meta.origin_lonlat))
    times = np.arange(num_points, dtype=np.float64) * dt
    return Probe(
        uuid=uuid or f"veh-{seed}",
        lonlat=lonlat, xy=xy.astype(np.float64), times=times,
        true_edges=np.asarray(true_e, np.int32),
        true_offsets=np.asarray(true_off, np.float32),
        path_edges=np.asarray(path, np.int32),
    )


def synthesize_fleet(ts: TileSet, n: int, *, num_points: int = 120,
                     seed: int = 0, gps_sigma: float = 5.0) -> list[Probe]:
    cache = _EdgeShapeCache(ts)  # segment sort is per-TileSet, share it
    ban = ts.ban_set or None     # restricted tiles get legal drivers
    return [
        synthesize_probe(ts, seed=seed * 1_000_003 + i, num_points=num_points,
                         gps_sigma=gps_sigma, uuid=f"veh-{seed}-{i}",
                         shape_cache=cache, ban=ban)
        for i in range(n)
    ]
