"""Declarative SLO engine: error budgets + multi-window burn-rate alerts.

Every prior observability round left raw signals (r10 spans + fixed-bucket
histograms, r15 link mood, r18 quality drift, r19 merged cross-worker
exports, r23 lease audits) but no layer that decides *when the system is
out of budget*. This module is that layer:

  - :class:`SloSpec` — a committed objective over EXISTING registry
    series: a good-event ratio over counters (``ratio``), a latency
    objective over a fixed-bucket histogram (``latency`` — legal only
    because ``HISTOGRAM_BUCKETS`` has been pinned since r10, so "requests
    under 250 ms" is an exact bucket prefix sum, cross-worker mergeable),
    or a level ceiling over a gauge (``gauge`` — sampled each tick into
    synthetic ``slo_sample_*`` counters so a level becomes delta-able and
    topology-mergeable like everything else).
  - :class:`SloEvaluator` — pushes ``export()`` snapshots into a
    :class:`~reporter_tpu.utils.metrics.SnapshotRing` and computes burn
    rate per spec from windowed *deltas* (``delta_since``), Google-SRE
    multi-window multi-burn-rate style: an alert fires only when burn
    exceeds a pair's threshold on BOTH its fast and slow window. Window
    scale is configurable (``RTPU_SLO_SCALE``) so bench/chaos runs
    exercise real transitions in seconds.

Alert TRANSITIONS follow the r18 drift-sentinel discipline: a tracer
instant on fire and resolve, ONE bounded flight-recorder post-mortem per
fire (an SLO that stays out of budget dumps once, not once per tick; the
budget is the recorder's shared ``max_dumps``), and a durable append to
an ``alerts.jsonl`` ledger via :class:`~reporter_tpu.utils.eventlog
.EventLog` (the one r24 JSONL spelling). Burn rates, budget remaining
and alert state publish as ``slo_*`` gauges into the registry, so
``/metrics`` carries ``rtpu_slo_*`` with no new plumbing.

Topology-wide evaluation is the same code over a different source: the
Supervisor passes ``source=lambda: merged_registry().export()`` — burn
is linear over counters/buckets, so topology burn over ``merge_exports``
equals the per-worker sum by construction (property-tested). A merged
evaluator passes ``sample_gauges=False``: workers already sampled their
own gauges into the synthetic counters, and the merge carries them.

Lock discipline (r14): ``obs.slo`` is a LEAF — it guards only the
snapshot ring, throttle stamp and alert state; the export pull, gauge
publication, ledger append and tracer all run outside it (the
quality.monitor shape).
"""

from __future__ import annotations

import dataclasses
import os
import time

from reporter_tpu.utils import locks, tracing
from reporter_tpu.utils.metrics import (HISTOGRAM_BUCKETS, SnapshotRing,
                                        _split_labels, labeled)

__all__ = ["SloSpec", "SloEvaluator", "DEFAULT_SLOS", "DEFAULT_WINDOWS",
           "enabled", "window_scale", "install", "active"]

_ENV_GATE = "RTPU_SLO"
_ENV_SCALE = "RTPU_SLO_SCALE"
_ENV_TICK = "RTPU_SLO_TICK"


def enabled(env: "dict[str, str] | None" = None) -> bool:
    """``RTPU_SLO`` gate, default ON (strict parse — the config.py lever
    discipline: a typo'd gate must raise, not silently disable the
    error-budget plane)."""
    e = os.environ if env is None else env
    raw = e.get(_ENV_GATE)
    if raw is None or not raw.strip():
        return True
    return tracing.env_flag(raw, strict=True)


def window_scale(env: "dict[str, str] | None" = None) -> float:
    """``RTPU_SLO_SCALE`` multiplier on every spec window (default 1.0).
    Bench/chaos runs set ~0.01 so the production-scale windows transition
    in seconds; the spec FILE stays at production scale, which is what
    the ``--slo`` validator checks."""
    e = os.environ if env is None else env
    raw = e.get(_ENV_SCALE)
    if raw is None or not raw.strip():
        return 1.0
    scale = float(raw)
    if scale <= 0:
        raise ValueError(f"{_ENV_SCALE} must be > 0, got {raw!r}")
    return scale


# (fast_s, slow_s, burn_threshold) pairs — the Google-SRE page/ticket
# split shrunk to this service's horizon: a fast 1 m / 12 m pair at
# 14.4× burn (budget gone in ~1 h at that rate) and a slow 5 m / 1 h
# pair at 6×. Both windows of a pair must exceed the threshold to alert
# (the fast window alone would page on blips; the slow alone would page
# an hour late).
DEFAULT_WINDOWS = ((60.0, 720.0, 14.4), (300.0, 3600.0, 6.0))


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One committed objective. ``kind``:

    - ``ratio``: bad/total are tuples of counter base names, summed
      across names and label blocks (tuples are what let the publish SLO
      count failures over attempts, or a fleet SLO sum a success counter
      with a failure counter for its denominator);
    - ``latency``: ``series`` is an observation series; ``threshold_s``
      MUST sit exactly on the ``HISTOGRAM_BUCKETS`` grid (validated) —
      bad events are the bucket counts strictly above it;
    - ``gauge``: each evaluator tick samples every series of ``gauge``
      against ``ceiling`` into synthetic per-spec counters, turning a
      level into a windowed ratio.
    """

    name: str
    kind: str  # "ratio" | "latency" | "gauge"
    objective: float  # good-event fraction target, e.g. 0.999
    bad: "tuple[str, ...]" = ()
    total: "tuple[str, ...]" = ()
    series: str = ""
    threshold_s: float = 0.0
    gauge: str = ""
    ceiling: float = 0.0
    windows: "tuple[tuple[float, float, float], ...]" = DEFAULT_WINDOWS

    def budget(self) -> float:
        """Error budget = 1 − objective (the burn-rate denominator)."""
        return 1.0 - self.objective

    def metric_names(self) -> "tuple[str, ...]":
        """Every registry series this spec reads — the validator checks
        each against the README metric-inventory block."""
        if self.kind == "ratio":
            return tuple(self.bad) + tuple(self.total)
        if self.kind == "latency":
            return (self.series,)
        return (self.gauge,)


# The committed objectives (ISSUE 20): serving availability + latency,
# publish success, dispatch-timeout rate, streaming lag, lease
# reacquire time. Objectives are seeded from the bench captures'
# steady-state behavior — gross-outage detectors first, tightened as
# captures accumulate (the quality-baseline precedent). Validated by
# ``python -m reporter_tpu.analysis --slo`` (windows ordered, burn
# thresholds consistent with budget, metric names in the README
# inventory, latency thresholds on the histogram grid).
DEFAULT_SLOS = (
    SloSpec("availability", "ratio", 0.999,
            bad=("http_errors",), total=("http_requests",)),
    SloSpec("latency", "latency", 0.99,
            series="request_seconds", threshold_s=0.25),
    SloSpec("publish", "ratio", 0.999,
            bad=("publish_failures",), total=("publish_attempts",)),
    SloSpec("dispatch_timeout", "ratio", 0.999,
            bad=("dispatch_timeout",), total=("match_seconds_count",)),
    SloSpec("stream_lag", "gauge", 0.99,
            gauge="stream_lag", ceiling=5000.0),
    SloSpec("lease_reacquire", "latency", 0.95,
            series="lease_reacquire_seconds", threshold_s=10.0),
)


def _sum_counters(counters: dict, bases: "tuple[str, ...]") -> float:
    tot = 0.0
    for k, v in counters.items():
        if _split_labels(k)[0] in bases:
            tot += float(v)
    return tot


def _sum_hist(hist: dict, base: str) -> "list[int]":
    out = [0] * (len(HISTOGRAM_BUCKETS) + 1)
    for k, buckets in hist.items():
        if _split_labels(k)[0] == base:
            for i, c in enumerate(buckets[:len(out)]):
                out[i] += int(c)
    return out


def _bad_total(spec: SloSpec, delta: dict) -> "tuple[float, float]":
    """(bad, total) event counts for one spec over one delta document."""
    counters = delta.get("counters") or {}
    if spec.kind == "ratio":
        return (_sum_counters(counters, spec.bad),
                _sum_counters(counters, spec.total))
    if spec.kind == "latency":
        buckets = _sum_hist(delta.get("hist") or {}, spec.series)
        idx = HISTOGRAM_BUCKETS.index(spec.threshold_s)
        good = float(sum(buckets[:idx + 1]))
        total = float(sum(buckets))
        return total - good, total
    # gauge: the tick already folded levels into per-spec synthetic
    # counters (exact keys — two gauge specs must never alias)
    bad = float(counters.get(labeled("slo_sample_bad", slo=spec.name),
                             0.0))
    total = float(counters.get(labeled("slo_sample_total",
                                       slo=spec.name), 0.0))
    return bad, total


class SloEvaluator:
    """Periodic burn-rate evaluation of ``specs`` over ``source()``
    exports, publishing into ``registry`` (see module docstring).

    ``clock`` is injectable (bench/tests drive window transitions
    deterministically); ``min_tick_s`` self-throttles callers that tick
    per wave/poll; ``ledger`` is an :class:`EventLog` receiving one
    entry per alert transition.
    """

    def __init__(self, registry, *, source=None, specs=DEFAULT_SLOS,
                 ledger=None, clock=time.monotonic,
                 scale: "float | None" = None,
                 min_tick_s: "float | None" = None,
                 sample_gauges: bool = True,
                 enabled_override: "bool | None" = None):
        self.registry = registry
        self._source = source if source is not None else registry.export
        self.enabled = (enabled() if enabled_override is None
                        else bool(enabled_override))
        s = window_scale() if scale is None else float(scale)
        self.scale = s
        self.specs = tuple(specs)
        self.ledger = ledger
        self._clock = clock
        self._sample_gauges = bool(sample_gauges)
        # scaled (fast, slow, threshold) triples per spec, fast-first
        self._windows = {
            spec.name: tuple(sorted((f * s, sl * s, thr)
                                    for f, sl, thr in spec.windows))
            for spec in self.specs}
        fastest = min((w[0][0] for w in self._windows.values()
                       if w), default=60.0)
        if min_tick_s is None:
            raw = os.environ.get(_ENV_TICK)
            min_tick_s = (float(raw) if raw and raw.strip()
                          else max(0.05, fastest / 6.0))
        self.min_tick_s = float(min_tick_s)
        self._lock = locks.named_lock("obs.slo")
        self._ring = SnapshotRing()
        self._last_tick: "float | None" = None
        self._active: "dict[str, bool]" = {
            spec.name: False for spec in self.specs}
        self._state: "dict[str, dict]" = {}
        self.ticks = 0
        self.alerts_total = 0

    # ---- evaluation ------------------------------------------------------

    def tick(self, now: "float | None" = None,
             force: bool = False) -> bool:
        """One evaluation pass; returns False when throttled/disabled.
        The lock guards only the throttle stamp, ring and alert state —
        export pull, gauge sampling, metric publication, ledger append
        and tracer all run outside it."""
        if not self.enabled:
            return False
        if now is None:
            now = self._clock()
        with self._lock:
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self.min_tick_s):
                return False
            self._last_tick = now
            self.ticks += 1
        export = self._source()
        if self._sample_gauges and self._sample(export):
            export = self._source()
        fired, resolved = [], []
        with self._lock:
            self._ring.push(now, export)
            for spec in self.specs:
                st = self._evaluate(spec, now)
                self._state[spec.name] = st
                was = self._active[spec.name]
                self._active[spec.name] = st["alerting"]
                if st["alerting"] and not was:
                    fired.append((spec, st))
                    self.alerts_total += 1
                elif was and not st["alerting"]:
                    resolved.append((spec, st))
            states = dict(self._state)
        self._publish(states)
        for spec, st in fired:
            self._transition("fire", spec, st)
        for spec, st in resolved:
            self._transition("resolve", spec, st)
        return True

    def _sample(self, export: dict) -> bool:
        """Fold current gauge levels into per-spec synthetic counters
        (one good/bad event per matching series per tick) so gauge SLOs
        ride the same delta/merge math as everything else."""
        gauges = export.get("gauges") or {}
        sampled = False
        for spec in self.specs:
            if spec.kind != "gauge":
                continue
            bad = total = 0
            for k, v in gauges.items():
                if _split_labels(k)[0] == spec.gauge:
                    total += 1
                    if float(v) > spec.ceiling:
                        bad += 1
            if total:
                sampled = True
                self.registry.count(
                    labeled("slo_sample_total", slo=spec.name), total)
                if bad:
                    self.registry.count(
                        labeled("slo_sample_bad", slo=spec.name), bad)
        return sampled

    def _evaluate(self, spec: SloSpec, now: float) -> dict:
        """Burn per window pair from ring deltas (lock held: pure dict
        math only). Zero traffic over a window is zero burn — an idle
        service is not out of budget."""
        budget = spec.budget()
        pairs = []
        alerting = False
        for fast_s, slow_s, thr in self._windows[spec.name]:
            burns = []
            for win in (fast_s, slow_s):
                delta, span = self._ring.delta_since(win, now)
                if delta is None:
                    burns.append(0.0)
                    continue
                bad, total = _bad_total(spec, delta)
                ratio = (bad / total) if total > 0 else 0.0
                burns.append(ratio / budget if budget > 0 else 0.0)
            pair_alerting = (burns[0] >= thr and burns[1] >= thr)
            alerting = alerting or pair_alerting
            pairs.append({"fast_s": fast_s, "slow_s": slow_s,
                          "threshold": thr, "burn_fast": burns[0],
                          "burn_slow": burns[1],
                          "alerting": pair_alerting})
        longest = max(p["slow_s"] for p in pairs)
        budget_burn = next(p["burn_slow"] for p in pairs
                           if p["slow_s"] == longest)
        return {"alerting": alerting, "pairs": pairs,
                "burn_fast": pairs[0]["burn_fast"],
                "burn_slow": pairs[0]["burn_slow"],
                "budget_remaining": max(0.0, 1.0 - budget_burn)}

    def _publish(self, states: "dict[str, dict]") -> None:
        m = self.registry
        for name, st in states.items():
            m.gauge(labeled("slo_burn_fast", slo=name), st["burn_fast"])
            m.gauge(labeled("slo_burn_slow", slo=name), st["burn_slow"])
            m.gauge(labeled("slo_budget_remaining", slo=name),
                    st["budget_remaining"])
            m.gauge(labeled("slo_alert_active", slo=name),
                    1.0 if st["alerting"] else 0.0)

    def _transition(self, event: str, spec: SloSpec, st: dict) -> None:
        """r18 discipline: instant on both edges, ONE bounded
        post-mortem per fire (a budget that stays blown dumps once),
        ledger entry on both — a fencing-style transition that vanished
        from the ledger would be undebuggable."""
        tr = tracing.tracer()
        args = {"slo": spec.name,
                "burn_fast": round(st["burn_fast"], 3),
                "burn_slow": round(st["burn_slow"], 3),
                "budget_remaining": round(st["budget_remaining"], 4)}
        tr.instant(f"slo_{event}", **args)
        if event == "fire":
            self.registry.count(labeled("slo_alerts_total",
                                        slo=spec.name))
            tr.post_mortem("slo_alert", failing=spec.name, **args)
        if self.ledger is not None:
            self.ledger.append({"t": round(time.time(), 3),
                                "event": event, **args})

    # ---- read side -------------------------------------------------------

    def status(self) -> dict:
        """The ``GET /slo`` body: full per-spec burn/pair detail."""
        with self._lock:
            states = {k: dict(v) for k, v in self._state.items()}
            ticks, total = self.ticks, self.alerts_total
        return {"enabled": self.enabled, "scale": self.scale,
                "ticks": ticks, "alerts_total": total,
                "slos": states,
                "active": sorted(k for k, v in states.items()
                                 if v.get("alerting"))}

    def health(self) -> dict:
        """The ``/health`` roll-up: small on purpose (full detail at
        ``/slo``)."""
        with self._lock:
            states = dict(self._state)
            total = self.alerts_total
        return {"enabled": self.enabled,
                "alerting": sorted(k for k, v in states.items()
                                   if v.get("alerting")),
                "alerts_total": total,
                "budget_remaining": {
                    k: round(v["budget_remaining"], 4)
                    for k, v in states.items()}}

    def exit_block(self) -> dict:
        """The worker-CLI exit-JSON block (rides member exit reports
        next to the r15 link and r18 quality blocks)."""
        h = self.health()
        with self._lock:
            ticks = self.ticks
        return {"active": h["alerting"], "alerts_total":
                h["alerts_total"], "ticks": ticks,
                "budget_remaining": h["budget_remaining"]}


# ---- process-global seam (the faults.install shape) ----------------------
#
# Apps, workers and the supervisor hold PER-INSTANCE evaluators; nothing
# in the package installs globally. The seam exists so embedders can
# share one evaluator — and so the r14 leak gate
# (analysis/global_state.py) can prove a test that installed one put it
# back (the r10 "tracer left ON for every later leg" class).

_installed: "SloEvaluator | None" = None


def install(evaluator: "SloEvaluator | None") -> None:
    global _installed
    _installed = evaluator


def active() -> "SloEvaluator | None":
    return _installed
