"""Round-24 SLO plane: declarative error-budget objectives + multi-window
burn-rate alerting over the metrics registry (see obs/slo.py)."""

from reporter_tpu.obs.slo import (DEFAULT_SLOS, SloEvaluator, SloSpec,
                                  active, enabled, install, window_scale)

__all__ = ["DEFAULT_SLOS", "SloEvaluator", "SloSpec", "active",
           "enabled", "install", "window_scale"]
