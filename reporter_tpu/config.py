"""Configuration for reporter_tpu.

Mirrors the reference's two-layer config (SURVEY.md §5 "Config / flag system"):
a structured matcher/compiler config (the ``valhalla.json`` analog — sigma_z,
beta, search radius, costing-ish knobs) plus environment variables for service
wiring (``DATASTORE_URL``, port, thread count).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any


# The narrow-grid launch width LADDER (round 17): ``sweep_nj_cap`` may
# only take these rungs, so the dense sweep's compiled-shape universe
# stays finite and manifest-pinned (analysis/compile_manifest.py
# enumerates ladder × kernel arm). Results are EXACT at any rung — hits
# sort first in the culled id list and the kernel falls back to the
# full-width launch whenever a chunk's hits exceed the cap (the round-5
# lax.cond) — so rung choice is a pure perf decision the per-metro
# autotuner (matcher/autotune.py) measures. Lives HERE (not in
# ops/dense_candidates) so config stays jax-import-free.
SWEEP_NJ_CAP_RUNGS = (64, 128, 256)


@dataclass(frozen=True)
class MatcherParams:
    """HMM map-matching parameters (the meili section of valhalla.json).

    Defaults follow Meili's documented defaults (SURVEY.md §2.2: emission =
    Gaussian(GPS error; sigma_z), transition = |route_dist − gc_dist| / beta).
    """

    sigma_z: float = 4.07          # GPS noise std-dev (m), emission model
    beta: float = 3.0              # transition scale (m)
    search_radius: float = 50.0    # candidate search radius (m)
    max_candidates: int = 8        # top-K candidates per point
    candidate_backend: str = "auto"  # "dense" = gather-free pallas sweep
                                   # (ops/dense_candidates.py, ~50x faster
                                   # than gathers on TPU); "grid" = cell-row
                                   # gather (ops/candidates.py, ~50x faster
                                   # than the sweep on CPU); "auto" picks by
                                   # the active jax backend
    sweep_subcull: bool = True     # dense sweep: in-kernel sub-block bbox
                                   # culling + fused narrow top-K (round 8
                                   # kernel). False = the round-7
                                   # whole-block kernel, kept for same-run
                                   # A/B (bench sweep_ab leg). Bit-identical
                                   # either way (test-asserted).
    sweep_lowp: str = "off"        # "bf16" = conservative low-precision
                                   # coarse pair filter with exact f32
                                   # refinement inside surviving sub-blocks
                                   # (also bit-identical — the bf16 pass
                                   # only ever SKIPS provably-out-of-radius
                                   # slices). "off" = f32 only. With
                                   # sweep_mxu=True this selects the MXU
                                   # matmul's operand dtype instead
                                   # ("bf16" = the MXU's native width).
    sweep_mxu: bool = False        # dense sweep: matmul-form coarse pair
                                   # pass on the MXU (round 13 kernel arm)
                                   # — per surviving sub-slice, one
                                   # [P,8]x[8,subw] dot over staged
                                   # quadratic feature rows yields every
                                   # pair's point-to-LINE distance; exact
                                   # f32 geometry + top-K run only on
                                   # slices the coarse pass can't prove
                                   # empty. Bit-identical to the other
                                   # kernel arms by construction
                                   # (test-asserted). Requires
                                   # sweep_subcull=True. Default off
                                   # pending chip numbers (bench sweep_ab
                                   # measures it every run).
    sweep_nj_cap: int = 128        # dense sweep: narrow-grid launch width
                                   # (max culled blocks per chunk before
                                   # the lax.cond falls back to the full-
                                   # width grid — ops/dense_candidates).
                                   # Must be a SWEEP_NJ_CAP_RUNGS rung
                                   # (finite compiled-shape universe);
                                   # exact at any rung, so the autotuner
                                   # may retune it per metro.
    sweep_autotune: bool = True    # per-metro self-tuning (round 17,
                                   # matcher/autotune.py): at staging
                                   # time measure real dispatches per
                                   # (kernel arm, lowp dtype, nj-cap
                                   # rung) on the metro's own tables and
                                   # serve the fastest plan — legal
                                   # because every arm is wire-byte-
                                   # identical (detail.sweep_ab). Only
                                   # acts on accelerator backends with
                                   # the dense sweep resolved and every
                                   # sweep lever still at its default
                                   # (explicit knobs ALWAYS win); CPU
                                   # short-circuits to the grid/auto
                                   # choice. False = static defaults.
    breakage_distance: float = 2000.0  # consecutive points farther apart break the HMM chain
    max_route_distance_factor: float = 5.0  # route dist > factor*gc ⇒ transition disallowed
    interpolation_distance: float = 10.0    # points closer than this are interpolated, not matched
    backward_slack: float = 10.0   # same-edge backward jitter tolerated as zero-cost (m);
                                   # GPS noise shifts projections backwards between samples —
                                   # Meili absorbs this via input interpolation, we absorb it
                                   # in the transition model (ops/hmm.route_distance)
    max_device_batch: int = 4096   # traces per device dispatch. Big enough
                                   # to amortize per-dispatch link
                                   # round-trips, small enough that
                                   # submit-all-then-harvest overlaps device
                                   # compute with result transfers (measured
                                   # optimum on a remote-attached v5e)
    dispatch_timeout_s: float = 0.0  # device-dispatch watchdog: the axon
                                   # tunnel dies by HANGING, not erroring
                                   # (CLAUDE.md), so a wedged dispatch must
                                   # be timed out, not caught. 0 = off (the
                                   # default: zero overhead, zero behavior
                                   # change). On timeout the dispatch raises
                                   # DispatchTimeout (matcher/api.py) —
                                   # streaming releases the wave's held rows
                                   # for retry, the scheduler retries per
                                   # submission. Set it ABOVE the worst-case
                                   # cold jit compile for your shapes (or
                                   # warm up first): the watchdog cannot
                                   # tell a compiling dispatch from a hung
                                   # one, and a too-tight timeout churns
                                   # retries until the cache warms.
    dispatch_fallback: str = "retry"  # what a timed-out dispatch degrades
                                   # to: "retry" = raise and let the caller
                                   # re-flush (bit-identical when the link
                                   # recovers); "reference_cpu" = serve the
                                   # batch from the in-process exact-
                                   # Dijkstra oracle (slow, link-free) —
                                   # graceful degradation when the tunnel
                                   # is gone for good

    def replace(self, **kw: Any) -> "MatcherParams":
        return dataclasses.replace(self, **kw)

    def with_env_overrides(self, env: dict[str, str] | None = None,
                           ) -> "MatcherParams":
        """Kernel-tuning env overrides (the matcher analog of
        ServiceConfig.with_env_overrides): only set variables apply.
        RTPU_SWEEP_SUBCULL=0|1, RTPU_SWEEP_LOWP=off|bf16 and
        RTPU_SWEEP_MXU=0|1 flip the dense-sweep kernel levers without a
        code edit — the on-chip A/B discipline every kernel knob here
        follows (RTPU_SBLK precedent).
        """
        e = os.environ if env is None else env
        kw: dict[str, Any] = {}
        # validate HERE, strictly: overrides apply after Config.validate()
        # in SegmentMatcher, and a typo'd lever that silently fell back to
        # its default would make an on-chip A/B measure an arm against
        # itself and record a bogus 1.0x
        # tracing.env_flag is THE boolean parse (round-14 env-flag lint);
        # strict=True keeps the round-8 fail-loudly contract for typos
        from reporter_tpu.utils.tracing import env_flag

        if "RTPU_SWEEP_SUBCULL" in e:
            try:
                kw["sweep_subcull"] = env_flag(e["RTPU_SWEEP_SUBCULL"],
                                               strict=True)
            except ValueError:
                raise ValueError(
                    f"RTPU_SWEEP_SUBCULL={e['RTPU_SWEEP_SUBCULL']!r}: "
                    "use 0/1") from None
        if "RTPU_SWEEP_LOWP" in e:
            lowp = e["RTPU_SWEEP_LOWP"] or "off"
            if lowp not in ("off", "bf16"):
                raise ValueError(
                    f"RTPU_SWEEP_LOWP={lowp!r}: use 'off' or 'bf16'")
            kw["sweep_lowp"] = lowp
        if "RTPU_SWEEP_MXU" in e:
            try:
                kw["sweep_mxu"] = env_flag(e["RTPU_SWEEP_MXU"], strict=True)
            except ValueError:
                raise ValueError(
                    f"RTPU_SWEEP_MXU={e['RTPU_SWEEP_MXU']!r}: "
                    "use 0/1") from None
        if "RTPU_NJ_CAP" in e:
            try:
                cap = int(e["RTPU_NJ_CAP"])
            except ValueError:
                raise ValueError(
                    f"RTPU_NJ_CAP={e['RTPU_NJ_CAP']!r}: use one of "
                    f"{SWEEP_NJ_CAP_RUNGS}") from None
            if cap not in SWEEP_NJ_CAP_RUNGS:
                # off-ladder caps would grow the compiled-shape universe
                # past the committed manifest — reject, don't round
                raise ValueError(
                    f"RTPU_NJ_CAP={cap}: not a ladder rung "
                    f"{SWEEP_NJ_CAP_RUNGS}")
            kw["sweep_nj_cap"] = cap
        if "RTPU_SWEEP_AUTOTUNE" in e:
            try:
                kw["sweep_autotune"] = env_flag(e["RTPU_SWEEP_AUTOTUNE"],
                                                strict=True)
            except ValueError:
                raise ValueError(
                    f"RTPU_SWEEP_AUTOTUNE={e['RTPU_SWEEP_AUTOTUNE']!r}: "
                    "use 0/1") from None
        if "RTPU_DISPATCH_TIMEOUT_S" in e:
            t = float(e["RTPU_DISPATCH_TIMEOUT_S"])
            if t < 0:
                raise ValueError(
                    f"RTPU_DISPATCH_TIMEOUT_S={t}: must be >= 0")
            kw["dispatch_timeout_s"] = t
        if "RTPU_DISPATCH_FALLBACK" in e:
            fb = e["RTPU_DISPATCH_FALLBACK"] or "retry"
            if fb not in ("retry", "reference_cpu"):
                raise ValueError(
                    f"RTPU_DISPATCH_FALLBACK={fb!r}: use 'retry' or "
                    "'reference_cpu'")
            kw["dispatch_fallback"] = fb
        out = dataclasses.replace(self, **kw) if kw else self
        if out.sweep_lowp == "bf16" and not out.sweep_subcull:
            # only the two-level kernel implements the low-precision
            # pass; accepting the combo would silently run plain f32
            raise ValueError(
                "sweep_lowp='bf16' requires sweep_subcull=True — the "
                "whole-block kernel has no low-precision pass")
        if out.sweep_mxu and not out.sweep_subcull:
            # the MXU coarse pass rides the sub-slice structure
            raise ValueError(
                "sweep_mxu=True requires sweep_subcull=True — the "
                "whole-block kernel has no matmul coarse pass")
        return out

    @classmethod
    def preset(cls, mode: str) -> "MatcherParams":
        """Mode-keyed matcher preset (the reference's per-mode Valhalla
        costing → meili tuning, SURVEY.md §2.1 "mode costing"). GPS noise
        is mode-independent (sigma_z stays), but plausible movement is
        not: slower modes cover less ground between samples, so chain
        breakage and route-deviation tolerances tighten, and the
        candidate radius narrows (a pedestrian 50 m from a path is more
        likely on another path than badly measured).

        Use with a tileset compiled for the same mode
        (``compile_network(net, params, mode=...)``) — the preset tunes
        the HMM; the tileset's subgraph decides legality.
        """
        if mode == "auto":
            return cls()
        if mode == "bicycle":
            return cls(search_radius=40.0, breakage_distance=1200.0,
                       max_route_distance_factor=4.0)
        if mode == "foot":
            return cls(search_radius=30.0, breakage_distance=400.0,
                       max_route_distance_factor=3.0,
                       interpolation_distance=5.0)
        raise ValueError(f"unknown mode {mode!r}; "
                         "one of ['auto', 'bicycle', 'foot']")


@dataclass(frozen=True)
class CompilerParams:
    """Offline tile-compiler parameters (the mjolnir/osmlr analog, SURVEY.md §7.1)."""

    cell_size: float = 64.0        # spatial-grid cell edge (m)
    cell_capacity: int = 64        # max line-segments indexed per cell (padded, -1 sentinel)
    index_radius: float = 50.0     # grid registration dilation (m): every segment is
                                   # indexed in all cells within this distance of its
                                   # bbox, so a query reads ONE cell row and still sees
                                   # every segment within search_radius <= index_radius
    reach_radius: float = 600.0    # reachability precompute radius (m)
    reach_max: int = 128           # max reachable targets kept per NODE row.
                                   # Node-keyed tables make a wide row cheap
                                   # (~3× fewer rows than per-edge); 128
                                   # keeps every audited transition at
                                   # 5s-sparse urban sampling (see
                                   # tiles/reach_audit.py; 32 truncated
                                   # coverage to ~170 m and dropped ~2% of
                                   # oracle-accepted transitions)
    osmlr_max_length: float = 1000.0  # OSMLR segment chaining target length (m)
    use_native: bool = True        # use the C++ reach/grid builder when available


@dataclass(frozen=True)
class ServiceConfig:
    """Service wiring (env-var layer of the reference, SURVEY.md §3.2)."""

    datastore_url: str = ""        # empty ⇒ publishing disabled (logged only)
    port: int = 8002
    threads: int = 4
    cache_ttl: float = 60.0        # per-uuid partial-trace cache TTL (s)
    cache_max_uuids: int = 100_000
    min_segment_length: float = 0.0
    mode: str = "auto"             # report transport mode tag
    # Request batching (service/scheduler.py). "scheduler" = continuous
    # in-flight batching: SLO-deadline batch close, shape-bucketed
    # padding, multiple device batches overlapping the link RTT.
    # "combine" = the round-4 queue-and-combine leader (one batch in
    # flight) — kept for A/B benches and as the conservative fallback.
    batching: str = "scheduler"
    batch_close_ms: float = 5.0    # a partial batch closes this many ms
    #                                after its oldest request was admitted
    #                                (the SLO deadline: a lone request is
    #                                never stuck waiting for peers)
    max_batch_traces: int = 256    # close-by-size threshold (traces)
    max_inflight_batches: int = 2  # device batches allowed in flight —
    #                                the serving twin of streaming's
    #                                pipeline_depth (submit wave N while
    #                                wave N-1 rides the link RTT)
    admission_queue_limit: int = 8192  # queued traces admitted before the
    #                                    service sheds with 503 (bounded
    #                                    memory; counted rejections)
    # Pipelined wave prepare (r22): run the PURE host prepare for wave
    # N+1 (column gather + lonlat→xy + native quantize/pack through the
    # matcher's prepared seam) on a read-ahead thread while wave N
    # occupies the device. Stateful steps (cache merge/retain, commit
    # floor, checkpoint) stay strictly in wave order, so wire bytes and
    # report streams are bit-identical to the serial loop — test- and
    # bench-asserted. False = the serial loop, kept as the same-run A/B
    # arm (r7-scheduler style). Only engages where overlap exists
    # (streaming pipeline_depth >= 1; scheduler prefab path).
    pipeline_prepare: bool = True
    # Publisher resilience (service/datastore.py). Defaults keep the
    # pre-chaos behavior exactly (one attempt, failures counted+dropped):
    # retries/dead-letter are DEPLOYMENT policy, opted into per worker.
    publish_retries: int = 0       # extra POST attempts per batch after
    #                                the first fails (bounded exponential
    #                                backoff with deterministic jitter —
    #                                faults.backoff_schedule)
    publish_backoff_ms: float = 50.0    # backoff base (doubles per retry)
    publish_backoff_cap_ms: float = 2000.0  # backoff ceiling
    publish_backoff_jitter: float = 0.1     # +[0, jitter)x seeded jitter
    # Span tracing / flight recorder (utils/tracing.py). The recorder is
    # PROCESS-GLOBAL (the fault sites in matcher/publisher/scheduler all
    # write the same ring); these knobs only ever turn it ON — an
    # env-enabled recorder (RTPU_TRACE=1) is never disabled by a second
    # component constructed with the defaults.
    trace: bool = False            # record host-side spans (consume /
    #                                prepare / device match / report
    #                                build / publish, wave-tagged).
    #                                Off = one attribute read per call
    #                                site (the 100k+ pps offer must not
    #                                pay for idle observability)
    trace_ring: int = 4096         # flight-recorder span capacity
    trace_dir: str = ""            # non-empty ⇒ post-mortem Chrome-trace
    #                                dumps are written here automatically
    #                                on dispatch-timeout, breaker-open,
    #                                dead-letter, and admission-shed
    #                                events (and on demand via
    #                                tracing.tracer().dump())
    dead_letter_dir: str = ""      # non-empty ⇒ batches that exhaust their
    #                                retries are SPOOLED to disk and
    #                                replayed automatically after the next
    #                                successful POST — an outage sheds to
    #                                disk, not to /dev/null. ONE DIR PER
    #                                WORKER PROCESS (like --checkpoint):
    #                                the spool file carries no inter-
    #                                process locking, and two workers
    #                                sharing it would corrupt each
    #                                other's torn-tail truncation and
    #                                prefix rewrites

    def with_env_overrides(self, env: dict[str, str] | None = None) -> "ServiceConfig":
        """Apply env vars on top of this config; only set variables override."""
        e = os.environ if env is None else env
        kw: dict[str, Any] = {}
        if "DATASTORE_URL" in e:
            kw["datastore_url"] = e["DATASTORE_URL"]
        if "REPORTER_PORT" in e:
            kw["port"] = int(e["REPORTER_PORT"])
        if "THREAD_POOL_COUNT" in e:
            kw["threads"] = int(e["THREAD_POOL_COUNT"])
        if "PARTIAL_TRACE_TTL" in e:
            kw["cache_ttl"] = float(e["PARTIAL_TRACE_TTL"])
        if "REPORTER_MODE" in e:
            kw["mode"] = e["REPORTER_MODE"]
        if "REPORTER_BATCHING" in e:
            kw["batching"] = e["REPORTER_BATCHING"]
        if "REPORTER_BATCH_CLOSE_MS" in e:
            kw["batch_close_ms"] = float(e["REPORTER_BATCH_CLOSE_MS"])
        if "REPORTER_MAX_INFLIGHT" in e:
            kw["max_inflight_batches"] = int(e["REPORTER_MAX_INFLIGHT"])
        if "DATASTORE_RETRIES" in e:
            kw["publish_retries"] = int(e["DATASTORE_RETRIES"])
        if "DATASTORE_BACKOFF_MS" in e:
            kw["publish_backoff_ms"] = float(e["DATASTORE_BACKOFF_MS"])
        if "DATASTORE_DEAD_LETTER_DIR" in e:
            kw["dead_letter_dir"] = e["DATASTORE_DEAD_LETTER_DIR"]
        if "RTPU_PIPELINE_PREPARE" in e:
            from reporter_tpu.utils.tracing import env_flag

            try:
                kw["pipeline_prepare"] = env_flag(
                    e["RTPU_PIPELINE_PREPARE"], strict=True)
            except ValueError:
                raise ValueError(
                    f"RTPU_PIPELINE_PREPARE={e['RTPU_PIPELINE_PREPARE']!r}: "
                    "expected a boolean (1/0/true/false/yes/no/on/off)")
        if "RTPU_TRACE" in e:
            from reporter_tpu.utils.tracing import env_flag

            kw["trace"] = env_flag(e["RTPU_TRACE"])
        if "RTPU_TRACE_RING" in e:
            kw["trace_ring"] = int(e["RTPU_TRACE_RING"])
        if "RTPU_TRACE_DIR" in e:
            kw["trace_dir"] = e["RTPU_TRACE_DIR"]
        return dataclasses.replace(self, **kw) if kw else self

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "ServiceConfig":
        return cls().with_env_overrides(env)


@dataclass(frozen=True)
class StreamingConfig:
    """Streaming-ingest wiring (the reference's Kafka layer analog,
    SURVEY.md §3.3): partition counts and the matcher worker's flush policy
    ("when enough points/time elapsed: Match(buffered trace)")."""

    num_partitions: int = 4        # uuid-hash partitions (Kafka partition analog)
    poll_max_records: int = 4096   # records consumed per partition per step
    flush_min_points: int = 16     # buffered points per uuid that trigger a match
    flush_max_age: float = 30.0    # seconds a buffer may age before forced flush
    speed_bins: tuple[float, ...] = (0., 2.5, 5., 7.5, 10., 12.5, 15., 17.5,
                                     20., 25., 30., 40.)  # m/s histogram edges
    queue_bins: tuple[float, ...] = (0., 10., 25., 50., 100., 200.,
                                     400.)  # meters-of-queue histogram edges
    hist_flush_interval: float = 60.0  # seconds between per-segment speed
                                       # histogram flushes to the datastore
                                       # (0 = manual flush only)
    # Pipelined flush (columnar worker): how many flush waves may be in
    # flight on the device while the main loop keeps consuming and the
    # publisher thread POSTs completed waves. 0 = the sequential
    # consume→match→publish loop (the dict worker's only shape); 1 =
    # double buffering, the firehose deployment default — per-wave link
    # RTT and datastore RTT amortize across waves instead of serializing.
    pipeline_depth: int = 1
    # Adaptive wave sizing (columnar worker, opt-in): the controller
    # raises the effective flush_min_points while broker lag is rising
    # (bigger waves amortize per-flush overheads) and decays it toward
    # wave_target_latency once caught up (smaller waves bound the
    # probe→report buffer wait). flush_min_points is the starting point.
    wave_autotune: bool = False
    wave_min_points: int = 16          # controller floor (points/vehicle)
    wave_max_points: int = 960         # controller ceiling
    wave_target_latency: float = 2.0   # p50 probe→report target (s)


@dataclass(frozen=True)
class Config:
    """Top-level structured config (the valhalla.json analog)."""

    matcher: MatcherParams = field(default_factory=MatcherParams)
    compiler: CompilerParams = field(default_factory=CompilerParams)
    service: ServiceConfig = field(default_factory=ServiceConfig)
    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    matcher_backend: str = "jax"   # {"jax", "reference_cpu"} — the backend boundary

    @classmethod
    def for_mode(cls, mode: str, **kw: Any) -> "Config":
        """Config serving one transport mode: the mode-keyed MatcherParams
        preset + the service mode tag (reports carry it; requests naming a
        different mode are rejected). Pair with a tileset compiled via
        ``compile_network(net, params, mode=...)`` — one deployment serves
        one mode, like the reference's per-mode valhalla config."""
        p = MatcherParams.preset(mode)    # validates the mode name
        svc = dataclasses.replace(kw.pop("service", ServiceConfig()),
                                  mode=mode)
        return cls(matcher=p, service=svc, **kw)

    def validate(self) -> "Config":
        """Cross-section invariants. The grid's single-cell candidate gather
        is only a superset of the radius ball when segment registration was
        dilated by at least the search radius (tiles/compiler._build_grid);
        the dense sweep visits every in-radius segment regardless."""
        if self.matcher.candidate_backend not in ("auto", "dense", "grid"):
            raise ValueError(
                f"unknown candidate_backend "
                f"{self.matcher.candidate_backend!r}; "
                "use 'auto', 'dense' or 'grid'")
        # Early error for explicitly-grid configs only: "auto" may resolve
        # to dense (no coverage requirement), and the authoritative check
        # against the ACTUAL tileset's index_radius happens at trace time
        # (ops/match._check_grid_coverage) — this one guards the common
        # case where one Config drives both compiler and matcher.
        if self.matcher.sweep_lowp not in ("off", "bf16"):
            raise ValueError(
                f"unknown matcher.sweep_lowp {self.matcher.sweep_lowp!r}; "
                "use 'off' or 'bf16'")
        if self.matcher.sweep_lowp == "bf16" and not self.matcher.sweep_subcull:
            raise ValueError(
                "matcher.sweep_lowp='bf16' requires sweep_subcull=True — "
                "the whole-block kernel has no low-precision pass")
        if self.matcher.sweep_mxu and not self.matcher.sweep_subcull:
            raise ValueError(
                "matcher.sweep_mxu=True requires sweep_subcull=True — "
                "the whole-block kernel has no matmul coarse pass")
        if self.matcher.sweep_nj_cap not in SWEEP_NJ_CAP_RUNGS:
            raise ValueError(
                f"matcher.sweep_nj_cap ({self.matcher.sweep_nj_cap}) is "
                f"not a ladder rung {SWEEP_NJ_CAP_RUNGS} — off-ladder "
                "caps grow the compiled-shape universe past the "
                "committed manifest")
        if (self.matcher.candidate_backend == "grid"
                and self.compiler.index_radius < self.matcher.search_radius):
            raise ValueError(
                f"compiler.index_radius ({self.compiler.index_radius}) must be "
                f">= matcher.search_radius ({self.matcher.search_radius}) for "
                "the single-cell grid gather to cover the search radius")
        if self.matcher_backend not in ("jax", "reference_cpu"):
            raise ValueError(f"unknown matcher_backend {self.matcher_backend!r}")
        svc = self.service
        if svc.batching not in ("scheduler", "combine"):
            raise ValueError(f"unknown service.batching {svc.batching!r}; "
                             "use 'scheduler' or 'combine'")
        if svc.batch_close_ms <= 0:
            raise ValueError("service.batch_close_ms must be > 0")
        if svc.max_batch_traces < 1 or svc.max_inflight_batches < 1:
            raise ValueError("service.max_batch_traces and "
                             "service.max_inflight_batches must be >= 1")
        if svc.admission_queue_limit < 1:
            raise ValueError("service.admission_queue_limit must be >= 1")
        if svc.publish_retries < 0:
            raise ValueError("service.publish_retries must be >= 0")
        if svc.publish_backoff_ms <= 0 or svc.publish_backoff_cap_ms <= 0:
            raise ValueError("service.publish_backoff_ms and "
                             "publish_backoff_cap_ms must be > 0")
        if svc.publish_backoff_jitter < 0:
            raise ValueError("service.publish_backoff_jitter must be >= 0")
        if svc.trace_ring < 1:
            raise ValueError("service.trace_ring must be >= 1")
        if self.matcher.dispatch_timeout_s < 0:
            raise ValueError("matcher.dispatch_timeout_s must be >= 0")
        if self.matcher.dispatch_fallback not in ("retry", "reference_cpu"):
            raise ValueError(
                f"unknown matcher.dispatch_fallback "
                f"{self.matcher.dispatch_fallback!r}; use 'retry' or "
                "'reference_cpu'")
        s = self.streaming
        if s.num_partitions < 1 or s.poll_max_records < 1 or s.flush_min_points < 1:
            raise ValueError(
                "streaming num_partitions / poll_max_records / "
                "flush_min_points must all be >= 1")
        if s.flush_max_age <= 0:
            raise ValueError("streaming.flush_max_age must be > 0")
        if s.pipeline_depth < 0:
            raise ValueError("streaming.pipeline_depth must be >= 0")
        if not (1 <= s.wave_min_points <= s.wave_max_points):
            raise ValueError(
                "streaming wave bounds need 1 <= wave_min_points "
                "<= wave_max_points")
        if s.wave_target_latency <= 0:
            raise ValueError("streaming.wave_target_latency must be > 0")
        for bins in ("speed_bins", "queue_bins"):
            edges = getattr(s, bins)
            if len(edges) < 1 or list(edges) != sorted(set(edges)):
                raise ValueError(f"streaming.{bins} must be strictly ascending")
        return self

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Config":
        raw = json.loads(text)
        streaming = dict(raw.get("streaming", {}))
        for bins in ("speed_bins", "queue_bins"):
            if bins in streaming:
                streaming[bins] = tuple(streaming[bins])
        return cls(
            matcher=MatcherParams(**raw.get("matcher", {})),
            compiler=CompilerParams(**raw.get("compiler", {})),
            service=ServiceConfig(**raw.get("service", {})),
            streaming=StreamingConfig(**streaming),
            matcher_backend=raw.get("matcher_backend", "jax"),
        )

    @classmethod
    def load(cls, path: str | None = None) -> "Config":
        """Load from a JSON file if given/exists; env vars that are actually
        set override the file's service section (the reference's two-layer
        precedence, SURVEY.md §5)."""
        if path and os.path.exists(path):
            with open(path) as f:
                cfg = cls.from_json(f.read())
        else:
            cfg = cls()
        cfg = dataclasses.replace(cfg,
                                  service=cfg.service.with_env_overrides(),
                                  matcher=cfg.matcher.with_env_overrides())
        return cfg.validate()
