"""DurableColumnarIngestQueue — file-backed columnar probe log.

The columnar twin of streaming/durable_queue.DurableIngestQueue (same
recovery model: "the buffer is derived state, the log is the truth", same
crash discipline), storing BATCHES instead of JSON lines so the durable
path keeps the columnar broker's unit of work. Layout under ``dir/``: one
append-only file per partition (``p0.colog`` …) of length-prefixed
frames; frame 0 is a JSON header ``{"_floor": N}`` (the partition's base
offset — the single authoritative offset field) and every later frame is
one npz-compressed ProbeColumns sub-batch. Retention
rewrites the file (header + surviving batches) through one atomic
``os.replace``, so floor and content can never desync. A torn final
frame (killed mid-write) is dropped on reload and truncated from the
file before the append handle reopens.

Broker directories are FORMAT-SPECIFIC: ``meta.json`` pins both the
partition count and ``format: columnar``, and a reopen with the dict
broker class (or vice versa) is refused instead of mis-parsed.

Durability level matches the dict broker's default: appends flush to the
OS per call (crash-safe against process death); pass ``fsync=True`` for
power-loss safety per append.
"""

from __future__ import annotations

import io
import json
import os
import struct

import numpy as np

from reporter_tpu.streaming.columnar import ColumnarIngestQueue, ProbeColumns
from reporter_tpu.streaming.durable_queue import open_or_create_meta

_LEN = struct.Struct(">Q")


def _encode_batch(cols: ProbeColumns) -> bytes:
    buf = io.BytesIO()
    # Normalize dtypes at the WRITE side: an object-dtype uuid column
    # (legal from a direct columnar producer) would savez as a pickle,
    # which the pickle-refusing decode below then treats as a torn tail —
    # silently truncating acked data on reload.
    np.savez_compressed(
        buf, uuid=np.asarray(cols.uuid, np.str_),
        lat=np.asarray(cols.lat, np.float64),
        lon=np.asarray(cols.lon, np.float64),
        time=np.asarray(cols.time, np.float64),
        accuracy=np.asarray(cols.accuracy, np.float32))
    blob = buf.getvalue()
    return _LEN.pack(len(blob)) + blob


def _decode_batch(blob: bytes) -> ProbeColumns:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        return ProbeColumns(z["uuid"], z["lat"], z["lon"], z["time"],
                            z["accuracy"])


class DurableColumnarIngestQueue(ColumnarIngestQueue):
    """ColumnarIngestQueue whose batch log survives the process."""

    def __init__(self, dir: str, num_partitions: int = 4,
                 fsync: bool = False,
                 max_records_per_partition: "int | None" = None,
                 overload_policy: str = "reject"):
        super().__init__(num_partitions, max_records_per_partition,
                         overload_policy)
        self.dir = dir
        self._fsync = bool(fsync)
        open_or_create_meta(dir, "columnar", self.num_partitions,
                            other_class="DurableIngestQueue")
        self._files = []
        for p in range(self.num_partitions):
            good = self._load_partition(p)
            path = self._log_path(p)
            if os.path.exists(path) and os.path.getsize(path) > good:
                with open(path, "rb+") as f:
                    f.truncate(good)      # cut the torn tail from the FILE
            self._files.append(open(path, "ab"))

    # ---- persistence ----------------------------------------------------

    def _log_path(self, p: int) -> str:
        return os.path.join(self.dir, f"p{p}.colog")

    def _load_partition(self, p: int) -> int:
        """Rebuild partition p in memory; returns the byte length of the
        valid frame prefix."""
        path = self._log_path(p)
        if not os.path.exists(path):
            return 0
        good = 0
        first = True
        with open(path, "rb") as f:
            data = f.read()
        i = 0
        while i + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, i)
            if i + _LEN.size + n > len(data):
                break                     # torn tail from a mid-write crash
            blob = data[i + _LEN.size:i + _LEN.size + n]
            try:
                if first:
                    hdr = json.loads(blob)
                    self._floor[p] = int(hdr["_floor"])
                    self._end[p] = int(hdr["_floor"])
                else:
                    cols = _decode_batch(blob)
                    self._bases[p].append(self._end[p])
                    self._batches[p].append(cols)
                    self._end[p] += cols.n
            except Exception:
                break                     # corrupt tail: stop at last good
            first = False
            i += _LEN.size + n
            good = i
        if first:
            return 0                      # empty/unreadable: fresh file
        return good

    def close(self) -> None:
        with self._lock:
            for f in self._files:
                f.close()
            self._files = []

    # ---- ColumnarIngestQueue durability hooks (run under the lock) ------

    def _persist_batch(self, p: int, cols: ProbeColumns) -> None:
        from reporter_tpu import faults

        f = self._files[p]
        if f.tell() == 0:                 # fresh file: header frame first
            hdr = json.dumps({"_floor": self._floor[p]}).encode()
            f.write(_LEN.pack(len(hdr)) + hdr)
        frame = _encode_batch(cols)
        rule = faults.check("broker")
        if rule is not None and rule.kind == "torn":
            # injected mid-append death: half a frame reaches disk, then
            # the "process" dies — the torn-tail reload path must drop
            # exactly this frame and keep every acked one before it
            f.write(frame[:len(frame) // 2])
            f.flush()
            raise faults.InjectedCrash(
                f"injected torn append (partition {p})")
        if rule is not None and rule.kind in ("crash", "fail"):
            raise faults.InjectedCrash(
                f"injected broker append crash (partition {p})")
        f.write(frame)
        f.flush()
        if self._fsync:
            os.fsync(f.fileno())

    def _persist_truncate(self, p: int) -> None:
        """Rewrite the partition log as header + surviving batches in one
        atomic rename — floor and content can never desync."""
        self._files[p].close()
        tmp = self._log_path(p) + ".tmp"
        with open(tmp, "wb") as f:
            hdr = json.dumps({"_floor": self._floor[p]}).encode()
            f.write(_LEN.pack(len(hdr)) + hdr)
            for cols in self._batches[p]:
                f.write(_encode_batch(cols))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path(p))
        if self._fsync:
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._files[p] = open(self._log_path(p), "ab")
