"""Probe formatter — the reference's raw→formatted normalization stage.

The reference's Kafka pipeline interposes a formatter worker between the
raw probe topic and the matcher workers (SURVEY.md §2.1 "Kafka streaming
workers": consume raw probe messages; *normalize/format*; partition by
uuid): vendors deliver probes as CSV lines, differently-keyed JSON, or
nested envelopes, and only canonical records reach the matcher. This
module is that stage: ``ProbeFormatter.normalize`` maps one raw vendor
payload to the canonical record the pipeline buffers
(``{"uuid", "lat", "lon", "time"[, "accuracy"]}``), and ``format_stream``
pumps raw payloads into a broker, preserving the invariant the rest of
the system relies on — records are partitioned by uuid AFTER
normalization, so one vehicle's stream lands in one partition regardless
of the vendor format it arrived in.

Formats are pluggable: built-ins cover canonical JSON dicts, flat CSV
lines, and common vendor field aliases; ``register`` adds new ones
without touching the pipeline. Malformed payloads return None and are
counted — the formatter drops them so a poison vendor message can never
wedge a partition (the same stance StreamPipeline takes post-broker).
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable

# one raw payload → canonical record dict, or None when not this format
FormatFn = Callable[[Any], "dict | None"]

_ALIASES = {
    "uuid": ("uuid", "id", "vehicle_id", "device_id", "driver_id"),
    "lat": ("lat", "latitude", "y"),
    "lon": ("lon", "lng", "longitude", "x"),
    "time": ("time", "timestamp", "ts", "t", "recorded_at"),
    "accuracy": ("accuracy", "acc", "hdop_m", "horizontal_accuracy"),
}


def _finite(v) -> "float | None":
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def _from_mapping(obj: "dict[str, Any]") -> "dict | None":
    """Canonical + alias-keyed flat dicts, and one nested-envelope level
    ({"location": {"lat": .., "lon": ..}, ...})."""
    loc = obj.get("location")
    if isinstance(loc, dict):
        obj = {**obj, **loc}
    rec: dict = {}
    for field, names in _ALIASES.items():
        # first alias with a USABLE value wins — a present-but-invalid
        # alias (e.g. "lat": null beside "latitude": 37.75) must not
        # shadow a later valid one
        for n in names:
            if n not in obj:
                continue
            if field == "uuid":
                # None must fall through to the next alias, not become the
                # literal "None" (which would merge every null-uuid vehicle
                # into one phantom stream)
                if obj[n] is None:
                    continue
                u = str(obj[n]).strip()
                if u:
                    rec["uuid"] = u
                    break
            else:
                v = _finite(obj[n])
                if v is not None:
                    rec[field] = v
                    break
    if "uuid" not in rec or "lat" not in rec or "lon" not in rec:
        return None
    if "accuracy" in rec and rec["accuracy"] < 0:
        del rec["accuracy"]
    return rec


def _from_csv(line: str) -> "dict | None":
    """``uuid,lat,lon,time[,accuracy]`` — the flat vendor CSV shape."""
    parts = [p.strip() for p in line.split(",")]
    if len(parts) < 3 or not parts[0]:
        return None
    lat, lon = _finite(parts[1]), _finite(parts[2])
    if lat is None or lon is None:
        return None
    rec = {"uuid": parts[0], "lat": lat, "lon": lon}
    if len(parts) > 3:
        t = _finite(parts[3])
        if t is not None:       # unparseable time degrades to a timeless
            rec["time"] = t     # record (like the mapping path), the
                                # pipeline assigns index seconds
    if len(parts) > 4:
        acc = _finite(parts[4])
        if acc is not None and acc >= 0:
            rec["accuracy"] = acc
    return rec


def _default_formats() -> "dict[str, FormatFn]":
    def auto(payload):
        if isinstance(payload, dict):
            return _from_mapping(payload)
        if isinstance(payload, (bytes, bytearray)):
            try:
                payload = payload.decode("utf-8")
            except UnicodeDecodeError:
                return None
        if isinstance(payload, str):
            s = payload.strip()
            if s.startswith("{"):
                try:
                    obj = json.loads(s)
                except json.JSONDecodeError:
                    return None
                return _from_mapping(obj) if isinstance(obj, dict) else None
            return _from_csv(s)
        return None

    def json_only(payload):
        """Pinned JSON contract: a dict, or a string/bytes holding a JSON
        object — anything else (CSV lines included) is malformed, not
        silently re-interpreted."""
        if isinstance(payload, dict):
            return _from_mapping(payload)
        if isinstance(payload, (bytes, bytearray)):
            try:
                payload = payload.decode("utf-8")
            except UnicodeDecodeError:
                return None
        if isinstance(payload, str):
            try:
                obj = json.loads(payload)
            except json.JSONDecodeError:
                return None
            return _from_mapping(obj) if isinstance(obj, dict) else None
        return None

    return {"auto": auto, "json": json_only, "csv": _from_csv}


class ProbeFormatter:
    """Normalizes raw vendor payloads into canonical probe records."""

    def __init__(self, fmt: str = "auto"):
        self._formats = _default_formats()
        self.fmt = fmt
        if fmt not in self._formats:
            raise ValueError(f"unknown format {fmt!r}; "
                             f"have {sorted(self._formats)}")
        self.normalized = 0
        self.dropped = 0

    def register(self, name: str, fn: FormatFn) -> None:
        """Plug in a vendor-specific format (fn: payload → record|None)."""
        self._formats[name] = fn

    def normalize(self, payload: Any, fmt: "str | None" = None,
                  ) -> "dict | None":
        name = fmt or self.fmt
        if name not in self._formats:   # per-call override gets the same
            raise ValueError(           # validation the constructor does
                f"unknown format {name!r}; have {sorted(self._formats)}")
        try:
            rec = self._formats[name](payload)
        except Exception:
            # a poison payload (or a buggy registered format fn) must
            # never wedge the stream — drop and count, as documented
            rec = None
        if rec is None:
            self.dropped += 1
        else:
            self.normalized += 1
        return rec

    def normalize_columns(self, payloads, fmt: "str | None" = None):
        """Normalize raw payloads into ONE ProbeColumns batch (the
        columnar ingest path, streaming/columnar.py). Vendor parsing is
        inherently per-payload Python — the win is downstream: the batch
        enters the broker and the matcher worker as flat columns, so the
        per-record cost stops at this (formatter-worker) stage instead of
        riding the matcher worker's core."""
        from reporter_tpu.streaming.columnar import pack_records

        recs = []
        for p in payloads:
            rec = self.normalize(p, fmt)
            if rec is not None:
                recs.append(rec)
        return pack_records(recs)

    def format_stream(self, payloads, queue, fmt: "str | None" = None,
                      ) -> int:
        """Normalize raw payloads into ``queue`` (any object with the
        IngestQueue producer surface — records route by uuid AFTER
        normalization). Returns the number of records appended."""
        n = 0
        for p in payloads:
            rec = self.normalize(p, fmt)
            if rec is not None:
                queue.append(rec)
                n += 1
        return n

    def format_stream_columns(self, payloads, queue,
                              fmt: "str | None" = None,
                              chunk: int = 4096) -> int:
        """format_stream's batch sibling for columnar brokers: normalize
        ``chunk`` payloads at a time and append each chunk as ONE column
        batch (queue.append_columns), so the durable log stores column
        frames instead of one frame per record. Returns records appended."""
        n = 0
        pending: list = []

        def flush():
            nonlocal n
            cols = self.normalize_columns(pending, fmt)
            queue.append_columns(cols)
            n += cols.n
            pending.clear()

        for p in payloads:
            pending.append(p)
            if len(pending) >= chunk:
                flush()
        if pending:
            flush()
        return n

    def stats(self) -> dict:
        return {"normalized": self.normalized, "dropped": self.dropped}
