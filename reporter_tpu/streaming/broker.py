"""ProbeConsumer — the broker-consumer protocol StreamPipeline depends on.

The reference consumes probe records from Kafka (SURVEY.md §3.3); this
environment has no broker, so the in-proc ``IngestQueue`` stands in. The
seam between the two is this protocol: everything the matcher worker needs
from a broker is offset-addressed polling over a fixed partition count.
An external adapter (kafka-python / confluent-kafka / PubSub) plugs into
``StreamPipeline(queue=...)`` by implementing these three members — no
pipeline changes:

  =================  ================================================
  protocol member    Kafka equivalent
  =================  ================================================
  num_partitions     partition count of the subscribed topic
  poll(p, off, n)    seek(TopicPartition(p), off) + poll(max_records=n)
  end_offset(p)      end_offsets([TopicPartition(p)])
  =================  ================================================

Offset semantics the pipeline relies on (contract-tested by
tests/test_broker_contract.py, which external adapters should reuse):

- Offsets are per-partition, dense, and stable: the record first seen at
  (p, off) is returned for every later poll covering off (replay is the
  recovery mechanism — at-least-once delivery).
- ``poll`` returns records in offset order, at most ``max_records``,
  starting at exactly ``offset``; an empty list past the end.
- ``end_offset`` is one past the last record (== the next offset to be
  assigned), so ``end_offset - committed`` is the lag.
- Polling below the retention floor raises ``LookupError`` (Kafka's
  OffsetOutOfRange). If the broker also offers ``retention_floor(p)``,
  the pipelines treat the raise as an overload shed (drop-oldest
  policy): they skip to the floor and COUNT the gap in their ``overrun``
  stat — the auto.offset.reset=earliest analog, explicit instead of
  silent. Without that accessor the raise stays unrecoverable data loss.

Bounded brokers (optional, for overload safety): the in-proc queues
accept ``max_records_per_partition`` + ``overload_policy`` ("reject" =
producer-side refusal, counted in ``rejected``; "drop_oldest" = floor
advances past aged records, counted in ``dropped_oldest``) and expose
``overload_stats()``, which the pipelines merge into their /stats
surface. An external adapter may implement the same members; the
pipelines only require the three-member core above.

Commit state intentionally lives in StreamPipeline (its commit floor is
the oldest *unflushed* record, a property of the matcher's buffers, not of
the broker); an adapter that mirrors commits to the broker's consumer
group can read ``pipeline.committed`` after each step.

Trace metadata (round 19, optional): a producer may stamp a record with
``tracing.stamp_record(record, trace_id)`` — one extra dict key
(``tracing.TRACE_KEY``) carrying ``{"id", "ts"}`` — before appending.
Record-format brokers store dicts verbatim, so the metadata rides the
log untouched; format-pinned directories stay compatible in BOTH
directions because an absent key reads as "untraced" and an unknown key
is ignored by every validator (the Kafka-headers analog: metadata
beside the payload, never inside it). Consumers that recognize the key
tag their spans with the inherited id (StreamPipeline), which is what
lets distributed/stitch.py merge producer and worker flight-recorder
dumps into one causal per-probe track across pids. The columnar broker
stores five fixed columns and deliberately does NOT carry the key —
trace stitching is a record-broker affordance; a columnar topology
still aggregates metrics and events, just without per-probe flows.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class ProbeConsumer(Protocol):
    """What StreamPipeline polls (see module docstring for semantics)."""

    num_partitions: int

    def poll(self, partition: int, offset: int,
             max_records: int) -> "list[tuple[int, dict]]":
        """Records at/after ``offset`` as [(offset, record)...], in offset
        order, at most ``max_records``; raises LookupError below the
        retention floor."""
        ...

    def end_offset(self, partition: int) -> int:
        """One past the last record of the partition."""
        ...
