"""StreamPipeline — the matcher worker of the streaming path.

Mirrors the reference's Kafka matcher worker (SURVEY.md §3.3): consume
partitions, buffer points per uuid, and "when enough points/time elapsed"
flush the buffered trace through the same match→filter→publish pipeline the
HTTP service uses (ReporterApp — one code path for both ingest modes, like
the reference's shared segment_matcher call).

Recovery model (SURVEY.md §5 "Failure detection"): offsets are committed
only up to the oldest record still sitting in a buffer, so a crash +
restore replays exactly the unflushed tail — at-least-once, duplicates
possible, loss impossible (the reference accepts the same semantics from
Kafka consumer groups; we improve on its lost-cache behavior by
checkpointing buffers and histograms too).
"""

from __future__ import annotations

import math
import time
from typing import Any, Sequence

import numpy as np

from reporter_tpu.config import Config
from reporter_tpu.service.app import ReporterApp
from reporter_tpu.service.datastore import Transport
from reporter_tpu.streaming.broker import ProbeConsumer
from reporter_tpu.streaming.histogram import SpeedHistogram
from reporter_tpu.streaming.queue import IngestQueue
from reporter_tpu.tiles.tileset import TileSet
from reporter_tpu.utils import tracing

# inherited trace ids recorded per span are BOUNDED: a wave may cover
# thousands of probes, and span args must stay a small payload (the
# traced count rides alongside so truncation is visible)
_TRACE_IDS_PER_SPAN = 8


class _Buffer:
    __slots__ = ("points", "first_offset", "born", "trace_ids", "traced")

    def __init__(self, born: float):
        self.points: list[dict] = []
        self.first_offset: "tuple[int, int] | None" = None  # (partition, offset)
        self.born = born
        self.trace_ids: list[str] = []   # inherited broker trace ids
        self.traced = 0                  # (bounded list + full count)


class StreamPipeline:
    """Single-worker streaming matcher over an IngestQueue."""

    def __init__(self, tileset: TileSet, config: Config | None = None,
                 queue: ProbeConsumer | None = None,
                 transport: Transport | None = None,
                 clock=time.monotonic,
                 partitions: "Sequence[int] | None" = None,
                 mesh=None):
        self.config = (config or Config()).validate()
        sc = self.config.streaming
        # Any ProbeConsumer works here (streaming/broker.py): the in-proc
        # IngestQueue is the default; an external Kafka/PubSub adapter
        # implementing the same poll/end_offset surface drops in.
        self.queue: ProbeConsumer = queue or IngestQueue(sc.num_partitions)
        if self.queue.num_partitions != sc.num_partitions:
            raise ValueError("queue/config partition count mismatch")
        # Partition assignment (Kafka consumer-group analog, SURVEY.md §3.3):
        # each worker owns a disjoint subset; uuid-hash routing guarantees a
        # vehicle's records live in exactly one partition, so per-worker
        # buffers never overlap. Reassigning a dead worker's partitions to a
        # live pipeline (constructed at the dead worker's committed offsets)
        # replays its unflushed tail — at-least-once, like a group rebalance.
        owned = range(sc.num_partitions) if partitions is None else partitions
        self.partitions = sorted(set(int(p) for p in owned))
        if any(p < 0 or p >= sc.num_partitions for p in self.partitions):
            raise ValueError(
                f"partitions {self.partitions} out of range "
                f"0..{sc.num_partitions - 1}")
        # The flush loop is a single-threaded internal caller: the serving
        # scheduler's SLO close wait (batch_close_ms) and executor handoff
        # would tax every flush for zero concurrency benefit — pin the
        # embedded app to the direct combine path (the worker's OWN
        # overlap machinery is the pipelined columnar flush).
        import dataclasses as _dc

        app_cfg = _dc.replace(
            self.config,
            service=_dc.replace(self.config.service, batching="combine"))
        self.app = ReporterApp(tileset, app_cfg, transport=transport,
                               mesh=mesh)
        self.clock = clock
        self.committed = [0] * sc.num_partitions
        self._consumed = [0] * sc.num_partitions   # read position (ahead of committed)
        self._buffers: dict[str, _Buffer] = {}
        self.hist = SpeedHistogram(len(tileset.osmlr_id), sc.speed_bins)
        # Same device-resident accumulator, binned by queue_length (meters
        # backed up from the stop line) — every report contributes one
        # observation, so bin 0 counts queue-free traversals too.
        self.qhist = SpeedHistogram(len(tileset.osmlr_id), sc.queue_bins)
        self._row_of = {int(sid): i for i, sid in enumerate(tileset.osmlr_id)}
        self._osmlr_ids = np.asarray(tileset.osmlr_id)
        self._hist_flushed = self.hist.snapshot()   # delta-flush baseline
        self._qhist_flushed = self.qhist.snapshot()
        self._hist_flush_at = self.clock()
        self.hist_flushes = 0
        self.steps = 0
        self.malformed = 0
        self.overrun = 0    # records lost to broker drop-oldest shed
        # broker-propagated trace stitching (round 19): spans this
        # worker records carry the trace ids inherited from producer-
        # stamped records, so distributed/stitch.py can thread a
        # probe's producer→worker path across pids
        self._tracer = tracing.tracer()
        self.traced_records = 0

    @property
    def publisher(self):
        """The app's datastore publisher (shared state.py helpers address
        the publisher uniformly across both pipeline flavors)."""
        return self.app.publisher

    # ---- one poll/flush cycle -------------------------------------------

    def step(self, force_flush: bool = False) -> int:
        """Consume available records, flush ripe buffers, commit offsets.

        Returns the number of reports produced this step.
        """
        from reporter_tpu.streaming.state import poll_with_overrun_skip

        sc = self.config.streaming
        with self._tracer.span("consume"):
            for p in self.partitions:
                pairs = poll_with_overrun_skip(
                    self, lambda pp, off, n: self.queue.poll(pp, off, n),
                    p, sc.poll_max_records)
                for off, rec in pairs:
                    self._consume(p, off, rec)
                    self._consumed[p] = off + 1

        now = self.clock()
        ripe = [u for u, b in self._buffers.items()
                if force_flush
                or len(b.points) >= sc.flush_min_points
                or (b.points and now - b.born >= sc.flush_max_age)]
        n_reports = self._flush(ripe) if ripe else 0
        self._commit()
        if (sc.hist_flush_interval > 0
                and now - self._hist_flush_at >= sc.hist_flush_interval):
            self.flush_histograms()
        self.steps += 1
        return n_reports

    def drain(self) -> int:
        """Flush everything (shutdown path)."""
        return self.step(force_flush=True)

    def _consume(self, p: int, off: int, rec: dict) -> None:
        uuid = str(rec.get("uuid", ""))
        try:
            # Full conversion before any state change: a poison record must
            # be droppable, never allowed to wedge its partition. Finiteness
            # included: float('nan') converts fine here but would fail the
            # service validator at FLUSH time, where the points are already
            # buffered and a raising flush retries forever.
            lat = float(rec["lat"])
            lon = float(rec["lon"])
            t = float(rec["time"]) if "time" in rec else None
            if not (math.isfinite(lat) and math.isfinite(lon)
                    and (t is None or math.isfinite(t))):
                raise ValueError("non-finite coordinate")
        except (KeyError, TypeError, ValueError):
            self.malformed += 1
            return                                   # malformed: skip
        if not uuid:
            self.malformed += 1
            return
        buf = self._buffers.get(uuid)
        if buf is None:
            buf = self._buffers[uuid] = _Buffer(self.clock())
        if buf.first_offset is None:
            buf.first_offset = (p, off)
        tid = tracing.trace_id_of(rec)
        if tid is not None:
            self.traced_records += 1
            buf.traced += 1
            if len(buf.trace_ids) < _TRACE_IDS_PER_SPAN:
                buf.trace_ids.append(tid)
        if t is None:
            # Timeless producer: index seconds per trace, matching the HTTP
            # path's convention (app._validate_payload), not the partition
            # offset (which interleaves across uuids).
            t = float(len(buf.points))
        point = {"lat": lat, "lon": lon, "time": t}
        if "accuracy" in rec:   # same optional field the HTTP path keeps
            try:
                acc = float(rec["accuracy"])
                if acc >= 0 and math.isfinite(acc):
                    point["accuracy"] = acc
                # negative OR non-finite would 400 the whole flush at
                # _validate_payload, and match-before-drop would retry
                # that 400 forever — drop the FIELD, keep the point
                # (it is advisory weighting)
            except (TypeError, ValueError):
                pass
        buf.points.append(point)

    def _flush(self, uuids: list[str]) -> int:
        payloads = [{"uuid": u, "trace": self._buffers[u].points}
                    for u in uuids]
        # inherited trace context (bounded) gathered BEFORE the buffers
        # are dropped — the worker_match span below is the event
        # stitch.py threads into the producer's causal track
        span_args: dict = {}
        if self._tracer.enabled:
            ids: list = []
            traced = 0
            for u in uuids:
                b = self._buffers[u]
                traced += b.traced
                if len(ids) < _TRACE_IDS_PER_SPAN:
                    ids.extend(b.trace_ids[:_TRACE_IDS_PER_SPAN
                                           - len(ids)])
            if traced:
                span_args = {"trace_ids": ids, "traced": traced}
        # Match BEFORE dropping buffers: if the matcher or publisher raises,
        # the points stay buffered and keep holding the commit floor down —
        # a supervisor retrying step() re-flushes instead of losing them.
        with self._tracer.span("worker_match", **span_args):
            results = self.app.report_many(payloads)
        for u in uuids:
            self._buffers.pop(u, None)
        n = 0
        rows: list[int] = []
        speeds: list[float] = []
        queues: list[float] = []
        for res in results:
            reports = res["reports"]
            n += len(reports)
            for r in reports:
                dur = r["t1"] - r["t0"]
                if dur <= 0:
                    continue
                rows.append(self._row_of.get(int(r["id"]), -1))
                speeds.append(r["length"] / dur)
                queues.append(r["queue_length"])
        rows_arr = np.asarray(rows, np.int32)
        self.hist.update(rows_arr, np.asarray(speeds, np.float64))
        self.qhist.update(rows_arr, np.asarray(queues, np.float64))
        return n

    def _commit(self) -> None:
        """Advance committed offsets to the oldest still-buffered record
        (shared floor rule — streaming/state.commit_floor)."""
        from reporter_tpu.streaming.state import commit_floor
        self.committed = commit_floor(
            self._consumed,
            (b.first_offset for b in self._buffers.values()
             if b.first_offset is not None))

    # ---- elastic membership (round 23: distributed/lease.py) -------------

    def adopt_partition(self, partition: int, offset: int) -> None:
        """Start consuming ``partition`` at ``offset`` — the lease
        table's committed floor, so a rebalanced partition replays
        exactly the previous owner's unflushed tail (at-least-once,
        zero loss). Adopting an already-owned partition is a caller
        bug: the lease protocol guarantees single ownership."""
        sc = self.config.streaming
        p = int(partition)
        if p < 0 or p >= sc.num_partitions:
            raise ValueError(f"partition {p} out of range "
                             f"0..{sc.num_partitions - 1}")
        if p in self.partitions:
            raise ValueError(f"partition {p} already owned")
        self.partitions = sorted(self.partitions + [p])
        self.committed[p] = int(offset)
        self._consumed[p] = int(offset)

    def release_partition(self, partition: int, flush: bool = True) -> int:
        """Stop consuming ``partition``. ``flush=True`` is the graceful
        handoff: its buffered rows go through the matcher first so the
        final committed floor covers them. ``flush=False`` is the
        lost-lease path: buffered rows are DISCARDED (the new owner
        replays them from the table's floor; publishing here would
        duplicate reports). Returns the number of points discarded
        (always 0 on the flush path). uuid-hash routing pins a
        vehicle's records to one partition, so ``first_offset[0]``
        identifies every affected buffer."""
        p = int(partition)
        if p not in self.partitions:
            return 0
        mine = [u for u, b in self._buffers.items()
                if b.first_offset is not None and b.first_offset[0] == p]
        dropped = 0
        if flush and mine:
            self._flush(mine)
        else:
            for u in mine:
                dropped += len(self._buffers.pop(u).points)
        self.partitions = [q for q in self.partitions if q != p]
        self._commit()
        return dropped

    def flush_histograms(self) -> int:
        """Publish the per-segment speed-histogram DELTA since the last
        flush (SURVEY.md §7.7 / BASELINE config 5: "online per-segment speed
        histograms … periodic flush to datastore path"). Returns the number
        of segments flushed. One shared implementation with the columnar
        pipeline — streaming/state.py."""
        from reporter_tpu.streaming.state import flush_histogram_delta
        return flush_histogram_delta(self)

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        out = {
            "steps": self.steps,
            "malformed": self.malformed,
            "lag": sum(self.queue.end_offset(p) - self.committed[p]
                       for p in self.partitions),
            "buffered_uuids": len(self._buffers),
            "buffered_points": sum(len(b.points)
                                   for b in self._buffers.values()),
            "published": self.app.publisher.published,
            "hist_rows": int(len(self.hist.nonzero_rows())),
            "qhist_rows": int(len(self.qhist.nonzero_rows())),
            "overrun": int(self.overrun),
            "traced_records": int(self.traced_records),
            **self.app.stats,
        }
        overload = getattr(self.queue, "overload_stats", None)
        if overload is not None:
            out.update(overload())
        return out

    # ---- checkpoint / resume (SURVEY.md §5) ------------------------------

    def checkpoint(self, path: str) -> None:
        """Snapshot offsets + uuid cache + histograms to one file (shared
        schema with the columnar pipeline — streaming/state.py: buffers
        are derived state, the offset log is the truth)."""
        from reporter_tpu.streaming.state import save_checkpoint
        save_checkpoint(path, self.committed, self.app.cache.dump(),
                        self.hist.snapshot(), self._hist_flushed,
                        self.qhist.snapshot(), self._qhist_flushed)

    def restore(self, path: str) -> None:
        """Reset to a checkpoint; consumption resumes at the committed
        offsets, replaying the unflushed tail (at-least-once: records whose
        uuid was flushed after the snapshot may produce duplicate reports,
        never lost ones)."""
        from reporter_tpu.streaming.state import load_checkpoint
        state = load_checkpoint(path, self)
        self.committed = list(state["committed"])
        self._consumed = list(state["committed"])
        self._buffers = {}
        outage = max(0.0, time.time() - float(state.get("saved_at", time.time())))
        self.app.cache.load(state["cache"], extra_age=outage)
