"""Streaming-worker CLI — the reference's Kafka worker entrypoint analog.

    python -m reporter_tpu.streaming --tiles metro.npz --broker-dir ./broker
        [--checkpoint worker.ckpt] [--partitions 0 1] [--config conf.json]
        [--poll-interval 0.05] [--max-steps N] [--format auto]

Runs one matcher worker: restore the checkpoint if present, consume the
durable broker log from the committed offsets (replaying any unflushed
tail), flush ripe traces through the device matcher, publish reports +
histogram deltas to the configured datastore, and checkpoint on SIGTERM/
SIGINT (and every --checkpoint-interval seconds). Several workers scale
out over one broker directory by giving each a disjoint --partitions
subset and its own checkpoint — the consumer-group model (SURVEY.md
§3.3, DISTRIBUTED.md "Ingest stays host-local").

--lease-dir (round 23) replaces the static --partitions subset with
ELASTIC assignment: the worker acquires epoch-fenced, time-bounded
partition leases from the table (distributed/lease.py), renews them as
it runs, adopts partitions the rebalancer assigns at their committed
floors, and hands off gracefully when revoked — membership scales
under live load with zero lost and zero duplicated records
(DISTRIBUTED.md "Partition leasing").

--stdin-format additionally accepts raw vendor payloads on stdin (one
per line), normalized through ProbeFormatter into the broker before
consuming — handy for piping a vendor feed straight into a worker.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import time

log = logging.getLogger("reporter_tpu.streaming.worker")


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m reporter_tpu.streaming",
        description="reporter_tpu streaming matcher worker")
    ap.add_argument("--tiles", required=True, help="compiled tileset .npz")
    ap.add_argument("--broker-dir", required=True,
                    help="durable ingest log directory (shared by workers)")
    ap.add_argument("--checkpoint", default=None,
                    help="checkpoint path (restored on start if present)")
    ap.add_argument("--checkpoint-interval", type=float, default=30.0)
    ap.add_argument("--partitions", type=int, nargs="*", default=None,
                    help="partition subset this worker owns (default: all)")
    ap.add_argument("--config", default=None, help="JSON config path")
    ap.add_argument("--poll-interval", type=float, default=0.05)
    ap.add_argument("--max-steps", type=int, default=None,
                    help="stop after N steps (tests/drains); default: run "
                         "until signalled")
    ap.add_argument("--exit-on-drain", action="store_true",
                    help="exit once the owned partitions' lag reaches 0 "
                         "(the pre-staged-broker shape: bench recovery / "
                         "consumer-group legs drive subprocess workers "
                         "this way)")
    ap.add_argument("--stdin-format", default=None,
                    help="also read raw payloads from stdin, normalized "
                         "via ProbeFormatter ('auto'|'json'|'csv')")
    ap.add_argument("--columnar", action="store_true",
                    help="use the columnar worker (streaming/columnar.py): "
                         "vectorized consume/flush/report build; full "
                         "columnar throughput additionally needs a batch "
                         "broker — over the durable dict log, polls pay a "
                         "per-record packing shim")
    # topology observability (round 19): spool an atomic metrics/health
    # snapshot the supervisor tails (distributed/aggregate.py). Env
    # twins RTPU_TOPO_* let the supervisor configure spawned workers
    # without rebuilding their command lines; explicit flags win.
    ap.add_argument("--snapshot-dir",
                    default=os.environ.get("RTPU_TOPO_SNAPSHOT_DIR")
                    or None,
                    help="spool per-worker metrics snapshots here "
                         "(atomic tmp+fsync+rename; env twin "
                         "RTPU_TOPO_SNAPSHOT_DIR)")
    ap.add_argument("--snapshot-interval", type=float,
                    default=float(os.environ.get(
                        "RTPU_TOPO_SNAPSHOT_INTERVAL_S") or 1.0),
                    help="seconds between snapshot spools (env twin "
                         "RTPU_TOPO_SNAPSHOT_INTERVAL_S; default 1)")
    ap.add_argument("--member",
                    default=os.environ.get("RTPU_TOPO_MEMBER") or None,
                    help="this worker's topology member name (snapshot "
                         "file + trace-dump naming; env twin "
                         "RTPU_TOPO_MEMBER; default worker-<pid>)")
    # elastic partition leasing (round 23): env twins follow the
    # RTPU_TOPO_* pattern — the supervisor can steer spawned workers
    # without rebuilding command lines; explicit flags win
    ap.add_argument("--lease-dir",
                    default=os.environ.get("RTPU_LEASE_DIR") or None,
                    help="partition lease-table directory "
                         "(distributed/lease.py): take partitions from "
                         "epoch-fenced leases instead of --partitions "
                         "(env twin RTPU_LEASE_DIR)")
    ap.add_argument("--lease-ttl", type=float,
                    default=float(os.environ.get("RTPU_LEASE_TTL_S")
                                  or 5.0),
                    help="lease time-to-live in seconds; renewals run at "
                         "~ttl/4 (env twin RTPU_LEASE_TTL_S; default 5)")
    args = ap.parse_args(argv)
    member = args.member or f"worker-{os.getpid()}"
    if args.lease_dir and args.partitions is not None:
        ap.error("--lease-dir and --partitions are mutually exclusive: "
                 "the lease table owns partition assignment")
    if args.lease_dir and args.columnar:
        ap.error("--lease-dir requires the dict worker for now: the "
                 "columnar pipeline's in-flight wave holds make "
                 "mid-wave partition handoff a separate contract "
                 "(DISTRIBUTED.md 'Partition leasing')")

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # honor a parent's CPU pin: the image's sitecustomize re-pins the
        # axon platform at interpreter start, so the env var alone is not
        # enough (CLAUDE.md) — bench chaos legs spawn CPU workers this way
        import jax
        jax.config.update("jax_platforms", "cpu")
    from reporter_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()   # restarts are the POINT of the recovery
    #                              story: a restarted worker must reload,
    #                              not recompile, its wire programs

    from reporter_tpu.config import Config
    from reporter_tpu.streaming.durable_queue import DurableIngestQueue
    from reporter_tpu.streaming.pipeline import StreamPipeline
    from reporter_tpu.tiles.tileset import TileSet

    config = Config.load(args.config)   # JSON file + env overrides
    ts = TileSet.load(args.tiles)
    # Broker directories are format-specific (meta.json pins it): reopen
    # with the class that wrote them; a NEW directory takes the columnar
    # format iff this worker is columnar.
    from reporter_tpu.streaming.durable_queue import read_broker_format

    existing_fmt = read_broker_format(args.broker_dir)
    use_columnar_broker = (existing_fmt == "columnar"
                           or (existing_fmt is None and args.columnar))
    if use_columnar_broker:
        from reporter_tpu.streaming.durable_columnar import (
            DurableColumnarIngestQueue,
        )

        queue = DurableColumnarIngestQueue(args.broker_dir,
                                           config.streaming.num_partitions)
    else:
        queue = DurableIngestQueue(args.broker_dir,
                                   config.streaming.num_partitions)
    if args.columnar:
        from reporter_tpu.streaming.columnar import ColumnarStreamPipeline

        pipe = ColumnarStreamPipeline(ts, config, queue=queue,
                                      partitions=args.partitions)
    else:
        # lease mode starts owning NOTHING: the first sync() below
        # adopts whatever the table assigns, at its committed floors
        pipe = StreamPipeline(ts, config, queue=queue,
                              partitions=([] if args.lease_dir
                                          else args.partitions))
    if args.checkpoint and os.path.exists(
            args.checkpoint if args.checkpoint.endswith(".npz")
            else args.checkpoint + ".npz"):
        pipe.restore(args.checkpoint)
        log.info("restored checkpoint %s (committed=%s)",
                 args.checkpoint, pipe.committed)

    if args.stdin_format:
        from reporter_tpu.streaming.formatter import ProbeFormatter

        fmt = ProbeFormatter(args.stdin_format)
        if use_columnar_broker:
            # columnar broker: normalize stdin in batches so the log
            # stores column frames, not one frame per record
            n = fmt.format_stream_columns((line for line in sys.stdin),
                                          queue)
        else:
            n = fmt.format_stream((line for line in sys.stdin), queue)
        log.info("stdin feed: %d records normalized, %d dropped",
                 n, fmt.stats()["dropped"])

    from reporter_tpu import faults

    # the worker's matcher registry is the one every layer feeds — the
    # snapshot spool exports IT, so the supervisor's merge sees the same
    # series /stats and /metrics would serve in-process
    matcher = getattr(pipe, "matcher", None) or pipe.app.matcher

    runner = None
    if args.lease_dir:
        from reporter_tpu.distributed.lease import LeaseRunner, LeaseTable

        table = LeaseTable(args.lease_dir,
                           num_partitions=config.streaming.num_partitions,
                           ttl_s=args.lease_ttl,
                           metrics=matcher.metrics)
        runner = LeaseRunner(table, member, pipe)
        runner.sync(force=True)
        log.info("lease member %s: partitions %s", member,
                 sorted(runner.epochs))

    # SLO plane (round 24): burn-rate evaluation over this worker's own
    # registry, ticked from the main loop (self-throttled). The durable
    # alert ledger rides the snapshot spool dir so the supervisor finds
    # every member's alerts beside its metrics snapshots.
    from reporter_tpu.obs import slo as obs_slo

    slo_eval = None
    if obs_slo.enabled():
        ledger = None
        if args.snapshot_dir:
            from reporter_tpu.utils.eventlog import EventLog

            ledger = EventLog(os.path.join(args.snapshot_dir,
                                           f"alerts_{member}.jsonl"))
        slo_eval = obs_slo.SloEvaluator(matcher.metrics, ledger=ledger)

    stop = {"now": False}

    def _handle(signum, frame):
        stop["now"] = True

    signal.signal(signal.SIGINT, _handle)
    signal.signal(signal.SIGTERM, _handle)

    def _spool_snapshot(seq: int, st: dict) -> None:
        from reporter_tpu.distributed import aggregate

        # per-worker chaos accounting (round 23): an in-worker fault
        # plan's stats ride the snapshot as gauges, so the supervisor's
        # merged registry surfaces them per member even when the
        # incarnation dies before printing an exit report
        plan = faults.active()
        if plan is not None:
            fs = plan.stats()
            matcher.metrics.gauge("fault_calls",
                                  float(sum(fs["calls"].values())))
            matcher.metrics.gauge("fault_fired",
                                  float(sum(fs["fired"].values())))
        try:
            aggregate.write_snapshot(
                aggregate.snapshot_path(args.snapshot_dir, member),
                matcher.metrics, member, seq=seq,
                stats={k: st.get(k) for k in
                       ("lag", "published", "malformed", "reports",
                        "dispatch_timeouts", "dead_letter_pending")})
        except OSError as exc:
            # a full/unwritable spool disk must degrade observability,
            # never take the matcher down with it
            log.warning("snapshot spool failed: %s", exc)

    reports = steps = snap_seq = 0
    last_ckpt = last_snap = time.monotonic()
    stall, prev_lag = 0, None
    try:
        while not stop["now"]:
            if runner is not None:
                runner.sync()
            reports += pipe.step()
            if runner is not None:
                runner.push_commits()
            steps += 1
            if slo_eval is not None:
                slo_eval.tick()  # self-throttled; cheap on the hot loop
            if args.checkpoint and (time.monotonic() - last_ckpt
                                    >= args.checkpoint_interval):
                pipe.checkpoint(args.checkpoint)
                last_ckpt = time.monotonic()
            if args.max_steps is not None and steps >= args.max_steps:
                break
            st = pipe.stats()
            if args.snapshot_dir and (time.monotonic() - last_snap
                                      >= args.snapshot_interval):
                snap_seq += 1
                _spool_snapshot(snap_seq, st)
                last_snap = time.monotonic()
            if args.exit_on_drain:
                if runner is not None:
                    # lease mode drains GLOBALLY: end offsets vs TABLE
                    # floors over every partition. A worker owning
                    # nothing must idle, not stall-exit — partitions
                    # can still rebalance onto it (a dead peer's lease
                    # has to expire first); only the table saying all
                    # floors have caught up ends the run. A lag pinned
                    # by a sub-threshold buffered tail gets the
                    # finally-drain's IN-LOOP analog: force-flush so
                    # the floor can reach the end offsets, then keep
                    # serving.
                    if st["lag"] == 0:
                        stall = 0
                        if runner.lag() == 0:
                            break
                        time.sleep(args.poll_interval)
                    elif (st["lag"] == prev_lag
                            and st.get("inflight_waves", 0) == 0
                            and st.get("publish_pending", 0) == 0):
                        stall += 1
                        if stall >= 3:
                            reports += pipe.step(force_flush=True)
                            runner.push_commits()
                            stall = 0
                    else:
                        stall = 0
                    prev_lag = st["lag"]
                    continue
                # drained = lag 0, OR lag pinned by a sub-threshold
                # buffered tail with nothing in flight (the commit floor
                # sits below buffered rows by design; the finally-drain
                # below flushes them) — same no-progress rule as the
                # bench pump loops
                if st["lag"] == 0:
                    break
                if (st["lag"] == prev_lag
                        and st.get("inflight_waves", 0) == 0
                        and st.get("publish_pending", 0) == 0):
                    stall += 1
                    if stall >= 3:
                        break
                else:
                    stall = 0
                prev_lag = st["lag"]
            elif st["lag"] == 0:
                time.sleep(args.poll_interval)
    except faults.InjectedCrash:
        # A chaos plan simulating process death must behave like one:
        # no drain, no final checkpoint, no exit report — the next
        # owner replays this worker's unflushed tail from the table
        # floor. os._exit skips the finally below on purpose.
        log.error("injected crash: dying hard")
        os._exit(17)
    finally:
        reports += pipe.drain()
        pipe.flush_histograms()
        if runner is not None:
            # graceful exit: fenced final floors + release, so the
            # partitions are instantly adoptable (no TTL wait)
            runner.push_commits()
            runner.shutdown()
        if getattr(pipe.publisher, "dead_letter_pending", 0):
            # an outage that covered the LAST wave leaves batches spooled
            # with no later success to auto-replay them — try once at
            # shutdown (fails fast if the datastore is still dark; the
            # spool survives for the next run to inherit)
            pipe.publisher.replay_dead_letters()
        if args.checkpoint:
            pipe.checkpoint(args.checkpoint)
        if args.snapshot_dir:
            # final spool AFTER the drain: the supervisor's last view of
            # this worker covers everything it ever published
            _spool_snapshot(snap_seq + 1, pipe.stats())
        close = getattr(pipe, "close", None)
        if close is not None:       # pipelined worker: stop the executor
            close()                 # + publisher threads
        queue.close()
        from reporter_tpu.utils import tracing

        tr = tracing.tracer()
        if tr.enabled and tr.dump_dir:
            # per-process ring dump for distributed/stitch.py (named by
            # member so the stitcher can label the track); a dump
            # failure must not cost the worker its exit report
            try:
                tr.dump(path=os.path.join(tr.dump_dir,
                                          f"ring_{member}.json"),
                        reason="worker_exit")
            except OSError as exc:
                log.warning("exit trace dump failed: %s", exc)
    st = pipe.stats()
    # link-health counters (r15 layer) + quality counters (r18 layer):
    # both run in-process all along — the exit report is where a
    # supervisor reads them after the worker is gone
    from reporter_tpu.utils import linkhealth

    if linkhealth.enabled():
        s = linkhealth.sampler()
        link = {**s.window(), "probes": int(s.probes_total),
                "dead_probes": int(s.dead_probes_total)}
    else:
        link = {"rtt_ms": None, "mbps": None, "mood": None,
                "samples": 0, "probes": 0, "dead_probes": 0}
    qh = matcher.quality.health()
    quality = {k: qh.get(k) for k in
               ("enabled", "window_waves", "drifted", "drift_events",
                "empty_match_rate", "breakage_rate",
                "discontinuity_rate", "violation_rate",
                "rejection_rate", "unmatched_point_rate")}
    # per-worker chaos accounting in the exit report (round 23): which
    # sites an in-worker RTPU_FAULTS plan actually fired
    plan = faults.active()
    fault_stats = None
    if plan is not None:
        fs = plan.stats()
        fault_stats = {"calls": int(sum(fs["calls"].values())),
                       "fired": {s: int(n) for s, n in fs["fired"].items()
                                 if n}}
    lease_stats = None if runner is None else dict(runner.stats)
    # SLO roll-up in the exit report (round 24): a final forced tick so a
    # short-lived worker's burn state reflects the full run, then the
    # active-alert/budget block the supervisor surfaces per member
    slo_block = None
    if slo_eval is not None:
        slo_eval.tick(force=True)
        slo_block = slo_eval.exit_block()
    print(json.dumps({"steps": steps, "reports": reports,
                      "committed": list(pipe.committed),
                      "member": member,
                      "faults": fault_stats, "lease": lease_stats,
                      "slo": slo_block,
                      "link": link, "quality": quality,
                      **{k: v for k, v in st.items()
                         if k in ("lag", "published", "malformed",
                                  "hist_rows", "qhist_rows",
                                  "buffered_points", "publish_retried",
                                  "dead_lettered", "dead_letter_pending",
                                  "dispatch_timeouts",
                                  "traced_records")}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
