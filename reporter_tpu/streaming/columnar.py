"""Columnar streaming ingest — the config-5 throughput path.

The dict-based StreamPipeline (streaming/pipeline.py) is semantically
complete but host-bound: at firehose rates the per-record Python dict
handling in poll → _consume → _flush → report build costs more than the
device match itself (VERDICT r4 missing #2: 71.6k probes/s vs 2.19M on
the batch path). This module re-plumbs the SAME pipeline semantics as
numpy record batches end to end:

  ProbeColumns            one batch of probes as flat columns
  ColumnarIngestQueue     partitioned offset log storing column batches
                          (ProbeConsumer-compatible via a dict-poll shim)
  ColumnarTraceCache      per-uuid trailing points as arrays (the
                          PartialTraceCache semantics, columnar storage)
  ColumnarStreamPipeline  consume/flush/report/histogram with per-RECORD
                          Python eliminated: uuid interning at np.unique
                          speed, per-code counters, one lonlat→xy batch
                          conversion per flush, the matcher's columnar
                          MatchBatch, and a vectorized report builder
                          (group-id chaining replaces the per-record
                          state machine in service/reports.build_reports)

Behavior parity with the dict pipeline — reports, histograms, commit
floors, malformed counts, cache contents, checkpoint format — is
test-asserted on identical streams (tests/test_streaming_columnar.py).
The per-record path stays the compatibility surface for external brokers;
this is the deployment shape for sustained firehose rates.
"""

from __future__ import annotations

import bisect
import time
from typing import Any, NamedTuple, Sequence

import numpy as np

from reporter_tpu.utils import locks
from reporter_tpu.config import Config
from reporter_tpu.geometry import lonlat_to_xy
from reporter_tpu.matcher.api import (DispatchTimeout, MatchBatch,
                                      SegmentMatcher, Trace)
from reporter_tpu.service.datastore import DatastorePublisher, Transport
from reporter_tpu.streaming.histogram import SpeedHistogram
from reporter_tpu.streaming.queue import partition_of
from reporter_tpu.tiles.tileset import TileSet
from reporter_tpu.utils import tracing


# ---------------------------------------------------------------------------
# Probe batches


class ProbeColumns(NamedTuple):
    """One batch of canonical probe records as flat columns. NaN marks an
    absent time/accuracy (the canonical dict shape simply omits the key)."""

    uuid: np.ndarray       # str_ [N]
    lat: np.ndarray        # f64 [N]
    lon: np.ndarray        # f64 [N]
    time: np.ndarray       # f64 [N]; NaN ⇒ absent (index seconds assigned)
    accuracy: np.ndarray   # f32 [N]; NaN ⇒ absent

    @property
    def n(self) -> int:    # NamedTuple.__len__ is the field count
        return len(self.lat)

    def rows(self, idx) -> "ProbeColumns":
        return ProbeColumns(*(a[idx] for a in self))


def empty_probe_columns() -> ProbeColumns:
    return ProbeColumns(np.empty(0, np.str_), np.empty(0), np.empty(0),
                        np.empty(0), np.empty(0, np.float32))


def pack_records(records: Sequence[dict]) -> ProbeColumns:
    """Canonical record dicts → one column batch (compatibility path for
    dict producers; columnar producers build ProbeColumns directly)."""
    n = len(records)
    uuid = np.array([str(r.get("uuid", "")) for r in records])
    lat = np.full(n, np.nan)
    lon = np.full(n, np.nan)
    t = np.full(n, np.nan)
    acc = np.full(n, np.nan, np.float32)
    for i, r in enumerate(records):
        try:
            lat[i] = float(r["lat"])
            lon[i] = float(r["lon"])
        except (KeyError, TypeError, ValueError):
            continue                      # row stays NaN ⇒ malformed
        if "time" in r:
            try:
                tv = float(r["time"])
            except (TypeError, ValueError):
                lat[i] = np.nan           # dict pipeline treats a bad
                continue                  # time as a poison record
            if not np.isfinite(tv):
                lat[i] = np.nan           # explicit NaN/inf time is poison
                continue                  # too — NaN in the column means
            t[i] = tv                     # "key absent", never "bad value"
        if "accuracy" in r:
            try:
                av = float(r["accuracy"])
            except (TypeError, ValueError):
                av = np.nan               # advisory field: drop it, keep
                                          # the point (dict-path parity)
            if np.isfinite(av):
                acc[i] = av               # non-finite = dropped too: an
                                          # inf weight would wedge the
                                          # dict flush validator
    if n and uuid.dtype == object:
        uuid = uuid.astype(np.str_)
    return ProbeColumns(uuid, lat, lon, t, acc)


# ---------------------------------------------------------------------------
# Columnar broker


class ColumnarIngestQueue:
    """Partitioned offset log whose unit of storage is a column batch.

    Offset semantics are identical to IngestQueue (dense per-partition
    offsets, replayable, LookupError below the retention floor —
    streaming/broker.py); ``poll`` materializes dicts for per-record
    consumers, ``poll_batch`` hands column slices to the columnar
    pipeline without touching Python objects per record.

    ``max_records_per_partition`` bounds the RETAINED backlog (end −
    retention floor) so a producer that outruns the consumer cannot grow
    RSS without bound. Overload is an explicit, COUNTED policy, never a
    silent one (VERDICT r5 missing #2):

      "reject"       producer-side shedding: rows over the bound are
                     refused at append (``append_columns`` returns the
                     accepted count; ``rejected`` counts the rest) — the
                     broker keeps every record it ever acked.
      "drop_oldest"  consumer-side shedding: the append is taken and the
                     OLDEST whole batches are aged out, the retention
                     floor advancing past them (``dropped_oldest``
                     counts the rows). A consumer polling below the new
                     floor gets the protocol's LookupError; the pipeline
                     skips to the floor and counts the gap (``overrun``).
    """

    def __init__(self, num_partitions: int = 4,
                 max_records_per_partition: "int | None" = None,
                 overload_policy: str = "reject"):
        self.num_partitions = int(num_partitions)
        if overload_policy not in ("reject", "drop_oldest"):
            raise ValueError(f"unknown overload_policy {overload_policy!r};"
                             " use 'reject' or 'drop_oldest'")
        self.max_records_per_partition = (
            None if max_records_per_partition is None
            else int(max_records_per_partition))
        if (self.max_records_per_partition is not None
                and self.max_records_per_partition < 1):
            raise ValueError("max_records_per_partition must be >= 1")
        self.overload_policy = overload_policy
        self.rejected = 0          # rows refused at append ("reject")
        self.dropped_oldest = 0    # rows aged out past the floor
        # per partition: parallel lists of batch base offsets and batches
        self._bases: list[list[int]] = [[] for _ in range(self.num_partitions)]
        self._batches: list[list[ProbeColumns]] = [
            [] for _ in range(self.num_partitions)]
        self._end = [0] * self.num_partitions
        self._floor = [0] * self.num_partitions
        self._lock = locks.named_lock("broker.partitions")

    # ---- producer surface ----------------------------------------------

    def append_columns(self, cols: ProbeColumns) -> int:
        """Route a batch's rows to uuid-hash partitions (vectorized at
        unique-uuid granularity) and append one sub-batch per partition.
        Returns the number of rows ACCEPTED (== cols.n unless a partition
        bound rejected the overflow)."""
        if not cols.n:
            return 0
        uniq, inv = np.unique(cols.uuid, return_inverse=True)
        pu = np.array([partition_of(str(u), self.num_partitions)
                       for u in uniq], np.int32)
        prow = pu[inv]
        bound = self.max_records_per_partition
        accepted = 0
        with self._lock:
            for p in range(self.num_partitions):
                idx = np.nonzero(prow == p)[0]
                if not len(idx):
                    continue
                if bound is not None and self.overload_policy == "reject":
                    room = bound - (self._end[p] - self._floor[p])
                    if room < len(idx):
                        self.rejected += len(idx) - max(0, room)
                        if room <= 0:
                            continue
                        idx = idx[:room]
                sub = cols.rows(idx)
                # durability hook BEFORE the in-memory append, so on-disk
                # batch order always matches offset order (same discipline
                # as IngestQueue._persist)
                self._persist_batch(p, sub)
                self._bases[p].append(self._end[p])
                self._batches[p].append(sub)
                self._end[p] += len(idx)
                accepted += len(idx)
                if bound is not None and self.overload_policy == "drop_oldest":
                    self._shed_oldest(p, bound)
        return accepted

    def _shed_oldest(self, p: int, bound: int) -> None:
        """Age out whole oldest batches until the partition fits its bound
        (the just-appended batch is never shed: a single over-bound batch
        is retained whole — the bound is enforced at batch granularity).
        Runs under the lock."""
        bases, batches = self._bases[p], self._batches[p]
        shed = False
        while (len(bases) > 1
               and self._end[p] - self._floor[p] > bound):
            b = batches[0]
            self.dropped_oldest += b.n - max(0, self._floor[p] - bases[0])
            del bases[0], batches[0]
            self._floor[p] = bases[0]
            shed = True
        if shed:
            self._persist_truncate(p)

    def overload_stats(self) -> dict:
        """Counted shedding outcomes for /stats surfaces."""
        with self._lock:
            return {"broker_policy": self.overload_policy,
                    "broker_bound": self.max_records_per_partition,
                    "broker_rejected": int(self.rejected),
                    "broker_dropped_oldest": int(self.dropped_oldest)}

    def _persist_batch(self, p: int, cols: ProbeColumns) -> None:
        """Durability hook (DurableColumnarIngestQueue). No-op in-proc."""

    def append(self, record: dict) -> None:
        self.append_columns(pack_records([record]))

    def append_many(self, records: Sequence[dict]) -> None:
        self.append_columns(pack_records(records))

    # ---- consumer surface ----------------------------------------------

    def poll_batch(self, partition: int, offset: int, max_records: int,
                   ) -> "list[tuple[int, ProbeColumns]]":
        """Column slices covering [offset, offset+max_records), in offset
        order: [(base_offset, columns)…]."""
        with self._lock:
            if offset < self._floor[partition]:
                raise LookupError(
                    f"offset {offset} below retention floor "
                    f"{self._floor[partition]} (partition {partition})")
            bases = self._bases[partition]
            batches = self._batches[partition]
            out: list[tuple[int, ProbeColumns]] = []
            k = bisect.bisect_right(bases, offset) - 1
            if k < 0:
                k = 0
            left = max_records
            while k < len(bases) and left > 0:
                base, b = bases[k], batches[k]
                lo = max(0, offset - base)
                hi = min(b.n, lo + left)
                if lo < hi:
                    sl = b if (lo == 0 and hi == b.n) else b.rows(
                        slice(lo, hi))
                    out.append((base + lo, sl))
                    left -= hi - lo
                k += 1
            return out

    def poll(self, partition: int, offset: int,
             max_records: int) -> "list[tuple[int, dict]]":
        """Per-record compatibility shim (ProbeConsumer protocol). Only
        NaN means "key absent"; a ±inf time/accuracy from a direct
        columnar producer must materialize AS inf so the dict consumer's
        validator rejects it exactly like the columnar consumer does —
        mapping it to an absent key would launder a poison value into a
        valid timeless record and fork the malformed counts."""
        out: list[tuple[int, dict]] = []
        for base, cols in self.poll_batch(partition, offset, max_records):
            for i in range(cols.n):
                rec = {"uuid": str(cols.uuid[i]), "lat": float(cols.lat[i]),
                       "lon": float(cols.lon[i])}
                if not np.isnan(cols.time[i]):
                    rec["time"] = float(cols.time[i])
                if not np.isnan(cols.accuracy[i]):
                    rec["accuracy"] = float(cols.accuracy[i])
                out.append((base + i, rec))
        return out

    def end_offset(self, partition: int) -> int:
        with self._lock:
            return self._end[partition]

    def retention_floor(self, partition: int) -> int:
        """Oldest pollable offset (consumers skip here after an overrun
        LookupError — the Kafka auto.offset.reset=earliest analog)."""
        with self._lock:
            return self._floor[partition]

    def lag(self, committed: Sequence[int]) -> int:
        return sum(self.end_offset(p) - committed[p]
                   for p in range(self.num_partitions))

    def truncate(self, committed: Sequence[int]) -> None:
        """Drop whole batches entirely below the committed offsets. The
        retention floor advances to the first RETAINED offset (a batch
        straddling the commit keeps its early rows pollable)."""
        with self._lock:
            for p, off in enumerate(committed):
                bases, batches = self._bases[p], self._batches[p]
                k = 0
                while k < len(bases) and bases[k] + batches[k].n <= off:
                    k += 1
                if k:
                    self._bases[p] = bases[k:]
                    self._batches[p] = batches[k:]
                new_floor = (self._bases[p][0] if self._bases[p]
                             else min(off, self._end[p]))
                if new_floor > self._floor[p]:
                    self._floor[p] = new_floor
                    self._persist_truncate(p)

    def _persist_truncate(self, p: int) -> None:
        """Durability hook: rewrite partition p's backing store to match
        the truncated in-memory state. Runs under the lock. No-op
        in-proc."""


# ---------------------------------------------------------------------------
# Columnar per-uuid tail cache


class _TailEntry:
    __slots__ = ("lat", "lon", "time", "acc", "wall", "last")

    def __init__(self, lat, lon, time_, acc, wall, last=None):
        self.lat, self.lon, self.time, self.acc = lat, lon, time_, acc
        self.wall = wall
        # last timestamp as a PYTHON float: merge_wave's append-vs-dedup
        # test runs per vehicle per wave, and a numpy scalar read there
        # costs more than the comparison itself
        self.last = float(time_[-1]) if last is None else last


class ColumnarTraceCache:
    """PartialTraceCache semantics (TTL + LRU + straddling-tail retention,
    service/cache.py) with the per-uuid points stored as numpy arrays.
    dump()/load() speak the dict cache's checkpoint schema, so a
    checkpoint written by either pipeline restores into the other."""

    def __init__(self, ttl: float = 60.0, max_uuids: int = 100_000,
                 max_points: int = 256, clock=time.monotonic):
        from collections import OrderedDict

        self.ttl = float(ttl)
        self.max_uuids = int(max_uuids)
        self.max_points = int(max_points)
        self._clock = clock
        self._entries: "OrderedDict[str, _TailEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def tail(self, uuid: str,
             now: "float | None" = None) -> "_TailEntry | None":
        """The live cached tail (TTL-checked; a stale entry is dropped
        on read) — THE lookup-and-expire rule, shared by merge() and
        merge_wave (which hoists one clock read per wave via ``now``)."""
        e = self._entries.get(uuid)
        if e is not None:
            if now is None:
                now = self._clock()
            if now - e.wall > self.ttl:
                del self._entries[uuid]
                e = None
        return e

    def _merge_overlap(self, e: _TailEntry, lat, lon, time_, acc):
        """The dedup branch of merge(): new timestamps overlap the
        cached tail, so filter duplicates and re-sort time-ascending."""
        fresh = ~np.isin(time_, e.time)
        lat = np.concatenate([e.lat, lat[fresh]])
        lon = np.concatenate([e.lon, lon[fresh]])
        t = np.concatenate([e.time, time_[fresh]])
        acc = np.concatenate([e.acc, acc[fresh]])
        order = np.argsort(t, kind="stable")
        return lat[order], lon[order], t[order], acc[order]

    def merge(self, uuid: str, lat, lon, time_, acc):
        """(cached tail ⊕ new rows) deduped by timestamp, time-ascending —
        exactly PartialTraceCache.merge, on arrays. Callers pass new rows
        time-sorted (the pipeline lexsorts the flush), and entries store
        sorted tails, so the common streaming case — every new timestamp
        past the cached tail — is a plain concat with no dedup/sort."""
        e = self.tail(uuid)
        if e is None:
            return lat, lon, time_, acc
        if len(time_) and e.time[-1] < time_[0]:
            return (np.concatenate([e.lat, lat]),
                    np.concatenate([e.lon, lon]),
                    np.concatenate([e.time, time_]),
                    np.concatenate([e.acc, acc]))
        return self._merge_overlap(e, lat, lon, time_, acc)

    def merge_wave(self, uuids: "Sequence[str]", lat, lon, time_, acc,
                   bounds: np.ndarray):
        """Batched merge() over one flush wave: vehicle v's new rows are
        the slice [bounds[v], bounds[v+1]) of the flat columns. Returns
        (lat, lon, time, acc, bounds) of the merged wave — each column
        concatenated ONCE instead of four np.concatenate calls per
        vehicle, which was the prepare stage's top host cost at
        firehose/validation scale. Element-for-element equal to calling
        merge() per vehicle (the common no-tail / append-tail cases are
        pure piece gathering; the rare overlap case reuses the same
        dedup branch)."""
        V = len(uuids)
        pl: list = []
        pn: list = []
        pt: list = []
        pa: list = []
        lens = np.empty(V, np.int64)
        # bulk scalar extraction (ONE .tolist() runs in C; per-vehicle
        # int()/float() of numpy scalars was a measured per-wave cost)
        b_list = bounds.tolist()
        firsts = time_[bounds[:-1]].tolist() if V else []
        now = self._clock()
        for v in range(V):
            lo, hi = b_list[v], b_list[v + 1]
            nl, nn = lat[lo:hi], lon[lo:hi]
            nt, na = time_[lo:hi], acc[lo:hi]
            e = self.tail(uuids[v], now=now)
            if e is None:
                pl.append(nl); pn.append(nn); pt.append(nt); pa.append(na)
                lens[v] = hi - lo
            elif e.last < firsts[v]:
                pl.append(e.lat); pn.append(e.lon)
                pt.append(e.time); pa.append(e.acc)
                pl.append(nl); pn.append(nn); pt.append(nt); pa.append(na)
                lens[v] = len(e.time) + (hi - lo)
            else:
                ml, mn, mt, ma = self._merge_overlap(e, nl, nn, nt, na)
                pl.append(ml); pn.append(mn); pt.append(mt); pa.append(ma)
                lens[v] = len(mt)
        out_bounds = np.zeros(V + 1, np.int64)
        np.cumsum(lens, out=out_bounds[1:])
        return (np.concatenate(pl) if pl else np.empty(0),
                np.concatenate(pn) if pn else np.empty(0),
                np.concatenate(pt) if pt else np.empty(0),
                np.concatenate(pa) if pa else np.empty(0, np.float32),
                out_bounds)

    def retain(self, uuid: str, lat, lon, time_, acc,
               from_time: float) -> None:
        """Keep rows from one before the first time >= from_time (the
        straddling pair rule of PartialTraceCache.retain)."""
        at = np.nonzero(time_ >= from_time)[0]
        cut = max(0, int(at[0]) - 1) if len(at) else max(0, len(time_) - 1)
        lo = max(cut, len(time_) - self.max_points)
        self.retain_cut(uuid, lat, lon, time_, acc, lo)
        self._evict()

    def retain_cut(self, uuid: str, lat, lon, time_, acc,
                   lo: int) -> None:
        """retain() with the cut precomputed (native_prepare.tail_cuts
        batches a whole wave's cuts in one call) and the eviction sweep
        deferred to sweep() — same final cache state, without a
        TTL/capacity scan per vehicle."""
        if lo >= len(time_):
            self._entries.pop(uuid, None)
            return
        self._entries[uuid] = _TailEntry(
            lat[lo:].copy(), lon[lo:].copy(), time_[lo:].copy(),
            acc[lo:].copy(), self._clock())
        self._entries.move_to_end(uuid)

    def retain_wave(self, uuids: "Sequence[str]", lat, lon, time_, acc,
                    bounds: np.ndarray, los: np.ndarray) -> None:
        """Batched retain_cut over a wave's flat merged columns: vehicle
        v retains rows [bounds[v] + los[v], bounds[v+1]). The per-wave
        scalar work (cut arithmetic, last timestamps) is bulk-extracted,
        then each entry gets OWNED contiguous-slice copies — owned, not
        views of a shared block, so a straggler vehicle's entry can
        never pin other vehicles' rows for its TTL lifetime — and ONE
        eviction sweep runs at the end. Final cache state identical to
        per-vehicle retain_cut + sweep."""
        b0, b1 = bounds[:-1], bounds[1:]
        src0 = b0 + los
        keep = np.nonzero(src0 < b1)[0]
        now = self._clock()
        entries = self._entries
        src_list = src0[keep].tolist()
        end_list = b1[keep].tolist()
        last_list = time_[b1[keep] - 1].tolist() if len(keep) else []
        kept = set()
        for k, v in enumerate(keep.tolist()):
            u = uuids[v]
            kept.add(v)
            a, b = src_list[k], end_list[k]
            entries[u] = _TailEntry(
                lat[a:b].copy(), lon[a:b].copy(), time_[a:b].copy(),
                acc[a:b].copy(), now, last=last_list[k])
            entries.move_to_end(u)
        if len(kept) < len(uuids):
            for v, u in enumerate(uuids):
                if v not in kept:       # nothing retained: entry drops
                    entries.pop(u, None)
        self._evict()

    def sweep(self) -> None:
        """The TTL + capacity eviction retain() runs per call, run once
        per wave by the batched retention path."""
        self._evict()

    def dump(self) -> dict:
        now = self._clock()
        out = {}
        for u, e in self._entries.items():
            pts = []
            for i in range(len(e.time)):
                p = {"lat": float(e.lat[i]), "lon": float(e.lon[i]),
                     "time": float(e.time[i])}
                if np.isfinite(e.acc[i]):
                    p["accuracy"] = float(e.acc[i])
                pts.append(p)
            out[u] = {"points": pts, "age": now - e.wall}
        return out

    def load(self, state: dict, extra_age: float = 0.0) -> None:
        now = self._clock()
        self._entries.clear()
        for u, rec in sorted(state.items(), key=lambda kv: -kv[1]["age"]):
            age = float(rec["age"]) + extra_age
            pts = rec["points"]
            if age > self.ttl or not pts:
                continue
            cols = pack_records(pts)
            self._entries[u] = _TailEntry(cols.lat, cols.lon, cols.time,
                                          cols.accuracy, now - age)
        self._evict()

    def _evict(self) -> None:
        now = self._clock()
        while self._entries:
            _, e = next(iter(self._entries.items()))
            if now - e.wall <= self.ttl:
                break
            self._entries.popitem(last=False)
        while len(self._entries) > self.max_uuids:
            self._entries.popitem(last=False)


# ---------------------------------------------------------------------------
# Vectorized report building


def build_report_columns(cols, n_traces: "int | None", min_length: float):
    """service/reports.build_reports, vectorized over RecordColumns.

    The per-record state machine becomes a group-id computation: a chain
    boundary between consecutive records survives iff the records are
    time-adjacent (|t0[r+1] − t1[r]| < 1e-3, within one trace) and the
    next record can carry the run (reportable, or a complete internal
    connector). Records sharing a group id are one unbroken run, so each
    reportable record's ``next_segment_id`` is simply the next reportable
    record in its group. Parity with the scalar builder is test-asserted.

    Returns (seg i64[R], next i64[R] (-1 ⇒ None), t0, t1, length, queue
    f64[R], per_trace_counts i64[n_traces] | None). ``n_traces=None``
    skips the per-trace bincount (the flush hot path doesn't use it).
    """
    n = cols.n_records
    if not n:
        z = np.empty(0, np.int64)
        zf = np.empty(0)
        return z, z, zf, zf, zf, zf, (
            None if n_traces is None else np.zeros(n_traces, np.int64))
    complete = (cols.start_time >= 0.0) & (cols.end_time >= 0.0)
    reportable = complete & ~cols.internal & (cols.length >= min_length)
    carry = reportable | (cols.internal & complete)
    same_trace = cols.trace[1:] == cols.trace[:-1]
    adj = np.abs(cols.start_time[1:] - cols.end_time[:-1]) < 1e-3
    link = same_trace & adj & carry[1:] & carry[:-1]
    group = np.concatenate([[0], np.cumsum(~link)])
    rep = np.nonzero(reportable)[0]
    nxt = np.full(len(rep), -1, np.int64)
    if len(rep) > 1:
        chained = group[rep[1:]] == group[rep[:-1]]
        nxt[:-1][chained] = cols.segment_id[rep[1:][chained]]
    per_trace = (None if n_traces is None else
                 np.bincount(cols.trace[rep],
                             minlength=n_traces).astype(np.int64))
    return (cols.segment_id[rep], nxt, cols.start_time[rep],
            cols.end_time[rep], cols.length[rep], cols.queue_length[rep],
            per_trace)


# ---------------------------------------------------------------------------
# The pipeline


class _Log:
    """Growable columnar buffer of consumed-but-unflushed probe rows.
    ``held`` carries the in-flight wave id (0 = free): a pipelined flush
    marks its rows instead of removing them, so a matcher failure simply
    unmarks them for retry and the commit-floor scan keeps seeing their
    offsets while the wave is on the device."""

    def __init__(self):
        self.n = 0
        self.cap = 0
        self.code = np.empty(0, np.int64)
        self.lat = np.empty(0)
        self.lon = np.empty(0)
        self.time = np.empty(0)
        self.acc = np.empty(0, np.float32)
        self.part = np.empty(0, np.int16)
        self.off = np.empty(0, np.int64)
        self.arrive = np.empty(0)
        self.held = np.empty(0, np.int64)
        self.tless = np.empty(0, bool)   # time was absent: index seconds
        #                                  were assigned (re-based on a
        #                                  failed-wave release)

    _COLS = ("code", "lat", "lon", "time", "acc", "part", "off", "arrive",
             "held", "tless")

    def append(self, **cols) -> None:
        k = len(cols["code"])
        if self.n + k > self.cap:
            self.cap = max(1024, 2 * (self.n + k))
            for f in self._COLS:
                a = getattr(self, f)
                grown = np.empty(self.cap, a.dtype)
                grown[:self.n] = a[:self.n]
                setattr(self, f, grown)
        for f in self._COLS:
            getattr(self, f)[self.n:self.n + k] = cols[f]
        self.n += k

    def compact(self, keep_mask: np.ndarray) -> None:
        k = int(keep_mask.sum())
        for f in self._COLS:
            a = getattr(self, f)
            a[:k] = a[:self.n][keep_mask]
        self.n = k


class _WaveController:
    """Adaptive wave sizing for the pipelined flush loop.

    One number — the effective ``flush_min_points`` — trades per-wave
    overhead (link RTT, dispatch fixed costs: fewer, bigger waves win)
    against probe→report latency (points sit in the buffer until the
    wave fills: smaller waves win). The policy works on the lag TREND,
    not its level (backlog is counted in records, waves in points per
    vehicle — the units don't compare): GROW after STREAK consecutive
    rising-lag updates (the worker is paying too many per-wave overheads
    for the offered rate), SHRINK toward the latency target after STREAK
    non-rising updates with p50 probe→report over target (caught up, so
    buy back latency). The streak hysteresis keeps per-step lag jitter
    from ratcheting the wave. Multiplicative steps, clamped to [lo, hi];
    pure arithmetic so convergence is unit-testable without a pipeline
    (tests/test_pipelined_flush.py)."""

    GROW = 1.3
    SHRINK = 0.85
    STREAK = 3

    def __init__(self, start: int, lo: int, hi: int, target_s: float):
        self.lo, self.hi = int(lo), int(hi)
        self.points = float(min(max(int(start), self.lo), self.hi))
        self.target_s = float(target_s)
        self._rising = 0
        self._steady = 0

    def update(self, lag: int, prev_lag: int,
               last_p50_s: "float | None") -> int:
        if lag > prev_lag * 1.05 + 64:      # real growth, not step jitter
            self._rising += 1
            self._steady = 0
        else:
            self._steady += 1
            self._rising = 0
        if self._rising >= self.STREAK:
            self.points = min(self.hi, self.points * self.GROW)
            self._rising = 0
        elif (self._steady >= self.STREAK and last_p50_s is not None
              and last_p50_s > self.target_s):
            self.points = max(self.lo, self.points * self.SHRINK)
            self._steady = 0
        return int(round(self.points))


class _InflightWave:
    """One flush wave moving through the pipelined loop.

    Until its match result is processed the wave's probe rows stay in the
    log marked ``held=id`` (failure ⇒ unmark + retry, the sequential
    path's match-before-drop discipline); until its publish ATTEMPT
    completes, ``holds`` keeps the commit floor at or below the wave's
    oldest offset, so a checkpoint taken with the wave in flight replays
    it — at-least-once, never lost."""

    __slots__ = ("id", "future", "prep", "uuids", "merged", "merged_flat",
                 "codes", "holds", "arrive", "n_points", "published",
                 "t_prep0", "t_submit", "t_result")

    def __init__(self, wid: int, codes: np.ndarray,
                 holds: "list[tuple[int, int]]", arrive: np.ndarray,
                 n_points: int):
        self.id = wid
        self.future = None
        self.prep = None        # read-ahead ticket → (traces, prepared);
        #                         None once consumed / on the serial arm
        self.uuids: "list[str]" = []
        self.merged: "list[tuple]" = []
        # (lat, lon, time, acc, bounds) flat wave columns — the merged
        # per-vehicle tuples above are views into these; the batched
        # tail-retention path reads the flat form directly
        self.merged_flat: "tuple | None" = None
        self.codes = codes
        self.holds = holds
        self.arrive = arrive
        self.n_points = int(n_points)
        self.published = False      # set by the publisher's on_done
        # latency-attribution timestamps (pipeline clock base): prepare
        # entered / match submitted / match result in hand. Always
        # stamped (three clock() calls per wave); only ACCUMULATED into
        # stage samples when the tracer is enabled. None = not yet
        # stamped — an injected clock may legitimately read 0.0, so the
        # unset sentinel must not be a falsy float.
        self.t_prep0: "float | None" = None
        self.t_submit = 0.0
        self.t_result = 0.0


class ColumnarStreamPipeline:
    """StreamPipeline semantics at columnar speed (see module docstring).

    Public surface mirrors StreamPipeline: step/drain/flush_histograms/
    stats/checkpoint/restore, committed offsets, injectable clock and
    partition ownership. ``mesh`` deploys the matcher across a device
    mesh (parallel/dp_e2e). The broker must offer ``poll_batch`` (e.g.
    ColumnarIngestQueue); a per-record ProbeConsumer also works through a
    packing shim, at per-record cost on the poll leg only.

    PIPELINED FLUSH (config.streaming.pipeline_depth > 0, the default):
    the three RTT-bearing legs of a flush run concurrently instead of in
    sequence — wave N's device match waits on the link in a one-thread
    executor (GIL released), wave N−1's datastore POST waits on its
    socket in the publisher thread (GIL released), and the main loop
    keeps consuming wave N+1 the whole time. step() submits at most one
    wave and harvests any completed one; drain() joins everything, so
    after drain() the pipelined worker is observably identical to the
    sequential loop (the dict-parity suite runs against exactly this).
    Correctness invariants:

      - a uuid is in at most one unharvested wave (its cache tail is
        retained at harvest; a second merge before that would read stale
        points) — ripe codes of in-flight waves wait;
      - commit floor ≤ the oldest offset of every wave whose publish
        attempt hasn't completed, so checkpoint/crash mid-wave replays
        the wave (at-least-once, never lost);
      - a matcher failure releases the wave's rows for retry, exactly
        like the sequential path's match-before-drop discipline.

    ``streaming.wave_autotune`` adds the adaptive wave-size controller
    (_WaveController) on top; pipeline_depth=0 restores the sequential
    loop.

    Lifecycle: the first pipelined flush lazily starts a one-thread
    executor and the async publisher's worker. Long-lived deployments
    should ``close()`` the pipeline after ``drain()``; a discarded
    pipeline's executor is reclaimed at GC (its idle worker exits via
    the executor's weakref hook) and the publisher thread is a
    daemon."""

    # newest-N bound on the unread latency accumulator (~4 MB of f64):
    # big enough that a bench drain's take-per-drain() keeps every sample
    # at sane backlogs, small enough that a reader-less production worker
    # neither grows RSS nor pays a growing per-flush concatenate
    _LAT_SAMPLES_CAP = 500_000

    def __init__(self, tileset: TileSet, config: "Config | None" = None,
                 queue=None, transport: "Transport | None" = None,
                 clock=time.monotonic,
                 partitions: "Sequence[int] | None" = None,
                 mesh=None):
        self.config = (config or Config()).validate()
        sc = self.config.streaming
        svc = self.config.service
        self.queue = queue or ColumnarIngestQueue(sc.num_partitions)
        if self.queue.num_partitions != sc.num_partitions:
            raise ValueError("queue/config partition count mismatch")
        owned = range(sc.num_partitions) if partitions is None else partitions
        self.partitions = sorted(set(int(p) for p in owned))
        if any(p < 0 or p >= sc.num_partitions for p in self.partitions):
            raise ValueError(
                f"partitions {self.partitions} out of range "
                f"0..{sc.num_partitions - 1}")
        self.matcher = SegmentMatcher(tileset, self.config, mesh=mesh)
        self.cache = ColumnarTraceCache(ttl=svc.cache_ttl,
                                        max_uuids=svc.cache_max_uuids)
        self._depth = int(sc.pipeline_depth)
        from reporter_tpu.service.datastore import publisher_kwargs
        pub_kw = publisher_kwargs(svc, metrics=self.matcher.metrics)
        if self._depth > 0:
            from reporter_tpu.service.datastore import AsyncDatastorePublisher
            self.publisher = AsyncDatastorePublisher(transport=transport,
                                                     **pub_kw)
        else:
            self.publisher = DatastorePublisher(transport=transport,
                                                **pub_kw)
        self.min_segment_length = svc.min_segment_length
        self.clock = clock
        self.committed = [0] * sc.num_partitions
        self._consumed = [0] * sc.num_partitions

        # pipelined-flush state
        self._pool = None                       # lazy 1-thread match executor
        self._inflight: "list[_InflightWave]" = []   # match leg (FIFO)
        self._pending: "list[_InflightWave]" = []    # publish attempt pending
        # pipelined wave PREPARE (r22): with pipeline_prepare on, the
        # pure half of wave prepare (trace build + the matcher's
        # prepared seam) runs on a read-ahead thread while earlier
        # waves occupy the device; stateful steps (cache merge/retain,
        # commit floor, checkpoint) stay on this thread in wave order —
        # wire bytes and report streams are bit-identical to the serial
        # arm (test- and bench-asserted).
        self._pp = bool(svc.pipeline_prepare) and self._depth > 0
        self._ra = None                         # lazy read-ahead worker
        self._staged: "list[_InflightWave]" = []   # staged ahead (FIFO),
        #                                            not yet on the device
        self._overlap_hits = 0    # read-ahead builds that overlapped a
        self._overlap_total = 0   # device-occupied window (gauge basis)
        self._wave_serial = 0
        self._wave_ctl = (_WaveController(sc.flush_min_points,
                                          sc.wave_min_points,
                                          sc.wave_max_points,
                                          sc.wave_target_latency)
                          if sc.wave_autotune else None)
        self._wave_points = int(sc.flush_min_points)
        self._prev_lag = 0
        self._last_flush_p50: "float | None" = None
        self.overrun = 0          # records lost to broker drop-oldest shed
        self.dispatch_timeouts = 0   # waves released by the watchdog
        self.waves_completed = 0     # waves fully processed (progress
        #                              signal for the drain stall guard)

        # uuid interning + per-code buffer state
        self._code_of: dict[str, int] = {}
        self._uuid_of: list[str] = []
        self._count = np.zeros(0, np.int64)     # buffered points per code
        self._born = np.zeros(0)                # buffer birth (clock)
        self._log = _Log()

        self.hist = SpeedHistogram(len(tileset.osmlr_id), sc.speed_bins)
        self.qhist = SpeedHistogram(len(tileset.osmlr_id), sc.queue_bins)
        self._osmlr_ids = np.asarray(tileset.osmlr_id)
        self._row_order = np.argsort(self._osmlr_ids, kind="stable")
        self._row_sorted = self._osmlr_ids[self._row_order]
        self._hist_flushed = self.hist.snapshot()
        self._qhist_flushed = self.qhist.snapshot()
        self._hist_flush_at = self.clock()
        self.hist_flushes = 0
        self.steps = 0
        self.malformed = 0
        self.stats_counters = {"traces": 0, "points": 0, "reports": 0,
                               "match_seconds": 0.0, "batches": 0}
        # probe→report latency samples ACCUMULATED since last read (wall
        # seconds from arrival to report build, per flushed probe row);
        # readers take the array and reset to None. Newest-N bounded
        # (_LAT_SAMPLES_CAP) so a reader-less worker stays flat-RSS.
        self.last_flush_latency: "np.ndarray | None" = None

        # span tracing / latency attribution (utils/tracing.py): the
        # PROCESS-GLOBAL recorder, optionally switched on by this
        # pipeline's ServiceConfig. When enabled, each completed wave
        # records its stage spans (broker_dwell → prepare →
        # device_match → report_build (+ publish)) wave-tagged into the
        # flight recorder, and per-probe stage components accumulate for
        # ``take_stage_samples()`` (same take-and-reset + newest-N
        # discipline as last_flush_latency) — the components TELESCOPE:
        # per probe,
        # dwell + prepare + match + build == the last_flush_latency
        # sample exactly, which is what lets the bench assert the
        # attribution reconciles with the measured end-to-end p50.
        tracing.configure_from_service(svc)
        self._tracer = tracing.tracer()
        # per-WAVE chunk list, concatenated once in take_stage_samples():
        # re-concatenating the accumulated history every completed wave
        # would be O(total^2/wave) memcpy charged to the traced soak arm
        # — inflating exactly the overhead number the bench A/B records
        self._stage_chunks: "list[dict[str, np.ndarray]]" = []
        self._stage_count = 0
        self._publish_durs: "list[float]" = []   # per-wave publish
        #                                          enqueue→completion
        #                                          seconds (async leg:
        #                                          INCLUDES publisher
        #                                          queue dwell and
        #                                          retry/backoff — time
        #                                          to durable publish,
        #                                          not one POST's wire
        #                                          time; lands after the
        #                                          e2e cut, reported as
        #                                          its own stage)

    # ---- one poll/flush cycle -------------------------------------------

    def step(self, force_flush: bool = False) -> int:
        if force_flush:
            return self._drain_step()
        sc = self.config.streaming
        n_reports = self._harvest(block=False)
        self._poll_all(sc.poll_max_records)
        now = self.clock()
        ripe = np.nonzero(
            (self._count >= self._wave_points)
            | ((self._count > 0)
               & (now - self._born >= sc.flush_max_age)))[0]
        ripe = self._without_busy(ripe)
        if len(ripe):
            if self._depth == 0:
                n_reports += self._flush(ripe)
            elif self._pp:
                # stage up to ONE wave beyond the device depth: its pure
                # prepare runs on the read-ahead thread while the
                # in-flight waves ride the link
                if (len(self._inflight) + len(self._staged)
                        < self._depth + 1):
                    self._stage_readahead(ripe)
            elif len(self._inflight) < self._depth:
                self._submit_wave(ripe)
        self._promote_staged()
        self._commit()
        self._tick(now)
        self.steps += 1
        return n_reports

    def drain(self) -> int:
        return self.step(force_flush=True)

    def _drain_step(self) -> int:
        """Flush EVERYTHING synchronously (shutdown path): join in-flight
        waves, consume the pollable tail, wave out every buffered point,
        and wait for the publisher — after this the pipelined worker is
        observably identical to the sequential one."""
        sc = self.config.streaming
        self._promote_staged(drain=True)
        n = self._harvest(block=True)
        self._poll_all(sc.poll_max_records)
        stalls = 0
        while True:
            ripe = np.nonzero(self._count > 0)[0]
            if not len(ripe):
                break
            before_to = self.dispatch_timeouts
            before_wc = self.waves_completed
            if self._depth == 0:
                n += self._flush(ripe)
            else:
                if self._pp:
                    if not self._stage_readahead(ripe):
                        break
                    self._promote_staged(drain=True)
                elif not self._submit_wave(ripe):
                    break
                n += self._harvest(block=True)
            if (self.dispatch_timeouts > before_to
                    and self.waves_completed == before_wc):
                # a live loop retries timed-out waves forever; a DRAIN
                # must not — three consecutive rounds with a watchdog
                # trip and ZERO completed waves means the link is gone,
                # and the shutdown path should say so instead of
                # spinning. (A trip alongside completed waves is a
                # flapping link making progress: keep draining.)
                stalls += 1
                if stalls >= 3:
                    raise DispatchTimeout(
                        "drain stalled: device dispatch timed out with "
                        f"no completed waves {stalls} rounds running")
            else:
                stalls = 0
        self.publisher.drain()
        self._commit()
        now = self.clock()
        if (sc.hist_flush_interval > 0
                and now - self._hist_flush_at >= sc.hist_flush_interval):
            self.flush_histograms()
        self.steps += 1
        return n

    def _poll_all(self, max_records: int) -> None:
        from reporter_tpu.streaming.state import poll_with_overrun_skip
        with self._tracer.span("consume"):
            for p in self.partitions:
                batches = poll_with_overrun_skip(self, self._poll_batches,
                                                 p, max_records)
                for offs, cols in batches:
                    self._consume_columns(p, offs, cols)
                    self._consumed[p] = int(offs[-1]) + 1

    def _without_busy(self, ripe: np.ndarray) -> np.ndarray:
        """Codes already in an unharvested wave must wait: their cache
        tails are retained at harvest, so a second merge now would read
        stale points. (Publish-pending waves don't bite — their retains
        already ran.)"""
        busy_waves = self._inflight + self._staged
        if not busy_waves or not len(ripe):
            return ripe
        busy = np.concatenate([w.codes for w in busy_waves])
        return ripe[~np.isin(ripe, busy)]

    def _tick(self, now: float) -> None:
        """Per-step bookkeeping: histogram interval flush, the wave-size
        controller, and observability gauges."""
        sc = self.config.streaming
        if (sc.hist_flush_interval > 0
                and now - self._hist_flush_at >= sc.hist_flush_interval):
            self.flush_histograms()
        lag = sum(self.queue.end_offset(p) - self.committed[p]
                  for p in self.partitions)
        if self._wave_ctl is not None:
            self._wave_points = self._wave_ctl.update(
                lag, self._prev_lag, self._last_flush_p50)
        self._prev_lag = lag
        m = self.matcher.metrics
        m.gauge("stream_lag", lag)
        m.gauge("stream_inflight_waves",
                len(self._inflight) + len(self._pending))
        m.gauge("stream_publish_pending", self.publisher.pending)
        m.gauge("stream_wave_points", self._wave_points)
        if self._pp:
            m.gauge("readahead_depth", len(self._staged))
            total = self._overlap_total
            m.gauge("prepare_overlap_pct",
                    100.0 * self._overlap_hits / total if total else 0.0)

    def _poll_batches(self, p: int, offset: int, max_records: int,
                      ) -> "list[tuple[np.ndarray, ProbeColumns]]":
        """[(per-row offsets i64[N], columns)…]. Offsets are carried
        per row, not as base+arange: the ProbeConsumer contract only
        promises offset ORDER, not density — a broker may skip offsets
        (compacted topics), and assuming density would re-poll past rows
        (duplicate probes) and corrupt the commit floor. A batch broker's
        poll_batch may return either (int base, cols) — declaring its
        batch offsets DENSE, as ColumnarIngestQueue's are by construction
        — or (i64[N] per-row offsets, cols) when they are not."""
        pb = getattr(self.queue, "poll_batch", None)
        if pb is not None:
            return [(base + np.arange(cols.n, dtype=np.int64)
                     if np.ndim(base) == 0 else np.asarray(base, np.int64),
                     cols)
                    for base, cols in pb(p, offset, max_records)]
        pairs = self.queue.poll(p, offset, max_records)   # per-record shim
        if not pairs:
            return []
        return [(np.array([o for o, _ in pairs], np.int64),
                 pack_records([r for _, r in pairs]))]

    # ---- consume ---------------------------------------------------------

    def _consume_columns(self, p: int, offs: np.ndarray,
                         cols: ProbeColumns) -> None:
        now = self.clock()
        # time contract: NaN = key absent (index seconds assigned); ±inf =
        # present-but-non-finite, which the dict pipeline counts malformed
        # at consume (a non-finite time would poison the flush validator)
        ok = (np.char.str_len(np.asarray(cols.uuid, np.str_)) > 0) \
            & np.isfinite(cols.lat) & np.isfinite(cols.lon) \
            & ~np.isinf(cols.time)
        bad = int((~ok).sum())
        if bad:
            self.malformed += bad
            offs = offs[ok]
            cols = cols.rows(ok)
        bad_acc = (cols.accuracy < 0) | np.isinf(cols.accuracy)
        if bad_acc.any():
            # advisory field: a negative or non-finite accuracy is
            # dropped, not the point (formatter + dict-consume behavior;
            # an inf here would become a 1.8e308 matcher weight via
            # nan_to_num at flush)
            cols = cols._replace(accuracy=np.where(
                bad_acc, np.nan, cols.accuracy))
        if not cols.n:
            return

        # intern uuids at unique granularity (the only per-string work)
        uniq, inv = np.unique(cols.uuid, return_inverse=True)
        ucodes = np.empty(len(uniq), np.int64)
        for i, u in enumerate(uniq):
            s = str(u)
            c = self._code_of.get(s)
            if c is None:
                c = len(self._uuid_of)
                self._code_of[s] = c
                self._uuid_of.append(s)
            ucodes[i] = c
        if len(self._uuid_of) > len(self._count):
            grow = len(self._uuid_of)
            cnt = np.zeros(grow, np.int64)
            cnt[:len(self._count)] = self._count
            brn = np.zeros(grow)
            brn[:len(self._born)] = self._born
            self._count, self._born = cnt, brn
        codes = ucodes[inv]

        # per-row ordinal within this batch's per-code groups (stable):
        # timeless rows get index seconds = prior buffered count + ordinal,
        # matching the dict pipeline's per-record len(buf.points)
        t = cols.time.copy()
        nan = ~np.isfinite(t)
        if nan.any():
            order = np.argsort(codes, kind="stable")
            sorted_codes = codes[order]
            starts = np.nonzero(np.concatenate(
                [[True], sorted_codes[1:] != sorted_codes[:-1]]))[0]
            within = np.arange(cols.n, dtype=np.int64)
            within -= np.repeat(starts, np.diff(
                np.concatenate([starts, [cols.n]])))
            ordinal = np.empty(cols.n, np.int64)
            ordinal[order] = within
            t[nan] = (self._count[codes] + ordinal)[nan].astype(np.float64)

        fresh = self._count[ucodes] == 0
        self._born[ucodes[fresh]] = now
        np.add.at(self._count, codes, 1)

        self._log.append(code=codes, lat=cols.lat, lon=cols.lon, time=t,
                         acc=cols.accuracy, part=np.full(cols.n, p, np.int16),
                         off=offs, arrive=np.full(cols.n, now),
                         held=np.zeros(cols.n, np.int64), tless=nan)

    # ---- flush -----------------------------------------------------------

    def _stage_wave(self, ripe_codes: np.ndarray,
                    ) -> "_InflightWave | None":
        """STATEFUL half of wave prepare (pipeline thread ONLY): select
        the ripe rows, merge cache tails in wave order, compute the
        commit-floor holds, and mark the rows held=wave-id. Everything
        the next wave's selection or the commit floor can observe
        happens here — which is what lets ``_build_traces`` run on a
        read-ahead thread without reordering any stateful step."""
        t_prep0 = self.clock()
        L = self._log
        # direct lookup, not np.isin: codes are dense interned ints, so a
        # boolean table is one O(n) gather (isin re-sorts per wave)
        ripe_lut = np.zeros(len(self._count), bool)
        ripe_lut[ripe_codes] = True
        mask = ripe_lut[L.code[:L.n]] & (L.held[:L.n] == 0)
        rows = np.nonzero(mask)[0]
        if not len(rows):
            return None
        # ONE stable (code, time) lexsort orders every flushed vehicle's
        # slice time-ascending at once — the dict path's _validate_payload
        # sorts every payload before the cache merge, and parity requires
        # the same point order into the matcher (a per-vehicle argsort
        # here was the top host cost at firehose rates).
        order = rows[np.lexsort((L.time[rows], L.code[rows]))]
        codes_sorted = L.code[order]
        starts = np.nonzero(np.concatenate(
            [[True], codes_sorted[1:] != codes_sorted[:-1]]))[0]
        bounds = np.concatenate([starts, [len(order)]])

        # ONE gather per column, then per-vehicle contiguous views — the
        # per-vehicle fancy-index gathers + concats this replaces were
        # the prepare stage's top host cost at validation scale
        lat_w = L.lat[order]
        lon_w = L.lon[order]
        t_w = L.time[order]
        acc_w = L.acc[order]
        uuids = [self._uuid_of[int(codes_sorted[s])] for s in starts]
        lat_m, lon_m, t_m, acc_m, mb = self.cache.merge_wave(
            uuids, lat_w, lon_w, t_w, acc_w, bounds)

        # commit-floor holds + arrival copy, then mark the rows held
        parts = L.part[rows]
        offs = L.off[rows]
        holds = [(int(p), int(offs[parts == p].min()))
                 for p in np.unique(parts)]
        self._wave_serial += 1
        # codes_sorted is sorted, so its run starts ARE the unique codes
        wave = _InflightWave(self._wave_serial, codes_sorted[starts],
                             holds, L.arrive[rows].copy(),
                             n_points=int(mb[-1]))
        wave.uuids = uuids
        wave.merged_flat = (lat_m, lon_m, t_m, acc_m, mb)
        wave.t_prep0 = t_prep0
        L.held[rows] = wave.id
        self._count[ripe_codes] = 0
        return wave

    def _build_traces(self, wave: "_InflightWave") -> list:
        """PURE half of wave prepare: lonlat→xy + accuracy cleaning +
        matcher Trace construction from the wave's already-merged flat
        columns. Reads only the wave and immutable tileset metadata —
        safe on the read-ahead thread while later waves stage."""
        lat_m, lon_m, t_m, acc_m, mb = wave.merged_flat
        uuids = wave.uuids

        # one lonlat→xy conversion for every flushed point
        n_pts = wave.n_points
        lonlat = np.empty((n_pts, 2))
        lonlat[:, 0] = lon_m
        lonlat[:, 1] = lat_m
        xy = lonlat_to_xy(lonlat, np.asarray(
            self.matcher.ts.meta.origin_lonlat)).astype(np.float32)

        # per-vehicle accuracy presence + cleaning in whole-wave passes
        finite = np.isfinite(acc_m)
        if finite.any():
            has_acc = np.bitwise_or.reduceat(finite, mb[:-1])
            acc_clean = np.nan_to_num(acc_m, nan=0.0)
        else:
            has_acc = np.zeros(len(uuids), bool)
            acc_clean = acc_m      # unread: every vehicle gets None

        merged: list[tuple] = []
        traces = []
        for v, u in enumerate(uuids):
            lo, hi = int(mb[v]), int(mb[v + 1])
            merged.append((lat_m[lo:hi], lon_m[lo:hi], t_m[lo:hi],
                           acc_m[lo:hi]))
            traces.append(Trace(
                uuid=u, xy=xy[lo:hi], times=t_m[lo:hi],
                accuracy=(acc_clean[lo:hi] if has_acc[v] else None)))
        wave.merged = merged
        return traces

    def _prepare_wave(self, ripe_codes: np.ndarray,
                      ) -> "tuple[_InflightWave, list] | None":
        """Serial-arm wave prepare (the r6 shape): stateful staging +
        trace build inline on the caller's thread. The rows stay in the
        log marked held=wave-id until the result is processed."""
        wave = self._stage_wave(ripe_codes)
        if wave is None:
            return None
        traces = self._build_traces(wave)
        wave.t_submit = self.clock()
        return wave, traces

    def _match_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=1,
                                            thread_name_prefix="wave-match")
        return self._pool

    def _timed_match(self, traces):
        t0 = time.perf_counter()
        result = self.matcher.match_many(traces)
        return result, time.perf_counter() - t0

    def _submit_wave(self, ripe_codes: np.ndarray) -> bool:
        """Pipelined flush, submit half: hand the wave's device match to
        the one-thread executor and return immediately — the link wait
        happens there with the GIL released while the main loop keeps
        consuming."""
        prep = self._prepare_wave(ripe_codes)
        if prep is None:
            return False
        wave, traces = prep
        wave.future = self._match_pool().submit(self._timed_match, traces)
        self._inflight.append(wave)
        return True

    # ---- pipelined wave prepare (r22) -----------------------------------

    def _ra_worker(self):
        if self._ra is None:
            from reporter_tpu.utils.readahead import ReadAheadWorker
            self._ra = ReadAheadWorker(name="wave-prepare")
        return self._ra

    def _stage_readahead(self, ripe_codes: np.ndarray) -> bool:
        """Pipelined-prepare submit half: run the STATEFUL staging here
        (wave order preserved), hand the pure trace build + matcher
        prepare to the read-ahead thread, and queue the wave for
        promotion once a device slot frees."""
        wave = self._stage_wave(ripe_codes)
        if wave is None:
            return False
        # The prepared seam needs the REAL matcher (prepare_many +
        # match_many(prepared=...)). A duck-typed or monkeypatched
        # stand-in (the test harnesses' gate matchers) gets the plain
        # match_many call — the read-ahead thread still overlaps the
        # trace build, just not the pack.
        use_prepared = (getattr(self.matcher, "supports_prepared", False)
                        and "match_many" not in getattr(
                            self.matcher, "__dict__", {}))
        wave.prep = self._ra_worker().submit(
            lambda: self._build_prepared(wave, use_prepared))
        self._staged.append(wave)
        return True

    def _build_prepared(self, wave: "_InflightWave", use_prepared: bool):
        """Read-ahead thread body: the PURE prepare for one staged wave
        (trace build + plan/pack through the matcher's prepared seam).
        Touches no pipeline state — only the wave and endpoint-sampled
        overlap counters (single-writer ints; the gauge is an
        estimate)."""
        overlapped = bool(self._inflight)
        t0 = self.clock()
        traces = self._build_traces(wave)
        prepared = (self.matcher.prepare_many(traces)
                    if use_prepared else None)
        if self._tracer.enabled:
            # the overlapped prepare attributes to its OWN span; the
            # wave's `prepare` stage component still covers
            # t_prep0→t_submit so the telescoping stays arithmetic
            self._tracer.add("prepare_readahead", t0, self.clock(),
                             wave=wave.id, traces=len(traces),
                             packed=prepared is not None)
        self._overlap_total += 1
        if overlapped or self._inflight:
            self._overlap_hits += 1
        return traces, prepared

    def _promote_staged(self, drain: bool = False) -> None:
        """Move staged waves onto the device executor as slots free
        (FIFO — wave order is the parity contract). ``drain`` ignores
        the depth bound: shutdown must flush every staged wave."""
        while self._staged and (drain
                                or len(self._inflight) < self._depth):
            wave = self._staged.pop(0)
            wave.future = self._match_pool().submit(
                self._timed_match_staged, wave)
            self._inflight.append(wave)

    def _timed_match_staged(self, wave: "_InflightWave"):
        """Match-pool thread body for a read-ahead wave: wait for the
        prepare ticket, stamp t_submit (so the `prepare` stage component
        absorbs read-ahead queueing + slot wait and the components still
        telescope), then dispatch — with the prebuilt pack when the
        prepared seam produced one."""
        traces, prepared = wave.prep.result()
        wave.prep = None
        wave.t_submit = self.clock()
        t0 = time.perf_counter()
        if prepared is not None:
            result = self.matcher.match_many(traces, prepared=prepared)
        else:
            result = self.matcher.match_many(traces)
        return result, time.perf_counter() - t0

    def _harvest(self, block: bool) -> int:
        """Process completed waves in submission order (FIFO: wave N+1
        must not retain cache tails before wave N). The non-blocking form
        stops at the first still-running future."""
        n = 0
        while self._inflight and (block or self._inflight[0].future.done()):
            wave = self._inflight.pop(0)
            try:
                result, match_dt = wave.future.result()
                wave.t_result = self.clock()
                # the pop freed a device slot: promote a staged wave
                # BEFORE building this one's reports, so wave N+1
                # occupies the device while wave N's report build runs
                # (the three-stage overlap; prepare for N+2 rides the
                # read-ahead thread). Stateful order is safe: in-flight
                # waves are code-disjoint (_without_busy), so N+1's
                # merge_wave touched no vehicle N's retain_wave will.
                # Promote only on the success path — a failed wave's
                # rows must go back in play before anything advances.
                self._promote_staged()
                n += self._complete_wave(wave, result, match_dt)
            except DispatchTimeout:
                # graceful degradation, not death: the watchdog bounded a
                # wedged device dispatch (the tunnel hangs, it doesn't
                # error). Release the wave's held rows — the next step
                # re-selects and re-flushes them (the held-row contract;
                # bit-identical on a recovered link) — count it, and keep
                # the loop alive.
                self._release_failed(wave)
                self.dispatch_timeouts += 1
                self.matcher.metrics.gauge("stream_dispatch_timeouts",
                                           self.dispatch_timeouts)
            except BaseException:
                # matcher OR result-processing failure: either way the
                # rows must go back in play, not leak held forever (a
                # leaked hold pins the commit floor and broker retention
                # without bound). Retry may duplicate a partially
                # published wave — at-least-once, never lost.
                self._release_failed(wave)
                raise
        return n

    def _release_failed(self, wave: _InflightWave) -> None:
        """A failed wave's rows go back in play: held rows freed,
        per-code counts restored — the next step re-selects them and the
        supervisor's retry re-flushes (at-least-once; the commit floor
        never moved past them).

        Timeless rows consumed WHILE the wave was in flight were stamped
        index seconds from the submit-time-zeroed count (correct for the
        success path — the dict worker restarts at 0 after a successful
        flush). On failure the dict worker's buffer would have kept
        counting up instead, so re-base those stamps past the restored
        rows — otherwise the retry lexsort interleaves two runs of
        duplicate timestamps into one trace."""
        L = self._log
        rows = np.nonzero(L.held[:L.n] == wave.id)[0]
        held_counts = np.bincount(L.code[rows],
                                  minlength=len(self._count)).astype(np.int64)
        flight = np.nonzero((L.held[:L.n] == 0) & L.tless[:L.n]
                            & (held_counts[L.code[:L.n]] > 0))[0]
        L.time[flight] += held_counts[L.code[flight]].astype(np.float64)
        L.held[rows] = 0
        self._count += held_counts

    def _flush(self, ripe_codes: np.ndarray) -> int:
        """Sequential flush (pipeline_depth=0): match, report, publish in
        line — one wave, fully processed before returning. A watchdog
        timeout degrades exactly like the pipelined path's (_harvest):
        rows released for the next step's retry, counted, loop alive —
        NOT a raise that would kill the sequential worker loop."""
        prep = self._prepare_wave(ripe_codes)
        if prep is None:
            return 0
        wave, traces = prep
        try:
            result, match_dt = self._timed_match(traces)
            wave.t_result = self.clock()
            return self._complete_wave(wave, result, match_dt)
        except DispatchTimeout:
            self._release_failed(wave)
            self.dispatch_timeouts += 1
            self.matcher.metrics.gauge("stream_dispatch_timeouts",
                                       self.dispatch_timeouts)
            return 0
        except BaseException:
            self._release_failed(wave)   # same leak-proofing as _harvest
            raise

    def _complete_wave(self, wave: _InflightWave, result,
                       match_dt: float) -> int:
        """Result-processing half (always the pipeline's thread): build
        and publish reports, update histograms, retain cache tails,
        sample latency, drop the wave's rows from the log."""
        self.stats_counters["match_seconds"] += match_dt
        self.stats_counters["batches"] += 1
        self.stats_counters["traces"] += len(wave.uuids)
        self.stats_counters["points"] += wave.n_points

        if isinstance(result, MatchBatch):
            n = self._reports_from_columns(result, wave)
        else:   # python-walk fallback (no native lib): per-trace records
            n = self._reports_from_records(result, wave)

        # flushed rows leave the buffer; retained tails live in the cache
        L = self._log
        t_done = self.clock()
        lat = t_done - wave.arrive
        if self._tracer.enabled:
            self._record_wave_stages(wave, t_done, lat)
        # ACCUMULATE between reads: drain() completes many waves in one
        # call, and overwriting would silently discard every wave's
        # samples but the last — biasing p50/p99 low exactly for the
        # highest-latency backlog waves. Readers take-and-reset to None.
        # Bounded newest-N because the CLI worker has NO reader: an
        # uncapped accumulator grows one f64 per probe forever and pays
        # an O(history) concatenate per flush.
        prev = self.last_flush_latency
        acc = lat if prev is None else np.concatenate([prev, lat])
        if len(acc) > self._LAT_SAMPLES_CAP:
            acc = acc[-self._LAT_SAMPLES_CAP:]
        self.last_flush_latency = acc
        self._last_flush_p50 = (float(np.median(lat)) if len(lat) else None)
        L.compact(L.held[:L.n] != wave.id)
        self.waves_completed += 1
        return n

    def _record_wave_stages(self, wave: _InflightWave, t_done: float,
                            lat: np.ndarray) -> None:
        """Tracing-enabled wave bookkeeping: emit the wave's stage spans
        into the flight recorder and accumulate the per-probe stage
        components. The components partition each probe's timeline at
        the wave's recorded boundaries, so per probe they sum EXACTLY to
        its last_flush_latency sample — the reconciliation the bench leg
        asserts is arithmetic, not coincidence."""
        tr = self._tracer
        n = len(wave.arrive)
        if n and wave.t_prep0 is not None:
            tr.add("broker_dwell", float(wave.arrive.min()), wave.t_prep0,
                   wave=wave.id, points=wave.n_points)
            tr.add("prepare", wave.t_prep0, wave.t_submit, wave=wave.id)
            tr.add("device_match", wave.t_submit, wave.t_result,
                   wave=wave.id, traces=len(wave.uuids))
            tr.add("report_build", wave.t_result, t_done, wave=wave.id)
            comp = {
                "broker_dwell": wave.t_prep0 - wave.arrive,
                "prepare": np.full(n, wave.t_submit - wave.t_prep0),
                "device_match": np.full(n, wave.t_result - wave.t_submit),
                "report_build": np.full(n, t_done - wave.t_result),
                "e2e": lat,
            }
            self._stage_chunks.append(comp)
            self._stage_count += n
            # newest-N bound at whole-wave granularity (take trims to
            # the exact cap): a reader-less traced worker stays flat-RSS
            while (len(self._stage_chunks) > 1
                   and self._stage_count - len(self._stage_chunks[0]["e2e"])
                   >= self._LAT_SAMPLES_CAP):
                dropped = self._stage_chunks.pop(0)
                self._stage_count -= len(dropped["e2e"])

    def take_stage_samples(self) -> "dict[str, np.ndarray] | None":
        """Take-and-reset the accumulated per-probe stage components
        (None when tracing was off or nothing flushed). The arrays are
        parallel: row i of every stage belongs to the same probe, and
        the non-'e2e' stages sum to 'e2e' row-wise. 'publish' rides
        separately (per-wave POST attempt seconds — it completes after
        the probe→report cut on the async publisher)."""
        chunks, self._stage_chunks = self._stage_chunks, []
        self._stage_count = 0
        out = None
        if chunks:
            out = {k: np.concatenate([c[k] for c in chunks])
                   for k in chunks[0]}
            if len(out["e2e"]) > self._LAT_SAMPLES_CAP:
                out = {k: v[-self._LAT_SAMPLES_CAP:]
                       for k, v in out.items()}
        if out is not None and self._publish_durs:
            # swap FIRST, convert after: copy-then-reset would drop any
            # duration the async publisher thread appends between the
            # two statements
            durs, self._publish_durs = self._publish_durs, []
            out = dict(out, publish=np.asarray(durs))
        return out

    def _reports_from_columns(self, batch: MatchBatch,
                              wave: _InflightWave) -> int:
        from reporter_tpu.matcher import native_prepare

        uuids = wave.uuids
        cols = batch.columns
        # group-id chaining: the native single pass when the library is
        # up, the numpy builder otherwise — same outputs by contract
        # (fuzz-asserted in tests/test_native_prepare.py)
        rep = native_prepare.build_reports(cols, None,
                                           self.min_segment_length)
        if rep is None:
            rep = build_report_columns(cols, None, self.min_segment_length)
        seg, nxt, rt0, rt1, rlen, rqueue, _ = rep
        self.stats_counters["reports"] += len(seg)

        # per-trace latest complete time → tail retention cut
        done = np.full(len(uuids), -np.inf)
        keep = (cols.start_time >= 0.0) & (cols.end_time >= 0.0) \
            & ~cols.internal
        if keep.any():
            np.maximum.at(done, cols.trace[keep], cols.end_time[keep])
        self._retain_tails(wave, done)

        dur = rt1 - rt0
        okd = dur > 0
        pos = np.searchsorted(self._row_sorted, seg[okd])
        pos = np.minimum(pos, len(self._row_sorted) - 1)
        hrows = np.where(self._row_sorted[pos] == seg[okd],
                         self._row_order[pos], -1).astype(np.int32)
        self.hist.update(hrows, rlen[okd] / dur[okd])
        self.qhist.update(hrows, rqueue[okd])

        self._publish_wave(wave, "publish_columns",
                           (seg, nxt, rt0, rt1, rlen, rqueue))
        return int(len(seg))

    def _retain_tails(self, wave: _InflightWave, done: np.ndarray) -> None:
        """Cache-tail retention for a completed wave: every vehicle's
        cut computed in ONE pass over the wave's flat time column
        (native_prepare.tail_cuts, or its per-vehicle reference), then
        the stores with a single deferred TTL/capacity sweep — the same
        final cache state as per-vehicle retain(), without a numpy
        nonzero/max chain and an eviction scan per vehicle."""
        from reporter_tpu.matcher import native_prepare

        lat_m, lon_m, t_m, acc_m, mb = wave.merged_flat
        # from_time: the latest complete report end, else the vehicle's
        # first timestamp (the straddling-pair rule keeps one row before
        # that point either way)
        first_t = (t_m[mb[:-1]] if len(t_m)
                   else np.zeros(len(wave.uuids)))
        from_time = np.where(np.isfinite(done), done, first_t)
        los = native_prepare.tail_cuts(t_m, mb, from_time,
                                       self.cache.max_points)
        if los is None:
            los = native_prepare.tail_cuts_python(t_m, mb, from_time,
                                                  self.cache.max_points)
        self.cache.retain_wave(wave.uuids, lat_m, lon_m, t_m, acc_m, mb,
                               los)

    def _publish_wave(self, wave: _InflightWave, method: str,
                      args: tuple) -> None:
        """Publish a wave's reports, releasing its commit-floor hold when
        the POST ATTEMPT completes. With the async publisher (pipelined)
        the on_done callback fires from the publisher thread after the
        socket wait; the sync publisher calls it before returning — one
        code path, two latencies."""
        self._pending.append(wave)
        traced = self._tracer.enabled
        t_pub0 = self.clock() if traced else 0.0

        def _done(ok: bool, w=wave) -> None:
            w.published = True      # plain attribute flip: GIL-atomic
            if traced:
                t1 = self.clock()
                self._tracer.add("publish", t_pub0, t1, wave=w.id, ok=ok)
                if len(self._publish_durs) < 65536:   # reader-less bound
                    self._publish_durs.append(t1 - t_pub0)

        getattr(self.publisher, method)(*args, on_done=_done)

    def _reports_from_records(self, per_trace, wave: _InflightWave) -> int:
        """Fallback parity path over SegmentRecord lists (no native lib)."""
        from reporter_tpu.service.reports import (Report, build_reports,
                                                  latest_complete_time)

        uuids, merged = wave.uuids, wave.merged
        n = 0
        all_reports: list[Report] = []
        for (u, m, records) in zip(uuids, merged, per_trace):
            reports = build_reports(records, self.min_segment_length)
            all_reports.extend(reports)
            done = latest_complete_time(records)
            from_time = float(m[2][0]) if done is None else done
            self.cache.retain(u, m[0], m[1], m[2], m[3], from_time)
            n += len(reports)
        self.stats_counters["reports"] += n
        rows, speeds, queues = [], [], []
        for r in all_reports:
            dur = r.end_time - r.start_time
            if dur <= 0:
                continue
            pos = int(np.searchsorted(self._row_sorted, r.segment_id))
            pos = min(pos, len(self._row_sorted) - 1)
            row = (int(self._row_order[pos])
                   if self._row_sorted[pos] == r.segment_id else -1)
            rows.append(row)
            speeds.append(r.length / dur)
            queues.append(r.queue_length)
        self.hist.update(np.asarray(rows, np.int32),
                         np.asarray(speeds, np.float64))
        self.qhist.update(np.asarray(rows, np.int32),
                          np.asarray(queues, np.float64))
        self._publish_wave(wave, "publish", (all_reports,))
        return n

    def _commit(self) -> None:
        from reporter_tpu.streaming.state import commit_floor

        holds: "list[tuple[int, int]]" = []
        L = self._log
        if L.n:
            for p in self.partitions:
                m = L.part[:L.n] == p
                if m.any():
                    holds.append((p, int(L.off[:L.n][m].min())))
        # waves hold the floor until their publish attempt completes
        # (in-flight waves' rows are still in the log — the scan above
        # already covers them; the explicit holds make it airtight)
        self._pending = [w for w in self._pending if not w.published]
        for w in self._inflight + self._staged + self._pending:
            holds.extend(w.holds)
        self.committed = commit_floor(self._consumed, holds)

    # ---- histograms (same delta-flush contract as StreamPipeline) -------

    def flush_histograms(self) -> int:
        from reporter_tpu.streaming.state import flush_histogram_delta
        return flush_histogram_delta(self)

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        out = {
            "steps": self.steps,
            "malformed": self.malformed,
            "lag": sum(self.queue.end_offset(p) - self.committed[p]
                       for p in self.partitions),
            "buffered_uuids": int((self._count > 0).sum()),
            "buffered_points": int(self._count.sum()),
            "published": self.publisher.published,
            "publish_dropped": self.publisher.dropped,
            "hist_rows": int(len(self.hist.nonzero_rows())),
            "qhist_rows": int(len(self.qhist.nonzero_rows())),
            # pipelined-flush observability (mirrored as metrics gauges)
            "inflight_waves": len(self._inflight),
            "staged_waves": len(self._staged),
            "pipeline_prepare": bool(self._pp),
            "prepare_overlap_pct": (
                100.0 * self._overlap_hits / self._overlap_total
                if self._overlap_total else 0.0),
            "publish_pending": sum(1 for w in self._pending
                                   if not w.published),
            "wave_points": int(self._wave_points),
            "overrun": int(self.overrun),
            "dispatch_timeouts": int(self.dispatch_timeouts),
            "publish_retried": self.publisher.retried,
            "dead_lettered": self.publisher.dead_lettered,
            "dead_letter_pending": self.publisher.dead_letter_pending,
            # online quality telemetry (round 18): every completed wave
            # rode the matcher's per-metro quality window via
            # match_many, so the worker's stats face carries the same
            # windowed rates + drift state the service /health reports
            "quality": self.matcher.quality.snapshot(),
            **self.stats_counters,
        }
        overload = getattr(self.queue, "overload_stats", None)
        if overload is not None:
            out.update(overload())
        return out

    # ---- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Stop the background machinery (call drain() first for a
        graceful shutdown; close alone joins whatever is in flight)."""
        # order matters: the match pool first (promoted waves' tickets
        # need the read-ahead worker ALIVE to resolve), then the
        # read-ahead worker (never-promoted tickets fail loudly — a
        # stale ticket wait must error, not hang)
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._ra is not None:
            self._ra.close()
            self._ra = None
        self.publisher.close()

    # ---- checkpoint / resume (StreamPipeline-compatible npz) -------------

    def checkpoint(self, path: str) -> None:
        """Snapshot offsets + uuid cache + histograms at a CONSISTENT
        cut: in-flight waves are harvested and the publisher drained
        first (bounded by the transport timeout), so the snapshot is a
        wave boundary — bitwise-compatible with the dict worker's, as
        the cross-restore suite asserts. A crash that skips this (no
        checkpoint at all) restores from the previous cut, whose
        ``committed`` was clamped below every then-unpublished wave (see
        _commit) — replay covers the wave, at-least-once, never lost."""
        from reporter_tpu.streaming.state import save_checkpoint
        self._promote_staged(drain=True)
        self._harvest(block=True)
        self.publisher.drain()
        self._commit()
        save_checkpoint(path, self.committed, self.cache.dump(),
                        self.hist.snapshot(), self._hist_flushed,
                        self.qhist.snapshot(), self._qhist_flushed)

    def restore(self, path: str) -> None:
        from reporter_tpu.streaming.state import load_checkpoint
        state = load_checkpoint(path, self)
        self.committed = list(state["committed"])
        self._consumed = list(state["committed"])
        self._log = _Log()
        self._count[:] = 0
        self._inflight = []
        self._staged = []
        self._pending = []
        self._prev_lag = 0
        self._last_flush_p50 = None
        outage = max(0.0, time.time()
                     - float(state.get("saved_at", time.time())))
        self.cache.load(state["cache"], extra_age=outage)
