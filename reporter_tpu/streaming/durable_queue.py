"""DurableIngestQueue — file-backed probe log (Kafka's durability role).

The recovery model (SURVEY.md §5, streaming/pipeline.py) is "replay from
committed offsets: the buffer is derived state, the log is the truth". The
in-proc IngestQueue plays the broker for tests and single-process serving,
but it dies with the process — after a crash there is nothing to replay
FROM. This subclass persists the same offset-addressed log to disk, so a
restarted worker constructs its pipeline over the same directory and
replays the unflushed tail exactly like a Kafka consumer rejoining its
group. All offset/retention semantics live in IngestQueue (one source of
truth, contract-tested for both classes); this class only adds the
persistence hooks.

Layout under ``dir/``: one append-only JSON-lines file per partition
(``p0.log`` …). After a retention rewrite the first line is a header
``{"_floor": N}`` recording the partition's base offset — INSIDE the log,
so content and floor change in one atomic ``os.replace`` (a sidecar floor
file could desync from the log on a crash between two renames, silently
re-keying surviving records to wrong offsets).

Durability: appends are flushed to the OS on every call (crash-safe
against process death); ``fsync=True`` additionally fsyncs per append for
power-loss safety at a large throughput cost. A torn final line (killed
mid-write) is dropped on reload AND truncated from the file before the
append handle reopens — otherwise the next acked record would concatenate
onto the fragment and take every later record down with it on the
following reload.

Implements the ProbeConsumer protocol (streaming/broker.py);
contract-tested by tests/test_broker_contract.py alongside the in-proc
implementation.
"""

from __future__ import annotations

import json
import os

from reporter_tpu.streaming.queue import IngestQueue


def _encode(record: dict) -> bytes:
    return json.dumps(record, separators=(",", ":")).encode() + b"\n"


def read_broker_format(dir: str) -> "str | None":
    """The format a broker directory was created with ('records' |
    'columnar'), or None for a fresh/absent directory. The one meta.json
    defaulting rule — shared by both durable queue classes and the
    worker CLI's broker sniff."""
    meta_path = os.path.join(dir, "meta.json")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        return json.load(f).get("format", "records")


def open_or_create_meta(dir: str, fmt: str, num_partitions: int,
                        other_class: str) -> None:
    """Pin (or validate) a broker directory's identity: partition count
    and log format. The pin is written once, fsync'd (file AND
    directory) — losing it to a power cut while fsync'd records survive
    would let a mis-configured reopen recreate it wrong; a mismatched
    reopen is refused, never reinterpreted."""
    os.makedirs(dir, exist_ok=True)
    meta_path = os.path.join(dir, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        stored_fmt = meta.get("format", "records")
        if stored_fmt != fmt:
            raise ValueError(
                f"{dir}: broker log format is {stored_fmt!r}, not "
                f"{fmt!r} — directories are format-specific; use "
                f"{other_class} or a fresh directory")
        stored = int(meta["num_partitions"])
        if stored != num_partitions:
            raise ValueError(
                f"{dir}: log was created with num_partitions={stored}, "
                f"reopened with {num_partitions} — records would "
                "be orphaned/mis-routed; migrate explicitly instead")
        return
    with open(meta_path + ".tmp", "w") as f:
        json.dump({"num_partitions": num_partitions, "format": fmt}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_path + ".tmp", meta_path)
    dfd = os.open(dir, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class DurableIngestQueue(IngestQueue):
    """IngestQueue whose log survives the process."""

    def __init__(self, dir: str, num_partitions: int = 4,
                 fsync: bool = False,
                 max_records_per_partition: "int | None" = None,
                 overload_policy: str = "reject"):
        super().__init__(num_partitions, max_records_per_partition,
                         overload_policy)
        self.dir = dir
        self._fsync = bool(fsync)
        # The partition count and format are the log's identity: a
        # mismatched reopen is refused (open_or_create_meta), never
        # reinterpreted.
        open_or_create_meta(dir, "records", self.num_partitions,
                            other_class="DurableColumnarIngestQueue")
        self._files = []
        for p in range(self.num_partitions):
            base, records, good_bytes = self._load_partition(p)
            self._base[p] = base
            self._parts[p] = records
            path = self._log_path(p)
            if os.path.exists(path) and os.path.getsize(path) > good_bytes:
                # torn/corrupt tail: cut it from the FILE too, or the next
                # acked append merges into the fragment and poisons the
                # line after it on the following reload
                with open(path, "rb+") as f:
                    f.truncate(good_bytes)
            self._files.append(open(path, "ab"))

    # ---- persistence ----------------------------------------------------

    def _log_path(self, p: int) -> str:
        return os.path.join(self.dir, f"p{p}.log")

    def _load_partition(self, p: int) -> "tuple[int, list, int]":
        """(base offset, records, byte length of the valid prefix)."""
        base, records, good = 0, [], 0
        path = self._log_path(p)
        if not os.path.exists(path):
            return base, records, good
        with open(path, "rb") as f:
            first = True
            for line in f:
                if not line.endswith(b"\n"):
                    break               # torn tail from a mid-write crash
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    break               # corrupt tail: stop at last good
                if first and isinstance(obj, dict) and set(obj) == {"_floor"}:
                    base = int(obj["_floor"])
                else:
                    records.append(obj)
                first = False
                good += len(line)
        return base, records, good

    def close(self) -> None:
        with self._lock:
            for f in self._files:
                f.close()
            self._files = []

    # ---- IngestQueue durability hooks (run under the lock) ---------------

    def _persist(self, p: int, record: dict) -> None:
        f = self._files[p]
        f.write(_encode(record))
        f.flush()
        if self._fsync:
            os.fsync(f.fileno())

    def _persist_truncate(self, p: int) -> None:
        """Rewrite the partition log as header + surviving records, in one
        atomic rename — base and content can never desync."""
        self._files[p].close()
        tmp = self._log_path(p) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_encode({"_floor": self._base[p]}))
            for r in self._parts[p]:
                f.write(_encode(r))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path(p))
        if self._fsync:
            # Power-loss safety requires the RENAME to be durable too, or
            # later fsync'd appends land on an inode the replayed journal
            # may not point at; process-death safety doesn't need this.
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._files[p] = open(self._log_path(p), "ab")
