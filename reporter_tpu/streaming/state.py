"""Shared stream-worker state logic: histogram delta-flush and the
checkpoint file format.

Both pipeline flavors — the dict-record StreamPipeline and the columnar
ColumnarStreamPipeline — speak exactly this flush payload and this npz
checkpoint schema, ONE implementation, so a checkpoint written by either
restores into the other and a payload-field change cannot drift between
them (they are duck-typed over the attribute surface used here)."""

from __future__ import annotations

import json
import os
import time
from typing import Iterable, Sequence

import numpy as np

from reporter_tpu import faults


def commit_floor(consumed: Sequence[int],
                 holds: "Iterable[tuple[int, int]]") -> list:
    """Committed offsets = the consumer's read position clamped below
    every HOLD — ONE implementation for both pipeline flavors so the
    at-least-once floor rule cannot drift between them.

    A hold (partition, offset) is anything whose loss a crash must be
    able to replay: the oldest record in a per-uuid buffer (dict
    pipeline), the oldest unflushed log row (columnar), and — pipelined —
    the oldest record of any wave whose publish attempt hasn't completed.
    A checkpoint stores exactly this floor, so restoring replays every
    record that had not made it out the far side of the publisher."""
    floor = list(consumed)
    for p, off in holds:
        if off < floor[p]:
            floor[p] = off
    return floor


def poll_with_overrun_skip(pl, poll, p: int, max_records: int):
    """Poll partition ``p`` from pl._consumed[p], absorbing a drop-oldest
    overrun — ONE implementation of the broker-shed protocol for both
    pipeline flavors (the twin of commit_floor, and for the same reason).

    A LookupError from below the retention floor normally means
    unrecoverable data loss and re-raises; but when the broker exposes
    ``retention_floor`` and the floor has genuinely advanced past our
    read position, the records were SHED by an overload policy: skip to
    the floor, count the gap in ``pl.overrun`` (explicit, never silent),
    and poll again. ``poll(p, offset, max_records)`` is the pipeline's
    poll callable; returns its result."""
    while True:
        try:
            return poll(p, pl._consumed[p], max_records)
        except LookupError:
            floor_fn = getattr(pl.queue, "retention_floor", None)
            if floor_fn is None:
                raise
            floor = int(floor_fn(p))
            if floor <= pl._consumed[p]:
                raise              # not an overrun: a real offset bug
            pl.overrun += floor - pl._consumed[p]
            pl._consumed[p] = floor


def flush_histogram_delta(pl) -> int:
    """Publish the per-segment speed + queue histogram DELTA since the
    last flush (SURVEY.md §7.7 / BASELINE config 5). Returns the number
    of segments flushed. The baseline advances only on successful
    publish, so a failed POST retries the same delta next interval.

    ``pl``: any pipeline with hist/qhist, _hist_flushed/_qhist_flushed,
    _hist_flush_at, clock, config, _osmlr_ids, publisher, hist_flushes.
    """
    snap = pl.hist.snapshot()
    qsnap = pl.qhist.snapshot()
    delta = snap - pl._hist_flushed
    qdelta = qsnap - pl._qhist_flushed
    rows = np.nonzero(delta.sum(axis=1))[0]
    qrows = np.nonzero(qdelta.sum(axis=1))[0]
    pl._hist_flush_at = pl.clock()
    if not len(rows) and not len(qrows):
        return 0
    payload = {
        "mode": pl.config.service.mode,
        "bin_edges_mps": list(pl.config.streaming.speed_bins),
        "histograms": [
            {"segment_id": int(pl._osmlr_ids[r]),
             "counts": delta[r].astype(int).tolist()}
            for r in rows
        ],
        "queue_bin_edges_m": list(pl.config.streaming.queue_bins),
        "queue_histograms": [
            {"segment_id": int(pl._osmlr_ids[r]),
             "counts": qdelta[r].astype(int).tolist()}
            for r in qrows
        ],
    }
    if pl.publisher.publish_json(payload):
        pl._hist_flushed = snap
        pl._qhist_flushed = qsnap
        pl.hist_flushes += 1
        # Count any segment with a published delta (speed OR queue):
        # callers use 0 to mean "nothing flushed / publish failed".
        return int(len(np.union1d(rows, qrows)))
    return 0


def save_checkpoint(path: str, committed: list, cache_dump: dict,
                    hist_snap, hist_flushed, qhist_snap,
                    qhist_flushed) -> None:
    """One-file snapshot: offsets + uuid cache + both histograms.

    Buffers are NOT stored: committed offsets sit at the oldest unflushed
    record, so replaying from them reconstructs every buffer exactly —
    the buffer is derived state, the log is the truth.

    ATOMIC: the snapshot is written to a tmp file, fsync'd, and renamed
    over the old one — a worker killed mid-checkpoint (the chaos leg
    SIGKILLs exactly here sometimes) leaves either the old complete
    snapshot or the new complete snapshot, never a torn npz that a
    restart would crash parsing. The ``checkpoint`` fault site fires
    between write and rename: the simulated death the contract covers."""
    state = {
        "committed": committed,
        "cache": cache_dump,
        "saved_at": time.time(),   # wall clock: outage spans processes
    }
    if not path.endswith(".npz"):
        path += ".npz"   # savez appends it; normalize so restore matches
    tmp = path + ".tmp.npz"        # savez would append .npz to a bare tmp
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            state=np.frombuffer(json.dumps(state).encode(), dtype=np.uint8),
            hist=hist_snap,
            hist_flushed=hist_flushed,
            qhist=qhist_snap,
            qhist_flushed=qhist_flushed)
        f.flush()
        os.fsync(f.fileno())
    faults.fire("checkpoint")      # injected mid-checkpoint death: tmp is
    #                                on disk, the rename never happens —
    #                                the previous snapshot must survive
    os.replace(tmp, path)


def load_checkpoint(path: str, pl) -> dict:
    """Restore histograms + flush baselines into ``pl`` (hist, qhist,
    _hist_flushed, _qhist_flushed) and return the JSON state
    {committed, cache, saved_at}. Handles pre-queue / pre-baseline
    checkpoints the way the original restore() did."""
    if not path.endswith(".npz"):
        path += ".npz"
    with np.load(path) as z:
        state = json.loads(bytes(z["state"]).decode())
        pl.hist.load(z["hist"])
        if "hist_flushed" in z.files:
            pl._hist_flushed = z["hist_flushed"]
        else:   # older checkpoint: re-flush everything (at-least-once)
            pl._hist_flushed = np.zeros_like(pl.hist.snapshot())
        if "qhist" in z.files:
            pl.qhist.load(z["qhist"])
            pl._qhist_flushed = z["qhist_flushed"]
        else:   # pre-queue checkpoint: start the queue track empty
            pl.qhist.load(np.zeros_like(pl.qhist.snapshot()))
            pl._qhist_flushed = pl.qhist.snapshot()
    return state
