"""IngestQueue — partitioned, offset-addressed probe log.

The Kafka-broker analog (SURVEY.md §2.3, §5 "host ingest queue with
replayable offsets"): records are appended to uuid-hash partitions,
consumers poll (partition, offset) ranges, and nothing is destroyed by
consumption — replay from any retained offset is the recovery mechanism.
"""

from __future__ import annotations

import zlib
from typing import Any, Sequence

from reporter_tpu.utils import locks


def partition_of(uuid: str, num_partitions: int) -> int:
    """Stable uuid→partition hash (crc32 — processes must agree, so no
    Python string-hash randomization)."""
    return zlib.crc32(uuid.encode()) % num_partitions


class IngestQueue:
    """Thread-safe partitioned append log with offset-based polling.

    ``max_records_per_partition`` bounds the retained backlog with the
    same counted overload policies as the columnar broker ("reject":
    producer-side refusal, ``append`` returns (partition, -1) and counts
    ``rejected``; "drop_oldest": the retention floor advances past aged
    records, counted in ``dropped_oldest``) — see
    ColumnarIngestQueue's docstring for the policy contract."""

    def __init__(self, num_partitions: int = 4,
                 max_records_per_partition: "int | None" = None,
                 overload_policy: str = "reject"):
        self.num_partitions = int(num_partitions)
        if overload_policy not in ("reject", "drop_oldest"):
            raise ValueError(f"unknown overload_policy {overload_policy!r};"
                             " use 'reject' or 'drop_oldest'")
        self.max_records_per_partition = (
            None if max_records_per_partition is None
            else int(max_records_per_partition))
        self.overload_policy = overload_policy
        self.rejected = 0
        self.dropped_oldest = 0
        self._parts: list[list[Any]] = [[] for _ in range(self.num_partitions)]
        self._base: list[int] = [0] * self.num_partitions   # offset of _parts[p][0]
        self._lock = locks.named_lock("broker.partitions")

    def append(self, record: dict) -> tuple[int, int]:
        """Producer API: route by record["uuid"], return (partition,
        offset); (partition, -1) when a "reject"-policy bound refused it."""
        p = partition_of(str(record.get("uuid", "")), self.num_partitions)
        bound = self.max_records_per_partition
        with self._lock:
            if bound is not None and len(self._parts[p]) >= bound:
                if self.overload_policy == "reject":
                    self.rejected += 1
                    return p, -1
                # shed a CHUNK, not one record: a per-record shed at the
                # bound costs an O(bound) list copy — and for the durable
                # subclass a full partition-file rewrite + fsync — per
                # appended probe, exactly when the broker is overloaded.
                # Chunking amortizes that to ~8 rewrites per bound-fill.
                drop = max(1, bound // 8, len(self._parts[p]) - bound + 1)
                drop = min(drop, len(self._parts[p]))
                self._parts[p] = self._parts[p][drop:]
                self._base[p] += drop
                self.dropped_oldest += drop
                self._persist_truncate(p)
            self._persist(p, record)
            self._parts[p].append(record)
            return p, self._base[p] + len(self._parts[p]) - 1

    def retention_floor(self, partition: int) -> int:
        """Oldest pollable offset (consumers skip here after an overrun
        LookupError)."""
        with self._lock:
            return self._base[partition]

    def overload_stats(self) -> dict:
        """Counted shedding outcomes for /stats surfaces."""
        with self._lock:
            return {"broker_policy": self.overload_policy,
                    "broker_bound": self.max_records_per_partition,
                    "broker_rejected": int(self.rejected),
                    "broker_dropped_oldest": int(self.dropped_oldest)}

    def _persist(self, p: int, record: dict) -> None:
        """Durability hook (DurableIngestQueue): runs under the lock BEFORE
        the in-memory append, so on-disk line order always matches offset
        order even with concurrent producers. No-op in-proc."""

    def append_many(self, records: Sequence[dict]) -> None:
        for r in records:
            self.append(r)

    def poll(self, partition: int, offset: int,
             max_records: int) -> list[tuple[int, dict]]:
        """Records at or after ``offset`` (as [(offset, record)…])."""
        with self._lock:
            base = self._base[partition]
            if offset < base:
                raise LookupError(
                    f"offset {offset} below retention floor {base} "
                    f"(partition {partition})")
            lo = offset - base
            chunk = self._parts[partition][lo:lo + max_records]
            return [(offset + i, r) for i, r in enumerate(chunk)]

    def end_offset(self, partition: int) -> int:
        with self._lock:
            return self._base[partition] + len(self._parts[partition])

    def lag(self, committed: Sequence[int]) -> int:
        """Total records past the given per-partition committed offsets."""
        return sum(self.end_offset(p) - committed[p]
                   for p in range(self.num_partitions))

    def truncate(self, committed: Sequence[int]) -> None:
        """Drop records below the committed offsets (retention)."""
        with self._lock:
            for p, off in enumerate(committed):
                drop = max(0, off - self._base[p])
                if drop:
                    self._parts[p] = self._parts[p][drop:]
                    self._base[p] += drop
                    self._persist_truncate(p)

    def _persist_truncate(self, p: int) -> None:
        """Durability hook: rewrite partition p's backing store to match
        the truncated in-memory state. Runs under the lock. No-op in-proc."""
