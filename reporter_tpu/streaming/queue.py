"""IngestQueue — partitioned, offset-addressed probe log.

The Kafka-broker analog (SURVEY.md §2.3, §5 "host ingest queue with
replayable offsets"): records are appended to uuid-hash partitions,
consumers poll (partition, offset) ranges, and nothing is destroyed by
consumption — replay from any retained offset is the recovery mechanism.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Sequence


def partition_of(uuid: str, num_partitions: int) -> int:
    """Stable uuid→partition hash (crc32 — processes must agree, so no
    Python string-hash randomization)."""
    return zlib.crc32(uuid.encode()) % num_partitions


class IngestQueue:
    """Thread-safe partitioned append log with offset-based polling."""

    def __init__(self, num_partitions: int = 4):
        self.num_partitions = int(num_partitions)
        self._parts: list[list[Any]] = [[] for _ in range(self.num_partitions)]
        self._base: list[int] = [0] * self.num_partitions   # offset of _parts[p][0]
        self._lock = threading.Lock()

    def append(self, record: dict) -> tuple[int, int]:
        """Producer API: route by record["uuid"], return (partition, offset)."""
        p = partition_of(str(record.get("uuid", "")), self.num_partitions)
        with self._lock:
            self._persist(p, record)
            self._parts[p].append(record)
            return p, self._base[p] + len(self._parts[p]) - 1

    def _persist(self, p: int, record: dict) -> None:
        """Durability hook (DurableIngestQueue): runs under the lock BEFORE
        the in-memory append, so on-disk line order always matches offset
        order even with concurrent producers. No-op in-proc."""

    def append_many(self, records: Sequence[dict]) -> None:
        for r in records:
            self.append(r)

    def poll(self, partition: int, offset: int,
             max_records: int) -> list[tuple[int, dict]]:
        """Records at or after ``offset`` (as [(offset, record)…])."""
        with self._lock:
            base = self._base[partition]
            if offset < base:
                raise LookupError(
                    f"offset {offset} below retention floor {base} "
                    f"(partition {partition})")
            lo = offset - base
            chunk = self._parts[partition][lo:lo + max_records]
            return [(offset + i, r) for i, r in enumerate(chunk)]

    def end_offset(self, partition: int) -> int:
        with self._lock:
            return self._base[partition] + len(self._parts[partition])

    def lag(self, committed: Sequence[int]) -> int:
        """Total records past the given per-partition committed offsets."""
        return sum(self.end_offset(p) - committed[p]
                   for p in range(self.num_partitions))

    def truncate(self, committed: Sequence[int]) -> None:
        """Drop records below the committed offsets (retention)."""
        with self._lock:
            for p, off in enumerate(committed):
                drop = max(0, off - self._base[p])
                if drop:
                    self._parts[p] = self._parts[p][drop:]
                    self._base[p] += drop
                    self._persist_truncate(p)

    def _persist_truncate(self, p: int) -> None:
        """Durability hook: rewrite partition p's backing store to match
        the truncated in-memory state. Runs under the lock. No-op in-proc."""
