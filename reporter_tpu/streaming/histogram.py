"""Device-resident per-segment speed histograms (BASELINE config 5).

The datastore's product is per-segment speed statistics; in streaming mode
we keep the live histogram ON DEVICE — an i32 [G, B] array updated by a
jit'd scatter-add per flushed batch — so the accumulator scales with the
matcher instead of becoming host-side pointer chasing. Snapshots come back
to host only for checkpointing / publishing. Under multi-chip data
parallelism the same array is what the multimetro step psums over "dp"
(parallel/multimetro.py); this class is the single-chip/streaming face.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def _accumulate(hist, rows, bins, ok):
    # dtype pinned: where(ok, 1, 0) materializes in the DEFAULT int width
    # (i64 under x64) before the astype — the bool cast is the same
    # values with the width pinned (device-contract x64 audit)
    upd = ok.astype(jnp.int32)
    return hist.at[jnp.maximum(rows, 0), jnp.maximum(bins, 0)].add(upd)


class SpeedHistogram:
    """i32 [num_rows, num_bins] observation counts; bin = speed (m/s) bucket."""

    def __init__(self, num_rows: int, bin_edges: tuple[float, ...]):
        self.bin_edges = np.asarray(bin_edges, np.float64)
        self.num_bins = len(bin_edges)          # last bin is open-ended
        self.num_rows = int(num_rows)
        self._hist = jnp.zeros((self.num_rows, self.num_bins), jnp.int32)

    # ONE batch shape for the jit'd scatter (updates pad to it; bigger
    # batches chunk through it): the r5 next-power-of-two padding still
    # left one executable per cap, and jit TRACE+LOWER is per process
    # per shape (~150 ms on the one-core box, NOT covered by the
    # persistent compile cache) — a fresh cap ballooning a measured
    # wave's report_build stage was exactly the r12 attribution noise.
    # A fixed shape compiles once, in the warm-up wave.
    _CAP = 4096

    def update(self, rows: np.ndarray, speeds: np.ndarray) -> None:
        """Add one observation per (segment row, speed m/s) pair."""
        if len(rows) == 0:
            return
        rows = np.asarray(rows, np.int32)
        bins = (np.searchsorted(self.bin_edges, np.asarray(speeds),
                                side="right") - 1).astype(np.int32)
        ok = (rows >= 0) & (rows < self.num_rows) & (bins >= 0)
        for lo in range(0, len(rows), self._CAP):
            r = rows[lo:lo + self._CAP]
            pad = self._CAP - len(r)
            b = bins[lo:lo + self._CAP]
            o = ok[lo:lo + self._CAP]
            if pad:
                r = np.pad(r, (0, pad))
                b = np.pad(b, (0, pad))
                o = np.pad(o, (0, pad))
            self._hist = _accumulate(self._hist, jnp.asarray(r),
                                     jnp.asarray(b), jnp.asarray(o))

    def snapshot(self) -> np.ndarray:
        """Host copy [num_rows, num_bins]."""
        return np.asarray(self._hist)

    def load(self, hist: np.ndarray) -> None:
        assert hist.shape == (self.num_rows, self.num_bins)
        self._hist = jnp.asarray(hist.astype(np.int32))

    def nonzero_rows(self) -> np.ndarray:
        return np.nonzero(self.snapshot().sum(axis=1))[0]
