"""StreamWorker — background thread driving a StreamPipeline.

The process shape of the reference's Kafka matcher workers (SURVEY.md §3.3:
one consumer-group member per partition set). Each worker owns a pipeline
(and through it a disjoint partition subset); a host can run several
workers as threads — while one blocks on the device link, the others
ingest and publish, which is the host-side half of the survey's
"double-buffered infeed" pipeline parallelism row (§2.3 PP).

Failure model: a worker that dies leaves its partitions' committed offsets
behind (pipeline.checkpoint, or simply its `committed` list); constructing
a replacement pipeline over those partitions and restoring from the
checkpoint replays the unflushed tail — the consumer-group rebalance
analog, tested in tests/test_streaming.py.
"""

from __future__ import annotations

import threading
import time

from reporter_tpu.streaming.pipeline import StreamPipeline


class StreamWorker:
    """Drives pipeline.step() until stopped; drains on stop by default."""

    def __init__(self, pipeline: StreamPipeline, poll_interval: float = 0.02,
                 name: str | None = None):
        self.pipeline = pipeline
        self.poll_interval = float(poll_interval)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=name or f"stream-worker-{id(self) & 0xFFFF:04x}")
        self.reports = 0
        self.errors = 0

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "StreamWorker":
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # Still inside step() (e.g. a cold-compile batch): draining here
            # would race the worker thread through the non-thread-safe
            # pipeline. The loop will exit after the in-flight step.
            return
        if drain:
            self.reports += self.pipeline.drain()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    # ---- loop ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                n = self.pipeline.step()
                self.reports += n
            except Exception:
                # Keep the worker alive (supervisor semantics): unflushed
                # buffers hold the commit floor, so the next step retries.
                self.errors += 1
                n = 0
            if n == 0:
                time.sleep(self.poll_interval)
