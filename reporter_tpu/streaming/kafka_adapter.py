"""KafkaProbeConsumer — a concrete ProbeConsumer over a Kafka client.

The reference's matcher workers consume probe records from Kafka
(SURVEY.md §3.3 "Kafka streaming workers"); StreamPipeline only depends on
the ProbeConsumer seam (streaming/broker.py). This adapter closes the gap
with a real adapter class written against the kafka-python consumer API
shape — ``KafkaConsumer`` is duck-typed and INJECTED, so the adapter is
fully testable with a fake client (tests/test_kafka_adapter.py runs the
shared contract suite over it) and this environment's lack of a broker or
the kafka-python package never matters. With the real package:

    from kafka import KafkaConsumer
    client = KafkaConsumer(bootstrap_servers=..., enable_auto_commit=False,
                           auto_offset_reset="none", group_id=None)
    pipeline = StreamPipeline(ts, cfg,
                              queue=KafkaProbeConsumer(client, "probes"))

Client surface used (kafka-python names and semantics):
  partitions_for_topic(topic) → set[int]
  assign([TopicPartition...]); seek(tp, offset); pause(*tps); resume(*tps)
  poll(timeout_ms=..., max_records=...) → {tp: [records with
      .offset/.value]}
  end_offsets([tp]) → {tp: int}

Mapping to the ProbeConsumer contract:
  - poll(p, off, n): resume partition p, pause the rest, seek to ``off``,
    then drain client.poll until ``n`` records or a poll comes back empty.
    Kafka's fetch is cursor-based; the explicit seek makes it
    offset-addressed the way the pipeline's replay recovery requires.
  - end_offset(p): end_offsets round trip.
  - OffsetOutOfRange (polling below the broker's retention floor) →
    LookupError, the contract's data-loss signal. Configure the real
    client with auto_offset_reset="none": "earliest"/"latest" would
    silently skip records instead of surfacing the loss.

``TopicPartition`` here is a structural twin of kafka-python's (both are
(topic, partition) namedtuples; equality and hashing are tuple-based, so
either type keys the other's dicts).
"""

from __future__ import annotations

import json
from typing import Any, NamedTuple


class TopicPartition(NamedTuple):
    topic: str
    partition: int


def _is_offset_out_of_range(exc: BaseException) -> bool:
    """kafka-python raises kafka.errors.OffsetOutOfRangeError; match by
    name so the real package is never imported here."""
    return any(t.__name__ == "OffsetOutOfRangeError"
               for t in type(exc).__mro__)


class KafkaProbeConsumer:
    """ProbeConsumer over an injected kafka-python-shaped client."""

    def __init__(self, client: Any, topic: str, *,
                 poll_timeout_ms: int = 500):
        parts = client.partitions_for_topic(topic)
        if not parts:
            raise ValueError(f"topic {topic!r} has no partitions "
                             "(missing, or metadata not yet fetched)")
        self.num_partitions = max(parts) + 1
        if set(parts) != set(range(self.num_partitions)):
            raise ValueError(f"topic {topic!r} partitions {sorted(parts)} "
                             "are not dense 0..P-1")
        self._client = client
        self._topic = topic
        self._timeout_ms = int(poll_timeout_ms)
        self._tps = [TopicPartition(topic, p)
                     for p in range(self.num_partitions)]
        # manual assignment, not subscribe(): partition ownership is the
        # PIPELINE's concern (its consumer-group analog hands partitions
        # to workers); the broker-side group protocol stays out of the loop
        client.assign(list(self._tps))

    # ---- ProbeConsumer -------------------------------------------------

    def poll(self, partition: int, offset: int,
             max_records: int) -> "list[tuple[int, dict]]":
        tp = self._tps[partition]
        others = [t for t in self._tps if t is not tp]
        try:
            if others:
                self._client.pause(*others)
            self._client.resume(tp)
            self._client.seek(tp, offset)
            out: list[tuple[int, dict]] = []
            while len(out) < max_records:
                batch = self._client.poll(
                    timeout_ms=self._timeout_ms,
                    max_records=max_records - len(out))
                recs = (batch or {}).get(tp, [])
                if not recs:
                    break               # caught up (or fetch timeout)
                for r in recs:
                    if r.offset < offset:   # pre-seek fetch straggler
                        continue
                    out.append((int(r.offset), self._decode(r.value)))
            return out
        except Exception as exc:
            if _is_offset_out_of_range(exc):
                raise LookupError(
                    f"partition {partition} offset {offset} is below the "
                    "broker retention floor (data loss)") from exc
            raise

    def end_offset(self, partition: int) -> int:
        tp = self._tps[partition]
        return int(self._client.end_offsets([tp])[tp])

    # ---- helpers -------------------------------------------------------

    @staticmethod
    def _decode(value: Any) -> dict:
        """bytes → JSON record; dicts pass through (a client configured
        with value_deserializer=json.loads hands us dicts already)."""
        if isinstance(value, dict):
            return value
        if isinstance(value, (bytes, bytearray)):
            value = value.decode("utf-8")
        return json.loads(value)
