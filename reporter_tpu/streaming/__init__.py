"""Streaming ingest — the reference's Kafka path, TPU-hosted (SURVEY.md §3.3).

Reference pipeline:  probe producer → topic "raw" → formatter worker →
topic "formatted" (partitioned by uuid) → matcher workers (consumer group,
per-uuid buffers) → datastore.

Here the broker becomes a partitioned log with replayable offsets behind
the ProbeConsumer protocol (broker.py) — in-memory (queue.IngestQueue) or
file-backed so the log survives the process (durable_queue.
DurableIngestQueue, Kafka's durability role); the matcher worker becomes
StreamPipeline,
which buffers per uuid, flushes ripe buffers through the batched device
matcher, accumulates per-segment speed histograms in device memory, and
checkpoints offsets + buffers + histograms for crash recovery
(at-least-once, like the reference's consumer groups).
"""

from reporter_tpu.streaming.broker import ProbeConsumer
from reporter_tpu.streaming.columnar import (
    ColumnarIngestQueue,
    ColumnarStreamPipeline,
    ColumnarTraceCache,
    ProbeColumns,
    pack_records,
)
from reporter_tpu.streaming.formatter import ProbeFormatter
from reporter_tpu.streaming.queue import IngestQueue
from reporter_tpu.streaming.durable_queue import DurableIngestQueue
from reporter_tpu.streaming.durable_columnar import DurableColumnarIngestQueue
from reporter_tpu.streaming.histogram import SpeedHistogram
from reporter_tpu.streaming.pipeline import StreamPipeline
from reporter_tpu.streaming.worker import StreamWorker

__all__ = ["ColumnarIngestQueue", "ColumnarStreamPipeline",
           "ColumnarTraceCache", "DurableColumnarIngestQueue",
           "DurableIngestQueue", "IngestQueue",
           "ProbeColumns", "ProbeConsumer", "ProbeFormatter",
           "SpeedHistogram", "StreamPipeline", "StreamWorker",
           "pack_records"]
