"""Metro fleet residency — many compiled metros per chip, LRU-paged HBM.

ROADMAP item 1: the staging plan says one bayarea-xl-scale metro uses
~176 MB of a ~12.8 GB HBM budget, so "one deployment = one metro" wastes
~98% of the chip. This module is the fleet layer that packs many
compiled metro tables onto one chip and pages the cold ones, following
the partition-the-planet strategy of large-scale map matching
(PAPERS.md, arXiv:1910.05312) with the hot/cold filter-refine residency
split of SeGraM (arXiv:2205.05883):

  hot tier   metros with device tables staged in HBM, serving;
  cold tier  metros demoted to HOST-PINNED staged arrays
             (``TileSet.host_tables`` — the expensive cell_pack /
             seg_pack build is done ONCE and kept), costing zero HBM;
  paging     a request for a cold metro promotes it behind a counted,
             traced ``fleet_promote`` span: one ``jax.device_put`` of
             the pinned host dict, then ``restage_tables`` on the
             metro's long-lived SegmentMatcher — the wire entries take
             tables as call arguments, so the matcher's compiled
             executables survive any number of evict→promote cycles
             and re-promotion never recompiles.

Capacity policy (``FleetConfig``): a max-resident-bytes budget, LRU
eviction that drains occupancy below a watermark fraction of the budget
(hysteresis — one promotion must not trigger an eviction per request at
the boundary), and a pin list for SLO metros that are never evicted.
Metros mid-dispatch (leased) are never evicted either: eviction drops
our references, and a dispatch that STARTED after the drop would see no
tables — the lease makes promote→dispatch atomic against eviction.

Bit-identity contract (test- and bench-asserted): a fleet-resident
metro's harvested wire bytes equal a dedicated single-metro
SegmentMatcher's for the same traces, including immediately after an
evict→promote cycle — promotion re-places the SAME host values through
the SAME wire programs, so this holds by construction and the tests
keep it that way.

Per-metro observability: ``rtpu_fleet_*`` labeled counters/gauges
(utils.metrics.labeled) plus a fixed-bucket ``fleet_promote_seconds``
histogram — aggregable across workers like every other exposition
series (round-10 discipline).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from dataclasses import dataclass
from typing import Iterator, Sequence

from reporter_tpu.utils import locks
from reporter_tpu import faults
from reporter_tpu.config import Config
from reporter_tpu.utils import watchdog as watchdog_mod
from reporter_tpu.utils.watchdog import AbandonedThreadWatchdog
from reporter_tpu.matcher.api import SegmentMatcher
from reporter_tpu.service.scheduler import ServiceOverloaded
from reporter_tpu.tiles.tileset import TileSet
from reporter_tpu.utils import tracing
from reporter_tpu.utils.metrics import MetricsRegistry, labeled


class FleetCapacityError(ServiceOverloaded):
    """No way to make a metro resident: the budget is full of pinned or
    mid-dispatch metros (or the metro alone exceeds the budget).
    Subclasses ServiceOverloaded so the WSGI face sheds it as a
    retryable 503, exactly like admission-queue overflow — overload
    degrades explicitly (round-6 discipline), whichever resource ran
    out."""


@dataclass(frozen=True)
class FleetConfig:
    """Residency capacity policy. Env overrides (``RTPU_FLEET_*``)
    follow the matcher-lever discipline: applied at construction,
    validated strictly, so a typo fails loudly instead of silently
    serving an unbounded fleet."""

    max_resident_bytes: int = 0        # HBM budget for staged metro
    #                                    tables; 0 = unbounded (no
    #                                    paging — every metro promotes
    #                                    once and stays)
    evict_watermark: float = 0.85      # eviction drains occupancy (incl.
    #                                    the incoming metro) below this
    #                                    fraction of the budget, not just
    #                                    barely under it — hysteresis so
    #                                    a fleet at the boundary doesn't
    #                                    page on every alternate request
    pins: tuple[str, ...] = ()         # SLO metros never evicted (their
    #                                    bytes still count against the
    #                                    budget)
    promote_wait_s: float = 5.0        # a promotion blocked ONLY by
    #                                    in-flight leases waits up to this
    #                                    long for dispatches to release
    #                                    before shedding 503 — a lease is
    #                                    transient (one dispatch), unlike
    #                                    a pin; 0 = shed immediately
    promote_timeout_s: float = 0.0     # page-in watchdog: the axon tunnel
    #                                    dies by HANGING (CLAUDE.md), and
    #                                    promotion's device_put is a device
    #                                    interaction on the serving path —
    #                                    unbounded, one dead-tunnel page-in
    #                                    wedges every request for that
    #                                    metro. >0 bounds the transfer on
    #                                    a watchdog thread (same
    #                                    abandoned-thread breaker
    #                                    discipline as the r9 dispatch
    #                                    watchdog); 0 = off, matching
    #                                    matcher.dispatch_timeout_s's
    #                                    opt-in default. Size it for the
    #                                    TABLE bytes (~7 s for a 176 MB
    #                                    metro at 25 MB/s), not for one
    #                                    dispatch.

    def validate(self) -> "FleetConfig":
        if self.max_resident_bytes < 0:
            raise ValueError("fleet.max_resident_bytes must be >= 0")
        if not 0.0 < self.evict_watermark <= 1.0:
            raise ValueError("fleet.evict_watermark must be in (0, 1]")
        if self.promote_wait_s < 0:
            raise ValueError("fleet.promote_wait_s must be >= 0")
        if self.promote_timeout_s < 0:
            raise ValueError("fleet.promote_timeout_s must be >= 0")
        return self

    def with_env_overrides(self, env: "dict[str, str] | None" = None,
                           ) -> "FleetConfig":
        e = os.environ if env is None else env
        kw: dict = {}
        if "RTPU_FLEET_MAX_BYTES" in e:
            kw["max_resident_bytes"] = int(float(e["RTPU_FLEET_MAX_BYTES"]))
        if "RTPU_FLEET_WATERMARK" in e:
            kw["evict_watermark"] = float(e["RTPU_FLEET_WATERMARK"])
        if "RTPU_FLEET_PROMOTE_WAIT" in e:
            kw["promote_wait_s"] = float(e["RTPU_FLEET_PROMOTE_WAIT"])
        if "RTPU_FLEET_PROMOTE_TIMEOUT" in e:
            kw["promote_timeout_s"] = float(e["RTPU_FLEET_PROMOTE_TIMEOUT"])
        if "RTPU_FLEET_PINS" in e:
            extra = tuple(p.strip() for p in e["RTPU_FLEET_PINS"].split(",")
                          if p.strip())
            kw["pins"] = tuple(dict.fromkeys(self.pins + extra))
        return dataclasses.replace(self, **kw) if kw else self


class _Metro:
    """One metro's residency entry (all mutation under the fleet lock)."""

    __slots__ = ("name", "tileset", "host", "matcher", "staged_bytes",
                 "resident", "pinned", "promoting", "reserved",
                 "last_used", "leases", "promotions", "demotions")

    def __init__(self, tileset: TileSet, pinned: bool):
        self.name = tileset.name
        self.tileset = tileset
        self.host: "dict | None" = None       # host-pinned staged arrays
        self.matcher: "SegmentMatcher | None" = None
        self.staged_bytes = 0                 # known after first staging
        self.resident = False
        self.pinned = pinned
        self.promoting = False                # a thread is paging it in
        #                                       (with the fleet lock
        #                                       dropped for the expensive
        #                                       phases) — other touches
        #                                       wait on the condvar
        self.reserved = False                 # staged_bytes are counted
        #                                       in the ledger (resident,
        #                                       or mid-promotion past the
        #                                       reservation point) — only
        #                                       reserved bytes can ever
        #                                       be freed by waiting
        self.last_used = 0                    # LRU clock (sequence, not
        #                                       wall time: monotone under
        #                                       bursts within one tick)
        self.leases = 0                       # dispatches in flight
        self.promotions = 0
        self.demotions = 0


class FleetResidency:
    """The registry of compiled metros + the HBM occupancy ledger.

    Construction registers every tileset COLD (zero HBM, zero staging
    work) — first traffic stages it. ``configs`` carries per-metro
    Config overrides (the FleetRouter's SLO plumbing); metros without an
    entry share ``config``. One lock guards the LEDGER (bytes, tiers,
    LRU, leases); the expensive promotion phases — first-touch staging
    build, device_put — run with that lock released behind a per-metro
    ``promoting`` flag, so a multi-second page-in of one cold metro
    never stalls other metros' leases (bytes are reserved in the ledger
    before the unlocked transfer, so concurrent promoters can't
    oversubscribe the budget). Matchers are jax-backend single-device
    by contract (``SegmentMatcher.unstage_tables``)."""

    def __init__(self, tilesets: Sequence[TileSet],
                 config: "Config | None" = None,
                 fleet: "FleetConfig | None" = None,
                 configs: "dict[str, Config] | None" = None,
                 metrics: "MetricsRegistry | None" = None):
        if not tilesets:
            raise ValueError("need at least one tileset")
        names = [ts.name for ts in tilesets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate metro names: {names}")
        self.config = (config or Config()).validate()
        if self.config.matcher_backend != "jax":
            raise ValueError("fleet residency pages DEVICE tables; "
                             "matcher_backend must be 'jax'")
        self.fleet = (fleet or FleetConfig()).with_env_overrides().validate()
        unknown_pins = set(self.fleet.pins) - set(names)
        if unknown_pins:
            raise ValueError(f"pins for unknown metros: "
                             f"{sorted(unknown_pins)}")
        self._configs = dict(configs or {})
        unknown_cfg = set(self._configs) - set(names)
        if unknown_cfg:
            raise ValueError(f"configs for unknown metros: "
                             f"{sorted(unknown_cfg)}")
        non_jax = sorted(n for n, c in self._configs.items()
                         if c.matcher_backend != "jax")
        if non_jax:
            # fail at construction, not on the metro's first touch —
            # staged_tables injection requires the jax backend
            raise ValueError(f"per-metro configs must keep "
                             f"matcher_backend='jax': {non_jax}")
        self.metrics = metrics or MetricsRegistry()
        self._lock = locks.named_lock("fleet.ledger")
        # one condvar (same underlying lock — wait() drops it) for both
        # wake events: a lease release (a capacity-blocked promotion may
        # now have an evictable victim) and a promotion finishing (other
        # touches of that metro were waiting for its tables)
        self._cond = locks.named_condition("fleet.ledger", lock=self._lock)
        self._seq = 0
        self._resident_bytes = 0
        self._resident_count = 0
        # promote-watchdog breaker (its own internal lock: an abandoned
        # transfer thread must be able to un-count itself without
        # touching the fleet condvar lock)
        self._watchdog = AbandonedThreadWatchdog(
            cap=4, thread_name="fleet-promote-watchdog")
        self._metros = {ts.name: _Metro(ts, ts.name in self.fleet.pins)
                        for ts in tilesets}
        self.metrics.gauge("fleet_capacity_bytes",
                           self.fleet.max_resident_bytes)
        self.metrics.gauge("fleet_registered_metros", len(self._metros))
        self._publish_occupancy_locked()

    # ---- read side -------------------------------------------------------

    @property
    def names(self) -> "list[str]":
        return sorted(self._metros)

    @property
    def resident_names(self) -> "list[str]":
        with self._lock:
            return sorted(n for n, m in self._metros.items() if m.resident)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def tileset(self, name: str) -> TileSet:
        return self._metros[name].tileset

    def occupancy(self) -> dict:
        """The occupancy/paging report (/health's fleet block and the
        bench leg's artifact): ledger totals + per-metro residency."""
        with self._lock:
            metros = {
                n: {"resident": m.resident, "pinned": m.pinned,
                    "staged_bytes": m.staged_bytes,
                    "promotions": m.promotions, "demotions": m.demotions,
                    "leases": m.leases, "last_used_seq": m.last_used,
                    # round 17: the self-tuned dispatch plan serving this
                    # metro (None = untuned: cold, CPU, or explicit knobs)
                    "tuned_plan": (m.matcher.tuned_plan.label
                                   if m.matcher is not None
                                   and m.matcher.tuned_plan is not None
                                   else None)}
                for n, m in sorted(self._metros.items())}
            occ = self._resident_bytes
        cap = self.fleet.max_resident_bytes
        return {
            "capacity_bytes": cap,
            "evict_watermark": self.fleet.evict_watermark,
            "resident_bytes": occ,
            "occupancy_frac": (occ / cap if cap else None),
            "resident_metros": sum(1 for m in metros.values()
                                   if m["resident"]),
            "registered_metros": len(metros),
            "promotions": int(self.metrics.value("fleet_promotions_total")),
            "demotions": int(self.metrics.value("fleet_demotions_total")),
            "metros": metros,
        }

    # ---- serving side ----------------------------------------------------

    @contextlib.contextmanager
    def lease(self, name: str) -> Iterator[SegmentMatcher]:
        """Promote-if-cold and HOLD the metro resident for the body —
        the only safe way to dispatch: eviction skips leased metros, so
        the tables a dispatch captured cannot be dropped under it."""
        with self._lock:
            m = self._touch_locked(name)
            m.leases += 1
        try:
            yield m.matcher
        finally:
            with self._lock:
                m.leases -= 1
                if m.leases == 0:
                    # a promotion may be waiting for this metro to
                    # become evictable
                    self._cond.notify_all()

    def matcher(self, name: str) -> SegmentMatcher:
        """Touch + promote-if-cold, WITHOUT a lease — for construction
        paths (the router building a metro's app). Dispatch through
        ``lease()``."""
        with self._lock:
            return self._touch_locked(name).matcher

    def promote(self, name: str) -> None:
        with self._lock:
            self._touch_locked(name)

    def demote(self, name: str) -> None:
        """Explicitly page a metro out (operational lever; eviction uses
        the same path). Pinned metros CAN be demoted explicitly — the
        pin only shields them from the LRU scan. No-op when cold.
        Refused while the metro is mid-dispatch: the leased body may
        dispatch again and would hit the unstaged-tables guard."""
        with self._lock:
            m = self._metros[name]
            if m.leases > 0:
                raise RuntimeError(
                    f"metro {name!r} has {m.leases} dispatch(es) in "
                    "flight; cannot demote under a lease")
            if m.resident:
                self._demote_locked(m)

    def set_capacity(self, max_resident_bytes: int) -> None:
        """Retune the budget live (and let the bench's promotion-storm
        leg shrink a steady-state fleet into a paging one). Shrinking
        below current occupancy evicts LRU immediately; pinned/leased
        metros can leave it over budget — counted, not silent."""
        with self._lock:
            # swap under the fleet lock: an in-flight promotion snapshots
            # self.fleet once, so it never mixes an old cap with a new
            # watermark mid-eviction
            self.fleet = dataclasses.replace(
                self.fleet, max_resident_bytes=int(max_resident_bytes)
            ).validate()
            self.metrics.gauge("fleet_capacity_bytes",
                               self.fleet.max_resident_bytes)
            cap = self.fleet.max_resident_bytes
            if cap:
                self._evict_locked(
                    need=0, budget=int(cap * self.fleet.evict_watermark))
            self._publish_occupancy_locked()

    # ---- internals (all under self._lock) --------------------------------

    def _touch_locked(self, name: str) -> _Metro:
        m = self._metros.get(name)
        if m is None:
            raise KeyError(f"unknown metro {name!r}; have {self.names}")
        self._seq += 1
        m.last_used = self._seq
        if m.resident:
            self.metrics.count(labeled("fleet_hits", metro=name))
            return m
        self.metrics.count(labeled("fleet_misses", metro=name))
        while True:
            if m.resident:              # a concurrent promoter finished
                return m
            if not m.promoting:
                self._promote_locked(m)
                return m
            # another thread is paging this metro in; wait for it to
            # finish (or fail — then the re-check promotes it ourselves)
            self._cond.wait()

    def _promote_locked(self, m: _Metro) -> None:
        """Page ``m`` in. Lock held on entry/exit; the EXPENSIVE phases
        (first-touch staging build, device_put) run with the lock
        RELEASED — ``m.promoting`` makes this thread the metro's only
        promoter, so a multi-second page-in of one cold metro never
        stalls other metros' leases behind the fleet lock. The ledger
        reserves ``staged_bytes`` before the unlocked transfer, so
        concurrent promoters can't oversubscribe the budget."""
        fleet = self.fleet      # ONE consistent (cap, watermark, wait)
        #                         snapshot — set_capacity may swap
        #                         self.fleet while we wait
        m.promoting = True
        try:
            if m.host is None:
                # first touch: the cell_pack/seg_pack build — done
                # once, pinned in host RAM for every later promotion
                # (metered apart from paging: staging is construction
                # cost, the promote histogram is the steady-state
                # paging cost). Staged layout follows the METRO'S
                # config (a per-metro candidate_backend override must
                # stage the tables its own matcher sweeps).
                cfg_m = self._configs.get(m.name, self.config)
                self._lock.release()
                try:
                    with self.metrics.stage("fleet_stage"):
                        host = m.tileset.host_tables(
                            cfg_m.matcher.candidate_backend)
                        # (round 17: no plan-cache lookup HERE — the
                        # matcher built after the guarded device_put
                        # does it. device_key()'s jax.devices() may be
                        # the process's FIRST backend init, which on a
                        # dead axon tunnel hangs forever outside any
                        # watchdog; after _device_put_guarded the
                        # backend exists and the link just worked.)
                finally:
                    self._lock.acquire()
                m.host = host
                m.staged_bytes = int(sum(v.nbytes for v in host.values()))
            cap = fleet.max_resident_bytes
            if cap:
                if m.staged_bytes > cap:
                    # no eviction can ever make it fit — shed BEFORE the
                    # LRU scan, or a hopeless promotion (retried on
                    # every 503) would mass-evict the whole resident
                    # fleet each attempt and keep every metro cold
                    self.metrics.count(labeled(
                        "fleet_promote_failures", metro=m.name))
                    raise FleetCapacityError(
                        f"metro {m.name!r} staged tables "
                        f"({m.staged_bytes} B) exceed the fleet budget "
                        f"({cap} B); no eviction can make it fit")
                # the watermark headroom target — but a metro bigger
                # than the watermark slice can still legally fit under
                # cap: clamp to the hard cap then, so eviction stops as
                # soon as the promotion fits instead of draining the
                # fleet toward an unreachable target
                target = int(cap * fleet.evict_watermark)
                if m.staged_bytes > target:
                    target = cap
                deadline = time.monotonic() + fleet.promote_wait_s
                while True:
                    if self._resident_bytes + m.staged_bytes <= cap:
                        break
                    self._evict_locked(need=m.staged_bytes, budget=target)
                    if self._resident_bytes + m.staged_bytes <= cap:
                        break
                    # Over budget even after the LRU scan. Occupancy
                    # held TRANSIENTLY — in-flight leases (one
                    # dispatch) or a concurrent promotion's reserved
                    # bytes (evictable once it lands and its lease
                    # releases) — is worth a brief wait; the condvar
                    # fires on both lease release and promotion
                    # completion. Blocked by pins (or the budget is
                    # just too small), shed now: waiting can't help.
                    # Only RESERVED bytes count as freeable: a promoter
                    # still parked in ITS capacity wait holds nothing in
                    # the ledger yet, and counting its staged_bytes
                    # would double-discount them — a doomed promotion
                    # would burn the whole promote_wait_s before the
                    # inevitable shed.
                    transient = [x for x in self._metros.values()
                                 if x is not m and not x.pinned
                                 and x.reserved
                                 and ((x.resident and x.leases > 0)
                                      or x.promoting)]
                    freeable = sum(x.staged_bytes for x in transient)
                    remaining = deadline - time.monotonic()
                    if (not transient or remaining <= 0
                            or self._resident_bytes - freeable
                            + m.staged_bytes > cap):
                        self.metrics.count(labeled(
                            "fleet_promote_failures", metro=m.name))
                        raise FleetCapacityError(
                            f"cannot make {m.name!r} resident "
                            f"({m.staged_bytes} B): "
                            f"{self._resident_bytes} B of {cap} B held "
                            f"by pinned/in-flight metros")
                    self.metrics.count(labeled("fleet_promote_waits",
                                               metro=m.name))
                    self._cond.wait(remaining)
            # reserve the bytes, then transfer with the lock released
            # (m stays invisible to eviction: resident is still False,
            # and `promoting` keeps us the only writer of m.matcher)
            self._resident_bytes += m.staged_bytes
            m.reserved = True
            placed = False
            self._lock.release()
            try:
                t0 = time.perf_counter()
                with tracing.span("fleet_promote", metro=m.name,
                                  bytes=m.staged_bytes):
                    tables = self._device_put_guarded(m, fleet)
                    # round 17: keep the tuned_plan leaf HOST-readable
                    # through the device dict — the plan seam reads it
                    # without a device readback (the staged_layout
                    # value-check discipline), so a pre-tuned host dict
                    # promotes without re-measuring even with no disk
                    # cache. The leaf is an unused 20 B wire argument;
                    # a host-backed copy costs nothing per dispatch.
                    if m.host is not None and "tuned_plan" in m.host:
                        tables = dict(tables)
                        tables["tuned_plan"] = m.host["tuned_plan"]
                    # paging cost = the transfer (+ pointer restage);
                    # first-touch matcher CONSTRUCTION is metered apart
                    # (fleet_matcher_build) so the promote histogram
                    # stays the steady-state number
                    dt = time.perf_counter() - t0
                    if m.matcher is None:
                        with self.metrics.stage("fleet_matcher_build"):
                            m.matcher = SegmentMatcher(
                                m.tileset,
                                self._configs.get(m.name, self.config),
                                staged_tables=tables)
                        # write the freshly resolved plan back into the
                        # host-pinned dict: every LATER promotion pages
                        # already-tuned tables (one device_put, never a
                        # re-measure). Values only — the plan leaf is an
                        # unused wire argument, so fleet wire bytes stay
                        # bit-identical through evict→promote regardless
                        # of plan (the r11 contract, unchanged).
                        arr = m.matcher.tuned_plan_array()
                        if arr is not None and m.host is not None \
                                and "tuned_plan" in m.host:
                            m.host["tuned_plan"] = arr
                    else:
                        m.matcher.restage_tables(tables)
                        dt = time.perf_counter() - t0
                placed = True
            finally:
                self._lock.acquire()
                if not placed:
                    self._resident_bytes -= m.staged_bytes
                    m.reserved = False
            self.metrics.observe("fleet_promote_seconds", dt)
            m.resident = True
            self._resident_count += 1
            m.promotions += 1
            self.metrics.count(labeled("fleet_promotions", metro=m.name))
            self.metrics.count("fleet_promotions_total")
            self._publish_metro_locked(m)
        finally:
            m.promoting = False
            self._cond.notify_all()     # waiters on this metro (and any
            #                             promoter waiting for capacity)

    def _device_put_guarded(self, m: _Metro, fleet: FleetConfig) -> dict:
        """One ``jax.device_put`` of the metro's host-pinned tables,
        bounded by the promote watchdog when ``promote_timeout_s`` > 0.

        The tunnel's failure mode is an infinite stall no try/except can
        catch (CLAUDE.md), and this transfer is the fleet's only device
        interaction outside the matcher's own guarded dispatch — left
        unbounded, one dead-tunnel page-in holds ``m.promoting`` forever
        and every later toucher of the metro parks on the condvar. Runs
        with the fleet lock RELEASED (the caller holds only the
        promoting flag). On timeout the transfer thread is ABANDONED
        (daemon) and the promotion sheds as a retryable 503; abandoned
        threads trip the shared ``AbandonedThreadWatchdog`` breaker so a
        permanently dead link costs bounded memory — the r9 dispatch-
        watchdog machinery (utils/watchdog.py), applied to paging."""
        import jax

        timeout = float(fleet.promote_timeout_s)
        if timeout <= 0:
            faults.fire("fleet_promote")
            tables = jax.device_put(m.host)
            # block_until_ready does NOT sync the remote link
            # (CLAUDE.md) — but it does bound the local dispatch+layout
            # work, and the first real dispatch pays any residual
            # transfer; the bench storm measures promote→first-report,
            # the honest number
            jax.block_until_ready(tables)
            return tables
        if self._watchdog.tripped:
            # circuit open: enough abandoned transfers are already stuck
            # on the dead link — shed IMMEDIATELY rather than pin yet
            # another thread + host-table reference. Counted as a
            # timeout TOO, so the timeout series keeps moving while the
            # breaker is open.
            self.metrics.count("fleet_promote_breaker_open")
            self.metrics.count(labeled("fleet_promote_timeouts",
                                       metro=m.name))
            tracing.post_mortem("fleet_promote_breaker",
                                failing="fleet_promote", metro=m.name,
                                abandoned=self._watchdog.abandoned)
            raise ServiceOverloaded(
                f"fleet promote breaker open "
                f"({self._watchdog.abandoned} transfers already stuck); "
                f"{m.name!r} not promoted")

        def _transfer():
            t = jax.device_put(m.host)
            jax.block_until_ready(t)
            return t

        out = self._watchdog.run(_transfer, timeout,
                                 fault_site="fleet_promote")
        if out is not watchdog_mod.TIMED_OUT:
            return out
        self.metrics.count(labeled("fleet_promote_timeouts", metro=m.name))
        tracing.post_mortem("fleet_promote_timeout",
                            failing="fleet_promote", metro=m.name,
                            bytes=m.staged_bytes, timeout_s=timeout)
        raise ServiceOverloaded(
            f"fleet promote of {m.name!r} ({m.staged_bytes} B) exceeded "
            f"{timeout:.3f}s; shed for retry")

    def _evict_locked(self, need: int, budget: int) -> None:
        """Demote LRU unpinned, unleased metros until occupancy + need
        fits under ``budget`` (the watermark — hysteresis headroom), or
        nothing evictable remains."""
        victims = sorted(
            (m for m in self._metros.values()
             if m.resident and not m.pinned and m.leases == 0),
            key=lambda m: m.last_used)
        for v in victims:
            if self._resident_bytes + need <= budget:
                break
            self._demote_locked(v)
            self.metrics.count(labeled("fleet_evictions", metro=v.name))

    def _demote_locked(self, m: _Metro) -> None:
        assert m.matcher is not None
        m.matcher.unstage_tables()      # HBM frees once in-flight
        #                                 dispatches (none: leases==0 on
        #                                 the eviction path) release it
        m.resident = False
        m.reserved = False
        self._resident_count -= 1
        m.demotions += 1
        self._resident_bytes -= m.staged_bytes
        self.metrics.count(labeled("fleet_demotions", metro=m.name))
        self.metrics.count("fleet_demotions_total")
        self._publish_metro_locked(m)

    def _publish_metro_locked(self, m: _Metro) -> None:
        """Occupancy gauges for ONE metro + the ledger totals — O(1) per
        paging event. A thrashing fleet of hundreds of metros must not
        pay an all-metros gauge sweep under the fleet lock (the lock
        every lease needs) for every promote and every eviction
        victim."""
        self.metrics.gauge(labeled("fleet_resident_bytes", metro=m.name),
                           m.staged_bytes if m.resident else 0)
        self.metrics.gauge(labeled("fleet_resident", metro=m.name),
                           1.0 if m.resident else 0.0)
        self.metrics.gauge("fleet_resident_bytes_total",
                           self._resident_bytes)
        self.metrics.gauge("fleet_resident_metros", self._resident_count)

    def _publish_occupancy_locked(self) -> None:
        """Full-fleet republish — construction and capacity retune only;
        the paging paths publish just the affected metro."""
        for m in self._metros.values():
            self._publish_metro_locked(m)
