"""FleetRouter — geo-sharded serving over a paged metro fleet.

Extends MetroRouter's bbox EP dispatch (service/router.py) from
"every metro's app and tables eagerly resident" to the fleet shape:

  - metros register COLD; an app (scheduler, cache, publisher) is
    constructed on first traffic and persists across paging — only the
    matcher's device tables page in and out (fleet/residency.py);
  - every dispatch runs under a residency LEASE, so promotion→dispatch
    is atomic against eviction;
  - per-metro SLO configs (``MetroSLO``): batch-close deadline, shed
    policy (the r7 scheduler's bounded admission queue), in-flight
    depth, and a residency pin for metros whose SLO cannot absorb a
    promotion stall;
  - unroutable traces get MetroRouter's counted 404-with-known-metros;
    fleet capacity exhaustion (all pinned/leased) sheds as 503 via
    FleetCapacityError ⊂ ServiceOverloaded;
  - ``/health`` adds the residency occupancy/paging report, ``/stats``
    a fleet section, and ``/metrics`` exposes the shared router+fleet
    registry (``rtpu_fleet_*`` per-metro labeled series).
"""

from __future__ import annotations

import contextlib
import dataclasses
from dataclasses import dataclass
from typing import Sequence

from reporter_tpu.utils import locks
from reporter_tpu.config import Config
from reporter_tpu.fleet.residency import FleetConfig, FleetResidency
from reporter_tpu.service.app import ReporterApp
from reporter_tpu.service.datastore import Transport
from reporter_tpu.service.router import MetroRouter
from reporter_tpu.tiles.tileset import TileSet


@dataclass(frozen=True)
class MetroSLO:
    """Per-metro serving policy, mapped onto the r7 scheduler's knobs.
    None keeps the fleet-wide default from the base Config."""

    deadline_ms: "float | None" = None   # scheduler batch-close SLO
    #                                      (ServiceConfig.batch_close_ms)
    queue_limit: "int | None" = None     # shed policy: admitted traces
    #                                      before 503
    #                                      (admission_queue_limit)
    max_inflight: "int | None" = None    # overlapped device batches
    pinned: bool = False                 # residency pin: this metro's
    #                                      tables are never LRU-evicted
    #                                      (its SLO cannot absorb a
    #                                      promotion stall)

    def apply(self, base: Config) -> Config:
        kw: dict = {}
        if self.deadline_ms is not None:
            kw["batch_close_ms"] = float(self.deadline_ms)
        if self.queue_limit is not None:
            kw["admission_queue_limit"] = int(self.queue_limit)
        if self.max_inflight is not None:
            kw["max_inflight_batches"] = int(self.max_inflight)
        if not kw:
            return base
        return dataclasses.replace(
            base, service=dataclasses.replace(base.service, **kw)
        ).validate()


class FleetRouter(MetroRouter):
    """One serving face over N≥ hundreds of metros on one chip.

    Apps are constructed lazily (first traffic) and kept; matchers'
    device tables page through the residency manager. The router's
    geo dispatch, WSGI surface, and error taxonomy are MetroRouter's —
    this class only changes WHERE apps/matchers come from and wraps
    dispatches in leases."""

    def __init__(self, tilesets: Sequence[TileSet],
                 config: "Config | None" = None,
                 transport: "Transport | None" = None,
                 fleet: "FleetConfig | None" = None,
                 slos: "dict[str, MetroSLO] | None" = None):
        names = self._init_routing(tilesets)
        if "fleet" in names:
            raise ValueError('metro name "fleet" is reserved (it keys '
                             "the residency section in /stats)")
        base = (config or Config()).validate()
        slos = dict(slos or {})
        unknown = set(slos) - set(names)
        if unknown:
            raise ValueError(f"SLOs for unknown metros: {sorted(unknown)}")
        self._slos = slos
        self._transport = transport
        self._configs = {n: s.apply(base) for n, s in slos.items()}
        fleet = (fleet or FleetConfig())
        pins = tuple(dict.fromkeys(
            fleet.pins + tuple(n for n, s in slos.items() if s.pinned)))
        # ONE registry for router + residency series: two registries
        # would each render their own exposition (duplicate
        # rtpu_uptime_seconds TYPE lines in a concatenation)
        self.residency = FleetResidency(
            tilesets, config=base,
            fleet=dataclasses.replace(fleet, pins=pins),
            configs=self._configs, metrics=self.metrics)
        self.apps: "dict[str, ReporterApp]" = {}
        self._apps_lock = locks.named_lock("fleet_router.apps")  # guards the dict only
        # construction is serialized PER METRO: building an app promotes
        # the metro (staging build + device_put + possibly a lease
        # wait), and doing that under one global lock would stall every
        # OTHER metro's traffic — including pinned-SLO metros — behind
        # one cold metro's first touch
        self._app_build_locks = {
            n: locks.named_lock("fleet_router.app_build") for n in names}

    # ---- app/matcher access ---------------------------------------------

    def app(self, name: str) -> ReporterApp:
        with self._apps_lock:
            a = self.apps.get(name)
        if a is not None:
            return a
        with self._app_build_locks[name]:   # KeyError = unknown metro
            with self._apps_lock:
                a = self.apps.get(name)
            if a is not None:
                return a
            # residency.matcher promotes if cold; the app wraps the
            # metro's LONG-LIVED matcher, so cache/scheduler state
            # and compiled executables survive later paging
            a = ReporterApp(
                self.residency.tileset(name),
                self._configs.get(name, self.residency.config),
                transport=self._transport,
                matcher=self.residency.matcher(name))
            with self._apps_lock:
                self.apps[name] = a
            return a

    @contextlib.contextmanager
    def _serving(self, metro: str):
        """The report bodies are MetroRouter's; only the dispatch
        context differs — a residency lease (promote-if-cold + hold
        resident), so eviction can never drop tables under an in-flight
        dispatch."""
        with self.residency.lease(metro):
            yield

    # ---- observability ---------------------------------------------------

    def health(self) -> dict:
        with self._apps_lock:
            apps = dict(self.apps)
        return {
            "status": "ok",
            "unroutable": int(self.metrics.value("router_unroutable")),
            "fleet": self.residency.occupancy(),
            # fleet-level quality roll-up (round 18): which metros'
            # windows are drifted right now and the total sentinel
            # events, without digging through N per-metro blocks (each
            # metro's full window rides its app health below)
            "quality": {
                "drifted_metros": sorted(
                    n for n, a in apps.items()
                    if a.matcher.quality.drifted),
                "drift_events": sum(
                    a.matcher.quality.health()["drift_events"]
                    for n, a in apps.items()),
            },
            # only metros that have seen traffic have an app to report;
            # the fleet block above covers every REGISTERED metro
            "metros": {n: a.health() for n, a in apps.items()},
        }

    def stats(self) -> dict:
        with self._apps_lock:
            apps = dict(self.apps)
        out = {n: a.matcher.metrics.snapshot() for n, a in apps.items()}
        out["fleet"] = {
            "occupancy": self.residency.occupancy(),
            "series": self.metrics.snapshot(),
        }
        return out

    def close(self) -> None:
        with self._apps_lock:
            apps = dict(self.apps)
        for a in apps.values():
            a.close()


def make_fleet_router(tilesets: Sequence[TileSet],
                      config: "Config | None" = None,
                      transport: "Transport | None" = None,
                      fleet: "FleetConfig | None" = None,
                      slos: "dict[str, MetroSLO] | None" = None,
                      ) -> FleetRouter:
    return FleetRouter(tilesets, config, transport, fleet=fleet, slos=slos)
