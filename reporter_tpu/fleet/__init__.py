"""Metro fleet residency: serve many metros per chip (ROADMAP item 1).

  residency.py   FleetResidency — registry of compiled metro tables,
                 HBM occupancy ledger, hot/cold tiers, LRU paging with
                 watermark + pin policy, traced/counted promotion
  router.py      FleetRouter — MetroRouter's geo dispatch over the
                 paged fleet, per-metro SLOs, lease-guarded dispatch
"""

from reporter_tpu.fleet.residency import (
    FleetCapacityError,
    FleetConfig,
    FleetResidency,
)
from reporter_tpu.fleet.router import FleetRouter, MetroSLO, make_fleet_router

__all__ = [
    "FleetCapacityError",
    "FleetConfig",
    "FleetResidency",
    "FleetRouter",
    "MetroSLO",
    "make_fleet_router",
]
