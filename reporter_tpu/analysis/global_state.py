"""Process-global state leak detection for the test harness.

Round 10's post-review log has the canonical bug: a bench leg enabled
the process-global tracer and an exception skipped the restore, so every
LATER leg ran traced and the overhead A/B measured traced-vs-traced.
Tests have the same failure mode — the tracer ring, the installed fault
plan, and the RTPU_*/REPORTER_* environment are process-global, and a
test that mutates one without restoring poisons every test after it.

``snapshot()`` captures the restorable global surface; ``diff()``
renders the human-readable delta. tests/conftest.py snapshots around
EVERY test (autouse) and fails the test that leaked — attribution at the
leak site, not three suites later.
"""

from __future__ import annotations

import os

__all__ = ["snapshot", "diff"]

_ENV_PREFIXES = ("RTPU_", "REPORTER_", "DATASTORE_")


def snapshot() -> dict:
    from reporter_tpu import faults
    from reporter_tpu.obs import slo as obs_slo
    from reporter_tpu.quality import audit as quality_audit
    from reporter_tpu.utils import linkhealth, tracing

    tr = tracing.tracer()
    return {
        "tracer.enabled": tr.enabled,
        "tracer.dump_dir": tr.dump_dir,
        "tracer.capacity": tr.capacity,
        "tracer.max_dumps": tr.max_dumps,
        # identity, not equality: `with faults.use(plan)` restores the
        # previous object; a leaked install leaves a different one
        "faults.installed": faults._installed,
        # the r15 process-global link sampler is swap-installable the
        # same way (linkhealth.configure); identity again. None -> X is
        # LEGAL (lazy first construction by ensure_serving); X -> Y or
        # X -> None is a test leaking its fake into every later test
        "linkhealth.sampler": linkhealth._global,
        # the r18 process-global shadow auditor follows the same
        # swap-install shape (quality/audit.configure); identity, and
        # None -> X lazy first construction is legal exactly like the
        # link sampler's
        "quality.auditor": quality_audit._global,
        # the r24 SLO evaluator seam (obs/slo.install) — identity; the
        # package never installs one itself, so ANY change (including
        # None -> X) is a test leaving its evaluator behind: later
        # tests would tick someone else's alert state
        "obs.slo": obs_slo._installed,
        "env": {k: v for k, v in os.environ.items()
                if k.startswith(_ENV_PREFIXES)},
    }


def diff(pre: dict, post: dict) -> "list[str]":
    out = []
    for key in ("tracer.enabled", "tracer.dump_dir", "tracer.capacity",
                "tracer.max_dumps"):
        if pre[key] != post[key]:
            out.append(f"{key}: {pre[key]!r} -> {post[key]!r} "
                       "(restore the process-global recorder — "
                       "tracing.configure mutates a singleton)")
    if pre["faults.installed"] is not post["faults.installed"]:
        out.append("faults plan left installed "
                   f"({post['faults.installed']!r}) — use "
                   "`with faults.use(plan):` so the restore is scoped")
    pre_lh = pre.get("linkhealth.sampler")
    if pre_lh is not None and pre_lh is not post.get("linkhealth.sampler"):
        out.append("linkhealth sampler swapped and not restored "
                   "(linkhealth.configure(fake) without restoring the "
                   "previous sampler in finally) — later tests publish "
                   "the fake's mood at /metrics and /health")
    pre_qa = pre.get("quality.auditor")
    if pre_qa is not None and pre_qa is not post.get("quality.auditor"):
        out.append("quality shadow auditor swapped and not restored "
                   "(quality.audit.configure(fake) without restoring "
                   "the previous auditor in finally) — later tests "
                   "sample audits on the fake's schedule and budget")
    if pre.get("obs.slo") is not post.get("obs.slo"):
        out.append("SLO evaluator left installed via obs.slo.install "
                   "and not restored — later tests would tick this "
                   "test's alert state (restore the previous evaluator "
                   "in finally; the package itself never installs one)")
    pe, qe = pre["env"], post["env"]
    for k in sorted(set(pe) | set(qe)):
        if pe.get(k) != qe.get(k):
            out.append(f"os.environ[{k!r}]: {pe.get(k)!r} -> {qe.get(k)!r} "
                       "(use monkeypatch.setenv / restore in finally)")
    return out
