"""``python -m reporter_tpu.analysis`` — run the repo lint gate from the
command line (same rules + waiver semantics as the CI gate in
tests/test_static_analysis.py). Exit 1 on any unwaived finding."""

from reporter_tpu.analysis.lint_rules import main

raise SystemExit(main())
