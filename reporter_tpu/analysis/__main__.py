"""``python -m reporter_tpu.analysis`` — the repo's static gates, from
the command line (same rules + waiver semantics as the CI gates in
tests/test_static_analysis.py and tests/test_device_contract.py).

  (no args)           AST lint + cross-file rules over reporter_tpu/ +
                      bench.py (round 14). Exit 1 on any unwaived
                      finding.
  --device            device-program contract (round 16): jaxpr audit of
                      every wire entry × kernel arm × wire layout ×
                      {single-device, mesh} path, the pinned
                      compile-shape manifest, and the static SMEM/HBM
                      budgets. CPU abstract eval only — no device, no
                      tunnel. Exit 1 on any unwaived finding or manifest
                      drift.
  --update-manifest   regenerate analysis/compile_manifest.py's GOLDEN
                      block from the live constants (the fixtures/
                      regen.py workflow — run it ONLY for intentional
                      compile-universe changes and commit the diff).
  --slo               SLO spec contract (round 24): validate the
                      committed DEFAULT_SLOS (window ordering, burn
                      thresholds vs budget, latency thresholds on the
                      histogram grid, metric names in README's
                      inventory). Exit 1 on any finding.
"""

import argparse


def _main() -> int:
    ap = argparse.ArgumentParser(prog="python -m reporter_tpu.analysis")
    ap.add_argument("--device", action="store_true",
                    help="run the device-program contract gate")
    ap.add_argument("--update-manifest", action="store_true",
                    help="regenerate the golden compile-shape manifest")
    ap.add_argument("--slo", action="store_true",
                    help="validate the committed SLO specs")
    args = ap.parse_args()
    if args.slo:
        from reporter_tpu.analysis.slo_contract import main as slo_main

        return slo_main()
    if args.update_manifest:
        from reporter_tpu.analysis.compile_manifest import update_golden

        print(f"golden manifest rewritten: {update_golden()}")
        return 0
    if args.device:
        from reporter_tpu.analysis.device_contract import main as device_main

        return device_main()
    from reporter_tpu.analysis.lint_rules import main as lint_main

    return lint_main()


raise SystemExit(_main())
