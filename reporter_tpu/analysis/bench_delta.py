"""Run-over-run bench-composite diff — the regression sentinel.

``python -m reporter_tpu.analysis.bench_delta old.json new.json`` diffs
two composite captures (the ``BENCH_DETAIL.json`` document shape)
SCHEMA-AWARE: every shared numeric leaf whose key names a known metric
direction is compared; keys only one side has are counted (schema
drift), never treated as regressions; unknown-direction leaves (configs,
counts, workload sizes) are skipped. Each worse-than-threshold delta is
then attributed:

  regression          worse beyond the threshold on a metric the link
                      cannot excuse (device-only numbers, fidelity,
                      host-side throughput) — or a link-sensitive metric
                      whose two captures recorded the SAME link mood;
  link-attributable   a link-sensitive metric (e2e throughput, request
                      latency, RTT-bound p50s, readback, streaming
                      rates) whose two captures recorded materially
                      different link conditions (mood changed, or
                      rtt/bandwidth moved past the drift band) — the
                      delta is drift until a same-mood capture says
                      otherwise (the link's documented ~2x swing,
                      CLAUDE.md);
  link-unknown        link-sensitive and worse, but at least one capture
                      carries no link window (every capture before round
                      15) — flagged, not blamed.

The sentinel REPORTS (exit 0 always): bench.py's tail runs it against
the committed capture on every run and embeds the summary, so the
driver sees "what moved and whether the link excuses it" without a
human diffing two 100 KB documents. CI never gates on it — a noisy link
must not turn the perf dashboard into a flaky test.
"""

from __future__ import annotations

import json
import re
from typing import Any

__all__ = ["compare", "summary_token", "render", "classify_direction",
           "is_link_sensitive", "link_drifted", "schema_coverage",
           "coverage_findings", "main"]

# ---------------------------------------------------------------------------
# direction classification (suffix rules over the LEAF key, narrow on
# purpose: an unclassified leaf is skipped, never guessed)

_HIGHER_SUFFIXES = (
    "probes_per_sec", "probes_per_sec_e2e", "probes_per_sec_wall",
    "probes_per_sec_active", "probes_per_sec_busy", "per_sec", "_rps",
    "_pps", "krows_per_s", "speedup", "speedup_2v1", "best_held_pps",
    "achieved_gbps", "achieved_gflops", "point_edge_rate",
    "point_segment_rate", "req_per_sec", "device_probes_per_sec",
    "vs_baseline", "readback_mbps",
    # r16 coverage sweep: throughput ratios, roofline efficiency, the
    # tracing-overhead A/B's sustained rates
    "throughput_vs_sf", "throughput_vs_unrestricted", "_peak",
    "pps_traced", "pps_untraced",
    # r21 mesh backfill arm: mesh-over-single-device open-loop ratio
    # (mesh krows/s itself classifies via the krows_per_s suffix)
    "vs_single_x",
    # r22 prepare A/B: pipelined_speedup rides the generic "speedup"
    # suffix; the overlap gauge is higher-is-better on its own (more of
    # the wave prepare hidden behind device flight)
    "prepare_overlap_pct",
)
_LOWER_SUFFIXES = (
    "_ms", "disagreement", "miss_rate", "step_miss_rate", "lag",
    "end_lag", "max_lag", "lost_reports", "duplicated_reports",
    "dead_letter_pending_end", "dead_lettered", "errors", "rejected",
    "dropped_rows", "recovery_seconds", "drain_seconds",
    "tracing_overhead_pct", "dispatch_timeout",
    # r16 coverage sweep: per-dispatch/per-slice/per-batch leg costs,
    # oracle disagreements, reach-audit misses, journal torn tails
    "_ms_per_dispatch", "_ms_per_slice", "_s_per_batch",
    "disagreement_k8", "disagreement_k12", "disagreement_vs_cpu_ref",
    "decode_slowdown_vs_sf", "e2e_over_decode", "_missed",
    "truncated_lines",
    # r18 quality leg: every online quality rate is worse when UP, as
    # is the shadow-audit's measured disagreement / overhead / timeout
    # count and the drift-sentinel event count
    "empty_match_rate", "breakage_rate", "discontinuity_rate",
    "violation_rate", "rejection_rate", "unmatched_point_rate",
    "disagreement_rate", "overhead_pct", "audit_timeouts",
    "drift_events",
    # r19 topology leg: supervisor detection latency and records the
    # replay failed to cover are worse when UP (lost_records' healthy
    # baseline is 0 — the zero-baseline rendering applies)
    "detect_seconds", "lost_records",
    # r20 backfill leg: records re-read across a checkpoint-resume are
    # the counted replay tax (healthy baseline 0 on a clean replay)
    "replay_tax_records",
)
# Whole subtrees that are bookkeeping, measurement conditions, or
# self-referential analysis — pruned before any leaf is classified (one
# rule shared by compare() and the coverage gate, so the two can never
# disagree about what "covered" means). Matched as exact dotted-path
# SEGMENTS, not substrings.
_NEUTRAL_SUBTREES = frozenset({
    "bench_delta",        # the embedded sentinel report (self-diff is noise)
    "link_health",        # measurement conditions — the normalizer
    "setup_split",        # where bench wall time went (setup re-runs)
    "legs_s_per_batch",   # per-leg attribution; the *_per_batch/_per_slice
    #                       headline keys above carry the claims
    "tile_stats",         # workload descriptors (edges, cells, compile)
    "staging_plan",       # capacity-plan echo (tiles/capacity.py)
    "occupancy",          # fleet paging bookkeeping (kpps carry the claims)
    "per_metro_kpps",     # leaf keys are metro NAMES; the mixed aggregate
    #                       kpps is the compared claim
    "event_counts",       # r19 topology event-log tallies — leaf keys are
    #                       EVENT NAMES; deaths/restarts/recovery carry
    #                       the claims at the leg's top level
    "exit_reports",       # r19 per-member exit echoes (leaf keys include
    #                       member-local rates already claimed elsewhere)
})
# leaf keys that are workload/config/bookkeeping, never a perf claim —
# matched exactly, skipped before the suffix rules run. THE explicit
# neutral list: schema_coverage() checks it BOTH ways (every committed
# numeric leaf must classify or sit here; every entry here must still
# exist in the committed schema), so a new metric can never be silently
# skipped and dead rows cannot accrete.
_SKIP_KEYS = {
    "seconds", "total_seconds", "build_seconds", "wall_seconds",
    "match_seconds", "host_seconds", "batch_seconds",
    "setup_seconds", "offered_pps", "offered_rps",
    "samples", "traces", "points", "reports", "steps", "rows",
    "clients", "rounds", "workers", "n_metros", "touches", "probes",
    "bucket", "capacity_bytes", "staged_bytes_total",
    "hbm_tile_bytes", "wire_bytes_per_slice",
    "rotation_index", "latency_samples",
    # measurement CONDITIONS, not claims: the link window is the
    # normalizer, never a compared metric
    "link_rtt_ms", "probe_duty_pct",
    # lint: allow[bench-coverage] 2026-08-04 chip-flavor link-window rows: the committed capture this round is CPU-flavored (rtt/mbps are null there); these entries guard the next chip capture, where bare _ms/_mbps suffixes would otherwise misclassify them
    "rtt_ms", "mbps",
    # autotune leg (round 17): chosen-plan/bookkeeping fields — the
    # candidate timings (device_ms_per_dispatch) and the
    # tuned_vs_default_speedup carry the compared claims
    # lint: allow[bench-coverage] 2026-08-04 r17 calibration_* rows are chip-probe fields (the committed capture this round is the CPU-validation flavor, whose mechanism leg has no real calibration cost to record); they guard the next chip capture. nj_cap is live in the r17 capture's plan block
    "nj_cap", "calibration_seconds", "calibration_dispatches",
    # roofline / culling descriptors (the efficiency *_peak percentages
    # and kpps rates above are the claims)
    "block_visits_per_dispatch", "blocks_total", "mean_blocks_per_chunk",
    "culled_fraction", "hbm_bytes_swept", "pair_flops",
    # reach-audit population counts (+ node-coverage distribution keys;
    # the *_miss_rate / *_missed leaves are the compared claims)
    "pairs_considered", "steps_considered", "pairs_accepted_exact",
    "steps_accepted_exact", "truncated_nodes", "min", "p50",
    # scheduler / service-curve bookkeeping (shed/deferred/padding are
    # by-design nonzero in the overload legs)
    "padded_traces", "deferred", "shed", "device_batches",
    "inflight_ge2_dispatches", "requests", "concurrency",
    # fleet paging counters outside the occupancy subtree (the fidelity
    # leg's per-metro evict→promote counts — cycle bookkeeping)
    "demotions", "promotions",
    # latency-attribution stage names (conditional means partitioning
    # the request — shifts between stages are attribution, not
    # regressions; the e2e/request _ms quantiles carry the claims)
    "sched_queue", "device_match", "publish", "report_build",
    "stage_sum_over_e2e_p50", "stage_sum_over_request_p50",
    # streaming soak / worker bookkeeping
    "consumed_probes", "produced_probes", "hist_rows_nonzero",
    "hist_segments_flushed", "per_worker_match_seconds",
    # quality leg (round 18): window/sample-count + audit-cost
    # bookkeeping — the *_rate leaves and audit_overhead_pct above
    # carry the compared claims; direct_overhead_pct is the raw
    # off-vs-on A/B at a 1/256 sampling rate, noise-dominated by
    # design (the implied audit_overhead_pct is the claim)
    # lint: allow[bench-coverage] 2026-08-04 r18 detail.quality rows land with this round's capture (the leg is new; no committed composite carries it yet) — they guard the next committed capture, CPU and chip flavors alike
    "window_waves", "audit_rate", "audited_batches", "audited_traces",
    # lint: allow[bench-coverage] 2026-08-04 same r18 detail.quality rows as the line above (new leg, lands with this round's capture)
    "audit_seconds", "direct_overhead_pct",
    # lint: allow[bench-coverage] 2026-08-04 same r18 detail.quality rows (the auditor's enforced-bound echoes; audit_overhead_pct carries the claim)
    "min_interval_s", "duty_pct_cap",
    # workload shape echoes
    "oracle_sample_traces", "total_traces", "trace_window", "wire_mode",
    "edges_vs_sf", "reach_rows_growth", "exact_tie_fraction",
    "lt_1cm_fraction", "lt_1m_fraction",
    # topology leg (round 19): injected-fault tallies and measurement
    # conditions — deaths/restarts are BY DESIGN 1/1 (the leg kills a
    # worker on purpose; recovery/detect_seconds + lost_records carry
    # the compared claims), kill-time state is a condition, aggregation/
    # stitch population counts are bookkeeping (their _ok bits gate)
    "deaths", "restarts", "deaths_total", "restarts_total",
    "reports_at_kill", "lag_at_kill", "stamped_records", "broker_probes",
    "counters_checked", "buckets_checked", "merged_series", "members",
    "processes", "unsynced_processes", "events", "traced_ids",
    "cross_pid_tracks", "posts",
    # service-leg per-draw spread (round 19): the per-round rates and
    # their spread DIAGNOSE the one-core closed loop's bimodality (r18
    # capture note) — run-over-run comparison of individual draws is
    # exactly the noise the best-of discipline exists to absorb
    "round_rps", "scheduler_draw_rps", "legacy_draw_rps",
    "scheduler_draw_spread_pct", "legacy_draw_spread_pct",
    "client_threads",
    # backfill leg (round 20): spool/wave/chunk shape echoes and the
    # k-anonymity harvest tallies — kanon_dropped/kept_segments are
    # cutoff bookkeeping at the leg's fixed k and scale, not perf
    # claims; krows_per_s/replay_tax_records above carry the compared
    # claims
    # lint: allow[bench-coverage] 2026-08-06 r20 detail.backfill rows land with this round's capture (the leg is new; no committed composite carries it yet) — they guard the next committed capture, CPU and chip flavors alike
    "records", "waves", "chunks", "kept_segments", "kanon_dropped",
    # r22: vs_soak_x moved NEUTRAL (was higher-is-better, r20). The
    # pipelined serving loop improves the ratio's DENOMINATOR — the
    # closed-loop soak — so a FALLING ratio is the win now, not a
    # backfill regression; stream_kpps/soak sustained carry the
    # closed-loop direction signal and krows_per_s the open-loop one.
    # lint: allow[bench-coverage] 2026-08-06 r22 direction is ambiguous by construction (numerator and denominator are both claims elsewhere); the ratio stays in the detail file as a diagnostic
    "vs_soak_x",
    # r21 mesh backfill arm: the shard count is a placement descriptor
    # (the CPU composite's 8 virtual devices, a chip slice's real count),
    # never a perf claim — mesh krows_per_s / vs_single_x above carry
    # the compared numbers
    # lint: allow[bench-coverage] 2026-08-06 r21 detail.backfill.mesh rows land with this round's capture (the mesh arm is new; no committed composite carries it yet)
    "devices",
    # r22 prepare A/B (detail.streaming_soak.prepare_ab): the injected
    # device flight is a measurement CONDITION (calibrated per run to
    # ~2x the serial arm's host time), and the per-draw times are the
    # same best-of diagnostics as the r19 service draws — the
    # pipelined_speedup ratio above carries the compared claim
    # lint: allow[bench-coverage] 2026-08-06 r22 prepare_ab rows land with this round's capture (the A/B is new; no committed composite carries it yet)
    "injected_flight_s", "serial_draw_s", "pipelined_draw_s",
    # SLO leg (round 24, detail.slo): mechanism-contract tallies at the
    # leg's FIXED synthetic scale — clean_alerts must be 0 and
    # chaos_alerts exactly 2 BY CONSTRUCTION (the folded slo summary
    # bit gates both; a delta here is a broken contract, not a perf
    # regression), ticks/ledger/post-mortem counts are bookkeeping of
    # the injected-clock driver
    # lint: allow[bench-coverage] 2026-08-07 r24 detail.slo rows land with this round's capture (the leg is new; no committed composite carries it yet)
    "ticks", "clean_alerts", "chaos_alerts", "post_mortems",
    # lint: allow[bench-coverage] 2026-08-07 r24 detail.slo rows land with this round's capture (the leg is new; no committed composite carries it yet)
    "ledger_entries",
}

# every throughput/latency number measured THROUGH the remote link is
# link-sensitive by default; this set names the ones that are not —
# device-only (link amortized out), host-only, and correctness counts
# a link mood can never excuse
_LINK_FREE_TOKENS = re.compile(
    r"colocated|device_probes_per_sec|device_ms_per_dispatch|krows"
    r"|disagreement|point_edge|point_segment|matcher_only"
    r"|cpu_reference|python_|miss_rate|lost|duplicated|dead_letter"
    r"|errors|rejected|dropped|overhead_pct|speedup|probe_duty"
    r"|replay_tax|vs_soak|vs_single|prepare_overlap",
    re.IGNORECASE)


def classify_direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 not a compared metric."""
    k = key.lower()
    if k in _SKIP_KEYS:
        return 0
    for s in _HIGHER_SUFFIXES:
        if k.endswith(s):
            return 1
    for s in _LOWER_SUFFIXES:
        if k.endswith(s):
            return -1
    return 0


def _neutral_key(leaf: str) -> bool:
    """Explicitly neutral: on the skip list, or a pure-digit key (the
    histogram/bucket dicts key samples BY NUMBER — "128" is a bucket
    label, not a metric name)."""
    return leaf.lower() in _SKIP_KEYS or leaf.isdigit()


def _neutral_subtree_segment(key: str) -> bool:
    return re.sub(r"\[\d+\]$", "", str(key)) in _NEUTRAL_SUBTREES


def is_link_sensitive(path: str) -> bool:
    """Does the remote link sit in this metric's denominator? Device-only
    and host-only numbers (and correctness counts) can't hide behind the
    tunnel's mood; everything else measured end-to-end can."""
    return not _LINK_FREE_TOKENS.search(path)


# ---------------------------------------------------------------------------
# link windows

def _link_of(doc: dict) -> "dict | None":
    d = doc.get("detail") or {}
    lh = d.get("link_health")
    if isinstance(lh, dict) and "mood" in lh:
        return lh
    return None


def link_drifted(old: "dict | None", new: "dict | None",
                 rtt_band: float = 1.5,
                 mbps_band: float = 1.5) -> "bool | None":
    """Did the link move enough between the captures to excuse a
    link-sensitive delta? None = can't say (a side has no window —
    pre-r15 captures). A mood change always counts; otherwise rtt or
    bandwidth moving past the band (either direction — a FASTER link in
    the new capture makes an improvement link-attributable too)."""
    if not old or not new or old.get("mood") is None \
            or new.get("mood") is None:
        return None
    if old["mood"] != new["mood"]:
        return True
    for key, band in (("rtt_ms", rtt_band), ("mbps", mbps_band)):
        a, b = old.get(key), new.get(key)
        if a and b and (a / b > band or b / a > band):
            return True
    return False


# ---------------------------------------------------------------------------
# the walk

def _numeric(x: Any) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _walk(old: Any, new: Any, path: str, rows: list,
          counts: dict) -> None:
    if isinstance(old, dict) and isinstance(new, dict):
        # keys stringified for alignment: the NEW doc is in-memory (int
        # histogram keys), the OLD one round-tripped through JSON (str)
        o = {str(k): v for k, v in old.items()}
        n = {str(k): v for k, v in new.items()}
        for k in sorted(set(o) | set(n)):
            if _neutral_subtree_segment(k):
                continue        # bookkeeping/conditions — never compared
            p = f"{path}.{k}" if path else k
            if k not in o:
                counts["only_new"] += 1
            elif k not in n:
                counts["only_old"] += 1
            else:
                _walk(o[k], n[k], p, rows, counts)
        return
    if isinstance(old, list) and isinstance(new, list):
        for i in range(min(len(old), len(new))):
            _walk(old[i], new[i], f"{path}[{i}]", rows, counts)
        if len(old) != len(new):
            counts["only_old" if len(old) > len(new)
                   else "only_new"] += abs(len(old) - len(new))
        return
    if not (_numeric(old) and _numeric(new)):
        return
    leaf = path.rsplit(".", 1)[-1]
    leaf = re.sub(r"\[\d+\]$", "", leaf)
    direction = classify_direction(leaf)
    if direction == 0:
        # by the coverage gate (schema_coverage), a direction-0 leaf in
        # the committed schema is ALWAYS explicitly neutral — never an
        # unclassified metric silently skipped
        return
    counts["compared"] += 1
    if old == new:
        counts["flat"] += 1
        return
    # old == 0 has no percentage, but a 0 -> nonzero move on a
    # lower-is-better counter (errors, lost_reports, dead_lettered: 0
    # IS the healthy baseline) is exactly what a regression sentinel
    # exists to surface — delta_pct stays None, the row still
    # classifies
    delta_pct = (None if old == 0
                 else round((new - old) / abs(old) * 100.0, 2))
    rows.append({"path": path, "old": old, "new": new,
                 "delta_pct": delta_pct, "direction": direction,
                 "link_sensitive": is_link_sensitive(path)})


def compare(old_doc: dict, new_doc: dict,
            threshold_pct: float = 10.0) -> dict:
    """Diff two composite documents. Returns the full row set plus the
    attributed regression/drift lists; see the module docstring for the
    verdict semantics."""
    old_link, new_link = _link_of(old_doc), _link_of(new_doc)
    drifted = link_drifted(old_link, new_link)
    rows: "list[dict]" = []
    counts = {"compared": 0, "flat": 0, "only_old": 0, "only_new": 0}
    # the headline "value" IS the e2e throughput (doc["metric"]) — walk
    # it under a classifiable name so it can never be skipped as config
    _walk({"headline_probes_per_sec_e2e": old_doc.get("value"),
           "detail": old_doc.get("detail") or {}},
          {"headline_probes_per_sec_e2e": new_doc.get("value"),
           "detail": new_doc.get("detail") or {}},
          "", rows, counts)
    regressions: "list[dict]" = []
    link_attrib: "list[dict]" = []
    improved = 0
    for r in rows:
        d = r["delta_pct"]
        if d is None:
            # zero baseline: any move is an infinite percentage —
            # direction decides worse/better, "big" by definition
            worse = (r["new"] - r["old"]) * r["direction"] < 0
            big = True
        else:
            worse = d * r["direction"] < 0
            big = abs(d) >= threshold_pct
        if not big:
            counts["flat"] += 1
            continue
        if not worse:
            improved += 1
            r["verdict"] = "improved"
            continue
        if r["link_sensitive"]:
            if drifted is None:
                r["verdict"] = "link-unknown"
                link_attrib.append(r)
            elif drifted:
                r["verdict"] = "link-drift"
                link_attrib.append(r)
            else:
                r["verdict"] = "regression"
                regressions.append(r)
        else:
            r["verdict"] = "regression"
            regressions.append(r)
    # None delta = zero-baseline move = effectively infinite % — most
    # severe, sorts first
    def _sev(r):
        return (0 if r["delta_pct"] is None else 1,
                -abs(r["delta_pct"] or 0.0))

    regressions.sort(key=_sev)
    link_attrib.sort(key=_sev)
    return {
        "threshold_pct": threshold_pct,
        "link": {"old": old_link, "new": new_link,
                 "drifted": drifted},
        "compared": counts["compared"],
        "flat": counts["flat"],
        "improved": improved,
        "only_old_keys": counts["only_old"],
        "only_new_keys": counts["only_new"],
        "regressions": regressions,
        "link_attributable": link_attrib,
        "old_provenance": (old_doc.get("provenance") or {}),
        "new_provenance": (new_doc.get("provenance") or {}),
    }


def summary_token(delta: "dict | None") -> list:
    """``delta = [regressions, link-attributable, worst regression %]``
    — the <1 KB summary-line form (None slots when no comparison ran)."""
    if not delta:
        return [None, None, None]
    worst = (delta["regressions"][0]["delta_pct"]
             if delta["regressions"] else None)
    return [len(delta["regressions"]), len(delta["link_attributable"]),
            worst]


def compact(delta: dict, top: int = 12) -> dict:
    """The bounded form bench.py embeds in the detail file: counters +
    the top-N rows of each attributed list (the full table is one
    ``bench_delta`` CLI run away — the detail must not double in size
    because a schema grew)."""
    slim = dict(delta)
    slim["regressions"] = delta["regressions"][:top]
    slim["link_attributable"] = delta["link_attributable"][:top]
    slim["regressions_total"] = len(delta["regressions"])
    slim["link_attributable_total"] = len(delta["link_attributable"])
    return slim


def render(delta: dict) -> str:
    """Human-readable table (the CLI face)."""
    out = []
    link = delta["link"]
    out.append(
        f"compared {delta['compared']} metric leaves "
        f"(threshold {delta['threshold_pct']}%): "
        f"{len(delta['regressions'])} regression(s), "
        f"{len(delta['link_attributable'])} link-attributable, "
        f"{delta['improved']} improved, {delta['flat']} flat; "
        f"schema drift: {delta['only_old_keys']} old-only / "
        f"{delta['only_new_keys']} new-only keys")
    op, np_ = delta.get("old_provenance", {}), delta.get("new_provenance", {})
    out.append(f"old: round={op.get('round')} sha={op.get('git_sha')}  "
               f"link={link['old']}")
    out.append(f"new: round={np_.get('round')} sha={np_.get('git_sha')}  "
               f"link={link['new']}  drifted={link['drifted']}")

    def _table(title, rows):
        if not rows:
            out.append(f"{title}: none")
            return
        out.append(title + ":")
        w = max(len(r["path"]) for r in rows)
        for r in rows:
            arrow = "^" if r["direction"] > 0 else "v"
            pct = ("   0->n " if r["delta_pct"] is None
                   else f"{r['delta_pct']:>+8.1f}")
            out.append(
                f"  {r['path']:<{w}}  {r['old']:>14g} -> "
                f"{r['new']:>14g}  {pct}%  "
                f"(better={arrow}) [{r.get('verdict', '')}]")

    _table("REGRESSIONS (link cannot excuse)", delta["regressions"])
    _table("link-attributable drift", delta["link_attributable"])
    return "\n".join(out)


# ---------------------------------------------------------------------------
# schema coverage (the r16 "no silently skipped metric" gate)

def _doc_leaves(doc: dict):
    """(leaf key, dotted path) for every numeric leaf the compare walk
    would visit, PLUS the ones inside neutral subtrees (coverage's
    observed set must see them so the reverse check stays honest)."""
    def rec(x, path, in_neutral):
        if isinstance(x, dict):
            for k, v in x.items():
                k = str(k)
                rec(v, f"{path}.{k}" if path else k,
                    in_neutral or _neutral_subtree_segment(k))
        elif isinstance(x, list):
            for i, v in enumerate(x):
                rec(v, f"{path}[{i}]", in_neutral)
        elif _numeric(x):
            leaf = re.sub(r"\[\d+\]$", "", path.rsplit(".", 1)[-1])
            yield_to.append((leaf, path, in_neutral))

    yield_to: "list[tuple[str, str, bool]]" = []
    rec({"headline_probes_per_sec_e2e": doc.get("value"),
         "detail": doc.get("detail") or {}}, "", False)
    return yield_to


def schema_coverage(docs: "list[dict]",
                    ) -> "tuple[list[tuple[str, str]], list[str]]":
    """Both directions of the coverage contract over the committed bench
    schema (the r14 env-table rule's shape):

    forward — every numeric leaf outside the neutral subtrees must be
    suffix-classifiable or explicitly neutral; returns (leaf, example
    path) per violation. A leaf this misses is a metric bench_delta
    would silently skip forever.

    reverse — every explicit neutral entry (_SKIP_KEYS) must still name
    a leaf observed SOMEWHERE in the committed schema; returns the dead
    entries. (Suffix rules also serve summary-line and historical docs,
    so only the exact-match list is held to this.)
    """
    unclassified: "dict[str, str]" = {}
    observed: "set[str]" = set()
    for doc in docs:
        for leaf, path, in_neutral in _doc_leaves(doc):
            observed.add(leaf.lower())
            if in_neutral:
                continue
            if classify_direction(leaf) == 0 and not _neutral_key(leaf):
                unclassified.setdefault(leaf.lower(), path)
    dead = sorted(k for k in _SKIP_KEYS if k not in observed)
    return sorted(unclassified.items()), dead


def coverage_findings(root: "str | None" = None):
    """The lint-gate face of schema_coverage: ``Finding``s over the
    committed BENCH_DETAIL*.json captures, attributed so the r14 waiver
    grammar applies (dead neutral entries point at their line in THIS
    file; unclassifiable leaves point at the capture — the fix is to
    classify or neutral-list, never to waive the capture)."""
    import os

    from reporter_tpu.analysis.lint_rules import Finding, REPO_ROOT

    root = root or REPO_ROOT
    docs: "list[tuple[str, dict]]" = []
    out = []
    for name in sorted(os.listdir(root)):
        if not (name.startswith("BENCH_DETAIL") and name.endswith(".json")):
            continue
        if "_PARTIAL" in name:
            # subset-run artifacts are local and gitignored (the r15
            # no-clobber discipline) — the coverage contract is over the
            # COMMITTED schema only, or the gate's verdict would depend
            # on whatever bench legs ran on this machine last
            continue
        try:
            with open(os.path.join(root, name)) as f:
                docs.append((name, json.load(f)))
        except (OSError, ValueError) as exc:
            # a committed capture that fails to parse must be loud — a
            # silently skipped doc is exactly how this gate would rot
            # vacuous-green
            out.append(Finding(
                "bench-coverage", name, 1,
                f"committed capture failed to load ({type(exc).__name__}:"
                f" {exc}) — the coverage contract cannot be checked"))
    if not docs and not out:
        out.append(Finding(
            "bench-coverage", "BENCH_DETAIL.json", 1,
            "no committed BENCH_DETAIL*.json capture found — the "
            "coverage contract has nothing to check against (the gate "
            "must not pass vacuously)"))
    if not docs:
        return out
    unclassified, dead = schema_coverage([d for _, d in docs])
    for leaf, path in unclassified:
        out.append(Finding(
            "bench-coverage", docs[0][0], 1,
            f"numeric leaf {leaf!r} ({path}) is neither "
            "suffix-classifiable nor on the explicit neutral list — "
            "bench_delta would silently skip it; add a direction "
            "suffix rule or a neutral entry in analysis/bench_delta.py"))
    src_lines = []
    try:
        with open(os.path.join(root, "reporter_tpu", "analysis",
                               "bench_delta.py")) as f:
            src_lines = f.read().splitlines()
    except OSError:
        pass

    def _line_of(token: str) -> int:
        pat = f'"{token}"'
        for i, ln in enumerate(src_lines, 1):
            if pat in ln:
                return i
        return 1

    for key in dead:
        out.append(Finding(
            "bench-coverage", "reporter_tpu/analysis/bench_delta.py",
            _line_of(key),
            f"neutral-list entry {key!r} names no leaf in any committed "
            "BENCH_DETAIL*.json — dead row; delete it (or waive with "
            "the capture flavor it still serves)"))
    return out


def main(argv: "list[str] | None" = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m reporter_tpu.analysis.bench_delta",
        description="schema-aware diff of two bench composite captures")
    ap.add_argument("old", help="baseline composite JSON "
                               "(e.g. the committed BENCH_DETAIL.json)")
    ap.add_argument("new", help="candidate composite JSON")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="delta %% below which a move is 'flat' "
                         "(default 10; the link noise floor is ~10%% "
                         "at bench draw counts)")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    print(render(compare(old, new, threshold_pct=args.threshold)))
    return 0            # a sentinel reports; it never gates


if __name__ == "__main__":          # pragma: no cover - CLI convenience
    raise SystemExit(main())
