"""Device-program contract checker — the round-14 static gates' device twin.

The wire programs' correctness/perf contracts (pinned u16/u32 wire
dtypes, i8/i16/f32 infeed, no host round-trips inside jitted bodies, one
jit boundary around ``shard_map``) were enforced only by running on
hardware — and the tunnel has been down at every driver bench since r5,
so violations ship blind. This module enforces them by ABSTRACT
interpretation: ``jax.make_jaxpr`` traces every wire entry
(``ops.match.wire_from_*``) across the full audit matrix — three
dense-sweep kernel arms (whole-block / two-level subcull / MXU) × three
wire layouts (compact u16 2-lane / full u16 3-lane / packed u32 1-lane)
× {single-device, mesh} — on a CPU host, no device needed, and walks the
closed jaxprs. Rules:

  device-x64         a 64-bit aval (f64/i64) anywhere in a jitted wire
                     body. Tracing runs with x64 ENABLED so every
                     unpinned dtype derivation widens and becomes
                     visible; under the production x32 runtime the same
                     sites silently compute in 32 bits TODAY, but they
                     are one ``jax_enable_x64`` away from doubling the
                     device bytes (weak-typed Python literal scalars are
                     exempt — they never promote their consumers).
  device-callback    host callbacks / transfers inside the jitted body
                     (pure_callback / io_callback / debug_callback /
                     infeed / outfeed / device_put): each is a host
                     round-trip serialized into the device program — on
                     the remote-attached link, ~130 ms per dispatch.
  device-nested-jit  a ``pjit`` of substance nested inside a
                     ``shard_map`` body (the lexical wire-fork lint sees
                     only the direct-argument spelling; this is the
                     semantic check over the traced program). jnp's own
                     tiny wrapper jits (where/clip/round, <= a handful
                     of eqns) are structural noise and exempt.
  device-wire-dtype  the traced entry's output aval does not carry its
                     layout's pinned wire dtype/lane shape (u16 [B,2,T]
                     compact, u16 [B,3,T] full, u32 [B,1,T] packed).
  device-trace       an audit case failed to trace at all (usually a
                     dtype mismatch a 64-bit widening forced into a scan
                     carry — the finding carries the trace error).

Findings are attributed to the source line the jaxpr equation's
traceback points at, so the r14 waiver grammar applies unchanged:
``# lint: allow[device-x64] YYYY-MM-DD reason`` on (or above) the line.

Run via ``python -m reporter_tpu.analysis --device`` (also checks the
committed compile-shape manifest and the static SMEM/HBM budgets —
analysis/compile_manifest.py); CI-pinned by tests/test_device_contract.py
and a named rung in ``__graft_entry__.py``'s multichip dry-run.
"""

from __future__ import annotations

import os
from typing import Any, Iterable

from reporter_tpu.analysis.lint_rules import (Finding, REPO_ROOT, _apply_waivers,
                                              _dedupe, _load)

__all__ = ["run_device_contract", "audit_jaxpr", "check_wire_avals",
           "AuditCase", "audit_cases", "main", "RULES"]

RULES = ("device-x64", "device-callback", "device-nested-jit",
         "device-wire-dtype", "device-trace")

# primitives that are host round-trips when they appear inside a jitted
# device body (callback-family names are also matched by substring —
# jax grows spellings faster than this list)
_DENY_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "device_put",
})

# a pjit inside shard_map smaller than this is one of jnp's own wrapper
# jits (where/clip/round/pad trace as 1-4 eqn pjits — measured on the
# full wire program); a user-nested jit of any real kernel body is
# hundreds of eqns
_NESTED_JIT_MIN_EQNS = 12

# the audit's trace shapes: tiny on purpose — trace cost is essentially
# shape-independent and the jaxpr structure is identical at any [B, T]
_B, _T = 2, 16
# edge count for the big-metro layouts (> ops.match._COMPACT_WIRE_EDGES
# so the 3-lane / packed branches are the ones traced)
_E_BIG = 50_000
_BIG_MAX_EDGE_LEN = 500.0


class AuditCase:
    """One cell of the audit matrix."""

    __slots__ = ("entry", "arm", "layout", "path")

    def __init__(self, entry: str, arm: str, layout: str, path: str):
        self.entry = entry      # "f32" | "q16" | "q8"
        self.arm = arm          # "subcull" | "block" | "mxu"
        self.layout = layout    # "compact" | "full" | "packed"
        self.path = path        # "single" | "mesh"

    @property
    def label(self) -> str:
        return f"{self.entry}/{self.arm}/{self.layout}/{self.path}"


def audit_cases() -> "list[AuditCase]":
    import itertools

    return [AuditCase(*c) for c in itertools.product(
        ("f32", "q16", "q8"), ("subcull", "block", "mxu"),
        ("compact", "full", "packed"), ("single", "mesh"))]


# ---------------------------------------------------------------------------
# jaxpr walking

def _rel(path: str) -> str:
    try:
        rel = os.path.relpath(path, REPO_ROOT)
    except ValueError:          # pragma: no cover - windows drive mismatch
        return path
    return rel if not rel.startswith("..") else path


def _eqn_site(eqn) -> "tuple[str, int] | None":
    """(repo-relative path, line) of the reporter_tpu frame an equation
    was traced from, or None when the trace has no repo frame."""
    tb = getattr(eqn.source_info, "traceback", None)
    if tb is None:
        return None
    for f in tb.frames:
        if "reporter_tpu" in f.file_name and "analysis" not in f.file_name:
            return _rel(f.file_name), int(f.line_num)
    return None


def _sub_jaxprs(eqn) -> Iterable[Any]:
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr"):        # ClosedJaxpr
                yield x.jaxpr
            elif hasattr(x, "eqns"):       # raw Jaxpr
                yield x


def _is_x64_leak(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    if dt is None or dt.itemsize != 8:
        return False
    # weak-typed rank-0 avals are the jaxpr representation of Python
    # literal scalars: they never promote a 32-bit consumer, and under
    # the x32 runtime they are the same weak f32/i32 — not a leak
    if getattr(aval, "weak_type", False) and not aval.shape:
        return False
    return True


def audit_jaxpr(closed, label: str,
                fallback_site: "tuple[str, int]") -> "list[Finding]":
    """Walk one closed jaxpr, returning device-contract findings.
    ``fallback_site`` attributes equations with no repo frame (pure
    jax-internal provenance)."""
    findings: "list[Finding]" = []

    def visit(jaxpr, inside_shard_map: bool) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            site = _eqn_site(eqn) or fallback_site
            if name in _DENY_PRIMITIVES or "callback" in name:
                findings.append(Finding(
                    "device-callback", site[0], site[1],
                    f"host primitive {name} inside the jitted device "
                    f"body ({label}) — a host round-trip serialized "
                    "into the device program; hoist it out of the wire "
                    "path"))
            if name == "pjit" and inside_shard_map:
                inner = eqn.params.get("jaxpr")
                n = len(inner.jaxpr.eqns) if inner is not None else 0
                if n >= _NESTED_JIT_MIN_EQNS:
                    findings.append(Finding(
                        "device-nested-jit", site[0], site[1],
                        f"jit of substance ({n} eqns) nested inside "
                        f"shard_map ({label}) — jit goes OUTSIDE "
                        "shard_map (jax.jit(shard_map(wire_from_*)))"))
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None and _is_x64_leak(aval):
                    findings.append(Finding(
                        "device-x64", site[0], site[1],
                        f"64-bit aval {aval.dtype} at primitive {name} "
                        "in a jitted wire body — pin the dtype (the "
                        "x64 audit widens every unpinned derivation; "
                        "wire programs carry u16/u32/i8/i16/f32 only)"))
            nested = inside_shard_map or name == "shard_map"
            for sub in _sub_jaxprs(eqn):
                visit(sub, nested)

    visit(closed.jaxpr, False)
    return _dedupe(findings)


_WIRE_AVAL_EXPECT = {
    "compact": ("uint16", 2),
    "full": ("uint16", 3),
    "packed": ("uint32", 1),
}


def check_wire_avals(out_avals, layout: str, label: str,
                     site: "tuple[str, int]") -> "list[Finding]":
    """The end-to-end dtype pin: the traced entry must emit exactly its
    layout's wire array — one [B, lanes, T] array of the pinned dtype."""
    want_dtype, want_lanes = _WIRE_AVAL_EXPECT[layout]
    out: "list[Finding]" = []
    ok = (len(out_avals) == 1
          and str(out_avals[0].dtype) == want_dtype
          and len(out_avals[0].shape) == 3
          and int(out_avals[0].shape[1]) == want_lanes)
    if not ok:
        got = [f"{a.dtype}{list(a.shape)}" for a in out_avals]
        out.append(Finding(
            "device-wire-dtype", site[0], site[1],
            f"wire output of {label} is {got}, expected one "
            f"{want_dtype}[B,{want_lanes},T] array — the {layout} "
            "layout's pinned wire format"))
    return out


# ---------------------------------------------------------------------------
# the tracer

def _ensure_cpu_devices():
    """CPU devices for the mesh leg, without ever instantiating the axon
    TPU client (whose tunnel can hang forever — CLAUDE.md): restrict the
    platform BEFORE any backend exists, exactly the __graft_entry__
    dry-run discipline. No-op when a backend (tier-1's pinned CPU) is
    already up."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass                    # a backend already exists; use it as-is
    devs = jax.local_devices(backend="cpu")
    if not devs:                # pragma: no cover - defensive
        raise RuntimeError("device-contract audit needs a CPU backend")
    return devs


def _tiny_tileset():
    from reporter_tpu.config import CompilerParams
    from reporter_tpu.netgen.synthetic import generate_city
    from reporter_tpu.tiles.compiler import compile_network

    return compile_network(generate_city("tiny"),
                           CompilerParams(reach_radius=400.0))


def _abstract_tables(ts, big_metro: bool):
    """The staged dense layout as ShapeDtypeStructs — shapes from a real
    tiny tileset's ``host_tables`` so the audit can never drift from the
    staging layout; the big-metro variant rescales only the edge-indexed
    arrays (the wire layout dispatches statically on the edge count)."""
    import jax

    host = ts.host_tables("dense")
    sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
           for k, v in host.items()}
    if big_metro:
        for k in ("edge_len", "reach_row", "edge_osmlr"):
            sds[k] = jax.ShapeDtypeStruct((_E_BIG,), sds[k].dtype)
    return sds


def _entry_args(entry: str):
    import jax
    import jax.numpy as jnp

    pts = jax.ShapeDtypeStruct((_B, _T, 2), jnp.float32)
    origins = jax.ShapeDtypeStruct((_B, 2), jnp.float32)
    lens = jax.ShapeDtypeStruct((_B,), jnp.int32)
    if entry == "f32":
        return (pts, lens)
    if entry == "q16":
        return (jax.ShapeDtypeStruct((_B, _T, 2), jnp.int16), origins, lens)
    return (jax.ShapeDtypeStruct((_B, _T, 2), jnp.int8), origins, lens)


def _arm_params(arm: str):
    from reporter_tpu.config import MatcherParams

    p = MatcherParams(candidate_backend="dense")
    if arm == "block":
        return p.replace(sweep_subcull=False)
    if arm == "mxu":
        # bf16 operands = the MXU arm the bench A/B measures
        return p.replace(sweep_mxu=True, sweep_lowp="bf16")
    return p


def _layout_spec(layout: str):
    from reporter_tpu.ops.match import wire_spec

    if layout != "packed":
        return None
    spec = wire_spec(_E_BIG, _BIG_MAX_EDGE_LEN)
    if spec is None:            # pragma: no cover - layout math regressed
        raise RuntimeError(
            f"wire_spec({_E_BIG}, {_BIG_MAX_EDGE_LEN}) rejected the "
            "packed layout the audit exists to cover")
    return spec


def _entry_site(entry: str) -> "tuple[str, int]":
    """(path, def line) of the wire entry — the fallback attribution and
    the anchor for case-level findings."""
    import inspect

    from reporter_tpu.ops import match

    impl = {"f32": match.wire_from_f32, "q16": match.wire_from_q16,
            "q8": match.wire_from_q8}[entry]
    try:
        line = inspect.getsourcelines(impl)[1]
    except OSError:             # pragma: no cover - no source available
        line = 1
    return "reporter_tpu/ops/match.py", line


def _trace_case(case: AuditCase, ts, tables, mesh):
    """ClosedJaxpr of one audit cell. x64 must already be enabled and the
    pallas override active (run_device_contract holds both contexts)."""
    import jax

    from reporter_tpu.ops import match

    impl = {"f32": match.wire_from_f32, "q16": match.wire_from_q16,
            "q8": match.wire_from_q8}[case.entry]
    params = _arm_params(case.arm)
    spec = _layout_spec(case.layout)
    args = _entry_args(case.entry)
    if case.path == "single":
        def fn(tb, *a):
            return impl(*a, tb, ts.meta, params, None, spec)

        return jax.make_jaxpr(fn)(tables, *args)
    from reporter_tpu.parallel.dp_e2e import mesh_wire_fn

    fn = mesh_wire_fn(mesh, case.entry, ts.meta, params, spec, tables,
                      has_acc=False)
    return jax.make_jaxpr(fn)(*args, tables)


def _audit_histogram() -> "list[Finding]":
    """The other jitted scatter on the product path: SpeedHistogram's
    fixed-shape accumulate (r12 — ONE batch shape). Same rules, same
    x64 widening discipline."""
    import jax
    import jax.numpy as jnp

    from reporter_tpu.streaming import histogram as hg

    cap = hg.SpeedHistogram._CAP
    closed = jax.make_jaxpr(hg._accumulate)(
        jax.ShapeDtypeStruct((64, 12), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.bool_))
    return audit_jaxpr(closed, "histogram/scatter",
                       ("reporter_tpu/streaming/histogram.py", 1))


def _audit_backfill_scatter(mesh) -> "list[Finding]":
    """Round 20: the backfill aggregates' shared FLAT scatter
    (ops/aggregate.py) — same fixed-batch-shape discipline as the
    histogram, audited under the same x64 widening rules. Round 21 adds
    the mesh-sharded case through the SAME program builder the serving
    path uses (agg.mesh_scatter_fn — per-device partial grids, leading
    dim sharded; the jaxpr structure is device-count independent, so the
    1-device audit mesh suffices here too)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from reporter_tpu.ops import aggregate as agg

    cap = agg._CAP
    site = ("reporter_tpu/ops/aggregate.py", 1)
    closed = jax.make_jaxpr(agg._scatter_add)(
        jax.ShapeDtypeStruct((4096,), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.int32),
        jax.ShapeDtypeStruct((cap,), jnp.bool_))
    findings = audit_jaxpr(closed, "backfill/scatter", site)
    ndev = int(np.prod(tuple(mesh.shape.values())))
    closed_mesh = jax.make_jaxpr(agg.mesh_scatter_fn(mesh))(
        jax.ShapeDtypeStruct((ndev, 4096), jnp.int32),
        jax.ShapeDtypeStruct((ndev, cap), jnp.int32),
        jax.ShapeDtypeStruct((ndev, cap), jnp.bool_))
    findings.extend(audit_jaxpr(closed_mesh, "backfill/scatter-mesh", site))
    return findings


def _merge_across_cases(findings: "list[Finding]") -> "list[Finding]":
    """One finding per (rule, path, line): a shared-code violation is hit
    by most of the 54 matrix cells (every case traces the same viterbi),
    and 54 near-identical lines would drown the gate output. The first
    case's message survives with a count of the rest."""
    merged: "dict[tuple, Finding]" = {}
    extra: "dict[tuple, int]" = {}
    for f in findings:
        key = (f.rule, f.path, f.line)
        if key in merged:
            if f.message != merged[key].message:
                extra[key] = extra.get(key, 0) + 1
        else:
            merged[key] = f
    for key, n in extra.items():
        merged[key].message += f" [+{n} more audit case(s) hit this site]"
    return list(merged.values())


def run_device_contract(root: str = REPO_ROOT) -> "list[Finding]":
    """Trace + audit the full matrix; returns waiver-applied findings."""
    import jax

    from reporter_tpu.ops import dense_candidates as dc
    from reporter_tpu.parallel.compat import shard_map  # noqa: F401  (import
    #             here so a broken shim fails the gate, not the serving path)
    from jax.sharding import Mesh

    import numpy as np

    devs = _ensure_cpu_devices()
    ts = _tiny_tileset()
    # ONE device is enough to trace the shard_map product program (the
    # jaxpr structure is device-count independent); it also keeps the
    # audit deterministic between the CLI (1 CPU device) and tier-1's
    # 8-device virtual mesh
    mesh = Mesh(np.asarray(devs[:1]), ("dp",))
    tables_small = _abstract_tables(ts, big_metro=False)
    tables_big = _abstract_tables(ts, big_metro=True)

    findings: "list[Finding]" = []
    with jax.experimental.enable_x64(), dc.pallas_trace_override():
        for case in audit_cases():
            tables = tables_small if case.layout == "compact" else tables_big
            site = _entry_site(case.entry)
            try:
                closed = _trace_case(case, ts, tables, mesh)
            except Exception as exc:   # noqa: BLE001 - the finding carries it
                findings.append(Finding(
                    "device-trace", site[0], site[1],
                    f"audit case {case.label} failed to trace: "
                    f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
                continue
            findings.extend(audit_jaxpr(closed, case.label, site))
            findings.extend(check_wire_avals(closed.out_avals, case.layout,
                                             case.label, site))
        findings.extend(_audit_histogram())
        findings.extend(_audit_backfill_scatter(mesh))

    findings = _merge_across_cases(findings)
    by_path: "dict[str, list[Finding]]" = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, group in by_path.items():
        mod = _load(os.path.join(root, path), root)
        if mod is not None:
            _apply_waivers(mod, group)
    return findings


def main(argv: "list[str] | None" = None) -> int:
    """The ``--device`` gate: jaxpr audit + compile-shape manifest +
    static SMEM/HBM budget checks. Exit 1 on any unwaived finding."""
    from reporter_tpu.analysis import compile_manifest

    findings = run_device_contract()
    problems = list(compile_manifest.check())
    for f in findings:
        print(f)
    for p in problems:
        print(f"compile-manifest: {p}")
    unwaived = [f for f in findings if not f.waived]
    n_cases = len(audit_cases())
    print(f"device contract: {n_cases} audit cases, {len(findings)} "
          f"finding(s), {len(unwaived)} unwaived; manifest "
          f"{'DRIFTED' if problems else 'pinned'}")
    return 1 if (unwaived or problems) else 0
