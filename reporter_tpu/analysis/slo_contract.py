"""``python -m reporter_tpu.analysis --slo`` — static validator for the
committed SLO specs (round 24).

The burn-rate engine (reporter_tpu/obs/slo.py) trusts its specs: a
window pair ordered backwards alerts on noise, a burn threshold above
the mathematical maximum can never fire, a latency threshold off the
``HISTOGRAM_BUCKETS`` grid silently measures the wrong objective, and a
metric name nothing registers burns zero forever. All four are spec
BUGS, not runtime conditions — so they are rejected here, at the same
layer that pins the env table and metric inventory, not discovered in
production. Rules (each seeded with a synthetic violation + clean twin
in tests/test_slo.py, the r14 discipline):

  slo-shape    objective strictly in (0, 1); kind one of ratio/latency/
               gauge with that kind's fields populated (ratio: bad+total
               counter tuples; latency: series + ``threshold_s`` exactly
               on the HISTOGRAM_BUCKETS grid; gauge: series name +
               ceiling > 0); spec names unique (gauge specs key their
               synthetic ``slo_sample_*`` counters by name — duplicates
               would alias).
  slo-windows  every (fast, slow, threshold) window pair has
               fast < slow STRICTLY and positive durations; at least one
               pair per spec. (Scale-independent: ``RTPU_SLO_SCALE``
               multiplies both sides.)
  slo-burn     1 < threshold <= 1/(1 - objective): a threshold <= 1
               alerts inside budget; one above the max possible burn
               (all-bad traffic) can never fire.
  slo-metric   every registry series a spec reads appears in README's
               marker-delimited metric inventory block (derived
               ``_count``/``_sum``/``_total`` suffixes resolve to their
               base series, the exposition's own convention).

Validating DEFAULT_SLOS against the committed README must stay clean —
tests/test_slo.py pins that, so spec drift and inventory drift both
fail CI before they fail an operator.
"""

from __future__ import annotations

import os

from reporter_tpu.analysis.lint_rules import Finding, _inventory_tokens
from reporter_tpu.utils.metrics import HISTOGRAM_BUCKETS

_SPEC_PATH = "reporter_tpu/obs/slo.py"
_KINDS = ("ratio", "latency", "gauge")
# suffixes the exposition derives from a base series (_with_suffix /
# histogram exports): a spec may reference the derived name, the
# inventory documents the base
_DERIVED_SUFFIXES = ("_count", "_sum", "_total")


def _shape_findings(spec) -> "list[str]":
    msgs: "list[str]" = []
    if not (0.0 < spec.objective < 1.0):
        msgs.append(f"objective {spec.objective!r} must lie strictly in "
                    "(0, 1) — 1.0 has zero error budget (every burn "
                    "divides by it) and 0 objectives nothing")
    if spec.kind not in _KINDS:
        msgs.append(f"unknown kind {spec.kind!r} (one of {_KINDS})")
        return msgs
    if spec.kind == "ratio" and not (spec.bad and spec.total):
        msgs.append("ratio spec needs non-empty bad= and total= counter "
                    "name tuples")
    if spec.kind == "latency":
        if not spec.series:
            msgs.append("latency spec needs series= (an observation "
                        "series name)")
        if spec.threshold_s not in HISTOGRAM_BUCKETS:
            msgs.append(
                f"threshold_s {spec.threshold_s!r} is not on the "
                "HISTOGRAM_BUCKETS grid — off-grid thresholds silently "
                "measure the nearest bucket's objective instead "
                f"(grid: {HISTOGRAM_BUCKETS})")
    if spec.kind == "gauge":
        if not spec.gauge:
            msgs.append("gauge spec needs gauge= (a gauge series name)")
        if spec.ceiling <= 0:
            msgs.append(f"gauge ceiling {spec.ceiling!r} must be > 0")
    return msgs


def _window_findings(spec) -> "list[str]":
    msgs: "list[str]" = []
    if not spec.windows:
        msgs.append("spec has no window pairs — it can never alert")
    for fast, slow, _thr in spec.windows:
        if fast <= 0 or slow <= 0:
            msgs.append(f"window pair ({fast}, {slow}) has a "
                        "non-positive duration")
        elif not fast < slow:
            msgs.append(
                f"window pair ({fast}, {slow}) must have fast < slow "
                "STRICTLY — the slow window is the sustained-burn "
                "confirmation; equal or inverted windows collapse the "
                "multi-window guard to a single noisy window")
    return msgs


def _burn_findings(spec) -> "list[str]":
    msgs: "list[str]" = []
    budget = spec.budget()
    if budget <= 0:
        return msgs  # already a slo-shape finding
    max_burn = 1.0 / budget
    for fast, slow, thr in spec.windows:
        if thr <= 1.0:
            msgs.append(
                f"pair ({fast}, {slow}) burn threshold {thr} <= 1 "
                "alerts while still INSIDE budget — thresholds are "
                "multiples of exactly-on-budget burn")
        elif thr > max_burn:
            msgs.append(
                f"pair ({fast}, {slow}) burn threshold {thr} exceeds "
                f"the maximum possible burn 1/(1-objective) = "
                f"{max_burn:g} (all-bad traffic) — it can never fire")
    return msgs


def _documented(name: str, tokens: "dict[str, int]") -> bool:
    if name in tokens:
        return True
    for suf in _DERIVED_SUFFIXES:
        if name.endswith(suf) and name[:-len(suf)] in tokens:
            return True
    return False


def validate_specs(specs, readme_path: "str | None" = None,
                   ) -> "list[Finding]":
    """All findings for ``specs``; ``readme_path=None`` skips the
    inventory cross-check (pure-shape validation for unit tests)."""
    out: "list[Finding]" = []
    seen: "dict[str, int]" = {}
    for spec in specs:
        if spec.name in seen:
            out.append(Finding(
                "slo-shape", _SPEC_PATH, 1,
                f"duplicate spec name {spec.name!r} — gauge sampling "
                "and per-spec gauges key on the name; duplicates alias"))
        seen.setdefault(spec.name, 1)
        for rule, fn in (("slo-shape", _shape_findings),
                         ("slo-windows", _window_findings),
                         ("slo-burn", _burn_findings)):
            for msg in fn(spec):
                out.append(Finding(rule, _SPEC_PATH, 1,
                                   f"spec {spec.name!r}: {msg}"))
    if readme_path is not None:
        try:
            with open(readme_path) as f:
                readme = f.readlines()
        except OSError:
            readme = []
        tokens, found = _inventory_tokens(readme)
        if not found:
            out.append(Finding(
                "slo-metric", "README.md", 1,
                "no metric-inventory block in README — the SLO metric "
                "cross-check has nothing to check against (the gate "
                "must not pass vacuously)"))
        else:
            for spec in specs:
                for name in spec.metric_names():
                    if not _documented(name, tokens):
                        out.append(Finding(
                            "slo-metric", _SPEC_PATH, 1,
                            f"spec {spec.name!r} reads metric {name!r} "
                            "but README's metric inventory has no such "
                            "row — an SLO over a series nothing "
                            "registers burns zero forever"))
    return out


def main(argv: "list[str] | None" = None) -> int:
    from reporter_tpu.obs.slo import DEFAULT_SLOS

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    findings = validate_specs(DEFAULT_SLOS,
                              os.path.join(root, "README.md"))
    for f in findings:
        print(f)
    print(f"slo contract: {len(DEFAULT_SLOS)} spec(s), "
          f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":          # pragma: no cover - CLI convenience
    raise SystemExit(main())
