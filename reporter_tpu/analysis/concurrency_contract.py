"""The committed lockdep golden state — extend with a DATED justification
only; never delete an entry to silence a failure without understanding
the ordering it pinned.

``LOCK_ORDER_EDGES`` is the set of legal lock-class acquisition-order
edges (A, B): "a thread may acquire B while holding A". The runtime
(utils/locks.py, armed by tests/conftest.py) records every observed edge
across the tier-1 concurrency suites; the per-test gate fails on

  - any edge NOT in this set (a new nesting — either add it here with a
    justification, or restructure the code so the nesting disappears);
  - any edge that would close a CYCLE in the graph (potential deadlock —
    never allowlist these; fix the order).

``BLOCKING_ALLOW`` is the set of (lock class, blocking call) pairs that
are deliberately held across a blocking call. The bar for an entry is
high: the hold must be load-bearing for correctness (not convenience)
and the blocking call bounded. Everything else is a bug — round 11's
promotion ``device_put`` under the fleet lock and round 9's dead-letter
replay POSTing under the spool lock both lived here until hand-found.

How to read a failure: the gate prints the violation dicts — ``kind``
(lock-order | blocking-under-lock), the offending ``edge`` or ``call``,
the ``held`` stack, and ``site`` (file:line of the acquisition). For a
lock-order violation, the fix is almost always to shrink the inner
critical section or to snapshot state and release before calling out.
"""

from __future__ import annotations

__all__ = ["LOCK_ORDER_EDGES", "BLOCKING_ALLOW", "validate"]

LOCK_ORDER_EDGES: "dict[tuple[str, str], str]" = {
    # ---- scheduler (service/scheduler.py) -------------------------------
    ("scheduler.cv", "scheduler.stats"): "2026-08-04 batch close updates "
        "deferral/hist counters while still deciding under the condvar; "
        "stats is a leaf lock held for a dict write",
    ("scheduler.cv", "metrics.registry"): "2026-08-04 admission/inflight "
        "gauges published at the decision point under the condvar; the "
        "registry lock is a leaf (O(1) dict write, never calls out)",
    ("scheduler.cv", "readahead.tasks"): "2026-08-06 a closing batch "
        "submits its prepare-ahead ticket under the condvar — the worker "
        "may pop the job immediately, so the ticket must exist before "
        "the work-queue put (r22 pipelined prepare); the read-ahead lock "
        "is a LEAF by construction (guards the task deque only; "
        "submitted callables run strictly outside it — "
        "utils/readahead.py docstring)",
    # ---- metrics as a leaf under component locks -------------------------
    ("scheduler.stats", "metrics.registry"): "2026-08-04 padding stats + "
        "occupancy gauge in one section (pad_traces); leaf write",
    ("fleet.ledger", "metrics.registry"): "2026-08-04 residency "
        "hit/miss/eviction counters and occupancy gauges publish at the "
        "paging event under the ledger (O(1) per event by design, "
        "round 11); leaf write",
    ("matcher.fallback", "metrics.registry"): "2026-08-04 the oracle "
        "fallback matcher counts its own traces while serialized on the "
        "fallback lock; leaf write",
    ("app.combine", "metrics.registry"): "2026-08-04 combine-mode leader "
        "observes request metrics while holding the one-batch-in-flight "
        "lock (legacy A/B path, kept by round-7 decision); leaf write",
    # ---- legacy combine leader (service/app.py) --------------------------
    ("app.combine", "app.pending"): "2026-08-04 the leader drains the "
        "pending queue it owns; pending is a leaf list-swap lock",
    ("app.combine", "app.stats"): "2026-08-04 leader bumps batch counters "
        "after a drain round; leaf write",
    ("app.combine", "cache.entries"): "2026-08-04 combine-mode "
        "_process_validated merges/retains per-uuid tails under the "
        "leader lock; cache is a leaf (TTL dict ops only)",
    ("app.combine", "publisher.counters"): "2026-08-04 combine-mode "
        "publish counts outcomes under the leader lock; leaf write",
    ("app.combine", "faults.plan"): "2026-08-04 combine-mode publish "
        "consults the active fault plan (a counter increment) under the "
        "leader lock; leaf write",
    ("app.combine", "faults.registry"): "2026-08-04 faults.active()'s "
        "lazy one-shot env parse takes the registry lock on first "
        "consultation, which can land under the combine leader; leaf",
    ("app.combine", "tracer.dump"): "2026-08-04 combine-mode publish "
        "failure can dead-letter and post-mortem under the leader lock "
        "(legacy path); dump lock is only contended by other dumps",
    ("app.combine", "publisher.spool"): "2026-08-04 combine-mode "
        "dead-letter append under the leader lock (legacy path); the "
        "spool append is a bounded local write",
    ("app.combine", "watchdog.ledger"): "2026-08-04 combine-mode "
        "dispatch checks the watchdog breaker (tripped/abandoned "
        "bookkeeping) under the leader lock; the ledger lock is held "
        "for nanoseconds by contract (utils/watchdog.py docstring)",
    # ---- fleet router (fleet/router.py) ----------------------------------
    ("fleet_router.app_build", "fleet_router.apps"): "2026-08-04 app() "
        "re-checks and publishes the built app in the dict under the "
        "per-metro build lock (double-checked construction); apps is a "
        "leaf dict guard",
    ("fleet_router.app_build", "fleet.ledger"): "2026-08-04 building a "
        "metro's app promotes it through residency under the per-metro "
        "build lock — the lock is PER METRO precisely so this nesting "
        "stalls only that metro's first touch (round-11 decision)",
    ("fleet_router.app_build", "metrics.registry"): "2026-08-04 "
        "promotion under the build lock publishes paging gauges; leaf",
    # ---- tracing ---------------------------------------------------------
    ("tracer.dump", "tracer.tid"): "2026-08-04 dump() resolves thread ids "
        "while holding the dump lock — tid got its OWN lock for exactly "
        "this nesting (round 10); tid is a leaf",
    # ---- link health (round 15) ------------------------------------------
    ("linkhealth.state", "metrics.registry"): "2026-08-04 every recorded "
        "link sample publishes its gauges to the attached registries in "
        "the same section (the ring append and the gauge write must see "
        "one consistent sample); the registry lock is a leaf O(1) dict "
        "write. Probes themselves NEVER run under linkhealth.state — "
        "the sampler bounds them with the shared watchdog first and "
        "records the finished result",
    ("fleet_router.app_build", "linkhealth.registry"): "2026-08-04 a "
        "metro app's first-touch construction (under its per-metro "
        "build lock, the round-11 design) attaches its registry to the "
        "process link sampler; the registry lock guards one lazy "
        "construction + a module pointer read, never calls out",
    ("fleet_router.app_build", "linkhealth.state"): "2026-08-04 same "
        "first-touch construction: attach/start take the sampler state "
        "lock for a list append + gauge replay; leaf section (probing "
        "happens on the sampler's own daemon thread, not here)",
    ("app.combine", "linkhealth.registry"): "2026-08-04 the legacy "
        "combine leader holds its lock through the whole dispatch (kept "
        "r7 A/B design), so a dispatch TIMEOUT's dead-link note "
        "(linkhealth.note_dispatch_timeout) lands under it; the "
        "registry lock guards one module-pointer read",
    ("app.combine", "linkhealth.state"): "2026-08-04 same path: the "
        "dead-link sample records under the sampler state lock — a "
        "ring append + leaf gauge writes, the same shape as the "
        "existing app.combine -> metrics.registry edge",
    # ---- quality telemetry (round 18) ------------------------------------
    ("app.combine", "quality.monitor"): "2026-08-04 the legacy combine "
        "leader holds its lock through match_many (kept r7 A/B design), "
        "so the harvest's quality-window append lands under it; the "
        "monitor lock is a LEAF by contract (guards the window deque "
        "only — publication/fault-plan/post-mortem all run outside it)",
    ("app.combine", "quality.audit"): "2026-08-04 same combine-leader "
        "path: the shadow-audit sampling decision (one counted seeded "
        "draw + a bounded enqueue) lands under the leader lock; the "
        "audit lock is a leaf — the oracle runs on the auditor's own "
        "daemon thread, never here",
    ("app.combine", "quality.registry"): "2026-08-04 same path: "
        "quality_audit.auditor()'s lazy one-shot construction guard "
        "(the faults.registry shape, already edged above)",
    # (NOTE r18: oracle instances — the watchdog fallback and the
    # shadow-audit oracle — run with their quality telemetry DISABLED,
    # so no matcher.fallback -> quality/faults/tracer nesting exists;
    # the shadow audit also runs a DEDICATED oracle instance and never
    # takes matcher.fallback at all)
    # ---- topology supervisor (round 19) ----------------------------------
    # supervisor.members / supervisor.sink are LEAF locks BY
    # CONSTRUCTION (distributed/supervisor.py docstring): spawning
    # (subprocess.Popen is a patched blocking entry point),
    # post-mortems, gauge publication, and snapshot merging all run
    # outside them, so the topology layer contributes zero order edges
    # and zero blocking-allow entries. (The r19 supervisor.events lock
    # was absorbed into the shared eventlog.append class in round 24 —
    # still a leaf.) A future edge from any of them is a design change
    # — justify it here with a date, don't just add it.
    # ---- event logs (round 24) -------------------------------------------
    ("lease.table", "eventlog.append"): "2026-08-07 lease audit events "
        "persist inside the table transaction window (through "
        "StaleLeaseError — a fencing rejection that vanished from the "
        "log would be undebuggable, round 23), and round 24 moved the "
        "append behind the shared utils/eventlog.py writer; "
        "eventlog.append is a LEAF by construction (append+flush of "
        "prebuilt lines, no fsync, never calls out)",
    # obs.slo (round 24) is a LEAF by construction: it guards only the
    # snapshot ring, throttle stamp and alert state — the export pull,
    # gauge publication, ledger append and tracer all run outside it
    # (the quality.monitor shape).
    # ---- streaming brokers ----------------------------------------------
    ("broker.partitions", "faults.plan"): "2026-08-04 durable append "
        "consults the broker fault site inside the partition lock so an "
        "injected torn write lands exactly where a real one would; the "
        "plan lock is a leaf counter",
    ("broker.partitions", "faults.registry"): "2026-08-06 "
        "faults.active()'s lazy one-shot env parse takes the registry "
        "lock on first consultation, which can land under a durable "
        "partition lock when a broker fault site is the process's first "
        "consultation (the backfill engine's reader thread reaches one "
        "before any matcher site); leaf — the app.combine edge's shape",
    # ---- publisher -------------------------------------------------------
    ("publisher.spool", "publisher.counters"): "2026-08-04 replay "
        "rewrites the spool prefix and reconciles pending/replayed "
        "counts in one section; counters is a leaf",
}

BLOCKING_ALLOW: "dict[tuple[str, str], str]" = {
    ("publisher.spool", "os.fsync"): "2026-08-04 dead-letter prefix "
        "rewrite must exclude concurrent appends or a just-spooled batch "
        "is lost in the os.replace; the spool is bounded and the POSTs "
        "(the unbounded leg) run outside the lock (round-9 hardening)",
    ("broker.partitions", "os.fsync"): "2026-08-04 durable broker "
        "appends fsync under the partition lock so on-disk batch order "
        "always matches offset order (round-6 discipline); per-append "
        "fsync is the opted-in durability cost",
    ("app.combine", "urllib.request.urlopen"): "2026-08-04 the legacy "
        "combine leader holds its lock through the full publish round "
        "trip BY DESIGN (round-7 A/B baseline: 'the leader holds the "
        "lock through the full link round-trip'); the scheduler path "
        "exists because of this — do not extend this entry to new code",
    ("app.combine", "time.sleep"): "2026-08-04 same combine-leader "
        "design: publish retry backoff sleeps ride the leader lock in "
        "the legacy path only",
    ("app.combine", "jax.block_until_ready"): "2026-08-04 same "
        "combine-leader design: the device dispatch rides the leader "
        "lock; the r7 scheduler is the fix, combine is the kept A/B arm",
    ("app.combine", "jax.device_put"): "2026-08-04 same combine-leader "
        "design: jnp.asarray of the submit slice device_puts under the "
        "leader lock in the legacy path only",
    ("fleet_router.app_build", "jax.device_put"): "2026-08-04 the "
        "per-metro build lock holds through the metro's first promotion "
        "BY DESIGN (round 11: replaced a global lock so one cold "
        "metro's multi-second page-in stalls only its own traffic); the "
        "transfer is bounded by FleetConfig.promote_timeout_s when armed",
    ("fleet_router.app_build", "jax.block_until_ready"): "2026-08-04 "
        "same per-metro first-promotion design (residency.py "
        "_device_put_guarded's local-dispatch bound)",
    ("fleet_router.app_build", "wait:fleet.ledger"): "2026-08-04 a "
        "first-touch app build can park on the fleet condvar (another "
        "thread mid-promotion of the same metro, or a capacity wait) "
        "while holding the per-metro build lock — the same round-11 "
        "design as the device_put hold above: only THIS metro's "
        "traffic waits, and the wait is bounded by promote_wait_s",
    ("lease.table", "os.fsync"): "2026-08-07 the lease state file is "
        "the cross-process ownership truth (round 23): a transaction's "
        "tmp-file fsync MUST complete under the table lock before the "
        "os.replace, or a torn/reordered write could hand one "
        "partition to two workers — the write is one small JSON doc "
        "and the lock is otherwise a leaf (lease.py docstring)",
}


def validate() -> None:
    """Golden-state self-checks (test-asserted): the edge set must be
    acyclic (a cyclic golden graph would bless a deadlock) and every
    entry must carry a dated justification."""
    import re

    dated = re.compile(r"20\d\d-\d\d-\d\d")
    for table in (LOCK_ORDER_EDGES, BLOCKING_ALLOW):
        for key, why in table.items():
            if not dated.search(why or ""):
                raise AssertionError(
                    f"{key}: justification must carry a date: {why!r}")
    # cycle check over the golden edges
    adj: "dict[str, list[str]]" = {}
    for (a, b) in LOCK_ORDER_EDGES:
        adj.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(adj) | {b for v in adj.values()
                                           for b in v}}

    def dfs(n: str, path: "list[str]") -> None:
        color[n] = GRAY
        for m in adj.get(n, ()):
            if color[m] == GRAY:
                raise AssertionError(
                    f"golden lock-order graph has a cycle through "
                    f"{path + [n, m]} — a committed deadlock; fix the "
                    "order instead of extending the graph")
            if color[m] == WHITE:
                dfs(m, path + [n])
        color[n] = BLACK

    for n in list(color):
        if color[n] == WHITE:
            dfs(n, [])
