"""Pinned compiled-shape universe + static SMEM/HBM budgets.

Every jit trace is ~150 ms of host time on this one-core box and is NOT
covered by the persistent compile cache (the r12 SpeedHistogram lesson:
a shape-varying scatter dropped fresh trace cost into whichever measured
wave first hit a new cap, and the attribution noise cost a round). The
executable population is therefore a deliberately SMALL, FIXED universe
— scheduler trace-count rungs × matcher point buckets × three wire
entries × two accuracy variants, one histogram scatter shape, one dense
sweep geometry — and this module pins it: ``compute_manifest()`` derives
the universe from the live constants, ``GOLDEN`` is the committed copy,
and any drift (a new rung, a changed bucket, a resized kernel block, a
bumped staged-table layout) is a CI failure instead of r12-style bench
noise. Intentional changes regenerate the golden block with::

    python -m reporter_tpu.analysis --update-manifest

(the fixtures/regen.py workflow: regenerate ONLY for intentional
compile-universe changes, and let the diff say what moved).

The same module carries the static device-memory bounds:

- ``smem_findings()`` — every grouped ``dense_candidates``
  scalar-prefetch launch (lane-padded ×128, the ~1 MB SMEM ceiling)
  stays within budget at every id-list width the envelope allows, using
  the launcher's OWN grouping math (ops.dense_candidates
  prefetch_smem_bytes — one spelling, checked not duplicated);
- ``hbm_findings(ts)`` — tiles/capacity.py's staged-byte shape math
  equals the bytes ``host_tables`` actually builds (cross-checked on a
  real tiny tileset), and the envelope metro's staged layout fits the
  committed HBM budget.
"""

from __future__ import annotations

from typing import Any

__all__ = ["compute_manifest", "GOLDEN", "check", "diff",
           "smem_findings", "hbm_findings", "update_golden"]

# The size envelope the static budget checks bound: generous multiples
# of the largest benched metro (bayarea-xl: 606k line segments / 485k
# directed edges), far under the continental scale where capacity.py
# already mandates sharding. Grow these when a bigger metro lands.
ENVELOPE = {
    "line_segments": 2_000_000,
    "directed_edges": 1_000_000,
    "nodes": 500_000,
    "reach_max": 128,
}

# static SMEM ceiling asserted per grouped prefetch launch (the hardware
# gives ~1 MB/core; dense_candidates self-caps its id lists at 512 KB)
SMEM_BOUND_BYTES = 1024 * 1024


def compute_manifest() -> "dict[str, Any]":
    """The compiled-shape universe, derived from the live constants."""
    from reporter_tpu.backfill import aggregate as bagg
    from reporter_tpu.config import (SWEEP_NJ_CAP_RUNGS, MatcherParams,
                                     ServiceConfig)
    from reporter_tpu.matcher import api, autotune
    from reporter_tpu.ops import aggregate as agg
    from reporter_tpu.ops import dense_candidates as dc
    from reporter_tpu.ops import match
    from reporter_tpu.service import scheduler
    from reporter_tpu.streaming.histogram import SpeedHistogram
    from reporter_tpu.tiles import capacity, tileset

    rungs = list(scheduler._TRACE_RUNGS)
    buckets = list(api._BUCKETS)
    nsub = dc._SBLK // dc._SUB if dc._SUB and dc._SBLK % dc._SUB == 0 else 1
    cap_rungs = list(SWEEP_NJ_CAP_RUNGS)
    arms = [autotune.TunedPlan(arm=a, lowp=l).label.split("@")[0]
            for a, l in autotune.CANDIDATE_ARMS]
    return {
        "manifest_version": 1,
        "scheduler": {
            "trace_count_rungs": rungs,
            "max_batch_traces_default": ServiceConfig().max_batch_traces,
        },
        "matcher": {
            "point_buckets": buckets,
            "max_device_batch_default": MatcherParams().max_device_batch,
            "wire_entries": ["f32", "q16", "q8"],
            "acc_scale_variants": 2,
            # the [B, T] executable-shape bound per tile per layout: every
            # serving dispatch shape is (rung | max_device_batch slice,
            # bucket) — an executable outside this grid is a NEW COMPILE
            "wire_executables_per_tile_bound":
                len(rungs) * len(buckets) * 3 * 2,
        },
        "wire_formats": {
            "compact_max_edges": match._COMPACT_WIRE_EDGES,
            "offset_quantum_m": match.OFFSET_QUANTUM,
            # layout → [wire dtype, lane count] (unpack_wire dispatches
            # on exactly this)
            "layouts": {"compact": ["uint16", 2], "full": ["uint16", 3],
                        "packed": ["uint32", 1]},
            "infeed_dtypes": {"f32": "float32", "q16": "int16",
                              "q8": "int8"},
        },
        "dense_sweep": {
            "point_chunk": dc._P,
            "seg_block": dc._SBLK,
            "sub_slice": dc._SUB,
            "nsub_per_block": nsub,
            "chunk_sub_bboxes": dc._NSUB,
            "narrow_grid_cap": dc._NJ_CAP,
            # round 17: the cap is plan-selectable from this fixed
            # ladder only (config.SWEEP_NJ_CAP_RUNGS) — the compiled-
            # shape universe stays finite; exact at any rung
            "nj_cap_rungs": cap_rungs,
            "split_len_m": dc.SPLIT_LEN,
            "pack_rows": dc.SP_NCOMP,
            "feat_rows": dc.SF_NCOMP,
            "smem_prefetch_budget_bytes": dc.SMEM_PREFETCH_BUDGET,
            "smem_lane_pad": dc.SMEM_LANE_PAD,
            "smem_bound_bytes": SMEM_BOUND_BYTES,
        },
        "histogram_scatter": {
            "cap_rows": SpeedHistogram._CAP,
        },
        # round 20: the backfill aggregates' shared flat scatter — ONE
        # update-batch shape and a fixed set of grids per tile, so an
        # open-loop run adds exactly two scatter executables to the
        # universe (ops/aggregate.py; grids in backfill/aggregate.py)
        "backfill_scatter": {
            "cap_rows": agg._CAP,
            "grids": ["speed_tod", "turns"],
            "tod_bins_default": bagg.DEFAULT_TOD_BINS,
            "turn_slots_default": bagg.DEFAULT_TURN_SLOTS,
            # r21: the mesh arm keeps per-device partial grids and
            # scatters cap_rows indices PER SHARD ([ndev, cap] blocks
            # through ONE jit(shard_map) program per mesh — still two
            # scatter executables per tile per process, mesh or not);
            # partials merge bucket-wise at the one harvest readback
            "mesh": {
                "cap_rows_per_shard": agg._CAP,
                "executables_per_grid": 1,
                "merge": "host i32 bucket sum at snapshot()",
            },
        },
        # round 17: the per-metro self-tuning plan space — the cap-rung
        # × kernel-arm matrix the tuner may pick from, fully enumerated
        # so per-metro tuning can never grow the executable population
        # past this block (matcher/autotune.py)
        "autotune": {
            "plan_version": autotune.PLAN_VERSION,
            "arms": arms,
            "nj_cap_rungs": cap_rungs,
            "plans_bound": len(arms) * len(cap_rungs),
            "cal_dispatches": autotune.CAL_DISPATCHES,
            "cal_batch_shape": list(autotune.CAL_BATCH_SHAPE),
            # two-phase calibration: every arm at the default rung +
            # the winner across the remaining rungs — the per-tile
            # compile cost of measuring, bounded
            "calibration_executables_per_tile_bound":
                len(arms) + len(cap_rungs) - 1,
            "staged_member": "tuned_plan",
            "nj_cap_default": MatcherParams().sweep_nj_cap,
        },
        "staged_tables": {
            "layout_version": tileset.STAGED_LAYOUT_VERSION,
            "dense_layout_keys": list(tileset._DENSE_LAYOUT_KEYS),
            "hbm_budget_bytes": capacity.DEFAULT_HBM_BUDGET,
        },
        "envelope": dict(ENVELOPE),
    }


# --- BEGIN GOLDEN MANIFEST (generated; do not hand-edit — run
#     `python -m reporter_tpu.analysis --update-manifest`) ---
GOLDEN: "dict[str, Any]" = \
{'autotune': {'arms': ['subcull',
                       'subcull+bf16',
                       'block',
                       'mxu',
                       'mxu+bf16'],
              'cal_batch_shape': [128, 64],
              'cal_dispatches': 4,
              'calibration_executables_per_tile_bound': 7,
              'nj_cap_default': 128,
              'nj_cap_rungs': [64, 128, 256],
              'plan_version': 1,
              'plans_bound': 15,
              'staged_member': 'tuned_plan'},
 'backfill_scatter': {'cap_rows': 4096,
                      'grids': ['speed_tod', 'turns'],
                      'mesh': {'cap_rows_per_shard': 4096,
                               'executables_per_grid': 1,
                               'merge': 'host i32 bucket sum at '
                                        'snapshot()'},
                      'tod_bins_default': 24,
                      'turn_slots_default': 8},
 'dense_sweep': {'chunk_sub_bboxes': 8,
                 'feat_rows': 8,
                 'narrow_grid_cap': 128,
                 'nj_cap_rungs': [64, 128, 256],
                 'nsub_per_block': 4,
                 'pack_rows': 8,
                 'point_chunk': 256,
                 'seg_block': 512,
                 'smem_bound_bytes': 1048576,
                 'smem_lane_pad': 128,
                 'smem_prefetch_budget_bytes': 524288,
                 'split_len_m': 256.0,
                 'sub_slice': 128},
 'envelope': {'directed_edges': 1000000,
              'line_segments': 2000000,
              'nodes': 500000,
              'reach_max': 128},
 'histogram_scatter': {'cap_rows': 4096},
 'manifest_version': 1,
 'matcher': {'acc_scale_variants': 2,
             'max_device_batch_default': 4096,
             'point_buckets': [16, 32, 64, 128, 256, 512, 1024],
             'wire_entries': ['f32', 'q16', 'q8'],
             'wire_executables_per_tile_bound': 546},
 'scheduler': {'max_batch_traces_default': 256,
               'trace_count_rungs': [1,
                                     2,
                                     4,
                                     8,
                                     16,
                                     32,
                                     64,
                                     128,
                                     256,
                                     512,
                                     1024,
                                     2048,
                                     4096]},
 'staged_tables': {'dense_layout_keys': ['seg_pack',
                                         'seg_bbox',
                                         'seg_sub',
                                         'seg_feat'],
                   'hbm_budget_bytes': 12884901888,
                   'layout_version': 3},
 'wire_formats': {'compact_max_edges': 16384,
                  'infeed_dtypes': {'f32': 'float32',
                                    'q16': 'int16',
                                    'q8': 'int8'},
                  'layouts': {'compact': ['uint16', 2],
                              'full': ['uint16', 3],
                              'packed': ['uint32', 1]},
                  'offset_quantum_m': 0.25}}
# --- END GOLDEN MANIFEST ---


def diff(golden: "dict | Any", computed: "dict | Any",
         path: str = "") -> "list[str]":
    """Flat list of drift descriptions (empty = pinned). Dropped keys and
    changed values both count — the manifest is extend-don't-drop."""
    out: "list[str]" = []
    if isinstance(golden, dict) and isinstance(computed, dict):
        for k in sorted(set(golden) | set(computed)):
            p = f"{path}.{k}" if path else str(k)
            if k not in computed:
                out.append(f"{p}: dropped from the computed universe "
                           f"(golden: {golden[k]!r})")
            elif k not in golden:
                out.append(f"{p}: new in the computed universe "
                           f"({computed[k]!r}) — not in the golden "
                           "manifest")
            else:
                out.extend(diff(golden[k], computed[k], p))
        return out
    if golden != computed:
        out.append(f"{path}: golden {golden!r} != computed {computed!r}")
    return out


def check() -> "list[str]":
    """Manifest drift + static budget findings, one string each — the
    full gate: shape-universe drift, the SMEM bound, AND the HBM
    cross-check (on a freshly compiled tiny tileset; the compile is
    ~20 ms and byte-exactness on ANY tileset pins the formula)."""
    from reporter_tpu.analysis.device_contract import _tiny_tileset

    out = diff(GOLDEN, compute_manifest())
    out.extend(smem_findings())
    out.extend(hbm_findings(_tiny_tileset()))
    return out


# ---------------------------------------------------------------------------
# static SMEM bound

def _envelope_blocks() -> int:
    from reporter_tpu.ops import dense_candidates as dc

    s = ENVELOPE["line_segments"]
    spad = max(dc._SBLK, -(-s // dc._SBLK) * dc._SBLK)
    return spad // dc._SBLK


def smem_findings() -> "list[str]":
    """Assert every grouped scalar-prefetch launch's id list fits the
    SMEM budget at every id-list width reachable inside the envelope:
    EVERY narrow-grid ladder rung (round 17 — the tuner may select any
    of them per metro), the envelope metro's full block count, and the
    degenerate single-block tile."""
    from reporter_tpu.config import SWEEP_NJ_CAP_RUNGS
    from reporter_tpu.ops import dense_candidates as dc

    out: "list[str]" = []
    nblocks = _envelope_blocks()
    huge_chunks = -(-ENVELOPE["directed_edges"] // dc._P) * 4  # any cap
    cases = [(f"rung-{r}", min(nblocks, r)) for r in SWEEP_NJ_CAP_RUNGS]
    cases += [("default-cap", min(nblocks, dc._NJ_CAP)),
              ("full-envelope", nblocks),
              ("single-block", 1)]
    for label, nj in cases:
        bytes_ = dc.prefetch_smem_bytes(huge_chunks, nj)
        if bytes_ > SMEM_BOUND_BYTES:
            out.append(
                f"smem: {label} launch (nj={nj}) prefetches {bytes_} B "
                f"of SMEM ids > bound {SMEM_BOUND_BYTES} B — shrink the "
                "per-call chunk cap (ops.dense_candidates."
                "prefetch_group_cap)")
        if bytes_ > dc.SMEM_PREFETCH_BUDGET:
            out.append(
                f"smem: {label} launch (nj={nj}) exceeds the launcher's "
                f"own {dc.SMEM_PREFETCH_BUDGET} B self-cap ({bytes_} B) "
                "— prefetch_group_cap and prefetch_smem_bytes disagree")
    return out


# ---------------------------------------------------------------------------
# static HBM bound

def hbm_findings(ts) -> "list[str]":
    """Cross-check capacity.py's staged-byte shape math against the
    bytes ``host_tables`` ACTUALLY builds (a formula that drifts from
    the layout under-plans silently), then bound the envelope metro."""
    import numpy as np

    from reporter_tpu.ops import dense_candidates as dc
    from reporter_tpu.tiles import capacity

    out: "list[str]" = []
    shardable, fixed = capacity.dense_staged_bytes(ts)
    host = ts.host_tables("dense")
    actual_shardable = sum(int(host[k].nbytes) for k in
                           ("seg_pack", "seg_bbox", "seg_sub", "seg_feat"))
    actual_fixed = sum(int(host[k].nbytes) for k in
                       ("edge_len", "reach_row", "edge_osmlr",
                        "reach_to", "reach_dist"))
    if shardable != actual_shardable:
        out.append(
            f"hbm: capacity.dense_staged_bytes shardable formula "
            f"({shardable} B) != bytes host_tables stages "
            f"({actual_shardable} B) for {ts.name!r} — the shape math "
            "drifted from build_seg_pack's layout")
    if fixed != actual_fixed:
        out.append(
            f"hbm: capacity.dense_staged_bytes fixed formula ({fixed} B) "
            f"!= staged per-edge/reach bytes ({actual_fixed} B) for "
            f"{ts.name!r}")

    # envelope metro, analytically (mirrors dense_staged_bytes; the
    # cross-check above is what licenses the mirror)
    env = ENVELOPE
    seg_len = np.full(env["line_segments"], 50.0, np.float32)
    spad = dc.packed_columns(seg_len)
    nsub = dc._SBLK // dc._SUB if dc._SUB and dc._SBLK % dc._SUB == 0 else 1
    env_shardable = ((dc.SP_NCOMP + dc.SF_NCOMP) * spad
                     + (spad // dc._SBLK) * 4 * (1 + nsub)) * 4
    env_fixed = (env["directed_edges"] * (4 + 4 + 4)
                 + env["nodes"] * env["reach_max"] * (4 + 4))
    total = env_shardable + env_fixed
    if total > capacity.DEFAULT_HBM_BUDGET:
        out.append(
            f"hbm: envelope metro stages {total} B replicated > budget "
            f"{capacity.DEFAULT_HBM_BUDGET} B — grow the budget, shard, "
            "or shrink the envelope with a dated note")
    return out


# ---------------------------------------------------------------------------
# regen (the fixtures/regen.py workflow)

_BEGIN = ("# --- BEGIN GOLDEN MANIFEST (generated; do not hand-edit — run\n"
          "#     `python -m reporter_tpu.analysis --update-manifest`) ---")
_END = "# --- END GOLDEN MANIFEST ---"


def update_golden(path: "str | None" = None) -> str:
    """Rewrite this module's GOLDEN block from the live constants."""
    import pprint

    if path is None:
        path = __file__.rstrip("c")      # .pyc safety, pragma-free
    with open(path) as f:
        src = f.read()
    lo = src.index(_BEGIN)
    hi = src.index(_END) + len(_END)
    body = pprint.pformat(compute_manifest(), width=72, sort_dicts=True)
    block = (f"{_BEGIN}\nGOLDEN: \"dict[str, Any]\" = \\\n{body}\n{_END}")
    with open(path, "w") as f:
        f.write(src[:lo] + block + src[hi:])
    return path
