"""Repo-invariant AST lints — the contracts CLAUDE.md writes down but
nothing enforced until round 14.

Each rule is a pure function over one parsed module (or, for the
cross-file rules, over the whole target set) returning ``Finding``s.
A finding is WAIVED by a comment on its line or the line above:

    # lint: allow[rule-id] 2026-08-04 why this one site is legal

The CI gate (tests/test_static_analysis.py) requires zero UNWAIVED
findings over ``reporter_tpu/`` + ``bench.py``, and requires every
waiver to carry a non-empty justification — an empty ``allow[...]`` is
itself a finding. Rules:

  env-flag        RTPU_*/REPORTER_* boolean env values must be parsed by
                  ``tracing.env_flag`` (strict=True where a typo must
                  raise) — ad-hoc ``== "1"`` / ``.lower() in (...)`` /
                  bare-truthiness parses are the r10 drift bug class
                  (config.py and tracing accepted different sets;
                  REPORTER_TPU_NO_NATIVE=0 DISABLED native).
  env-table       every RTPU_*/REPORTER_* env read must have a row in
                  README's consolidated env table, and every table row
                  must correspond to a real read (drift both ways).
  metric-inventory  every metric name LITERALLY registered through a
                  utils.metrics registry (count/gauge/observe/stage
                  first-arg string, incl. through ``labeled(...)``)
                  must appear in README's marker-delimited metric
                  inventory, and every inventory token must name a real
                  registration (drift both ways — the env-table pattern
                  applied to the round-19 aggregation plane, where an
                  undocumented series silently changes the fleet
                  exposition's shape). Dynamically-composed names
                  (``"quality_" + rate``) are out of scope by
                  construction and documented in prose, not the block.
  lock-blocking   no known-blocking call (sleep, urlopen, fsync,
                  subprocess, device_put, block_until_ready, foreign
                  ``.wait``) lexically inside a ``with <lock>:`` body.
                  The runtime twin (utils/locks.py) catches the
                  non-lexical cases; this catches them at review time.
  wire-fork       ``wire_from_*`` bodies are defined ONLY in
                  ops/match.py (don't fork the wire programs), and
                  ``shard_map`` targets are never jit-wrapped inside the
                  shard_map call (jit goes outside).
  staged-layout   a module that references ANY dense staged-table member
                  (tiles/tileset._DENSE_LAYOUT_KEYS) must reference ALL
                  of them — "seg_feat stages everywhere seg_sub rides"
                  (round 13) as a checked invariant, auto-extending when
                  the layout version grows.
  jit-shape-len   next-power-of-2 shape derivations (``1 << x.bit_length()``
                  / ``2 ** ceil(log2 ...)``) without a visible cap/rung
                  clamp — the r12 per-shape-trace lesson (each new cap
                  dropped ~150 ms of jit trace into a measured wave).
  dead-import     unused imports (pyflakes-equivalent; none installed in
                  this image, so the check is implemented here).
  dead-private    private (single-underscore) module-LEVEL functions,
                  classes and constants referenced nowhere — liveness is
                  word occurrence across lint targets + tests/ +
                  examples/ + the driver hooks, outside the definition's
                  own lines (round 16; same never-flag-a-live-symbol
                  stance as dead-import).
  bench-coverage  every numeric leaf in the committed BENCH_DETAIL*.json
                  captures must be suffix-classifiable by
                  analysis/bench_delta.py or explicitly neutral, and
                  every neutral entry must still name a committed leaf —
                  drift both ways, like env-table (round 16).

The device-side twin of these gates — jaxpr audit, compile-shape
manifest, static SMEM/HBM budgets — is analysis/device_contract.py
(``python -m reporter_tpu.analysis --device``).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

__all__ = ["Finding", "run_lint", "lint_source", "iter_targets",
           "RULES", "REPO_ROOT"]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)

_ENV_NAME = re.compile(r"^(RTPU|REPORTER)_[A-Z0-9_]+$")
_README_TOKEN = re.compile(r"`((?:RTPU|REPORTER)_[A-Z0-9_]+)`")
_WAIVE = re.compile(r"lint:\s*allow\[([a-z0-9-]+)\]\s*(.*)")

# boolean-ish literal sets an ad-hoc env truthiness parse compares with
_TRUTHY_TOKENS = {"1", "0", "true", "false", "on", "off", "yes", "no", ""}

# call names that block (must never run while a lock is held); dotted
# suffixes are matched against the call's rendered qualname
_BLOCKING_SUFFIXES = (
    "time.sleep", "os.fsync", "subprocess.run", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen", "request.urlopen",
    "socket.create_connection",
    "jax.device_put", "jax.block_until_ready",
)
_BLOCKING_ATTRS = {"sleep", "urlopen", "fsync", "device_put",
                   "block_until_ready", "create_connection"}

_LOCKISH = re.compile(r"lock|_cv\b|\bcv\b|cond", re.IGNORECASE)
# with-targets that merely LOOK lockish but aren't locks
_LOCKISH_NOT = re.compile(r"stage|span|tracer|use\(|open\(")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative
    line: int
    message: str
    waived: bool = False
    justification: str = ""

    def __str__(self) -> str:
        tag = " (waived: %s)" % self.justification if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class _Module:
    path: str                     # repo-relative
    source: str
    tree: ast.AST
    lines: "list[str]" = field(default_factory=list)

    def seg(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""


def _apply_waivers(mod: _Module, findings: "list[Finding]") -> None:
    """Waiver = ``lint: allow[rule]`` on the finding line, or anywhere in
    the contiguous comment block directly above it (multi-line dated
    justifications are the norm)."""
    for f in findings:
        candidates = []
        if 1 <= f.line <= len(mod.lines):
            candidates.append(mod.lines[f.line - 1])
        ln = f.line - 1
        while ln >= 1 and mod.lines[ln - 1].lstrip().startswith("#"):
            candidates.append(mod.lines[ln - 1])
            ln -= 1
        for text in candidates:
            m = _WAIVE.search(text)
            if m and m.group(1) == f.rule:
                f.waived = True
                f.justification = m.group(2).strip()
                if not f.justification:
                    # an unexplained waiver is itself a finding
                    f.waived = False
                    f.message += (" (waiver present but carries no "
                                  "justification)")
                break
    return None


# ---------------------------------------------------------------------------
# env helpers

def _env_read_name(node: ast.AST) -> "str | None":
    """Env var name when ``node`` is an env read — ``X.get("NAME"[, d])``
    or ``X["NAME"]`` where X smells like an environ mapping — possibly
    wrapped in chained str methods (``.strip().lower()``)."""
    # unwrap chained method calls on the read result
    while isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in ("strip", "lower", "upper", "casefold"):
            node = node.func.value
            continue
        break
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        key = node.args[0]
        holder = node.func.value
    elif isinstance(node, ast.Subscript):
        key = node.slice
        holder = node.value
    else:
        return None
    if not (isinstance(key, ast.Constant) and isinstance(key.value, str)
            and _ENV_NAME.match(key.value)):
        return None
    h = ast.unparse(holder)
    if "environ" in h or h in ("e", "env", "_e"):
        return key.value
    return None


def _env_reads(mod: _Module) -> "list[tuple[str, int]]":
    """(name, line) for every env read + env-name constant declaration
    (``_ENV_VAR = "RTPU_FAULTS"`` counts: the read goes through the
    constant)."""
    out = []
    for node in ast.walk(mod.tree):
        n = _env_read_name(node)
        if n is not None:
            out.append((n, node.lineno))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str) \
                and _ENV_NAME.match(node.left.value):
            out.append((node.left.value, node.lineno))
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Constant) \
                and isinstance(node.value.value, str) \
                and _ENV_NAME.match(node.value.value):
            out.append((node.value.value, node.lineno))
    return out


# ---------------------------------------------------------------------------
# rule: env-flag

def _rule_env_flag(mod: _Module) -> "list[Finding]":
    out: "list[Finding]" = []

    def flag(node, name, how):
        out.append(Finding(
            "env-flag", mod.path, node.lineno,
            f"{name} parsed by {how} — boolean env values go through "
            "tracing.env_flag (strict=True where a typo must raise), "
            "the ONE truthiness parser"))

    # env names also read in a clearly NON-boolean way in this module
    # (int()/float() coercion, plain subscript value use): a bare
    # truthiness test on those is a presence gate ("is it set"), not a
    # boolean parse — multihost's `env.get("…_NUM_PROCESSES")` guard
    # before `int(env["…"])` must not be flagged.
    bare_atoms: "set[int]" = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            for t in _test_atoms(node.test):
                bare_atoms.add(id(t))
    value_read: "set[str]" = set()
    for node in ast.walk(mod.tree):
        n = _env_read_name(node)
        if n is not None and id(node) not in bare_atoms:
            value_read.add(n)

    for node in ast.walk(mod.tree):
        # (a): comparison of a (possibly str-method-chained) env read
        # with truthy literal tokens
        if isinstance(node, ast.Compare):
            name = _env_read_name(node.left)
            if name is None:
                continue
            for comp in node.comparators:
                toks = _literal_strings(comp)
                if toks is not None and toks <= _TRUTHY_TOKENS:
                    flag(node, name, "an ad-hoc literal comparison")
                    break
        # (c): env read used directly as a boolean test, with no other
        # value-read of the same name in the module (presence gates pass)
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            for t in _test_atoms(node.test):
                name = _env_read_name(t)
                if name is not None and name not in value_read:
                    flag(t, name, "bare string truthiness")
    # (b) taint pass: x = <env read>[.strip().lower()]; if x in ("1", …)
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted: "dict[str, str]" = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                n = _env_read_name(node.value)
                if n is not None:
                    tainted[node.targets[0].id] = n
            elif isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id in tainted:
                for comp in node.comparators:
                    toks = _literal_strings(comp)
                    if toks is not None and toks <= _TRUTHY_TOKENS:
                        flag(node, tainted[node.left.id],
                             "an ad-hoc literal comparison")
                        break
    return out


def _literal_strings(node: ast.AST) -> "set[str] | None":
    """The set of string constants when ``node`` is a string literal or a
    tuple/list/set of them; None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            vals.add(el.value)
        return vals
    return None


def _test_atoms(test: ast.AST):
    """The atomic truthiness operands of a test expression (BoolOp and
    ``not`` unwrapped)."""
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            yield from _test_atoms(v)
    elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        yield from _test_atoms(test.operand)
    else:
        yield test


# ---------------------------------------------------------------------------
# rule: lock-blocking

def _rule_lock_blocking(mod: _Module) -> "list[Finding]":
    out: "list[Finding]" = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lockish = []
        for item in node.items:
            txt = mod.seg(item.context_expr)
            if _LOCKISH.search(txt) and not _LOCKISH_NOT.search(txt):
                lockish.append(txt)
        if not lockish:
            continue
        for body_stmt in node.body:
            for call in ast.walk(body_stmt):
                if not isinstance(call, ast.Call):
                    continue
                qn = ast.unparse(call.func) if not isinstance(
                    call.func, ast.Lambda) else ""
                blocked = (qn.endswith(_BLOCKING_SUFFIXES)
                           or qn.split(".")[-1] in _BLOCKING_ATTRS)
                if not blocked \
                        and (qn.endswith(".wait")
                             or qn.endswith(".wait_for")) \
                        and not any(qn[:qn.rfind(".")] == lk
                                    for lk in lockish):
                    # foreign condvar/event wait (either spelling): the
                    # with-target's own wait (``with self._cv:
                    # self._cv.wait()``) is the condvar idiom and exempt
                    blocked = True
                if blocked:
                    out.append(Finding(
                        "lock-blocking", mod.path, call.lineno,
                        f"blocking call {qn}() inside `with "
                        f"{lockish[0]}:` — move it outside the lock or "
                        "waive with a dated justification"))
    return out


# ---------------------------------------------------------------------------
# rule: wire-fork

def _rule_wire_fork(mod: _Module) -> "list[Finding]":
    out: "list[Finding]" = []
    is_match_py = mod.path.replace(os.sep, "/").endswith(
        "reporter_tpu/ops/match.py")
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("wire_from_") and not is_match_py:
            out.append(Finding(
                "wire-fork", mod.path, node.lineno,
                f"wire body {node.name}() defined outside ops/match.py — "
                "the mesh product path shard_maps the ONE set of "
                "undecorated wire programs; don't fork them"))
        elif isinstance(node, ast.Call):
            qn = ast.unparse(node.func) if not isinstance(node.func,
                                                          ast.Lambda) else ""
            if qn.split(".")[-1] == "shard_map" and node.args:
                tgt = node.args[0]
                if isinstance(tgt, ast.Call):
                    tq = ast.unparse(tgt.func)
                    if tq.split(".")[-1] == "jit":
                        out.append(Finding(
                            "wire-fork", mod.path, node.lineno,
                            "jit-wrapped function passed to shard_map — "
                            "jit goes OUTSIDE shard_map "
                            "(jax.jit(shard_map(wire_from_*)))"))
    return out


# ---------------------------------------------------------------------------
# rule: staged-layout

def _dense_layout_keys() -> "tuple[str, ...]":
    from reporter_tpu.tiles.tileset import _DENSE_LAYOUT_KEYS

    return _DENSE_LAYOUT_KEYS


def _rule_staged_layout(mod: _Module) -> "list[Finding]":
    keys = set(_dense_layout_keys())
    seen: "dict[str, int]" = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value in keys and node.value not in seen:
            seen[node.value] = node.lineno
    if not seen or set(seen) == keys:
        return []
    missing = sorted(keys - set(seen))
    line = min(seen.values())
    return [Finding(
        "staged-layout", mod.path, line,
        f"references staged dense members {sorted(seen)} but not "
        f"{missing} — every member of tiles/tileset._DENSE_LAYOUT_KEYS "
        "stages together (seg_feat rides everywhere seg_sub rides, "
        "round 13); handle the missing members or bump the layout "
        "contract")]


# ---------------------------------------------------------------------------
# rule: jit-shape-len

def _rule_jit_shape_len(mod: _Module) -> "list[Finding]":
    out: "list[Finding]" = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.BinOp):
            continue
        src = mod.seg(node)
        pow2 = (isinstance(node.op, ast.LShift)
                and isinstance(node.left, ast.Constant)
                and node.left.value == 1 and "bit_length" in src) or \
               (isinstance(node.op, ast.Pow)
                and isinstance(node.left, ast.Constant)
                and node.left.value == 2 and "log2" in src)
        if not pow2:
            continue
        # a visible clamp (min(..., CAP) / a rung table lookup) on the
        # same source line absolves it: the executable population stays
        # a small fixed set instead of growing with the data. The LINE,
        # not the BinOp segment — the clamp wraps the pow2 expression.
        parent = node
        line_src = (mod.lines[node.lineno - 1]
                    if 1 <= node.lineno <= len(mod.lines) else src)
        if "min(" in line_src or re.search(r"\b_?[A-Z][A-Z0-9_]*CAP\b",
                                           line_src):
            continue
        out.append(Finding(
            "jit-shape-len", mod.path, parent.lineno,
            "next-pow2 shape derivation without a visible cap — a "
            "jit-fed shape that grows with the data re-traces per new "
            "size (the r12 SpeedHistogram lesson: ~150 ms of trace cost "
            "landing in whichever wave first hits a new cap); clamp to "
            "a fixed rung set or waive with the reason the population "
            "is bounded"))
    return out


# ---------------------------------------------------------------------------
# rule: dead-import

def _rule_dead_import(mod: _Module) -> "list[Finding]":
    out: "list[Finding]" = []
    imports: "list[tuple[str, int, str]]" = []   # (bound name, line, shown)
    import_lines = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                imports.append((bound, node.lineno, a.name))
                import_lines.update(range(node.lineno,
                                          (node.end_lineno or node.lineno)
                                          + 1))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                imports.append((bound, node.lineno, a.name))
                # the WHOLE statement (parenthesized multi-line
                # from-imports are the dominant style here): a name on a
                # continuation line must not count as its own use
                import_lines.update(range(node.lineno,
                                          (node.end_lineno or node.lineno)
                                          + 1))
    if not imports:
        return out
    # usage = word occurrence anywhere outside the import statement's own
    # line(s). String annotations ("FaultPlan | None") and __all__ entries
    # count as uses by construction — deliberately conservative: this
    # rule must never flag a live import.
    body = "\n".join(ln for i, ln in enumerate(mod.lines, 1)
                     if i not in import_lines)
    for bound, line, shown in imports:
        if not re.search(rf"\b{re.escape(bound)}\b", body):
            out.append(Finding(
                "dead-import", mod.path, line,
                f"import {shown!r} (bound as {bound!r}) is never used"))
    return out


# ---------------------------------------------------------------------------
# cross-file rule: dead-private (the dead-import rule's sibling, round 16)

_IDENT = re.compile(r"\b[A-Za-z_][A-Za-z0-9_]*\b")


def _private_defs(mod: _Module):
    """(name, first line incl. decorators, end line) for every private
    (single-underscore, non-dunder) module-LEVEL function/class/constant
    definition. Top-level statements only — nested and conditional
    definitions are out of scope on purpose."""
    body = getattr(mod.tree, "body", ())
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            name = node.name
            lo = min([node.lineno]
                     + [d.lineno for d in node.decorator_list])
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            lo = node.lineno
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            name = node.target.id
            lo = node.lineno
        else:
            continue
        if not name.startswith("_") or name.startswith("__"):
            continue
        yield name, lo, (node.end_lineno or node.lineno)


def _token_counts(source: str) -> "dict[str, int]":
    counts: "dict[str, int]" = {}
    for tok in _IDENT.findall(source):
        counts[tok] = counts.get(tok, 0) + 1
    return counts


def _usage_sources(root: str) -> "list[str]":
    """Sources consulted for liveness BEYOND the lint targets: tests,
    examples, and the driver hooks legitimately reach into private
    names (tests import _DENSE_LAYOUT_KEYS; capacity imports _SBLK), so
    the usage scan must see them or the rule would flag live code."""
    out = []
    for rel in ("tests", "examples"):
        d = os.path.join(root, rel)
        if not os.path.isdir(d):
            continue
        for dirpath, dirnames, filenames in os.walk(d):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for extra in ("__graft_entry__.py",):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            out.append(p)
    return out


def _rule_dead_private(mods: "list[_Module]",
                       extra_sources: "list[str]") -> "list[Finding]":
    """A private module-level function/class/constant no source anywhere
    references is dead weight. Liveness = WORD OCCURRENCE of the name,
    in any lint target / test / example / driver hook, outside the
    definition's own line range — the dead-import discipline: strings,
    comments, and docstrings count as uses, so the rule can never flag
    a live symbol (getattr-by-string included); it only catches the
    truly unreferenced."""
    total = _token_counts("\n".join(m.source for m in mods))
    for path in extra_sources:
        try:
            with open(path) as f:
                extra = f.read()
        except OSError:
            continue
        for tok, n in _token_counts(extra).items():
            total[tok] = total.get(tok, 0) + n
    out: "list[Finding]" = []
    for mod in mods:
        for name, lo, hi in _private_defs(mod):
            own = "\n".join(mod.lines[lo - 1:hi])
            own_n = _token_counts(own).get(name, 0)
            if total.get(name, 0) <= own_n:
                out.append(Finding(
                    "dead-private", mod.path, lo,
                    f"private module-level {name!r} is never referenced "
                    "outside its own definition (lint targets + tests + "
                    "examples scanned) — delete it, or waive with why "
                    "it must stay"))
    return out


# ---------------------------------------------------------------------------
# cross-file rule: metric-inventory (round 19 — the env-table pattern
# applied to the metric namespace the aggregation plane merges)

_INVENTORY_BEGIN = "<!-- metric-inventory:begin -->"
_INVENTORY_END = "<!-- metric-inventory:end -->"
_METRIC_TOKEN = re.compile(r"`([a-z][a-z0-9_]*)`")
# names the registry itself derives/registers (not literal call sites);
# documented rows for these are legal without a registration
_REGISTRY_INTRINSIC = {"uptime_seconds", "probes_per_sec_busy"}
_METRIC_RECEIVER = re.compile(r"(^(m|reg|registry)$|metrics$|registry$)")


def _metric_registrations(mod: _Module) -> "dict[str, tuple[str, int]]":
    """name → (path, line) for every metric name LITERALLY registered in
    this module: the first string argument of a
    ``<registry>.count/gauge/observe/stage(...)`` call (receiver must
    smell like a metrics registry) or of any ``labeled(...)`` call.
    ``stage`` registers ``<name>_seconds`` (StageTimer's derived
    series). utils/metrics.py itself is excluded — its docstring
    examples and generic machinery are not registrations."""
    out: "dict[str, tuple[str, int]]" = {}
    if mod.path.replace(os.sep, "/").endswith(
            "reporter_tpu/utils/metrics.py"):
        return out

    def is_labeled(f: "ast.AST") -> bool:
        # both spellings: bare `labeled(...)` and the qualified
        # `metrics.labeled(...)` CLAUDE.md's own convention note uses
        return ((isinstance(f, ast.Name) and f.id == "labeled")
                or (isinstance(f, ast.Attribute) and f.attr == "labeled"))

    def lit(node: "ast.AST") -> "str | None":
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        # labeled("name", ...) wraps the literal: unwrap one level
        if isinstance(node, ast.Call) and is_labeled(node.func) \
                and node.args:
            return lit(node.args[0])
        return None

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if is_labeled(f):
            name = lit(node.args[0])
            if name:
                out.setdefault(name, (mod.path, node.lineno))
        elif isinstance(f, ast.Attribute) \
                and f.attr in ("count", "gauge", "observe", "stage") \
                and _METRIC_RECEIVER.search(ast.unparse(f.value)):
            name = lit(node.args[0])
            if name:
                if f.attr == "stage":
                    name += "_seconds"
                out.setdefault(name, (mod.path, node.lineno))
    return out


def _inventory_tokens(readme_lines: "list[str]",
                      ) -> "tuple[dict[str, int], bool]":
    """(token → first line) inside the marker-delimited inventory block,
    plus whether the markers were found at all (absent markers are a
    finding — the contract must not pass vacuously)."""
    documented: "dict[str, int]" = {}
    inside = found = False
    for i, ln in enumerate(readme_lines, 1):
        if _INVENTORY_BEGIN in ln:
            inside = found = True
            continue
        if _INVENTORY_END in ln:
            inside = False
            continue
        if inside:
            for tok in _METRIC_TOKEN.findall(ln):
                documented.setdefault(tok, i)
    return documented, found


def _rule_metric_inventory(mods: "list[_Module]",
                           readme_path: str) -> "list[Finding]":
    out: "list[Finding]" = []
    registered: "dict[str, tuple[str, int]]" = {}
    for mod in mods:
        for name, where in _metric_registrations(mod).items():
            registered.setdefault(name, where)
    try:
        with open(readme_path) as f:
            readme = f.readlines()
    except OSError:
        return [Finding("metric-inventory", "README.md", 1,
                        "README.md not found — the metric inventory is "
                        "the documentation contract")]
    documented, found = _inventory_tokens(readme)
    if not found:
        return [Finding(
            "metric-inventory", "README.md", 1,
            f"no {_INVENTORY_BEGIN} … {_INVENTORY_END} block in README "
            "— the metric inventory contract has nothing to check "
            "against (the gate must not pass vacuously)")]
    for name, (path, line) in sorted(registered.items()):
        if name not in documented:
            out.append(Finding(
                "metric-inventory", path, line,
                f"metric {name!r} is registered here but has no row in "
                "README's metric inventory block — an undocumented "
                "series changes the fleet exposition's shape silently"))
    for name, line in sorted(documented.items()):
        if name not in registered and name not in _REGISTRY_INTRINSIC:
            out.append(Finding(
                "metric-inventory", "README.md", line,
                f"README metric inventory documents {name!r} but "
                "nothing in the lint targets registers it — dead row "
                "(or the registration stopped being a literal)"))
    return out


# ---------------------------------------------------------------------------
# cross-file rule: env-table

def _rule_env_table(mods: "list[_Module]",
                    readme_path: str) -> "list[Finding]":
    out: "list[Finding]" = []
    reads: "dict[str, tuple[str, int]]" = {}
    for mod in mods:
        for name, line in _env_reads(mod):
            reads.setdefault(name, (mod.path, line))
    documented: "dict[str, int]" = {}
    try:
        with open(readme_path) as f:
            readme = f.readlines()
    except OSError:
        return [Finding("env-table", "README.md", 1,
                        "README.md not found — the consolidated env "
                        "table is the documentation contract")]
    for i, ln in enumerate(readme, 1):
        if not ln.lstrip().startswith("|"):
            continue
        for tok in _README_TOKEN.findall(ln):
            documented.setdefault(tok, i)
    for name, (path, line) in sorted(reads.items()):
        if name not in documented:
            out.append(Finding(
                "env-table", path, line,
                f"env var {name} is read here but has no row in "
                "README's consolidated env table"))
    for name, line in sorted(documented.items()):
        if name not in reads:
            out.append(Finding(
                "env-table", "README.md", line,
                f"README env table documents {name} but nothing in the "
                "lint targets reads it — dead row (or the read moved "
                "outside reporter_tpu/ + bench.py)"))
    return out


# ---------------------------------------------------------------------------
# runner

RULES = {
    "env-flag": _rule_env_flag,
    "lock-blocking": _rule_lock_blocking,
    "wire-fork": _rule_wire_fork,
    "staged-layout": _rule_staged_layout,
    "jit-shape-len": _rule_jit_shape_len,
    "dead-import": _rule_dead_import,
}


def iter_targets(root: str = REPO_ROOT) -> "list[str]":
    """Lint scope: the package + the driver-facing scripts at repo root
    (bench.py reads REPORTER_BENCH_*; the env table documents them)."""
    out = []
    pkg = os.path.join(root, "reporter_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    for extra in ("bench.py",):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            out.append(p)
    return out


def _load(path: str, root: str) -> "_Module | None":
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    rel = os.path.relpath(path, root)
    return _Module(rel, source, tree, source.splitlines())


def lint_source(source: str, path: str = "<synthetic>",
                rules: "list[str] | None" = None) -> "list[Finding]":
    """Lint one source string (the seeded-violation tests' entry)."""
    mod = _Module(path, source, ast.parse(source), source.splitlines())
    out: "list[Finding]" = []
    for rid, fn in RULES.items():
        if rules is not None and rid not in rules:
            continue
        out.extend(fn(mod))
    if rules is None or "dead-private" in rules:
        out.extend(_rule_dead_private([mod], []))
    out = _dedupe(out)
    _apply_waivers(mod, out)
    return out


def _dedupe(findings: "list[Finding]") -> "list[Finding]":
    seen = set()
    out = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def run_lint(root: str = REPO_ROOT,
             rules: "list[str] | None" = None) -> "list[Finding]":
    mods = [m for m in (_load(p, root) for p in iter_targets(root))
            if m is not None]
    out: "list[Finding]" = []
    for mod in mods:
        per_mod: "list[Finding]" = []
        for rid, fn in RULES.items():
            if rules is not None and rid not in rules:
                continue
            per_mod.extend(fn(mod))
        per_mod = _dedupe(per_mod)
        _apply_waivers(mod, per_mod)
        out.extend(per_mod)
    by_path = {m.path: m for m in mods}
    if rules is None or "dead-private" in rules:
        dead = _rule_dead_private(mods, _usage_sources(root))
        for f in dead:
            m = by_path.get(f.path)
            if m is not None:
                _apply_waivers(m, [f])
        out.extend(dead)
    if rules is None or "env-table" in rules:
        table = _rule_env_table(mods, os.path.join(root, "README.md"))
        for f in table:
            m = by_path.get(f.path)
            if m is not None:
                _apply_waivers(m, [f])
        out.extend(table)
    if rules is None or "metric-inventory" in rules:
        inv = _rule_metric_inventory(mods,
                                     os.path.join(root, "README.md"))
        for f in inv:
            m = by_path.get(f.path)
            if m is not None:
                _apply_waivers(m, [f])
        out.extend(inv)
    if rules is None or "bench-coverage" in rules:
        from reporter_tpu.analysis.bench_delta import coverage_findings

        cov = coverage_findings(root)
        for f in cov:
            m = by_path.get(f.path)
            if m is not None:
                _apply_waivers(m, [f])
        out.extend(cov)
    return out


def main(argv: "list[str] | None" = None) -> int:
    findings = run_lint()
    unwaived = [f for f in findings if not f.waived]
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s), {len(unwaived)} unwaived")
    return 1 if unwaived else 0


if __name__ == "__main__":          # pragma: no cover - CLI convenience
    raise SystemExit(main())
