"""Static analysis + concurrency-contract enforcement (round 14).

Two detectors, both CI-gated by tests/test_static_analysis.py:

  lint_rules.py          AST lints encoding the repo's written-but-
                         unenforced contracts (CLAUDE.md): env-var
                         truthiness through the ONE parser
                         (tracing.env_flag), env reads documented in
                         README's consolidated table, no blocking calls
                         under locks, no forked wire bodies, staged-table
                         member-set completeness, no uncapped
                         pow2-of-len jit shapes, no dead imports.
                         ``python -m reporter_tpu.analysis`` runs it.
  concurrency_contract   the committed lockdep golden state: the
                         allowed lock-order edge set and the
                         blocking-call-under-lock allowlist, both
                         extend-with-dated-justification only. The
                         runtime half lives in utils/locks.py and is
                         armed by tests/conftest.py.
"""

from reporter_tpu.analysis.lint_rules import Finding, run_lint

__all__ = ["Finding", "run_lint"]
