"""Continuous in-flight batching for the serving face.

The round-5/6 serving path was queue-and-combine: concurrent requests
enqueue, one leader drains everything under ``self._lock`` and holds the
lock through the full device dispatch — so the HTTP face never has more
than one device batch in flight and every request serializes behind the
leader's ~110 ms remote-link round-trip (BENCH_DETAIL
``detail.service_curve``: exactly 2 sequential device batches per
measured round at every client level). Round 6 fixed exactly this
serialization for the streaming face (pipelined flush); this module is
the same treatment for request/response traffic, in the continuous-
batching shape large-scale map-matching services use (arXiv:1910.05312):

  - requests enqueue into a BOUNDED admission queue (full ⇒ 503, a
    counted rejection — overload degrades explicitly, like the round-6
    broker bounds);
  - a scheduler thread closes batches by SIZE (``max_batch_traces``) or
    SLO DEADLINE (``batch_close_ms`` after the oldest admitted request —
    a lone request is never stuck waiting for peers);
  - closed batches are PADDED into a small fixed set of shape buckets
    (trace-count rungs × the matcher's max-point buckets) so
    ``match_many`` reuses compiled executables instead of recompiling
    per arrival pattern — padding rows are clones of real traces and the
    result is bit-identical because decode is independent of batch
    composition (tests/test_determinism.py pins this);
  - dispatch runs on a small executor so up to ``max_inflight_batches``
    device batches overlap the link RTT (submit wave N while wave N−1 is
    in flight — the serving twin of streaming's ``pipeline_depth``);
  - completions are routed back to per-request futures; a uuid already
    in an in-flight batch DEFERS later requests for that uuid (and
    everything queued behind them for the same uuid), so per-uuid cache
    merge/retain ordering is exactly the sequential path's.

Error isolation: a failed batched match is retried per submission, in
arrival order — one poisoned request fails alone, co-batched requests
are still served (validation errors never get this far; they are raised
request-scoped before admission).

The legacy queue-and-combine path stays selectable
(``ServiceConfig.batching = "combine"``) so the bench can A/B the two
schedulers in the same run under the same link mood.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Sequence

from reporter_tpu.utils import locks
from reporter_tpu.utils import tracing
from reporter_tpu.utils.readahead import ReadAheadWorker

if TYPE_CHECKING:                            # pragma: no cover
    from reporter_tpu.matcher.api import Trace
    from reporter_tpu.service.app import ReporterApp


class ServiceOverloaded(RuntimeError):
    """Admission queue full or service shutting down → HTTP 503."""


# Trace-count rungs: a closed batch's per-point-bucket group is padded up
# to the next rung so the jitted wire executable's [B, T] shape comes
# from a small fixed set. Powers of two keep the worst-case padding waste
# below 50% and the executable population logarithmic; groups beyond the
# last rung are already sliced to max_device_batch multiples upstream.
# The rung set is part of the pinned compiled-shape universe
# (analysis/compile_manifest.py): changing it requires regenerating the
# golden manifest (`python -m reporter_tpu.analysis --update-manifest`).
_TRACE_RUNGS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def _rung(n: int) -> int:
    for r in _TRACE_RUNGS:
        if n <= r:
            return r
    return n


class _ScheduledSubmission:
    """One report_many call: validated pairs + a completion future.
    (Distinct from app.py's legacy combine-path ``_Submission``: this one
    carries admission-time and deferral bookkeeping the combine leader
    has no use for.)"""

    __slots__ = ("pairs", "uuids", "done", "results", "error", "t_enqueue",
                 "was_deferred")

    def __init__(self, pairs, t_enqueue: float):
        self.pairs = pairs
        self.uuids = frozenset(u for u, _ in pairs)
        self.done = threading.Event()
        self.results: list[dict] = []
        self.error: "Exception | None" = None
        self.t_enqueue = t_enqueue
        self.was_deferred = False


class BatchScheduler:
    """SLO-aware request scheduler keeping the device pipeline full.

    Owns one scheduler thread (batch assembly) and a small DAEMON worker
    pool (``max_inflight_batches`` workers running the match+publish
    pipeline; each worker's link wait releases the GIL, so waves
    overlap). Daemon, not concurrent.futures: the stdlib executor's
    atexit hook joins its non-daemon workers unconditionally, so one
    dispatch wedged on a dead link (the tunnel CAN hang forever) would
    block process exit no matter what close() decided — daemon workers
    keep the bounded-drain guarantee real. jax backend only: the app's
    cache/publisher/jax matcher are thread-safe, but the reference_cpu
    backend's shared DijkstraCache is not (and padding buys a
    non-compiled backend nothing) — the app falls back to the combine
    path for it. Per-uuid ordering is enforced here by deferral;
    everything else runs concurrently.
    """

    def __init__(self, app: "ReporterApp", clock=time.monotonic):
        svc = app.config.service
        self.app = app
        self.metrics = app.matcher.metrics
        self.batch_close_s = float(svc.batch_close_ms) / 1e3
        self.max_batch = int(svc.max_batch_traces)
        self.max_inflight = int(svc.max_inflight_batches)
        self.limit = int(svc.admission_queue_limit)
        self._clock = clock
        self._cv = locks.named_condition("scheduler.cv")
        self._queue: "deque[_ScheduledSubmission]" = deque()
        self._queued_traces = 0
        self._dispatch_serial = 0      # batch id for trace spans (under _cv)
        self._inflight = 0
        self._inflight_uuids: set[str] = set()
        self._closed = False
        self._stats_lock = locks.named_lock("scheduler.stats")
        self.stats = {"batches": 0, "submissions": 0, "padded_traces": 0,
                      "deferred": 0, "rejected": 0, "isolated_retries": 0,
                      "max_inflight_seen": 0}
        self.inflight_hist: dict[int, int] = {}   # dispatches at depth k
        self.padding_by_bucket: dict[int, int] = {}
        # Prepare-ahead (r22): a closed batch's dispatch-free head
        # (cache merge, Trace build, padding, the matcher's prepared
        # seam — app._prefab_validated) runs on a read-ahead thread
        # while earlier batches occupy the device. Per-uuid deferral
        # makes it safe: a batch only closes with uuids disjoint from
        # every in-flight batch, so the prefab reads exactly the cache
        # tails an inline call would. Off (pipeline_prepare=False) =
        # the serial arm, workers compute the head inline.
        self._prefab = (ReadAheadWorker(name="sched-prepare")
                        if svc.pipeline_prepare else None)
        self._work: "_queue.Queue" = _queue.Queue()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"reporter-batch-{i}")
            for i in range(self.max_inflight)]
        for w in self._workers:
            w.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="reporter-scheduler")
        self._thread.start()

    def _worker_loop(self) -> None:
        while True:
            job = self._work.get()
            if job is None:
                return
            self._run_batch(*job)

    # ---- request side ----------------------------------------------------

    def submit(self, pairs: "list[tuple[str, list[dict]]]") -> list[dict]:
        """Admit validated pairs, block until the batch pipeline resolves
        them. Raises the request's own error; ServiceOverloaded when the
        admission queue is full or the scheduler is shut down."""
        with self._cv:
            if self._closed:
                raise ServiceOverloaded("service is shutting down")
            queued = self._queued_traces
            if queued + len(pairs) > self.limit and self._queue:
                # Always admit into an empty queue: a single oversized
                # report_many must not be unservable.
                with self._stats_lock:
                    self.stats["rejected"] += 1
                sub = None
            else:
                sub = _ScheduledSubmission(pairs, self._clock())
                self._queue.append(sub)
                self._queued_traces += len(pairs)
                self.metrics.gauge("sched_admission_depth",
                                   len(self._queue))
                self._cv.notify_all()
        if sub is None:
            # post-mortem OUTSIDE _cv: dumping the ring is disk I/O and
            # must not stall every concurrent submit() plus the dispatch
            # thread at exactly the overload peak (the other fault sites
            # all dump outside their locks too)
            tracing.post_mortem("shed", failing="admission",
                                queued_traces=queued, limit=self.limit)
            raise ServiceOverloaded(
                f"admission queue full ({queued} traces "
                f"queued, limit {self.limit})")
        while not sub.done.wait(timeout=5.0):
            with self._cv:
                closed = self._closed
            # During a graceful close the scheduler thread exits as soon
            # as the queue is flushed while OUR batch may still ride the
            # link on an executor worker — that is drain, not death: keep
            # waiting for the completion close() guarantees. Thread death
            # with the scheduler OPEN is a real bug -> fail loudly.
            if (not closed and not self._thread.is_alive()
                    and not sub.done.is_set()):
                raise RuntimeError("scheduler thread died")   # pragma: no cover
        if sub.error is not None:
            raise sub.error
        return sub.results

    # ---- scheduler thread ------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                batch = None
                while batch is None:
                    if self._closed and not self._queue:
                        return
                    batch, wait = self._try_close_locked()
                    if batch is None:
                        self._cv.wait(timeout=wait)
                uuids = frozenset().union(*(s.uuids for s in batch))
                self._inflight += 1
                self._inflight_uuids |= uuids
                depth = self._inflight
                self.metrics.gauge("sched_inflight_batches", depth)
                self.metrics.gauge("sched_admission_depth", len(self._queue))
                # hand off UNDER _cv: close() clears the queue and enqueues
                # the worker sentinels in one _cv section, so a dispatched
                # batch is always FIFO-ahead of every sentinel — a job can
                # never land behind them and starve its clients. The batch
                # serial rides in the job: a worker reading a shared
                # counter later would race other dispatches' increments
                # and mis-tag its trace spans.
                serial = self._dispatch_serial
                self._dispatch_serial += 1
                # prepare-ahead ticket UNDER _cv too: the worker may pop
                # the job immediately, so the ticket must exist before
                # the put. (scheduler.cv → readahead.tasks is a dated
                # contract edge; the submit only appends to a deque.)
                ticket = None
                if self._prefab is not None:
                    combined = [pair for s in batch for pair in s.pairs]
                    ticket = self._prefab.submit(
                        lambda c=combined: self.app._prefab_validated(c))
                self._work.put((batch, uuids, serial, ticket))
            now = self._clock()
            for s in batch:
                self.metrics.observe("sched_queue_age_seconds",
                                     now - s.t_enqueue)
            with self._stats_lock:
                # hist writes share _stats_lock with snapshot()'s copy —
                # a /health racing a dispatch must never see a mid-insert
                # dict
                self.inflight_hist[depth] = self.inflight_hist.get(depth,
                                                                   0) + 1
                self.stats["batches"] += 1
                self.stats["submissions"] += len(batch)
                self.stats["max_inflight_seen"] = max(
                    self.stats["max_inflight_seen"], depth)
            # keep the app's device-batch counters meaningful in either
            # batching mode (bench A/B and /health read the same keys)
            with self.app._stats_lock:
                self.app.stats["batches"] += 1
                self.app.stats["batched_submissions"] += len(batch)

    def _try_close_locked(self):
        """(batch, None) when a batch should dispatch now, else
        (None, seconds-to-wait | None). Runs under self._cv."""
        if self._inflight >= self.max_inflight:
            return None, None          # a completion will notify
        blocked = set(self._inflight_uuids)
        ready: list[_ScheduledSubmission] = []
        n_traces = 0
        for sub in self._queue:
            if n_traces >= self.max_batch:
                break
            if blocked and (sub.uuids & blocked):
                # per-uuid ordering: this submission waits for the
                # in-flight batch holding its uuid, and so does every
                # later submission sharing a uuid with IT (counted once,
                # at its eventual dispatch)
                blocked |= sub.uuids
                sub.was_deferred = True
                continue
            ready.append(sub)
            n_traces += len(sub.pairs)
        if not ready:
            return None, None
        age = self._clock() - ready[0].t_enqueue
        if (n_traces >= self.max_batch or age >= self.batch_close_s
                or self._closed):
            taken = set(map(id, ready))
            self._queue = deque(s for s in self._queue
                                if id(s) not in taken)
            self._queued_traces -= n_traces
            deferred = sum(1 for s in ready if s.was_deferred)
            if deferred:
                with self._stats_lock:
                    self.stats["deferred"] += deferred
            return ready, None
        return None, max(1e-4, self.batch_close_s - age)

    # ---- executor side ---------------------------------------------------

    def _run_batch(self, batch: "list[_ScheduledSubmission]", uuids,
                   serial: int, ticket=None) -> None:
        try:
            combined = [pair for s in batch for pair in s.pairs]
            with tracing.tracer().span("sched_batch", wave=serial,
                                       submissions=len(batch),
                                       traces=len(combined)):
                self._run_batch_traced(batch, combined, ticket)
        except Exception as exc:
            for s in batch:
                s.error = exc
        finally:
            with self._cv:
                self._inflight -= 1
                self._inflight_uuids -= uuids
                self.metrics.gauge("sched_inflight_batches", self._inflight)
                self._cv.notify_all()
            for s in batch:
                s.done.set()

    def _run_batch_traced(self, batch: "list[_ScheduledSubmission]",
                          combined, ticket=None) -> None:
        try:
            prefab = None
            if ticket is not None:
                try:
                    prefab = ticket.result()
                except Exception:
                    # prepare-ahead failure (incl. a closed read-ahead
                    # worker during drain) degrades to the inline head —
                    # same work, same error surface, just not overlapped
                    prefab = None
            results = self.app._process_validated(combined, prefab=prefab)
            lo = 0
            for s in batch:
                s.results = results[lo:lo + len(s.pairs)]
                lo += len(s.pairs)
        except Exception:
            # Error isolation: retry per submission, in arrival order
            # (preserves duplicate-uuid sequencing). A request that
            # fails ALONE owns its error; co-batched requests are
            # still served. Single-submission batches skip the retry
            # — the batched attempt WAS the isolated attempt.
            if len(batch) == 1:
                raise
            with self._stats_lock:
                self.stats["isolated_retries"] += 1
            for s in batch:
                try:
                    s.results = self.app._process_validated(s.pairs)
                except Exception as exc:
                    s.error = exc

    # ---- shape-bucket padding -------------------------------------------

    def pad_traces(self, traces: "Sequence[Trace]") -> "list[Trace]":
        """Pad a closed batch into the fixed executable-shape set: within
        each max-point bucket, clone that bucket's first trace until the
        trace count hits the next rung. Called by the app right before
        ``match_many``; padded rows ride the dispatch and their results
        are dropped (the app zips results against real items only), so
        the only cost is occupancy — which is what the waste metrics
        price."""
        from reporter_tpu.matcher.api import _bucket_len

        groups: dict[int, int] = {}
        templates: dict[int, "Trace"] = {}
        for t in traces:
            b = _bucket_len(len(t.xy))
            groups[b] = groups.get(b, 0) + 1
            templates.setdefault(b, t)
        pad: list = []
        with self._stats_lock:
            for b, n in groups.items():
                deficit = _rung(n) - n
                if deficit:
                    pad.extend([templates[b]] * deficit)
                    self.stats["padded_traces"] += deficit
                    self.padding_by_bucket[b] = (
                        self.padding_by_bucket.get(b, 0) + deficit)
        total = len(traces) + len(pad)
        if total:
            self.metrics.observe("sched_batch_occupancy",
                                 len(traces) / total)
        if pad:
            self.metrics.count("sched_padded_traces", len(pad))
        return list(traces) + pad

    # ---- observability / lifecycle --------------------------------------

    def snapshot(self) -> dict:
        """Scheduler state for /health: operators see saturation without
        the metrics port (admission depth, in-flight, counters)."""
        with self._cv:
            depth, traces = len(self._queue), self._queued_traces
            inflight, closed = self._inflight, self._closed
        with self._stats_lock:
            return {
                "admission_depth": depth,
                "admission_traces": traces,
                "admission_limit": self.limit,
                "inflight_batches": inflight,
                "max_inflight_batches": self.max_inflight,
                "batch_close_ms": self.batch_close_s * 1e3,
                "max_batch_traces": self.max_batch,
                "inflight_hist": dict(self.inflight_hist),
                "padding_by_bucket": dict(self.padding_by_bucket),
                "draining": closed,
                # watchdog visibility (matcher/api.py counts these): how
                # many dispatches the timeout bounded and how many were
                # served by the reference_cpu degradation path
                "dispatch_timeouts": int(
                    self.metrics.value("dispatch_timeout")),
                "dispatch_fallbacks": int(
                    self.metrics.value("dispatch_fallback")),
                **self.stats,
            }

    def close(self, timeout: "float | None" = 30.0) -> None:
        """Graceful drain: stop admitting (new submits → 503), flush the
        queue (deadlines are waived — everything closes now), join the
        in-flight batches. ``timeout`` bounds the WHOLE drain: a dispatch
        wedged on a dead link (the tunnel can hang forever) must not
        wedge shutdown with it — on timeout the daemon workers are
        abandoned (never joined at process exit) and every submission
        still queued or riding a wedged batch is failed with
        ServiceOverloaded so no client thread waits forever. Idempotent."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)

        def _left(floor: float = 0.0) -> "float | None":
            if deadline is None:
                return None
            return max(floor, deadline - time.monotonic())

        with self._cv:
            already = self._closed
            self._closed = True
            self._cv.notify_all()
        if already:
            return
        self._thread.join(timeout=_left())
        abandoned: "list[_ScheduledSubmission]" = []
        with self._cv:
            # wait for BOTH the queue and the in-flight count to drain;
            # a still-alive scheduler thread (timed-out join above) keeps
            # dispatching during this window — that is the drain working
            while self._inflight > 0 or self._queue:
                wait = _left()
                if wait is not None and wait <= 0:
                    break
                self._cv.wait(timeout=wait)
            if self._inflight > 0 or self._queue:
                # timed-out drain (wedged link): whatever is still queued
                # will never dispatch — resolve those clients with the
                # drain status instead of leaving them blocked. In-flight
                # batches' clients resolve if/when the wedge clears (the
                # workers are daemons; process exit is never blocked).
                abandoned = list(self._queue)
                self._queue.clear()
                self._queued_traces = 0
            # sentinels inside the SAME _cv section that emptied the
            # queue: every dispatched batch reached the work queue under
            # _cv before this point, so the sentinels are FIFO-behind all
            # real jobs and no job can land after them (nothing is left
            # to dispatch, and new submits are refused)
            for _ in self._workers:
                self._work.put(None)
            self._cv.notify_all()
        for s in abandoned:
            s.error = ServiceOverloaded("service drain timed out")
            s.done.set()
        for w in self._workers:
            w.join(timeout=_left(0.1))
        if self._prefab is not None:
            # after the workers: a draining worker's ticket must resolve
            # before the read-ahead thread goes away (an unstarted
            # ticket fails loudly and the worker recomputes inline)
            self._prefab.close(timeout=_left(0.1))
        self.metrics.gauge("sched_inflight_batches", 0)
        self.metrics.gauge("sched_admission_depth", 0)
