"""Per-vehicle partial-trace cache.

The reference keeps recent points per ``uuid`` (TTL'd) so segment traversals
that span multiple ``/report`` requests can still be reported as complete
(SURVEY.md §2.1 "Per-vehicle partial-trace cache"). This is also the privacy
boundary: points live at most ``ttl`` seconds and only the tail needed to
finish an in-progress segment is retained — full trajectories are never
accumulated.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass

from reporter_tpu.utils import locks


@dataclass
class _Entry:
    points: list[dict]              # [{"lat","lon","time"}…], ascending time
    wall: float                     # host wall-clock of last touch (eviction)


class PartialTraceCache:
    """Thread-safe TTL + LRU cache of per-uuid trailing trace points.

    ``merge`` prepends the cached tail to an incoming trace (deduping by
    timestamp); ``retain`` stores the tail that is still "in progress" after
    matching. ``clock`` is injectable for deterministic tests (SURVEY.md §4
    "streaming tests: … deterministic clock").
    """

    def __init__(self, ttl: float = 60.0, max_uuids: int = 100_000,
                 max_points: int = 256, clock=time.monotonic):
        self.ttl = float(ttl)
        self.max_uuids = int(max_uuids)
        self.max_points = int(max_points)
        self._clock = clock
        self._lock = locks.named_lock("cache.entries")
        self._entries: OrderedDict[str, _Entry] = OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def merge(self, uuid: str, points: list[dict]) -> list[dict]:
        """Cached tail + new points, ascending in time, deduped by time."""
        with self._lock:
            self._evict_locked()
            entry = self._entries.get(uuid)
            if entry is not None and self._clock() - entry.wall > self.ttl:
                del self._entries[uuid]     # expired but not yet at LRU front
                entry = None
            cached = list(entry.points) if entry is not None else []
        if not cached:
            return list(points)
        seen = {float(p["time"]) for p in cached}
        merged = cached + [p for p in points if float(p["time"]) not in seen]
        merged.sort(key=lambda p: float(p["time"]))
        return merged

    def retain(self, uuid: str, points: list[dict], from_time: float) -> None:
        """Keep points with time >= from_time as the uuid's pending tail.

        ``from_time`` is the end of the last *complete* segment the caller
        reported — anything earlier has been consumed and is dropped (privacy:
        reported history is never retained). The single point immediately
        before ``from_time`` is kept too: segment entry times are interpolated
        between GPS samples, so completing the in-progress segment on the next
        request needs the straddling pair, not just the points after the cut.
        """
        cut = 0
        for i, p in enumerate(points):
            if float(p["time"]) >= from_time:
                cut = max(0, i - 1)
                break
        else:
            cut = max(0, len(points) - 1)
        tail = points[cut:]
        tail = tail[-self.max_points:]
        with self._lock:
            if not tail:
                self._entries.pop(uuid, None)
                return
            self._entries[uuid] = _Entry(points=tail, wall=self._clock())
            self._entries.move_to_end(uuid)
            self._evict_locked()

    def dump(self) -> dict[str, dict]:
        """Snapshot {uuid: {points, age}} (checkpointing; SURVEY.md §5).

        ``age`` is seconds since last touch, so a restore into a new process
        (fresh clock) keeps the TTL privacy bound instead of resetting it.
        """
        now = self._clock()
        with self._lock:
            return {u: {"points": list(e.points), "age": now - e.wall}
                    for u, e in self._entries.items()}

    def load(self, state: dict[str, dict], extra_age: float = 0.0) -> None:
        """Restore a dump(); entries past the TTL are discarded.

        ``extra_age`` is time elapsed since the dump (e.g. outage duration
        from a wall-clock stamp) — monotonic ages alone can't see it.
        """
        now = self._clock()
        with self._lock:
            self._entries.clear()
            for u, rec in sorted(state.items(), key=lambda kv: -kv[1]["age"]):
                age = float(rec["age"]) + extra_age
                if age > self.ttl or not rec["points"]:
                    continue
                self._entries[u] = _Entry(points=list(rec["points"]),
                                          wall=now - age)
            self._evict_locked()

    def drop(self, uuid: str) -> None:
        with self._lock:
            self._entries.pop(uuid, None)

    def _evict_locked(self) -> None:
        # retain() always move_to_end's, so the OrderedDict is ordered by
        # last-touch wall time: expired entries cluster at the front and
        # eviction is amortized O(evicted), not O(cached).
        now = self._clock()
        while self._entries:
            _, entry = next(iter(self._entries.items()))
            if now - entry.wall <= self.ttl:
                break
            self._entries.popitem(last=False)
        while len(self._entries) > self.max_uuids:
            self._entries.popitem(last=False)   # LRU
